# Development entry points.
#
# Tests run on the CPU backend with 8 fake devices (SURVEY.md §4) and with
# the axon TPU plugin *disabled*: the sitecustomize in this image claims a
# TPU session for every Python interpreter when PALLAS_AXON_POOL_IPS is set,
# which is slow/serialized — and tests must not touch the real chip anyway.

PYTEST_ENV = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
             XLA_FLAGS="--xla_force_host_platform_device_count=8"

.PHONY: test test-fast chaos chaos-pipeline pipeline-smoke observe-smoke \
        ingest-smoke multichip-smoke audit-smoke kernel-smoke update-smoke \
        ddos-smoke cluster-smoke pressure-smoke rss-smoke qos-smoke \
        fqdn-smoke chiploss-smoke lint-serving shim bench clean

test:
	$(PYTEST_ENV) python -m pytest tests/ -q

test-fast:
	$(PYTEST_ENV) python -m pytest tests/ -q -x -m "not slow"

# Pipeline-guard gate (pipeline/guard.py): the fast, tier-1-safe stall +
# breaker + watchdog-restart subset — deadline shed, circuit-breaker
# open/probe/close, hang-forced restart parity, close-timeout sweep,
# drain-vs-close races. Wired into `make chaos` below.
chaos-pipeline:
	$(PYTEST_ENV) python -m pytest tests/test_pipeline_guard.py -q -m "not slow"

# Scripted fault-injection scenario (runtime/faults.py): regen failure storm
# → last-good serving + DEGRADED, clustermesh peer flap → ipcache
# convergence, pipeline dispatch storm + stall-storm (watchdog restart) +
# circuit breaker open/probe/close, corrupt checkpoint → cold-start
# fallback. Runs the scenario through the real jit datapath twice: directly
# via the CLI (prints the verdict-continuity report) and as the slow-marked
# pytest, plus the slow-marked 10k-submission watchdog soak. A fast subset
# on the fake datapath runs in tier-1 (tests/test_faults.py,
# tests/test_pipeline_guard.py via chaos-pipeline).
# Multi-chip serving gate (parallel/mesh.py + the sharded staging ring):
# the host-platform 8-device tier-1 subset — steering invariants, mesh
# parity, the sharded-pipeline parity suite (1-shard vs 8-shard
# bit-identical, steered staging mechanics, steer-overflow shed,
# alloc-free steered staging) — plus the slow-marked 10k-submission
# sharded soak with `shim.rx_ring` faults armed, which asserts
# `datapath_pack_fallback_total{reason="steered"}` stays 0 (the steered
# serving path packs in place into pooled per-shard wire segments).
multichip-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_parallel.py tests/test_sharded_pipeline.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_sharded_pipeline.py -q -m slow

# Verdict-provenance gate (observe/audit.py + observe/blackbox.py): the
# tier-1 audit subset — deterministic capture sampling, bounded-pool
# skipped accounting, the audit.corrupt detection drill (health DEGRADED +
# frozen debug bundle with the offending rows/revision), wedged-auditor
# serving survival, e2e SLO plumbing, scrape-race + trace-wraparound
# satellites — plus the slow-marked 10k-submission soak with the auditor
# armed at sampling 1.0 (zero mismatches, checked > 0, then a
# corruption-injection phase) and the <2%-overhead attestation.
audit-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_audit.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_audit.py -q -m slow

# Fused-megakernel gate (kernels/fused.py): the tier-1 kernel/parity
# subset — per-kernel fused-vs-jnp-vs-host parity (LPM fuzz incl. the
# grid path, CT probe pair, policy+L7+verdict), the fused end-to-end
# oracle parity suite, selector/memoization units, fused pipeline +
# 4-shard mesh + audit integration — plus the slow-marked soaks (100k-
# prefix v6 walk, long-horizon fused parity, audited pipeline soak) and a
# `bench.py --kernels` round with interpret-mode parity asserted and a
# second round --compare'd against the first (the per-kernel regression
# gate). Tier-1 already runs the fused path in interpret mode via
# tests/test_fused.py, so no PR can land a divergent kernel.
kernel-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_fused.py tests/test_kernels.py tests/test_parity.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_fused.py -q -m slow
	$(PYTEST_ENV) python bench.py --kernels --config 3 --batch 1024 --batches 4 --fused on > /tmp/cilium_tpu_kernels_gate.json
	$(PYTEST_ENV) python bench.py --kernels --config 3 --batch 1024 --batches 4 --fused on --compare /tmp/cilium_tpu_kernels_gate.json > /dev/null

# Live-update gate (compile/incremental delta path + runtime/datapath
# scatter-apply + overlapped CT GC): the tier-1 subset — delta-patch
# bit-identity vs the oracle on warm geometry, the StalePlacement donation
# fence + engine retry, sharded scatter parity, chunk-sweep == whole-table
# sweep, CT restart survival, the bounded classify-fn memo — plus the
# slow-marked soaks (restart-mid-soak, the policy storm audited at
# sampling 1.0) and a `bench.py --update-storm` round whose artifact gate
# (parity mismatches, delta-path usage, GC churn ratio, the ≥50x rule-add
# bar) exits 4 on failure, --compare'd against itself for the
# round-over-round surface.
update-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_update_storm.py tests/test_incremental.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_update_storm.py -q -m slow
	$(PYTEST_ENV) python bench.py --update-storm --preset smoke > /tmp/cilium_tpu_update_gate.json
	$(PYTEST_ENV) python bench.py --update-storm --preset smoke --compare /tmp/cilium_tpu_update_gate.json > /dev/null

# Adversarial-load gate (ISSUE 10: CT exhaustion + the degradation ladder):
# the tier-1 overload-ladder + CT-full subset — insert-when-full tail
# eviction bit-identical across jnp/fused-interpret/bounded-oracle,
# CT_FULL fail-closed verdicts, emergency GC hysteresis, ladder state
# machine + priority shed + SHED-NEW harvest shed + blackbox shed split +
# the labeled-scrape race — plus the slow flood soak (thousands of
# pipelined submissions saturating a tiny CT with `ct.insert` faults armed
# and the auditor at sampling 1.0: zero mismatches, checked > 0), and a
# `bench.py --ddos` round whose gate (≥99% established-flow survival,
# SHED-NEW reached, occupancy bounded + recovered, no post-storm
# throughput collapse, zero parity mismatches) exits 4 on failure,
# --compare'd against itself for the round-over-round surface.
ddos-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_overload.py tests/test_ctfull.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_ctfull.py -q -m slow
	$(PYTEST_ENV) python bench.py --ddos > /tmp/cilium_tpu_ddos_gate.json
	$(PYTEST_ENV) python bench.py --ddos --compare /tmp/cilium_tpu_ddos_gate.json > /dev/null

# Multi-host serving gate (ISSUE 12: runtime/clustermesh.py +
# runtime/cluster.py): the tier-1 clustermesh subset — the partition
# contract (last-good serving, MESH_STALE past the staleness budget,
# lease expiry only under a healthy listing, dead-peer tombstones),
# deterministic conflict resolution pinned on BOTH ingest orders, store
# hygiene (spoofed peer files, tmp-litter sweep, loud withdraw), the
# prefix hand-off racing lease expiry, replication-lag clamping — plus
# the slow-marked 2-proc partition/heal soak (real spawned engine
# processes over one store, `clustermesh.peer_read` +
# `clustermesh.store_list` faults armed through six partition rounds,
# gating on convergence-after-heal and zero parity mismatches at
# sampling 1.0), and a `bench.py --cluster 3` round whose artifact gate
# (convergence via the delta-patch path, cross-boundary verdict
# spot-audit, partition / peer-kill+restart / conflicting-claims /
# skewed-clock chaos, relay fan-in spanning every node, zero audit
# mismatches) exits 4 on failure.
cluster-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_clustermesh.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_clustermesh.py -q -m slow
	$(PYTEST_ENV) env CILIUM_TPU_CLUSTER_DATAPATH=fake python bench.py --cluster 3 --preset smoke > /tmp/cilium_tpu_cluster_gate.json

# Resource-pressure gate (ISSUE 13: observe/pressure.py ledger + the HBM
# ledger): the tier-1 ledger subset — registration floor (≥12 resources),
# CT-row-tracks-gauge exactness, ETA/forecast latching, RESOURCE_PRESSURE
# health detail, the ladder's fourth latch, {resource=} scrape races,
# register/deregister under engine restart, trace-ring drop accounting,
# departed-shard/peer gauge sweeps, verifier budget doc, JIT HBM groups —
# plus the slow-marked soaks: the cfg6-form storm (ct_table row bit-
# identical to ct_occupancy every tick, time-to-exhaustion fired before
# SHED-NEW, auditor clean at 1.0) and the 8-shard audited scrape-race soak
# with a mid-soak watchdog restart (the PR 7/11 house pattern on the new
# families). The full-scale acceptance rides `bench.py --ddos` (ddos-smoke
# above), whose artifact now gates trajectory exactness, forecast-before-
# SHED-NEW, and the <2% ledger-polling attestation.
pressure-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_pressure.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_pressure.py -q -m slow

# Device-side RSS gate (parallel/exchange.py + rss_mode="device"): the
# tier-1 device-RSS subset — ring-primitive units, exchange-vs-steered
# bit-identity through a saturating flood (CT_FULL + tail-evict order),
# the device parity suite vs the steered mesh and the oracle, the
# skewed/alternating/cfg6-storm arrival patterns with zero sheds, the
# degraded steer-revision fence, the rss_exchange ledger row + swept
# steer gauges, and the auditor at sampling 1.0 — plus the slow-marked
# 10k-row all-one-shard skewed soak host steering cannot survive
# shed-free, and a steered-vs-unsteered `bench.py --rss device` A/B
# round (cfg1: the policy/LPM-weighted workload where the steered
# path's skew collapse is visible) whose rss_gate exits 4 on failure —
# skew immunity + zero device sheds always; the absolute fps
# comparison arms on TPU (CPU-unmeasurable by construction, like the
# --kernels fused gate).
rss-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_rss.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_rss.py -q -m slow
	$(PYTEST_ENV) python bench.py --pipeline --config 1 --shards 4 --rss device --preset smoke > /tmp/cilium_tpu_rss_gate.json

# Multi-tenant QoS gate (cilium_tpu/qos): the tier-1 QoS subset — tenant
# spec/LUT mechanics, DRR weight shares + FIFO-within-tenant + the
# zero-weight starvation floor + the lane bypass debt bound, tenant-scoped
# caps / over-share fail-fast / priority displacement, the `qos.enqueue`
# fail-closed fault, the QoS-off byte-identical surface, engine parity
# with the auditor at 1.0 while QoS is armed — plus the slow-marked
# 8-shard mixed-tenant soak (concurrent `{tenant=}` metric scrapes racing
# a mid-soak watchdog restart), and a `bench.py --tenants` cfg8 round
# whose gate (victim survival ≥99%, lane p99 within budget under the
# flood, the flooder's DRR share confined to its 1/7 weight band, zero
# parity mismatches) exits 4 on failure, --compare'd against itself for
# the round-over-round per-tenant surface.
qos-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_qos.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_qos.py -q -m slow
	$(PYTEST_ENV) python bench.py --tenants > /tmp/cilium_tpu_qos_gate.json
	$(PYTEST_ENV) python bench.py --tenants --compare /tmp/cilium_tpu_qos_gate.json > /dev/null

# In-band DNS plane gate (fqdn/ + the delta-path identity retirement in
# compile/incremental.py): the tier-1 FQDN subset (parser edge cases,
# proxy fail-open, refresh coalescing, retirement/fresh-rebuild parity,
# the wire-path feeder tap) plus the cfg9 churn workload behind its
# exit-4 gate (zero oracle mismatches at sampling 1.0, established
# survival >= 0.99, zero full rebuilds in steady churn, refresh p99
# inside the delta budget) — run twice to prove --compare regression
# detection stays wired.
fqdn-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_fqdn.py tests/test_fqdn_plane.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_fqdn_plane.py -q -m slow
	$(PYTEST_ENV) python bench.py --fqdn > /tmp/cilium_tpu_fqdn_gate.json
	$(PYTEST_ENV) python bench.py --fqdn --compare /tmp/cilium_tpu_fqdn_gate.json > /dev/null

# Mesh self-healing gate (ISSUE 19: runtime/datapath.remesh +
# Pipeline.remesh + the engine's mesh-heal / ct-snapshot controllers):
# the serving-path exception-hygiene lint (a swallowed broad catch eats
# exactly the dispatch evidence device-loss detection runs on), the
# tier-1 chip-loss subset — dead-device triage, fenced re-mesh geometry
# + queued-submission survival, CT salvage/archive/grace mechanics,
# probe-canary heal with hysteresis, degraded n-1 parity — plus the
# cfg10 chip-loss workload behind its exit-4 gate (established survival
# >= 0.99 through loss+heal, zero oracle mismatches at sampling 1.0,
# degraded fps >= 0.7x the ideal (n-1)/n, exactly one re-mesh each
# direction, the grace window actually fired, full width restored) —
# run twice to prove --compare regression detection stays wired.
lint-serving:
	python tools/lint_serving.py

chiploss-smoke: lint-serving
	$(PYTEST_ENV) python -m pytest tests/test_chiploss.py \
		"tests/test_sharded_pipeline.py::TestDegradedMeshParity" \
		"tests/test_rss.py::TestDeviceRSSDegradedMesh" -q
	$(PYTEST_ENV) python bench.py --chiploss > /tmp/cilium_tpu_chiploss_gate.json
	$(PYTEST_ENV) python bench.py --chiploss --compare /tmp/cilium_tpu_chiploss_gate.json > /dev/null

chaos: chaos-pipeline ingest-smoke multichip-smoke audit-smoke kernel-smoke update-smoke ddos-smoke cluster-smoke pressure-smoke rss-smoke qos-smoke fqdn-smoke chiploss-smoke
	$(PYTEST_ENV) python -m cilium_tpu.cli.main faults chaos --failures 10
	$(PYTEST_ENV) python -m pytest tests/test_faults.py -q -m slow
	$(PYTEST_ENV) python -m pytest tests/test_pipeline_guard.py -q -m slow

# Zero-copy-ingestion gate (shim/feeder.py + the out= pack kernels): the
# tier-1 feeder/pack subset (poll-buffer reuse parity, FIFO verdict order
# through mock rings incl. an armed shim.rx_ring storm, fail-closed on
# pipeline rejection, the tracemalloc steady-state zero-alloc soak) plus
# the slow-marked 10k-frame feeder soak with faults armed the whole run.
ingest-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_feeder.py tests/test_kernels.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_feeder.py -q -m slow

# Ingestion-pipeline gate (pipeline/scheduler.py): the tier-1 pipeline
# subset (ordering, backpressure, deadline flush, fault retries, clean
# shutdown, serial-vs-pipelined verdict parity) plus the slow-marked
# FakeDatapath soak — 10k submissions with `pipeline.dispatch` faults
# armed, asserting no queued batch is lost or reordered.
pipeline-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_pipeline.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_pipeline.py -q -m slow

# Observability gate (cilium_tpu/observe/): the tier-1 observe + observer +
# pipeline subset (tracer sampling/ring, flow-metrics windows, autotuner
# hysteresis/convergence, tracing-on parity; ISSUE 11: FlowFilter mask
# composition, follow-mode gap accounting incl. a live writer race, relay
# merge/lag/gap re-emission, {rule=} hit counters + scrape race) plus the
# slow-marked soaks — the sampled-trace <2% contract, the observer
# filters-armed <2% attestation (PR 3 form), and the relay fan-in phase
# over a live 4-shard mesh + 3 peers — and a `bench.py --ingest --observer`
# D/A/D/A round gating the <2% fps attestation in the artifact.
observe-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_observe.py tests/test_observer.py tests/test_pipeline.py -q -m "not slow"
	$(PYTEST_ENV) python -m pytest tests/test_observe.py tests/test_observer.py -q -m slow
	$(PYTEST_ENV) python bench.py --ingest --observer --frames 24000 > /tmp/ingest_observer.json
	python -c "import json; d=json.loads([l for l in open('/tmp/ingest_observer.json') if l.strip()][-1]); s=d['observer_soak']; print('observer soak:', s); assert s['ok'], 'observer overhead %s%% > %s%%' % (s['overhead_pct'], s['budget_pct'])"

shim:
	$(MAKE) -C cilium_tpu/shim

bench:
	python bench.py

clean:
	$(MAKE) -C cilium_tpu/shim clean 2>/dev/null || true
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
