#!/usr/bin/env python
"""Benchmarks: the five BASELINE.md configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
headline config (5: conntrack churn — 50k-rule policy, 1M-flow CT, 10%
new-flow rate, single chip), plus per-batch latency percentiles
("p50_batch_ms"/"p99_batch_ms", BASELINE metric: "+ p99 batch latency") and
a "configs" sub-object with every config's throughput + latency so
round-over-round visibility covers the LPM-heavy and L7 shapes too.
``vs_baseline`` normalizes against the driver-set north star — 10M flows/sec
on a v5e-8 (8 chips) → 1.25M flows/sec/chip; there are no reference-published
numbers (BASELINE.json.published == {}, see BASELINE.md provenance note).

Usage:
  python bench.py [--config 1..5] [--preset smoke|full|auto]
                  [--batch N] [--batches K] [--only]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 10e6 / 8  # north-star flows/sec per chip


# --------------------------------------------------------------------------- #
# world builders (one per config)
# --------------------------------------------------------------------------- #
def _ctx_repo():
    from cilium_tpu.model.identity import IdentityAllocator
    from cilium_tpu.model.ipcache import IPCache
    from cilium_tpu.policy import PolicyContext, Repository
    from cilium_tpu.policy.selectorcache import SelectorCache
    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache())
    return ctx, Repository(ctx)


def _add_web_ep(ctx, ip="192.168.0.10"):
    from cilium_tpu.model.endpoint import Endpoint
    from cilium_tpu.model.labels import Labels
    lbls = Labels.parse(["k8s:app=web"])
    ident = ctx.allocator.allocate(lbls)
    ctx.ipcache.upsert(f"{ip}/32", ident.id)
    return Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)


def _compile(ctx, repo, eps, ct_capacity):
    from cilium_tpu.compile.ct_layout import CTConfig
    from cilium_tpu.compile.snapshot import build_snapshot
    return build_snapshot(repo, ctx, eps, CTConfig(capacity=ct_capacity))


def build_config1(preset):
    """1k static CIDR allow/deny rules, single endpoint, IPv4 only."""
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_rules = 1000
    rules = []
    for i in range(n_rules):
        a, b = 1 + (i % 200), (i * 7) % 256
        block = {"toCIDR": [f"{a}.{b}.0.0/16"]}
        if i % 3 == 2:
            rules.append(parse_rule({
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egressDeny": [block]}))
        else:
            rules.append(parse_rule({
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [block]}))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    def gen(rng, n):
        b = _base_batch(n)
        b["dst"][:, 3] = ((rng.integers(1, 220, n) << 24)
                          + rng.integers(0, 1 << 24, n)).astype(np.uint32)
        b["dport"][:] = rng.integers(1, 65535, n)
        return b
    return snap, gen, True  # v4_only


def build_config2(preset):
    """10k pod identities, 5k CNP port rules, mixed v4/v6 traffic."""
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_ids = 2000 if preset == "smoke" else 10000
    n_rules = 1000 if preset == "smoke" else 5000
    groups = 200
    for i in range(n_ids):
        ident = ctx.allocator.allocate(
            Labels.parse([f"k8s:group=g{i % groups}", f"k8s:pod=p{i}"]))
        ctx.ipcache.upsert(f"172.{16 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32",
                           ident.id)
        if i % 4 == 0:
            ctx.ipcache.upsert(f"2001:db8:{i >> 8:x}:{i & 0xFF:x}::1/128",
                               ident.id)
    rules = []
    for j in range(n_rules):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"group": f"g{j % groups}"}}],
                "toPorts": [{"ports": [
                    {"port": str(1000 + (j % 4000)), "protocol":
                     "TCP" if j % 3 else "UDP"}]}],
            }],
        }))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    def gen(rng, n):
        b = _base_batch(n, direction=1)
        i = rng.integers(0, n_ids, n)
        b["src"][:, 3] = (0xAC100000 + ((16 + (i >> 16)) - 16 << 24)
                          + ((i >> 8) & 0xFF) * 256 + (i & 0xFF)).astype(np.uint32)
        # (v6 share omitted from the hot loop; the snapshot still carries v6)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = rng.integers(20000, 60000, n)
        # ~70% aimed at a port the identity's group actually allows
        # (group g allows ports {1000 + j%4000 : j ≡ g mod groups})
        k = rng.integers(0, max(1, n_rules // groups), n)
        aligned = 1000 + ((i % groups) + groups * k) % 4000
        b["dport"][:] = np.where(rng.random(n) < 0.7, aligned,
                                 rng.integers(1000, 5000, n))
        b["proto"][:] = np.where(rng.random(n) < 0.9, 6, 17)
        return b
    return snap, gen, True


def build_config3(preset):
    """100k CIDR prefixes (BGP-table-like) + ToServices, Zipf traffic."""
    from cilium_tpu.model.rules import parse_rule
    from cilium_tpu.model.services import Service
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_prefix = 20000 if preset == "smoke" else 100000
    rng0 = np.random.default_rng(0)
    # one covering allow for half the space + direct ipcache prefix churn
    repo.add([parse_rule({
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toCIDR": ["0.0.0.0/1"]}]})])
    ctx.services.upsert(Service(name="api", namespace="prod",
                                backends=("10.200.0.1", "10.200.0.2")))
    repo.add([parse_rule({
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toServices": [{"k8sService": {
            "serviceName": "api", "namespace": "prod"}}]}]})])
    # the BGP-slice: prefixes straight into the ipcache (identity per /16
    # block to bound identity count)
    from cilium_tpu.model.identity import cidr_identity_labels
    for i in range(n_prefix):
        plen = int(rng0.choice([16, 20, 24], p=[0.2, 0.3, 0.5]))
        addr = int(rng0.integers(0x01000000, 0xDF000000)) & (0xFFFFFFFF << (32 - plen))
        prefix = f"{addr >> 24}.{(addr >> 16) & 0xFF}.{(addr >> 8) & 0xFF}.{addr & 0xFF}/{plen}"
        ident = ctx.allocator.allocate_cidr(f"{addr >> 24}.0.0.0/8")
        ctx.ipcache.upsert(prefix, ident.id)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    # Zipf-skewed destination pool
    pool_n = 1 << 16
    pool = ((rng0.integers(1, 220, pool_n) << 24)
            + rng0.integers(0, 1 << 24, pool_n)).astype(np.uint32)
    zipf_w = 1.0 / np.arange(1, pool_n + 1) ** 1.1
    zipf_p = zipf_w / zipf_w.sum()

    def gen(rng, n):
        b = _base_batch(n)
        b["dst"][:, 3] = rng.choice(pool, size=n, p=zipf_p)
        b["dport"][:] = rng.integers(1, 65535, n)
        return b
    return snap, gen, True


def build_config4(preset):
    """L7-lite: HTTP method/path-prefix matching via token tensors."""
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_rulesets = 50 if preset == "smoke" else 200
    rules = []
    for i in range(n_rulesets):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{
                "ports": [{"port": str(80 + i), "protocol": "TCP"}],
                "rules": {"http": [
                    {"method": "GET", "path": f"/api/v{i}"},
                    {"method": "POST", "path": f"/submit/{i}"},
                    {"path": f"/public/{i}"},
                ]},
            }]}],
        }))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 16))
    paths = [f"/api/v{i}/x".encode() for i in range(n_rulesets)] + \
            [b"/forbidden/zone", b"/public/7/asset.js"]
    path_arr = np.zeros((len(paths), 64), dtype=np.uint8)
    for i, p in enumerate(paths):
        path_arr[i, :len(p)] = np.frombuffer(p[:64], dtype=np.uint8)

    def gen(rng, n):
        b = _base_batch(n, direction=1)
        b["src"][:, 3] = rng.integers(0x0B000000, 0x0BFFFFFF, n).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        port_idx = rng.integers(0, n_rulesets, n)
        b["dport"][:] = 80 + port_idx
        b["tcp_flags"][:] = 0x10
        # ~70% requests aligned with their port's ruleset (GET /api/v{i});
        # the rest random (exercise the drop path)
        aligned = rng.random(n) < 0.7
        pi = np.where(aligned, port_idx, rng.integers(0, len(paths), n))
        b["http_method"][:] = np.where(aligned, 0, rng.integers(0, 2, n))
        b["http_path"][:] = path_arr[pi]
        return b
    return snap, gen, True


def build_config5(preset):
    """Conntrack churn: 50k-rule policy, 1M concurrent flows, 10% new rate."""
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_ids = 500 if preset == "smoke" else 2000
    n_rules = 5000 if preset == "smoke" else 50000
    for i in range(n_ids):
        ident = ctx.allocator.allocate(Labels.parse([f"k8s:pod=p{i}"]))
        ctx.ipcache.upsert(f"172.{16 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32",
                           ident.id)
    rules = []
    for j in range(n_rules):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"pod": f"p{j % n_ids}"}}],
                "toPorts": [{"ports": [
                    {"port": str(1024 + (j % 25000)), "protocol": "TCP"}]}],
            }],
        }))
    repo.add(rules)
    cap = 1 << (16 if preset == "smoke" else 21)
    snap = _compile(ctx, repo, [ep], cap)

    n_flows = (1 << 14) if preset == "smoke" else 1_000_000
    rng0 = np.random.default_rng(1)
    flow_src = rng0.integers(0, n_ids, n_flows).astype(np.int64)
    flow_sport = rng0.integers(20000, 60000, n_flows).astype(np.int32)
    # dports drawn from the flow's identity's ALLOWED set so flows actually
    # establish and churn the CT (pod i allows {1024 + (i + n_ids*k) % 25000})
    k0 = rng0.integers(0, max(1, n_rules // n_ids), n_flows)
    flow_dport = (1024 + (flow_src + n_ids * k0) % 25000).astype(np.int32)

    def gen(rng, n):
        # 90% existing flows, 10% replaced with fresh ones (the churn)
        idx = rng.integers(0, n_flows, n)
        n_new = n // 10
        repl = idx[:n_new]
        flow_sport[repl] = rng.integers(20000, 60000, n_new)
        b = _base_batch(n, direction=1)
        i = flow_src[idx]
        b["src"][:, 3] = (0xAC100000 + ((i >> 8) & 0xFF) * 256
                          + (i & 0xFF)).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = flow_sport[idx]
        b["dport"][:] = flow_dport[idx]
        b["tcp_flags"][:] = 0x10
        return b
    return snap, gen, True


def _base_batch(n, direction=0):
    from cilium_tpu.kernels.records import empty_batch
    b = empty_batch(n)
    b["src"][:, 2] = 0xFFFF
    b["dst"][:, 2] = 0xFFFF
    b["src"][:, 3] = 0xC0A8000A
    b["sport"][:] = 40000
    b["dport"][:] = 443
    b["proto"][:] = 6
    b["tcp_flags"][:] = 0x02
    b["direction"][:] = direction
    b["valid"][:] = True
    return b


BUILDERS = {1: build_config1, 2: build_config2, 3: build_config3,
            4: build_config4, 5: build_config5}
METRIC_NAMES = {
    1: "cfg1_l3_cidr_1k_rules",
    2: "cfg2_multi_identity_l3l4",
    3: "cfg3_lpm_heavy",
    4: "cfg4_l7_lite",
    5: "cfg5_conntrack_churn_50k_rules",
}


# --------------------------------------------------------------------------- #
# runner
# --------------------------------------------------------------------------- #
def run_bench(config: int, preset: str, batch: int, batches: int,
              verbose: bool = False, windows: int = 3):
    """One config → throughput dict.

    Pipeline modeled: packed wire batches (kernels/records.pack_batch — the
    single-buffer format the C++ shim emits) are device_put with one-batch
    prefetch (the next transfer overlaps the current classify), then the
    fused classify step runs with donated CT buffers. Transfers ARE included
    in the timing. ``windows`` timing windows are run and the best is
    reported — the steady-state rate, robust to transport-link jitter (this
    rig's host↔TPU tunnel varies several-fold run to run).
    """
    import jax
    import jax.numpy as jnp
    from cilium_tpu.compile.ct_layout import make_ct_arrays
    from cilium_tpu.kernels.classify import make_classify_fn
    from cilium_tpu.kernels.records import pack_batch

    t0 = time.time()
    snap, gen, v4_only = BUILDERS[config](preset)
    compile_s = time.time() - t0

    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    ct = {k: jnp.asarray(v) for k, v in make_ct_arrays(snap.ct_config).items()}
    fn = make_classify_fn(v4_only=v4_only, donate_ct=True, packed=True)
    rng = np.random.default_rng(7)
    wi = jnp.int32(snap.world_index)

    # pre-generate packed host batches (generation excluded from the timed
    # loop — the shim does it in C++; transfer included, it is part of the
    # real pipeline). One packed width per config so a single jit serves.
    host_dicts = [gen(rng, batch) for _ in range(min(batches, 16))]
    from cilium_tpu.utils import constants as C
    from cilium_tpu.kernels.records import pack_batch_v4
    # L7 presence must be decided across ALL pre-generated batches: deciding
    # from the first alone silently drops later batches' http_path data
    # (changing measured verdicts) whenever the first happens to be L7-free.
    # (Same detection expression pack_batch uses, without packing twice.)
    has_l7 = any(bool((hb["http_method"] != C.HTTP_METHOD_ANY).any()
                      or hb["http_path"].any()) for hb in host_dicts)
    has_v6 = any(bool(hb["is_v6"].any()) for hb in host_dicts)
    if not has_l7 and not has_v6:
        # compact 16B/record wire format — the transfer-bound fast path
        host_batches = [pack_batch_v4(hb) for hb in host_dicts]
    else:
        host_batches = [pack_batch(hb, l7=has_l7) for hb in host_dicts]

    # warmup / compile
    now = 10_000
    out, ct, counters = fn(tensors, ct, jnp.asarray(host_batches[0]),
                           jnp.uint32(now), wi)
    jax.block_until_ready(out)
    trace_s = time.time() - t0 - compile_s

    best_dt = None
    for _w in range(windows):
        nxt = jax.device_put(host_batches[0])
        t1 = time.time()
        for i in range(batches):
            cur = nxt
            nxt = jax.device_put(host_batches[(i + 1) % len(host_batches)])
            now += 1
            out, ct, counters = fn(tensors, ct, cur, jnp.uint32(now), wi)
        jax.block_until_ready(out)
        dt = time.time() - t1
        best_dt = dt if best_dt is None else min(best_dt, dt)
    throughput = batches * batch / best_dt

    # per-batch latency distribution: synchronous dispatch (transfer +
    # classify + result fence per batch) — the per-batch time an enforcing
    # shim would wait for a verdict bitmap, deliberately unpipelined.
    lat_n = max(20, min(batches, 50))
    lat_ms = np.empty(lat_n)
    for i in range(lat_n):
        now += 1
        t1 = time.time()
        cur = jax.device_put(host_batches[i % len(host_batches)])
        out, ct, counters = fn(tensors, ct, cur, jnp.uint32(now), wi)
        jax.block_until_ready(out["allow"])
        lat_ms[i] = (time.time() - t1) * 1e3
    p50_ms = float(np.percentile(lat_ms, 50))
    p99_ms = float(np.percentile(lat_ms, 99))

    if verbose:
        by = np.asarray(counters["by_reason_dir"]).reshape(256, 2)
        print(f"# config={config} preset={preset} platform="
              f"{jax.devices()[0].platform} batch={batch} batches={batches}"
              f" windows={windows}\n"
              f"# compile={compile_s:.1f}s trace={trace_s:.1f}s"
              f" best-window={best_dt:.3f}s\n"
              f"# sync batch latency p50={p50_ms:.2f}ms p99={p99_ms:.2f}ms"
              f" last-batch reasons={ {int(r): int(by[r].sum()) for r in np.nonzero(by.sum(1))[0]} }",
              file=sys.stderr)
    return {
        "metric": f"flow_classify_throughput_{METRIC_NAMES[config]}",
        "value": round(throughput, 1),
        "unit": "flows/sec/chip",
        "vs_baseline": round(throughput / PER_CHIP_TARGET, 4),
        "p50_batch_ms": round(p50_ms, 3),
        "p99_batch_ms": round(p99_ms, 3),
        "batch": batch,
        "preset": preset,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=5, choices=sorted(BUILDERS))
    ap.add_argument("--preset", default="auto",
                    choices=["auto", "smoke", "full"])
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--batches", type=int, default=0)
    ap.add_argument("--only", action="store_true",
                    help="run just --config (default: all five, with "
                         "--config as the headline metric)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    import jax
    platform = jax.devices()[0].platform
    preset = args.preset
    if preset == "auto":
        preset = "smoke" if platform == "cpu" else "full"
    # 64k records ≈ 2.9MB packed — big enough to amortize dispatch, small
    # enough to stay under the transport's fast-path transfer size
    batch = args.batch or (4096 if preset == "smoke" else 65536)
    batches = args.batches or (10 if preset == "smoke" else 40)

    result = run_bench(args.config, preset, batch, batches,
                       verbose=args.verbose)
    if not args.only:
        configs = {METRIC_NAMES[args.config]: {
            "value": result["value"], "vs_baseline": result["vs_baseline"],
            "p50_batch_ms": result["p50_batch_ms"],
            "p99_batch_ms": result["p99_batch_ms"]}}
        for cfg in sorted(BUILDERS):
            if cfg == args.config:
                continue
            # non-headline configs: fewer timed batches (visibility, not the
            # headline number) so the whole sweep stays bounded
            res = run_bench(cfg, preset, batch, max(10, batches // 2),
                            verbose=args.verbose)
            print(json.dumps(res), file=sys.stderr)
            configs[METRIC_NAMES[cfg]] = {
                "value": res["value"], "vs_baseline": res["vs_baseline"],
                "p50_batch_ms": res["p50_batch_ms"],
                "p99_batch_ms": res["p99_batch_ms"]}
        result["configs"] = configs
    print(json.dumps(result))


if __name__ == "__main__":
    main()
