#!/usr/bin/env python
"""Benchmarks: the five BASELINE.md configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
headline config (5: conntrack churn — 50k-rule policy, 1M-flow CT, 10%
new-flow rate, single chip), plus per-batch latency percentiles
("p50_batch_ms"/"p99_batch_ms", BASELINE metric: "+ p99 batch latency") and
a "configs" sub-object with every config's throughput + latency so
round-over-round visibility covers the LPM-heavy and L7 shapes too.
``vs_baseline`` normalizes against the driver-set north star — 10M flows/sec
on a v5e-8 (8 chips) → 1.25M flows/sec/chip; there are no reference-published
numbers (BASELINE.json.published == {}, see BASELINE.md provenance note).

Usage:
  python bench.py [--config 1..5] [--preset smoke|full|auto]
                  [--batch N] [--batches K] [--only]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

PER_CHIP_TARGET = 10e6 / 8  # north-star flows/sec per chip

# Watchdog: this rig's host↔TPU tunnel can wedge mid-run (a device op
# never completes; the process freezes in a futex wait). A hung benchmark
# reports nothing — worse than a partial report. The watchdog emits the
# best-effort JSON line from whatever completed and exits.
WATCHDOG_DEADLINE_S = float(os.environ.get(
    "CILIUM_TPU_BENCH_DEADLINE_S", 2400))
_progress: dict = {"headline": None, "configs": {}}


def _start_watchdog(headline_metric: str) -> None:
    if WATCHDOG_DEADLINE_S <= 0:
        return                          # 0/negative = watchdog disabled

    def fire():
        time.sleep(WATCHDOG_DEADLINE_S)
        doc = _progress["headline"] or {
            "metric": f"flow_classify_throughput_{headline_metric}",
            "value": 0, "unit": "flows/sec/chip", "vs_baseline": 0,
        }
        doc = dict(doc)
        doc["watchdog_timeout"] = True
        doc["error"] = (f"bench stalled past {WATCHDOG_DEADLINE_S:.0f}s "
                        "(tunnel wedge); partial results reported")
        if _progress["configs"]:
            doc["configs"] = _progress["configs"]
        print(json.dumps(doc), flush=True)
        os._exit(3)
    threading.Thread(target=fire, daemon=True,
                     name="bench-watchdog").start()


# --------------------------------------------------------------------------- #
# artifact provenance + regression compare
# --------------------------------------------------------------------------- #
def _provenance(argv=None):
    """Artifact provenance: enough to answer "what produced this number"
    months later — the git revision, the jax stack, and a hash of the
    bench's whole config surface (argv + every CILIUM_TPU_* env knob, the
    things that silently change reference numbers between runs)."""
    import hashlib
    rev = "unknown"
    try:
        import subprocess
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           capture_output=True, text=True, timeout=10)
        if r.returncode == 0 and r.stdout.strip():
            rev = r.stdout.strip()
    except Exception:
        pass
    try:
        import jax
        jax_version = jax.__version__
        platform = jax.devices()[0].platform
    except Exception:
        jax_version = platform = "unknown"
    cfg = {"argv": list(sys.argv[1:] if argv is None else argv),
           "env": {k: v for k, v in sorted(os.environ.items())
                   if k.startswith("CILIUM_TPU_")}}
    doc = {
        "git_rev": rev,
        "jax_version": jax_version,
        "platform": platform,
        "config_hash": hashlib.sha256(
            json.dumps(cfg, sort_keys=True).encode()).hexdigest()[:12],
        "config": cfg,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if _HBM_REPORT["budget"] is not None:
        # offline verifier HBM budget (cilium-tpu verify --report FILE,
        # embedded via --hbm-report): the artifact cites the same numbers
        # the --max-hbm-bytes gate judged and the live ledger exports
        doc["hbm_budget"] = _HBM_REPORT["budget"]
    return doc


#: `--hbm-report FILE` payload (the budget summary of a `cilium-tpu verify
#: --report` sweep), stamped into every artifact's provenance when given
_HBM_REPORT = {"budget": None}


#: fields --compare judges, with direction: +1 higher-is-better
#: (throughput), -1 lower-is-better (latency)
COMPARE_FIELDS = (
    ("value", +1),
    ("compute_only", +1),
    ("speedup_vs_serial", +1),
    ("e2e_p50_ms", -1),
    ("e2e_p99_ms", -1),
    ("pack_p50_ms", -1),
    # --ddos artifacts: adversarial-load survival
    ("survival_rate", +1),
    ("legit_e2e_p99_ms", -1),
    # --tenants artifacts: multi-tenant isolation (lower flooder share =
    # better confined to its weight)
    ("victim_survival_min", +1),
    ("lane_e2e_p99_ms", -1),
    ("flood_admitted_share", -1),
    # --fqdn artifacts: DNS-churn policy refresh on the delta path
    ("refresh_p50_ms", -1),
    ("refresh_p99_ms", -1),
    ("established_survival", +1),
    # --update-storm artifacts: live-patch latency under pipelined traffic
    ("rule_add_ms", -1),
    ("rule_add_p99_ms", -1),
    ("device_apply_p50_ms", -1),
    # --kernels artifacts: per-kernel compute-only latency
    ("kernel_lpm_p50_ms", -1),
    ("kernel_ct_probe_p50_ms", -1),
    ("kernel_policy_l7_p50_ms", -1),
    ("kernel_full_step_p50_ms", -1),
)

#: max tolerated regression ratio for --compare (generalizes the PR 6
#: --shards 1 gate to ANY prior artifact; deliberately generous — the gate
#: catches wholesale regressions, not jitter)
BENCH_COMPARE_FACTOR = float(os.environ.get(
    "CILIUM_TPU_BENCH_COMPARE_FACTOR", "1.75"))


def _metric_surface(doc: dict) -> dict:
    """The comparable numbers of one artifact, flattened (pack p50 lives
    in the stage/trace span split depending on the mode; per-kernel p50s
    come from the --kernels artifact's ``kernels`` block)."""
    out = {}
    for key, _d in COMPARE_FIELDS:
        v = doc.get(key)
        if isinstance(v, (int, float)):
            out[key] = v
    spans = doc.get("stage_split") or doc.get("trace_spans") or {}
    p = (spans.get("datapath.pack") or {}).get("p50_ms")
    if p is not None:
        out["pack_p50_ms"] = p
    for kname, kdoc in (doc.get("kernels") or {}).items():
        p = kdoc.get("p50_ms")
        if isinstance(p, (int, float)):
            out[f"kernel_{kname}_p50_ms"] = p
    return out


def _compare_artifacts(new_doc: dict, old_path: str,
                       factor: float = BENCH_COMPARE_FACTOR) -> dict:
    """Diff this run against a prior JSON artifact: every comparable field
    present in BOTH is ratio-checked against ``factor`` in its
    direction. ``failed`` fails the artifact (exit 4 from main) — the
    round-over-round regression gate."""
    with open(old_path) as f:
        old_doc = json.load(f)
    new_m, old_m = _metric_surface(new_doc), _metric_surface(old_doc)
    checked, regressions = {}, []
    for key, direction in COMPARE_FIELDS:
        old_v, new_v = old_m.get(key), new_m.get(key)
        if old_v is None or new_v is None or old_v <= 0:
            continue
        ratio = new_v / old_v
        checked[key] = {"old": old_v, "new": new_v,
                        "ratio": round(ratio, 4)}
        if direction > 0 and ratio < 1.0 / factor:
            regressions.append(
                f"{key}: {new_v} < {old_v}/{factor} (ratio {ratio:.3f})")
        elif direction < 0 and ratio > factor:
            regressions.append(
                f"{key}: {new_v} > {old_v}*{factor} (ratio {ratio:.3f})")
    return {
        "baseline": old_path,
        "baseline_rev": (old_doc.get("provenance") or {}).get("git_rev"),
        "factor": factor,
        "checked": checked,
        # steered and unsteered sharded artifacts are deliberately
        # comparable (same metric surface; the span-attribution contract
        # lives in each artifact's own schema_check, not here) — the
        # annotation makes a cross-mode diff visible in the artifact
        **({"rss": {"old": old_doc.get("rss", "host"),
                    "new": new_doc.get("rss", "host")}}
           if (new_doc.get("rss") or old_doc.get("rss")) else {}),
        "failed": bool(regressions),
        **({"regressions": regressions} if regressions else {}),
    }


# --------------------------------------------------------------------------- #
# world builders (one per config)
# --------------------------------------------------------------------------- #
def _ctx_repo():
    from cilium_tpu.model.identity import IdentityAllocator
    from cilium_tpu.model.ipcache import IPCache
    from cilium_tpu.policy import PolicyContext, Repository
    from cilium_tpu.policy.selectorcache import SelectorCache
    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache())
    return ctx, Repository(ctx)


def _add_web_ep(ctx, ip="192.168.0.10"):
    from cilium_tpu.model.endpoint import Endpoint
    from cilium_tpu.model.labels import Labels
    lbls = Labels.parse(["k8s:app=web"])
    ident = ctx.allocator.allocate(lbls)
    ctx.ipcache.upsert(f"{ip}/32", ident.id)
    return Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)


def _compile(ctx, repo, eps, ct_capacity):
    from cilium_tpu.compile.ct_layout import CTConfig
    from cilium_tpu.compile.snapshot import build_snapshot
    return build_snapshot(repo, ctx, eps, CTConfig(capacity=ct_capacity))


def build_config1(preset):
    """1k static CIDR allow/deny rules, single endpoint, IPv4 only."""
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_rules = 1000
    rules = []
    for i in range(n_rules):
        a, b = 1 + (i % 200), (i * 7) % 256
        block = {"toCIDR": [f"{a}.{b}.0.0/16"]}
        if i % 3 == 2:
            rules.append(parse_rule({
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egressDeny": [block]}))
        else:
            rules.append(parse_rule({
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [block]}))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    def gen(rng, n):
        b = _base_batch(n)
        b["dst"][:, 3] = ((rng.integers(1, 220, n) << 24)
                          + rng.integers(0, 1 << 24, n)).astype(np.uint32)
        b["dport"][:] = rng.integers(1, 65535, n)
        return b

    def pcap_replay(batch, count):
        """BASELINE cfg1 'IPv4-only 5-tuple pcap replay': frames through the
        C++ parser/batcher (the AF_XDP ingest path), not a numpy generator.
        Returns None (→ numpy fallback) if the shim isn't built."""
        import os
        import tempfile
        from cilium_tpu.shim.bindings import LIB_PATH
        if not os.path.exists(LIB_PATH):
            return None
        from cilium_tpu.shim.bindings import FlowShim
        from cilium_tpu.shim.pcap import replay_pcap, synthesize_pcap
        fd, path = tempfile.mkstemp(suffix=".pcap")
        os.close(fd)
        try:
            synthesize_pcap(path, batch * count)
            shim = FlowShim(batch_size=batch, timeout_us=0)
            shim.register_endpoint("192.168.0.10", 1)
            batches = replay_pcap(shim, path, batch, max_batches=count)
            shim.close()
        finally:
            os.unlink(path)
        for b in batches:
            raw = b.pop("_ep_raw")
            b.pop("_frame_idx")
            b["ep_slot"][:] = 0              # single endpoint at slot 0
            b["valid"] = raw != 0
        return batches

    gen.pcap_replay = pcap_replay
    return snap, gen, True  # v4_only


def build_config2(preset):
    """10k pod identities, 5k CNP port rules, mixed v4/v6 traffic."""
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_ids = 2000 if preset == "smoke" else 10000
    n_rules = 1000 if preset == "smoke" else 5000
    groups = 200
    for i in range(n_ids):
        ident = ctx.allocator.allocate(
            Labels.parse([f"k8s:group=g{i % groups}", f"k8s:pod=p{i}"]))
        ctx.ipcache.upsert(f"172.{16 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32",
                           ident.id)
        if i % 4 == 0:
            ctx.ipcache.upsert(f"2001:db8:{i >> 8:x}:{i & 0xFF:x}::1/128",
                               ident.id)
    rules = []
    for j in range(n_rules):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"group": f"g{j % groups}"}}],
                "toPorts": [{"ports": [
                    {"port": str(1000 + (j % 4000)), "protocol":
                     "TCP" if j % 3 else "UDP"}]}],
            }],
        }))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    def gen(rng, n):
        b = _base_batch(n, direction=1)
        i = rng.integers(0, n_ids, n)
        b["src"][:, 3] = (0xAC100000 + ((16 + (i >> 16)) - 16 << 24)
                          + ((i >> 8) & 0xFF) * 256 + (i & 0xFF)).astype(np.uint32)
        # real mixed v4/v6 (BASELINE config 2): identities with a v6 /128
        # (every 4th) send over v6 — ~25% of traffic walks the 16-level v6
        # LPM; the kernel compiles with v4_only=False
        v6 = (i % 4 == 0)
        b["is_v6"][v6] = True
        b["src"][v6, 0] = 0x20010DB8
        b["src"][v6, 1] = (((i[v6] >> 8) << 16) | (i[v6] & 0xFF)).astype(np.uint32)
        b["src"][v6, 2] = 0
        b["src"][v6, 3] = 1
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = rng.integers(20000, 60000, n)
        # ~70% aimed at a port the identity's group actually allows
        # (group g allows ports {1000 + j%4000 : j ≡ g mod groups})
        k = rng.integers(0, max(1, n_rules // groups), n)
        aligned = 1000 + ((i % groups) + groups * k) % 4000
        b["dport"][:] = np.where(rng.random(n) < 0.7, aligned,
                                 rng.integers(1000, 5000, n))
        b["proto"][:] = np.where(rng.random(n) < 0.9, 6, 17)
        return b
    return snap, gen, False


def build_config3(preset):
    """100k CIDR prefixes (BGP-table-like) + ToServices, Zipf traffic."""
    from cilium_tpu.model.rules import parse_rule
    from cilium_tpu.model.services import Service
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_prefix = 20000 if preset == "smoke" else 100000
    rng0 = np.random.default_rng(0)
    # one covering allow for half the space + direct ipcache prefix churn
    repo.add([parse_rule({
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toCIDR": ["0.0.0.0/1"]}]})])
    ctx.services.upsert(Service(name="api", namespace="prod",
                                backends=("10.200.0.1", "10.200.0.2")))
    repo.add([parse_rule({
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toServices": [{"k8sService": {
            "serviceName": "api", "namespace": "prod"}}]}]})])
    # the BGP-slice: prefixes straight into the ipcache (identity per /16
    # block to bound identity count)
    from cilium_tpu.model.identity import cidr_identity_labels
    for i in range(n_prefix):
        plen = int(rng0.choice([16, 20, 24], p=[0.2, 0.3, 0.5]))
        addr = int(rng0.integers(0x01000000, 0xDF000000)) & (0xFFFFFFFF << (32 - plen))
        prefix = f"{addr >> 24}.{(addr >> 16) & 0xFF}.{(addr >> 8) & 0xFF}.{addr & 0xFF}/{plen}"
        ident = ctx.allocator.allocate_cidr(f"{addr >> 24}.0.0.0/8")
        ctx.ipcache.upsert(prefix, ident.id)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 18))

    # Zipf-skewed destination pool
    pool_n = 1 << 16
    pool = ((rng0.integers(1, 220, pool_n) << 24)
            + rng0.integers(0, 1 << 24, pool_n)).astype(np.uint32)
    zipf_w = 1.0 / np.arange(1, pool_n + 1) ** 1.1
    zipf_p = zipf_w / zipf_w.sum()

    def gen(rng, n):
        b = _base_batch(n)
        b["dst"][:, 3] = rng.choice(pool, size=n, p=zipf_p)
        b["dport"][:] = rng.integers(1, 65535, n)
        return b
    return snap, gen, True


def build_config4(preset):
    """L7-lite: HTTP method/path-prefix matching via token tensors."""
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_rulesets = 50 if preset == "smoke" else 200
    rules = []
    for i in range(n_rulesets):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{
                "ports": [{"port": str(80 + i), "protocol": "TCP"}],
                "rules": {"http": [
                    {"method": "GET", "path": f"/api/v{i}"},
                    {"method": "POST", "path": f"/submit/{i}"},
                    {"path": f"/public/{i}"},
                ]},
            }]}],
        }))
    repo.add(rules)
    snap = _compile(ctx, repo, [ep], 1 << (14 if preset == "smoke" else 16))
    paths = [f"/api/v{i}/x".encode() for i in range(n_rulesets)] + \
            [b"/forbidden/zone", b"/public/7/asset.js"]
    path_arr = np.zeros((len(paths), 64), dtype=np.uint8)
    for i, p in enumerate(paths):
        path_arr[i, :len(p)] = np.frombuffer(p[:64], dtype=np.uint8)

    def gen(rng, n):
        b = _base_batch(n, direction=1)
        b["src"][:, 3] = rng.integers(0x0B000000, 0x0BFFFFFF, n).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        port_idx = rng.integers(0, n_rulesets, n)
        b["dport"][:] = 80 + port_idx
        b["tcp_flags"][:] = 0x10
        # ~70% requests aligned with their port's ruleset (GET /api/v{i});
        # the rest random (exercise the drop path)
        aligned = rng.random(n) < 0.7
        pi = np.where(aligned, port_idx, rng.integers(0, len(paths), n))
        b["http_method"][:] = np.where(aligned, 0, rng.integers(0, 2, n))
        b["http_path"][:] = path_arr[pi]
        return b
    return snap, gen, True


def _config5_world(preset):
    """The cfg5 control plane (50k-rule policy over 2k pod identities) —
    shared by the throughput bench and the update-latency bench."""
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule
    ctx, repo = _ctx_repo()
    ep = _add_web_ep(ctx)
    n_ids = 500 if preset == "smoke" else 2000
    n_rules = 5000 if preset == "smoke" else 50000
    for i in range(n_ids):
        ident = ctx.allocator.allocate(Labels.parse([f"k8s:pod=p{i}"]))
        ctx.ipcache.upsert(f"172.{16 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32",
                           ident.id)
    rules = []
    for j in range(n_rules):
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"pod": f"p{j % n_ids}"}}],
                "toPorts": [{"ports": [
                    {"port": str(1024 + (j % 25000)), "protocol": "TCP"}]}],
            }],
        }))
    repo.add(rules)
    return ctx, repo, ep, n_ids, n_rules


def build_config5(preset):
    """Conntrack churn: 50k-rule policy, 1M concurrent flows, 10% new rate."""
    ctx, repo, ep, n_ids, n_rules = _config5_world(preset)
    cap = 1 << (16 if preset == "smoke" else 21)
    snap = _compile(ctx, repo, [ep], cap)

    n_flows = (1 << 14) if preset == "smoke" else 1_000_000
    rng0 = np.random.default_rng(1)
    flow_src = rng0.integers(0, n_ids, n_flows).astype(np.int64)
    flow_sport = rng0.integers(20000, 60000, n_flows).astype(np.int32)
    # dports drawn from the flow's identity's ALLOWED set so flows actually
    # establish and churn the CT (pod i allows {1024 + (i + n_ids*k) % 25000})
    k0 = rng0.integers(0, max(1, n_rules // n_ids), n_flows)
    flow_dport = (1024 + (flow_src + n_ids * k0) % 25000).astype(np.int32)

    def gen(rng, n):
        # 90% existing flows, 10% replaced with fresh ones (the churn)
        idx = rng.integers(0, n_flows, n)
        n_new = n // 10
        repl = idx[:n_new]
        flow_sport[repl] = rng.integers(20000, 60000, n_new)
        b = _base_batch(n, direction=1)
        i = flow_src[idx]
        b["src"][:, 3] = (0xAC100000 + ((i >> 8) & 0xFF) * 256
                          + (i & 0xFF)).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = flow_sport[idx]
        b["dport"][:] = flow_dport[idx]
        b["tcp_flags"][:] = 0x10
        return b
    return snap, gen, True


def _base_batch(n, direction=0):
    from cilium_tpu.kernels.records import empty_batch
    b = empty_batch(n)
    b["src"][:, 2] = 0xFFFF
    b["dst"][:, 2] = 0xFFFF
    b["src"][:, 3] = 0xC0A8000A
    b["sport"][:] = 40000
    b["dport"][:] = 443
    b["proto"][:] = 6
    b["tcp_flags"][:] = 0x02
    b["direction"][:] = direction
    b["valid"][:] = True
    return b


def update_latency_bench(preset):
    """1-rule policy-update latency on the cfg5 world: full rebuild vs the
    incremental patch path (round-4 verdict item 2's 'done' metric; upstream
    analog: incremental policymap diffs vs endpoint regeneration)."""
    from cilium_tpu.compile.ct_layout import CTConfig
    from cilium_tpu.compile.incremental import IncrementalCompiler
    from cilium_tpu.compile.snapshot import build_snapshot
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.rules import parse_rule

    ctx, repo, ep, n_ids, _n_rules = _config5_world(preset)
    ct_cfg = CTConfig(capacity=1 << 14)

    t0 = time.time()
    snap = build_snapshot(repo, ctx, [ep], ct_cfg)
    full_s = time.time() - t0
    t0 = time.time()
    inc = IncrementalCompiler(repo, ctx, [ep], snap)
    seed_s = time.time() - t0

    one = parse_rule({
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{
            "fromEndpoints": [{"matchLabels": {"pod": "p7"}}],
            "toPorts": [{"ports": [{"port": "4242", "protocol": "TCP"}]}]}]})
    object.__setattr__(one, "labels", Labels.parse(["k8s:bench=u1"]))

    t0 = time.time()
    repo.add([one])
    res = inc.try_update(ct_cfg)
    assert res is not None, f"update fell back: {inc.last_fallback}"
    add_s = time.time() - t0

    t0 = time.time()
    repo.delete_by_labels(Labels.parse(["k8s:bench=u1"]))
    res = inc.try_update(ct_cfg)
    assert res is not None, f"remove fell back: {inc.last_fallback}"
    remove_s = time.time() - t0

    return {
        "full_rebuild_ms": round(full_s * 1e3, 1),
        "incremental_seed_ms": round(seed_s * 1e3, 1),
        "rule_add_ms": round(add_s * 1e3, 2),
        "rule_remove_ms": round(remove_s * 1e3, 2),
        "speedup_vs_full": round(full_s / max(add_s, 1e-9), 1),
    }


#: BENCH_r05-era incremental-update reference (full cfg5 world, host
#: COW-copy path): what the ≥50x acceptance gate for the delta-patch
#: path is judged against. Override when re-baselining on other hardware.
REF_RULE_ADD_MS = float(os.environ.get(
    "CILIUM_TPU_BENCH_REF_RULE_ADD_MS", "619.5"))


def update_storm_bench(preset: str, updates: int = 0, traffic_batch: int = 512,
                       verbose: bool = False):
    """Live policy patching under pipelined traffic (ROADMAP item 3a).

    Builds the cfg5 control plane INSIDE an Engine (JITDatapath,
    incremental + delta-patch on, shadow auditor armed at sampling 1.0),
    keeps a feeder thread pushing conntrack-churn traffic through the
    ingestion pipeline the whole time, and storms rule adds/removes
    against warm geometry — the long-lived-daemon steady state where every
    update rides the sparse-delta scatter-apply path.

    Reported: ``rule_add_ms``/``rule_remove_ms`` p50+p99 (the full
    regenerate() wall time per update, host compile + device apply),
    the span split (``engine.regen.patch`` host compile,
    ``datapath.patch.apply`` device scatter enqueue) and
    ``device_ready_p50_ms`` (block-until-ready on the patched verdict
    under load). Parity: the auditor replays every finalized batch against
    the exact revision it classified under — ``audit.mismatched_rows``
    must be 0 (no batch classified under a torn update). A second phase
    re-runs the cfg5 churn loop with the overlapped device-side CT GC off
    vs armed and gates the throughput ratio.
    """
    import jax
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.observe.trace import (CT_GC_SPAN, PATCH_APPLY_SPAN,
                                          TRACER)
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine

    if updates <= 0:
        updates = 40 if preset == "smoke" else 120
    n_ids = 500 if preset == "smoke" else 2000
    n_rules = 5000 if preset == "smoke" else 50000
    storm_pods = 8                     # warm split set the storm cycles
    TRACER.configure(sample_rate=1.0, capacity=1 << 16)
    TRACER.reset()

    cfg = DaemonConfig(ct_capacity=1 << 14, auto_regen=False,
                       batch_size=traffic_batch,
                       pipeline_flush_ms=1.0,
                       # one epoch ≈ 8 ticks: the production shape (chunks
                       # small relative to the table), scaled to the
                       # bench's CT capacity
                       ct_gc_chunk_rows=1 << 11,
                       audit_enabled=True, audit_sample_rate=1.0,
                       audit_pool_batches=64, flowlog_mode="none",
                       trace_sample_rate=1.0)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.auditor.configure(sample_rate=1.0)

    # -- the cfg5 world, engine-resident ------------------------------------
    t0 = time.time()
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
    for i in range(n_ids):
        ident = eng.ctx.allocator.allocate(
            Labels.parse([f"k8s:pod=p{i}"]))
        eng.ctx.ipcache.upsert(
            f"172.{16 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32",
            ident.id)
    from cilium_tpu.model.rules import parse_rule
    base_rules = []
    for j in range(n_rules):
        base_rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"pod": f"p{j % n_ids}"}}],
                "toPorts": [{"ports": [
                    {"port": str(1024 + (j % 25000)), "protocol": "TCP"}]}],
            }]}))
    eng.repo.add(base_rules)
    eng.regenerate()
    world_s = time.time() - t0

    def storm_docs(pod: int, port: int, label: str):
        return [{"endpointSelector": {"matchLabels": {"app": "web"}},
                 "labels": [label],
                 "ingress": [{
                     "fromEndpoints": [{"matchLabels":
                                        {"pod": f"p{pod}"}}],
                     "toPorts": [{"ports": [{"port": str(port),
                                             "protocol": "TCP"}]}]}]}]

    # warm: split each storm pod's class once (ports reuse existing
    # boundaries so no port-class splits ride along)
    storm_ports = [1024 + 7 * k for k in range(storm_pods)]
    for k in range(storm_pods):
        eng.replace_policy([f"k8s:storm=w{k}"],
                           storm_docs(k, storm_ports[k],
                                      f"k8s:storm=w{k}"))
        eng.regenerate()
    patch_base = dict(eng.datapath.patch_stats)

    # -- live traffic (the cfg5 churn stream through the pipeline) ----------
    rng = np.random.default_rng(9)

    def churn_batch(n):
        b = _base_batch(n, direction=1)
        i = rng.integers(0, n_ids, n)
        b["src"][:, 3] = (0xAC100000 + ((i >> 8) & 0xFF) * 256
                          + (i & 0xFF)).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = rng.integers(20000, 60000, n)
        b["dport"][:] = (1024 + i % 25000).astype(np.int32)
        b["tcp_flags"][:] = 0x10
        return b

    stop_traffic = threading.Event()
    traffic_sent = [0]
    traffic_errors = [0]
    traffic_now = [50_000]

    def feeder():
        while not stop_traffic.is_set():
            traffic_now[0] += 1
            try:
                eng.submit(churn_batch(traffic_batch),
                           now=traffic_now[0], deadline_ms=0)
                traffic_sent[0] += 1
            except Exception:
                # counted AND gated below: a feeder that stops feeding
                # would make this an idle-engine benchmark lying about
                # "under live traffic"
                traffic_errors[0] += 1
                time.sleep(0.005)

    # warm the pipeline's device shapes before timing updates
    eng.submit(churn_batch(traffic_batch), now=traffic_now[0]).result(
        timeout=120)
    th = threading.Thread(target=feeder, daemon=True, name="storm-feeder")
    th.start()

    # -- the storm ----------------------------------------------------------
    add_ms, remove_ms, ready_ms = [], [], []
    try:
        for u in range(updates):
            k = u % storm_pods
            label = f"k8s:storm=w{k}"
            adding = (u // storm_pods) % 2 == 1
            body = storm_docs(k, storm_ports[k], label) if adding else None
            t1 = time.time()
            eng.replace_policy([label], body)
            eng.regenerate()
            dt = (time.time() - t1) * 1e3
            (add_ms if adding else remove_ms).append(dt)
            if u % 8 == 0:
                t2 = time.time()
                jax.block_until_ready(eng.active.tensors["verdict"])
                ready_ms.append((time.time() - t2) * 1e3)
    finally:
        stop_traffic.set()
        th.join(timeout=10)
    drained = eng.drain(timeout=300)

    # -- parity: drain the audit pool at sampling 1.0 -----------------------
    for _ in range(400):
        step = eng.audit_step(budget=128)
        if not step or (not step.get("replayed")
                        and not step.get("pending")):
            break
    audit = eng.auditor.stats()
    patch_stats = {k: v - patch_base.get(k, 0)
                   for k, v in eng.datapath.patch_stats.items()}

    spans = TRACER.summary()
    span_keys = ("engine.regen.patch", "engine.regen.place",
                 PATCH_APPLY_SPAN)
    stage_split = {k: spans[k] for k in span_keys if k in spans}

    def _p(vals, q):
        return round(float(np.percentile(np.asarray(vals), q)), 3) \
            if vals else 0.0

    # -- phase 2: overlapped CT GC on/off over the churn stream -------------
    # cadence: one chunk tick per 16 buckets ≈ 40ms of traffic on this rig —
    # still ~50x the production duty cycle (ct_gc_interval_s=2.0), so the
    # measured overhead upper-bounds the real one. The sweep program is
    # warmed first: its one-time jit compile is not a per-tick cost.
    gc_doc = {}
    gc_batches = 32 if preset == "smoke" else 64
    eng.sweep_step(now=traffic_now[0])      # warm the chunk-sweep jit
    eng.sweep_step(now=traffic_now[0])
    for mode in ("off", "on"):
        tps = []
        for _w in range(3):
            t1 = time.time()
            for i in range(gc_batches):
                traffic_now[0] += 1
                eng.submit(churn_batch(traffic_batch),
                           now=traffic_now[0])
                if mode == "on" and i % 16 == 0:
                    eng.sweep_step(now=traffic_now[0])
            eng.drain(timeout=300)
            tps.append(gc_batches * traffic_batch
                       / max(time.time() - t1, 1e-9))
        gc_doc[f"gc_{mode}_flows_per_sec"] = round(
            float(np.percentile(tps, 50)), 1)
    gc_ratio = gc_doc["gc_on_flows_per_sec"] \
        / max(gc_doc["gc_off_flows_per_sec"], 1e-9)
    gc_doc.update({
        "gc_on_vs_off_ratio": round(gc_ratio, 4),
        "reclaimed_total": getattr(eng.datapath, "_gc_reclaimed_total", 0),
        "gc_span": TRACER.summary().get(CT_GC_SPAN),
    })

    eng.stop()

    rule_add_p50 = _p(add_ms, 50)
    apply_span = stage_split.get(PATCH_APPLY_SPAN, {})
    gate_reasons = []
    if audit["mismatched_rows"]:
        gate_reasons.append(
            f"parity: {audit['mismatched_rows']} mismatched rows at "
            "sampling 1.0")
    if patch_stats.get("patch_delta", 0) < updates // 4:
        gate_reasons.append(
            f"delta path underused: {patch_stats.get('patch_delta', 0)} "
            f"delta patches over {updates} updates")
    if audit["checked_rows"] == 0:
        gate_reasons.append("auditor checked nothing")
    if traffic_sent[0] < max(4, updates // 4):
        gate_reasons.append(
            f"live-traffic floor missed: only {traffic_sent[0]} batches "
            f"fed during {updates} updates ({traffic_errors[0]} submit "
            "errors) — the storm measured an idle engine")
    if gc_ratio < 1.0 / BENCH_NOISE_FACTOR:
        gate_reasons.append(
            f"CT GC regressed churn throughput: ratio {gc_ratio:.3f}")
    if not add_ms:
        gate_reasons.append(
            f"no rule adds measured over {updates} updates (the headline "
            "metric never ran — raise --updates)")
    elif REF_RULE_ADD_MS / rule_add_p50 < 50:
        gate_reasons.append(
            f"rule_add_ms {rule_add_p50} not ≥50x under the "
            f"{REF_RULE_ADD_MS}ms reference")
    if patch_stats.get("patch_scatter_errors", 0):
        gate_reasons.append(
            f"{patch_stats['patch_scatter_errors']} scatter failures "
            "self-healed by full uploads during the storm")

    if verbose:
        print(f"# update-storm preset={preset} updates={updates} "
              f"world={world_s:.1f}s traffic_batches={traffic_sent[0]} "
              f"add p50={rule_add_p50}ms device-apply "
              f"p50={apply_span.get('p50_ms')}ms "
              f"audit checked={audit['checked_rows']} "
              f"mism={audit['mismatched_rows']} gc_ratio={gc_ratio:.3f}",
              file=sys.stderr)

    return {
        "metric": "live_update_storm_cfg5",
        "value": rule_add_p50,
        "unit": "ms",
        # higher-is-better speedup vs the BENCH_r05-era reference
        "vs_baseline": round(REF_RULE_ADD_MS / rule_add_p50, 1)
        if add_ms else 0.0,
        "baseline_rule_add_ms": REF_RULE_ADD_MS,
        "rule_add_ms": rule_add_p50,
        "rule_add_p99_ms": _p(add_ms, 99),
        "rule_remove_ms": _p(remove_ms, 50),
        "rule_remove_p99_ms": _p(remove_ms, 99),
        "device_apply_p50_ms": apply_span.get("p50_ms", 0.0),
        "device_apply_p99_ms": apply_span.get("p99_ms", 0.0),
        "device_ready_p50_ms": _p(ready_ms, 50),
        "updates": updates,
        "traffic_batches": traffic_sent[0],
        "traffic_errors": traffic_errors[0],
        "traffic_batch": traffic_batch,
        "drained": bool(drained),
        "preset": preset,
        "stage_split": stage_split,
        "patch_stats": patch_stats,
        "audit": {
            "checked_rows": audit["checked_rows"],
            "checked_batches": audit["checked_batches"],
            "mismatched_rows": audit["mismatched_rows"],
            "skipped_batches": audit["skipped_batches"],
        },
        "ct_gc": gc_doc,
        "storm_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


def ddos_bench(preset: str, verbose: bool = False, batch: int = 256):
    """cfg6: adversarial drop-storm survival over the live pipelined
    engine (ROADMAP item 4d — the ``bpf_xdp.c`` mitigation role with real
    drop-heavy traffic, not fault-injected hangs).

    A flood of randomized-source SYNs ramps against a small CT table: a
    40% junk slice (unknown identities → POLICY drops, the drop storm) and
    a 60% allowed-SYN slice (an open port reachable from a /8 — the CT
    filler that saturates the table), while a fixed population of
    established legitimate flows keeps serving through the same pipeline.
    The bench plays the shim feeder's role at "harvest": flood batches
    carry ``_prio=1``, legit batches ``_prio=0``, and once the overload
    ladder commands SHED-NEW the flood is dropped at harvest
    (shim/feeder.shed_new_rows) without ever being submitted. Logical time
    drives the engine's overload and ct-gc controllers deterministically
    (manual ``overload_step``/``sweep_step`` ticks — no wall-clock
    flakiness), with the parity auditor armed at sampling 1.0 throughout.

    Reported: established-flow survival rate, legit-slice e2e p50/p99,
    the CT occupancy trajectory (saturation → emergency-GC-bounded plateau
    → post-storm recovery), ladder state dwell times, eviction/insert-fail
    counters, and pre/storm/post throughput. ``ddos_gate`` fails the
    artifact (exit 4) on: survival < 99%, any parity mismatch (or nothing
    checked), the ladder never reaching SHED-NEW, occupancy never
    pressuring / not stabilizing below 1.0 / not recovering below
    ``ct_pressure_low``, no evictions (the table never actually
    saturated), or post-storm throughput collapsing past 20% of
    pre-storm."""
    from cilium_tpu.pipeline.guard import OVERLOAD_SHED_NEW
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine
    from cilium_tpu.shim.feeder import shed_new_rows

    smoke = preset == "smoke"
    flood_per_iter = 11 if smoke else 16
    hold_iters = 8 if smoke else 20        # iters to hold after SHED-NEW
    max_iters = 48 if smoke else 120
    n_legit = batch                        # one direct-dispatch bucket
    cap = 1 << 13
    cfg = DaemonConfig(
        ct_capacity=cap, auto_regen=False, batch_size=batch,
        pipeline_flush_ms=0.5, pipeline_queue_batches=16,
        pipeline_block_timeout_s=0.05,
        audit_enabled=True, audit_sample_rate=1.0, audit_pool_batches=64,
        flowlog_mode="none",
        ct_gc_chunk_rows=1 << 10, ct_gc_emergency_chunks=8,
        ct_gc_emergency_ttl_slash_s=56,
        ct_pressure_high=0.8, ct_pressure_low=0.5,
        overload_up_ticks=1, overload_down_ticks=4,
        # the bench's iteration cadence is wall-fast (logical seconds tick
        # faster than real ones): judge the shed rate against a threshold
        # the flood's admission-drop + deadline-shed stream actually
        # crosses on this rig
        overload_shed_rate_high=15.0, overload_shed_rate_low=2.0,
        overload_interval_s=0.1)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.auditor.configure(sample_rate=1.0)
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
    # the cfg6 policy world: legit clients (172.16/16) on 443, an open
    # port 80 reachable from 10/8 (the flood's CT-filler surface), ingress
    # enforced — every other source drops (the storm)
    eng.apply_policy([
        {"endpointSelector": {"matchLabels": {"app": "web"}},
         "ingress": [{"fromCIDR": ["172.16.0.0/16"],
                      "toPorts": [{"ports": [
                          {"port": "443", "protocol": "TCP"}]}]}]},
        {"endpointSelector": {"matchLabels": {"app": "web"}},
         "ingress": [{"fromCIDR": ["10.0.0.0/8"],
                      "toPorts": [{"ports": [
                          {"port": "80", "protocol": "TCP"}]}]}]},
    ])
    eng.regenerate()

    class _BenchHarvester:
        """The shim feeder's role, played by the bench: carries the
        harvest-shed counter the overload controller folds into its shed
        signal, and receives the ladder state like the real feeder."""
        prio_shed_rows = 0
        prio_shed_batches = 0
        level = 0

        def set_overload_state(self, level):
            self.level = int(level)

        def stats(self):
            return {"alive": True, "pending": 0, "pool_free": 0,
                    "prio_shed_rows": self.prio_shed_rows,
                    "prio_shed_batches": self.prio_shed_batches,
                    "overload_level": self.level}

        def stop(self, timeout=0.0):
            pass

    harvester = _BenchHarvester()
    eng._feeder = harvester

    rng = np.random.default_rng(5)

    def legit_batch():
        b = _base_batch(n_legit, direction=1)
        b["src"][:, 3] = (0xAC100000
                          + np.arange(n_legit) % 250 + 1
                          + ((np.arange(n_legit) // 250) << 8)
                          ).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = 40000 + np.arange(n_legit)
        b["dport"][:] = 443
        b["tcp_flags"][:] = 0x10     # ACK → SEEN_NON_SYN → protected class
        b["_prio"] = np.zeros((n_legit,), np.int8)
        return b

    def flood_batch():
        b = _base_batch(batch, direction=1)
        junk = rng.random(batch) < 0.4
        b["src"][:, 3] = np.where(
            junk,
            0xCB000000 + rng.integers(0, 1 << 20, batch),   # 203.x → world
            0x0A000000 + rng.integers(1, 1 << 24, batch),   # 10/8 → open 80
        ).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = rng.integers(1024, 65535, batch)
        b["dport"][:] = np.where(junk, rng.integers(1, 65535, batch), 80)
        b["tcp_flags"][:] = 0x02                            # SYN storm
        b["_prio"] = np.ones((batch,), np.int8)
        return b

    L = [50_000]                      # logical clock (seconds)
    survival = {"rows": 0, "allowed": 0}
    legit_lat_ms: list = []
    pending_legit: list = []

    def submit_legit():
        t0 = time.monotonic()
        try:
            pending_legit.append((eng.submit(legit_batch(), now=L[0]), t0))
        except Exception:
            survival["rows"] += n_legit       # whole batch lost = 0 allowed

    def pump_legit(block_s=None):
        """Account resolved legit tickets; ``block_s`` resolves everything
        (end of a phase), None sweeps only already-done tickets — the
        storm loop must never serialize behind its own victims."""
        rest = []
        for tk, t0 in pending_legit:
            if block_s is None and not tk.done():
                rest.append((tk, t0))
                continue
            try:
                out = tk.result(timeout=block_s if block_s is not None
                                else 0)
                survival["allowed"] += int(np.asarray(out["allow"]).sum())
            except Exception:
                pass
            survival["rows"] += n_legit
            legit_lat_ms.append((time.monotonic() - t0) * 1e3)
        pending_legit[:] = rest

    def run_legit(count, timeout=120.0):
        for _ in range(count):
            submit_legit()
        pump_legit(block_s=timeout)

    def fps_of(count):
        t0 = time.monotonic()
        run_legit(count)
        return count * n_legit / max(time.monotonic() - t0, 1e-9)

    # -- phase 0: establish + pre-storm throughput --------------------------
    run_legit(2)                      # warm/compile + create entries
    L[0] += 1
    run_legit(2)                      # revisit: flows now ESTABLISHED
    pre_rows0 = survival["rows"]
    legit_lat_ms.clear()              # cold-compile warmup is not latency
    pre_fps = fps_of(12 if smoke else 24)
    eng.overload_step()

    # -- phase 0b: ledger-overhead attestation (the PR 3 form) --------------
    # D/A/D/A interleaved windows (disarmed / armed-with-polling) for the
    # fps evidence, with the GATED number measured directly: wall time
    # spent inside resource_step as a fraction of the armed windows'
    # serving time. The armed cadence — one full ledger sweep per
    # dozen-batch window, the storm loop's own per-iteration rhythm — is
    # still ~250x denser per served row than the production controller's
    # resource_interval_s, so a pass bounds the real overhead from far
    # above. (The fps delta alone flakes: window-to-window variance on a
    # shared CPU rig is several percent, an order above the poll cost —
    # the ratio-of-measured-times form is what "<2% of armed serving
    # time" actually states.)
    att_w = 12 if smoke else 24
    att_fps = {"off": [], "on": []}
    att_poll_s = att_armed_s = 0.0
    for mode in ("off", "on", "off", "on"):
        t0 = time.monotonic()
        for i in range(att_w):
            run_legit(1)
            if mode == "on" and i % 12 == 11:
                p0 = time.monotonic()
                eng.resource_step(now=float(L[0]))
                att_poll_s += time.monotonic() - p0
        dt = max(time.monotonic() - t0, 1e-9)
        att_fps[mode].append(att_w * n_legit / dt)
        if mode == "on":
            att_armed_s += dt
    att_off = sum(att_fps["off"]) / len(att_fps["off"])
    att_on = sum(att_fps["on"]) / len(att_fps["on"])
    att_overhead_pct = 100.0 * att_poll_s / max(att_armed_s, 1e-9)
    pressure_attestation = {
        "fps_disarmed": round(att_off, 1),
        "fps_armed": round(att_on, 1),
        "fps_delta_pct": round(
            max(0.0, (1.0 - att_on / max(att_off, 1e-9)) * 100), 2),
        "poll_s": round(att_poll_s, 4),
        "armed_serving_s": round(att_armed_s, 4),
        "overhead_pct": round(att_overhead_pct, 2),
        "budget_pct": 2.0,
        "ok": att_overhead_pct < 2.0,
    }

    # per-iteration ledger polling through the storm (logical clock →
    # deterministic ETA math): the cfg6 acceptance gates — the CT resource
    # row must track the ct_occupancy gauge EXACTLY, and the
    # time-to-exhaustion forecast must fire before the ladder reaches
    # SHED-NEW (forecast-then-shed is the ledger doing its job; shed
    # without forecast means the forecast is useless under attack)
    ct_track_mismatches = 0
    forecast_iter = shed_new_iter = None

    def poll_ledger(it_now: int):
        nonlocal ct_track_mismatches, forecast_iter
        rep = eng.resource_step(now=float(L[0]))
        row = rep["resources"].get("ct_table")
        gauge = float(eng.metrics.gauges.get("ct_occupancy", 0.0))
        if row is None or row["pressure"] != gauge:
            ct_track_mismatches += 1
        if forecast_iter is None and row is not None and row["forecast"]:
            forecast_iter = it_now
        return rep

    # -- phase 1a: CT saturation burst --------------------------------------
    # the flood fully processed (drained per iteration): the table fills
    # past ct_pressure_high, emergency GC arms and bounds occupancy, tail
    # evictions + CT_FULL fails happen under the auditor — the
    # table-exhaustion half of the scenario, before admission pressure
    # starts refusing the flood at the door
    occ_trajectory = []
    flood_sent = flood_dropped = flood_harvest_shed = 0
    max_level = 0
    it = 0
    storm_t0 = time.monotonic()
    storm_rows = 0
    sat_hold = 0
    while it < max_iters // 2 and sat_hold < 4:
        it += 1
        L[0] += 1
        for _ in range(flood_per_iter):
            try:
                tk = eng.submit(flood_batch(), now=L[0], deadline_ms=0)
                flood_sent += 1
            except Exception:
                flood_dropped += 1
            storm_rows += batch
        run_legit(1, timeout=120.0)   # drain: device-bound, not ingest-bound
        storm_rows += n_legit
        st = eng.overload_step()
        max_level = max(max_level, st["level"])
        eng.sweep_step(now=L[0])
        eng.audit_step(budget=16)
        poll_ledger(it)
        occ = float(eng.metrics.gauges.get("ct_occupancy", 0.0))
        occ_trajectory.append((it, occ))
        if occ >= cfg.ct_pressure_high:
            sat_hold += 1             # hold a few iters at the plateau

    # -- phase 1b: the ingest storm -----------------------------------------
    # flood submitted faster than the device drains: queue + shed signals
    # light, the ladder escalates PRESSURE → OVERLOAD → SHED-NEW, and the
    # bench plays the feeder's harvest-time SHED-NEW once commanded
    shed_new_iters = 0
    while it < max_iters and shed_new_iters < hold_iters:
        it += 1
        L[0] += 1
        level = harvester.level
        max_level = max(max_level, level)
        for _ in range(flood_per_iter):
            fb = flood_batch()
            storm_rows += batch
            if level >= OVERLOAD_SHED_NEW:
                # the feeder's SHED-NEW behavior: drop verdicts at
                # harvest, nothing submitted — rx-ring relief
                shed = shed_new_rows(fb)
                harvester.prio_shed_rows += shed
                harvester.prio_shed_batches += 1
                flood_harvest_shed += shed
                continue
            try:
                tk = eng.submit(fb, now=L[0], deadline_ms=200)
                if tk.dropped:
                    flood_dropped += 1
                else:
                    flood_sent += 1
            except Exception:
                flood_dropped += 1
        submit_legit()
        pump_legit()                  # non-blocking: backlog must build
        storm_rows += n_legit
        st = eng.overload_step()
        if st["level"] >= OVERLOAD_SHED_NEW:
            shed_new_iters += 1
            if shed_new_iter is None:
                shed_new_iter = it
        eng.sweep_step(now=L[0])
        eng.audit_step(budget=16)
        poll_ledger(it)
        occ_trajectory.append(
            (it, float(eng.metrics.gauges.get("ct_occupancy", 0.0))))
    pump_legit(block_s=120.0)         # storm stragglers resolve now
    storm_s = max(time.monotonic() - storm_t0, 1e-9)
    storm_fps = storm_rows / storm_s
    occ_peak = max((o for _i, o in occ_trajectory), default=0.0)
    occ_late = occ_trajectory[-1][1] if occ_trajectory else 0.0

    # -- phase 2: recovery --------------------------------------------------
    recovered_level = None
    for _r in range(80):
        L[0] += 2
        run_legit(1, timeout=60.0)
        st = eng.overload_step()
        eng.sweep_step(now=L[0])
        recovered_level = st["level"]
        occ = float(eng.metrics.gauges.get("ct_occupancy", 0.0))
        if recovered_level == 0 and occ <= cfg.ct_pressure_low:
            break
    occ_final = float(eng.metrics.gauges.get("ct_occupancy", 0.0))
    post_fps = fps_of(12 if smoke else 24)
    ladder = eng.overload_status() or {}
    # final ledger sweep: the artifact carries every resource's high-water
    # through the storm + the device-memory ledger (ROADMAP item 6's
    # hardware-truth landing zone — re-baselined per-group on a real v5e)
    final_rep = eng.resource_step(now=float(L[0]))
    resource_high_water = {
        r: d["high_water"] for r, d in final_rep["resources"].items()}
    hbm_ledger = eng.datapath.hbm_ledger() \
        if hasattr(eng.datapath, "hbm_ledger") else None

    # -- drain + audit ------------------------------------------------------
    drained = eng.drain(timeout=120)
    for _ in range(200):
        step = eng.audit_step(budget=128)
        if not step or (not step.get("replayed")
                        and not step.get("pending")):
            break
    audit = eng.auditor.stats()
    evicted = eng.metrics.ct_evicted
    insert_fail = eng.metrics.insert_fail
    by = eng.metrics.by_reason_dir.reshape(256, 2)
    eng._feeder = None                # the harvester is not a real feeder
    eng.stop()

    survival_rate = survival["allowed"] / max(1, survival["rows"])
    legit_p50 = round(float(np.percentile(legit_lat_ms, 50)), 3) \
        if legit_lat_ms else 0.0
    legit_p99 = round(float(np.percentile(legit_lat_ms, 99)), 3) \
        if legit_lat_ms else 0.0
    post_ratio = post_fps / max(pre_fps, 1e-9)

    gate_reasons = []
    if survival_rate < 0.99:
        gate_reasons.append(
            f"established-flow survival {survival_rate:.4f} < 0.99")
    if audit["mismatched_rows"]:
        gate_reasons.append(
            f"parity: {audit['mismatched_rows']} mismatched rows at "
            "sampling 1.0")
    if audit["checked_rows"] == 0:
        gate_reasons.append("auditor checked nothing")
    if max_level < OVERLOAD_SHED_NEW:
        gate_reasons.append(
            f"ladder never reached SHED-NEW (max level {max_level})")
    if occ_peak < cfg.ct_pressure_high:
        gate_reasons.append(
            f"flood never pressured the CT (peak occupancy {occ_peak:.3f} "
            f"< {cfg.ct_pressure_high})")
    if occ_late >= 0.995:
        gate_reasons.append(
            f"emergency GC failed to bound occupancy ({occ_late:.3f} at "
            "storm end)")
    if occ_final > cfg.ct_pressure_low:
        gate_reasons.append(
            f"occupancy did not recover below ct_pressure_low "
            f"({occ_final:.3f} > {cfg.ct_pressure_low})")
    if not evicted:
        gate_reasons.append("no CT tail-evictions — the table never "
                            "actually saturated")
    if post_ratio < 1.0 / 1.2:
        gate_reasons.append(
            f"post-storm throughput collapsed: {post_fps:.0f} vs "
            f"pre-storm {pre_fps:.0f} (ratio {post_ratio:.3f} < 1/1.2)")
    if ct_track_mismatches:
        gate_reasons.append(
            f"resource ledger: ct_table pressure diverged from the "
            f"ct_occupancy gauge on {ct_track_mismatches} poll(s)")
    if forecast_iter is None:
        gate_reasons.append(
            "resource ledger: time-to-exhaustion never fired for ct_table")
    elif shed_new_iter is not None and forecast_iter >= shed_new_iter:
        gate_reasons.append(
            f"resource ledger: forecast fired at iter {forecast_iter}, "
            f"after SHED-NEW at iter {shed_new_iter}")
    if not pressure_attestation["ok"]:
        gate_reasons.append(
            f"ledger polling overhead {att_overhead_pct:.2f}% > 2% of "
            "armed serving time")

    if verbose:
        print(f"# ddos preset={preset} iters={it} survival="
              f"{survival_rate:.4f} max_level={max_level} "
              f"occ peak/late/final={occ_peak:.3f}/{occ_late:.3f}/"
              f"{occ_final:.3f} evicted={evicted} ct_full_fails="
              f"{insert_fail} audit={audit['checked_rows']}/"
              f"{audit['mismatched_rows']} fps pre/storm/post="
              f"{pre_fps:.0f}/{storm_fps:.0f}/{post_fps:.0f}",
              file=sys.stderr)

    return {
        "metric": "ddos_drop_storm_cfg6",
        "value": round(survival_rate, 6),
        "unit": "established_flow_survival",
        "vs_baseline": round(survival_rate / 0.99, 4),
        "survival_rate": round(survival_rate, 6),
        "legit_rows": survival["rows"],
        "legit_allowed": survival["allowed"],
        "legit_e2e_p50_ms": legit_p50,
        "legit_e2e_p99_ms": legit_p99,
        "preset": preset,
        "batch": batch,
        "storm_iters": it,
        "flood": {
            "batches_submitted": flood_sent,
            "batches_rejected": flood_dropped,
            "rows_harvest_shed": flood_harvest_shed,
            "per_iter": flood_per_iter,
        },
        "ladder": {
            "max_level": max_level,
            "recovered_level": recovered_level,
            "dwell_s": ladder.get("dwell_s"),
            "transitions": ladder.get("transitions"),
            "trail": (ladder.get("trail") or [])[-8:],
        },
        "ct": {
            "capacity": cap,
            "occupancy_peak": round(occ_peak, 4),
            "occupancy_storm_end": round(occ_late, 4),
            "occupancy_final": round(occ_final, 4),
            "evicted_total": int(evicted),
            "insert_fail_total": int(insert_fail),
            "trajectory": [(i, round(o, 4)) for i, o in
                           occ_trajectory[:: max(1, len(occ_trajectory)
                                                 // 32)]],
        },
        "drops_by_reason": {
            str(int(r)): int(by[r].sum())
            for r in np.nonzero(by.sum(1))[0] if r != 0},
        "throughput": {
            "pre_storm_fps": round(pre_fps, 1),
            "storm_fps": round(storm_fps, 1),
            "post_storm_fps": round(post_fps, 1),
            "post_vs_pre_ratio": round(post_ratio, 4),
        },
        "audit": {
            "checked_rows": audit["checked_rows"],
            "checked_batches": audit["checked_batches"],
            "mismatched_rows": audit["mismatched_rows"],
            "skipped_batches": audit["skipped_batches"],
        },
        "pre_storm_rows": pre_rows0,
        "drained": bool(drained),
        "resources": {
            "registered": len(final_rep["resources"]),
            "high_water": resource_high_water,
            "ct_trajectory_exact": ct_track_mismatches == 0,
            "forecast_iter": forecast_iter,
            "shed_new_iter": shed_new_iter,
            "forecasts_total": final_rep["forecasts_total"],
            "exhaustions_total": final_rep["exhaustions_total"],
        },
        "hbm_ledger": hbm_ledger,
        "pressure_attestation": pressure_attestation,
        "ddos_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


def tenants_bench(preset: str, verbose: bool = False, batch: int = 256):
    """cfg8: mixed-tenant isolation under a noisy neighbor (ROADMAP item
    4 — multi-tenant QoS over the live pipelined engine).

    Three tenants share one pipeline: ``gold`` (weight 4, latency lane),
    ``silver`` (weight 2), and ``bulk`` (weight 1, occupancy-capped) —
    the noisy neighbor, replaying cfg6's randomized-source SYN storm
    with ``_tenant`` stamped at "harvest" the way the shim feeder's
    compiled LUT would. Three phases:

    - **lane baseline**: unloaded gold lane probes (small always-armed
      bucket, bypassing deadline microbatching) establish the e2e p99
      the loaded gate is judged against.
    - **isolation**: bulk floods at cfg6 rates while gold (lane probes)
      and silver (steady established-flow batches) keep serving.
      Victims must survive >= 99% and the loaded lane p99 must stay
      within 2x the unloaded baseline plus a head-of-line allowance for
      the committed bulk units a lane batch cannot preempt (the
      in-flight dispatches plus the staged-ahead batch, each costed at
      2x its unloaded round-trip for load inflation — µs of slack on a
      real TPU, the dominant term on the CPU smoke rig), with a small
      absolute floor against scheduler jitter.
    - **share convergence**: all three tenants push saturating backlogs
      through the admission queue for a wall-clock window; the DRR
      scheduler's per-tenant admitted-row shares must converge to the
      4:2:1 weights — the flooder confined to within [0.5x, 1.5x] of
      its 1/7 share.

    The parity auditor rides at sampling 1.0 throughout (QoS reorders
    batches, never rows — verdicts stay bit-identical). ``qos_gate``
    fails the artifact (exit 4) on: victim survival < 99%, lane p99
    past budget, the flooder's share escaping its weight band, any
    parity mismatch (or nothing checked), or an unclean drain."""
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine

    smoke = preset == "smoke"
    lane_rows = 32                      # well under the lane bucket (64)
    flood_per_iter = 6 if smoke else 10
    iso_iters = 24 if smoke else 60
    share_window_s = 3.0 if smoke else 8.0
    lane_floor_ms = 2.0                 # absolute floor on the lane budget
    cfg = DaemonConfig(
        ct_capacity=1 << 13, auto_regen=False, batch_size=batch,
        # generous flush deadline: bulk microbatching coalesces while the
        # lane's immediate flush is what keeps gold fast — the contrast
        # the lane gate actually measures
        pipeline_flush_ms=5.0, pipeline_queue_batches=16,
        pipeline_block_timeout_s=0.05,
        # latency-biased serving profile: one batch in flight keeps the
        # lane's head-of-line wait to a single bulk dispatch — the profile
        # a lane tenant's SLO would be sold against
        pipeline_inflight=1,
        audit_enabled=True, audit_sample_rate=1.0, audit_pool_batches=64,
        flowlog_mode="none",
        qos_enabled=True,
        # the flooder is capped below the queue so victims always have
        # admission headroom — the occupancy-cap half of isolation
        qos_tenants="gold=4:lane,silver=2,bulk=1:cap=10",
        qos_lane_bucket=64,
        overload_interval_s=0.1)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.auditor.configure(sample_rate=1.0)
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
    # the cfg6 policy world: victims (172.16/16) on 443, an open port 80
    # reachable from 10/8 (the flood's allowed slice), ingress enforced
    eng.apply_policy([
        {"endpointSelector": {"matchLabels": {"app": "web"}},
         "ingress": [{"fromCIDR": ["172.16.0.0/16"],
                      "toPorts": [{"ports": [
                          {"port": "443", "protocol": "TCP"}]}]}]},
        {"endpointSelector": {"matchLabels": {"app": "web"}},
         "ingress": [{"fromCIDR": ["10.0.0.0/8"],
                      "toPorts": [{"ports": [
                          {"port": "80", "protocol": "TCP"}]}]}]},
    ])
    eng.regenerate()
    pl = eng.start_pipeline()
    tid_of = {name: tid for tid, name in eng.qos.tenants().items()}

    rng = np.random.default_rng(8)

    def victim_batch(tenant, n, sport_base):
        b = _base_batch(n, direction=1)
        b["src"][:, 3] = (0xAC100000
                          + np.arange(n) % 250 + 1
                          + ((np.arange(n) // 250) << 8)
                          ).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = sport_base + np.arange(n)
        b["dport"][:] = 443
        b["tcp_flags"][:] = 0x10     # ACK → SEEN_NON_SYN → protected class
        b["_prio"] = np.zeros((n,), np.int8)
        b["_tenant"] = np.full((n,), tid_of[tenant], np.int32)
        return b

    def flood_batch(tenant="bulk"):
        b = _base_batch(batch, direction=1)
        junk = rng.random(batch) < 0.4
        b["src"][:, 3] = np.where(
            junk,
            0xCB000000 + rng.integers(0, 1 << 20, batch),   # 203.x → world
            0x0A000000 + rng.integers(1, 1 << 24, batch),   # 10/8 → open 80
        ).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = rng.integers(1024, 65535, batch)
        b["dport"][:] = np.where(junk, rng.integers(1, 65535, batch), 80)
        b["tcp_flags"][:] = 0x02                            # SYN storm
        b["_prio"] = np.ones((batch,), np.int8)
        b["_tenant"] = np.full((batch,), tid_of[tenant], np.int32)
        return b

    L = [50_000]                      # logical clock (seconds)
    survival = {"gold": {"rows": 0, "allowed": 0},
                "silver": {"rows": 0, "allowed": 0}}
    pending: list = []                # (ticket, tenant, rows)

    def pump(block_s=None):
        rest = []
        for tk, tenant, rows in pending:
            if block_s is None and not tk.done():
                rest.append((tk, tenant, rows))
                continue
            try:
                out = tk.result(timeout=block_s if block_s is not None
                                else 0)
                survival[tenant]["allowed"] += \
                    int(np.asarray(out["allow"]).sum())
            except Exception:
                pass
            survival[tenant]["rows"] += rows
        pending[:] = rest

    def submit_victim(tenant, n, sport_base):
        try:
            pending.append((eng.submit(victim_batch(tenant, n, sport_base),
                                       now=L[0]), tenant, n))
        except Exception:
            survival[tenant]["rows"] += n     # whole batch lost

    def lane_probe(record):
        """One blocking gold lane round-trip: small batch → immediate
        lane flush → result. The victim's latency-sensitive traffic."""
        t0 = time.monotonic()
        try:
            tk = eng.submit(victim_batch("gold", lane_rows, 30000),
                            now=L[0])
            out = tk.result(timeout=60.0)
            record.append((time.monotonic() - t0) * 1e3)
            survival["gold"]["allowed"] += \
                int(np.asarray(out["allow"]).sum())
        except Exception:
            pass
        survival["gold"]["rows"] += lane_rows

    # -- phase 0: establish + unloaded lane baseline ------------------------
    # warm both dispatch shapes (the lane bucket AND the full bucket) and
    # revisit so victim flows are ESTABLISHED before anything is timed
    for _r in range(2):
        lane_probe([])                # cold-compile warmup is not latency
        submit_victim("silver", batch, 40000)
        pump(block_s=120.0)
        L[0] += 1
    survival = {"gold": {"rows": 0, "allowed": 0},
                "silver": {"rows": 0, "allowed": 0}}     # warmup not scored
    lane_base_ms: list = []
    for _p in range(12 if smoke else 32):
        lane_probe(lane_base_ms)
        L[0] += 1
    lane_base_p99 = float(np.percentile(lane_base_ms, 99)) \
        if lane_base_ms else 0.0
    # unloaded full-bucket round-trip: the indivisible head-of-line unit.
    # Dispatches are not preempted, so a lane batch can land behind
    # every committed bulk unit — one per inflight slot plus the
    # staged-ahead batch — each up to ~2x its unloaded cost on a
    # contended rig. The lane budget allows those on top of the
    # 2x-baseline contract: µs of slack on a real TPU, the dominant
    # term on the CPU smoke rig where a dispatch is ms-scale
    bulk_ms: list = []
    for _p in range(6 if smoke else 12):
        t0 = time.monotonic()
        try:
            tk = eng.submit(victim_batch("silver", batch, 40000), now=L[0])
            out = tk.result(timeout=60.0)
            bulk_ms.append((time.monotonic() - t0) * 1e3)
            survival["silver"]["allowed"] += \
                int(np.asarray(out["allow"]).sum())
        except Exception:
            pass
        survival["silver"]["rows"] += batch
        L[0] += 1
    bulk_p50 = float(np.percentile(bulk_ms, 50)) if bulk_ms else 0.0

    # -- phase 1: isolation — bulk floods, gold + silver keep serving -------
    lane_loaded_ms: list = []
    flood_sent = flood_rejected = 0
    for _it in range(iso_iters):
        L[0] += 1
        for _f in range(flood_per_iter):
            try:
                tk = eng.submit(flood_batch(), now=L[0], deadline_ms=0)
                if tk.dropped:
                    flood_rejected += 1
                else:
                    flood_sent += 1
            except Exception:
                flood_rejected += 1
        submit_victim("silver", batch, 40000)
        pump()                        # non-blocking: backlog must build
        lane_probe(lane_loaded_ms)
        eng.overload_step()
        eng.sweep_step(now=L[0])
        eng.audit_step(budget=16)
    pump(block_s=120.0)
    lane_loaded_p99 = float(np.percentile(lane_loaded_ms, 99)) \
        if lane_loaded_ms else 0.0
    hol_units = 2 * (cfg.pipeline_inflight + 1)
    lane_budget_ms = max(2.0 * lane_base_p99,
                         lane_base_p99 + hol_units * bulk_p50,
                         lane_floor_ms)

    surv_rate = {
        t: s["allowed"] / max(1, s["rows"]) for t, s in survival.items()}
    victim_survival_min = min(surv_rate.values())

    # -- phase 2: DRR share convergence under saturating backlogs -----------
    # every tenant pushes as hard as admission lets it for a wall-clock
    # window; admitted_rows (counted at DRR pop) must split ~4:2:1. The
    # snapshot is taken at window end, BEFORE the drain — residual queue
    # rows (<= queue_batches) are noise against hundreds of pops
    shares0 = {n: d["admitted_rows"]
               for n, d in pl.stats()["tenants"].items()}
    share_sent = {"gold": 0, "silver": 0, "bulk": 0}
    share_rejected = {"gold": 0, "silver": 0, "bulk": 0}
    # pre-built batch pools: submission must outrun dispatch or the
    # queue never saturates and "shares" degenerate to arrival order.
    # (No audit_step in the loop either — replay is a second classify
    # per batch and would pace submissions to the drain rate; the pool
    # overflows into skipped_batches, which the gate ignores.)
    pool = {n: [flood_batch(n) for _ in range(8)]
            for n in ("gold", "silver", "bulk")}
    t_end = time.monotonic() + share_window_s
    k = 0
    while time.monotonic() < t_end:
        L[0] += 1
        k += 1
        for name in ("gold", "silver", "bulk"):
            for _r in range(2):
                try:
                    tk = eng.submit(pool[name][(k + _r) % 8], now=L[0],
                                    deadline_ms=0)
                    if tk.dropped:
                        share_rejected[name] += 1
                    else:
                        share_sent[name] += 1
                except Exception:
                    share_rejected[name] += 1
    shares1 = {n: d["admitted_rows"]
               for n, d in pl.stats()["tenants"].items()}
    share_rows = {n: shares1.get(n, 0) - shares0.get(n, 0)
                  for n in shares1}
    share_total = max(1, sum(share_rows.values()))
    admitted_share = {n: r / share_total for n, r in share_rows.items()}
    flood_admitted_share = admitted_share.get("bulk", 0.0)
    w_share = 1.0 / 7.0               # bulk's weight share of 4+2+1

    # -- drain + audit ------------------------------------------------------
    drained = eng.drain(timeout=120)
    pump(block_s=120.0)
    for _ in range(200):
        step = eng.audit_step(budget=128)
        if not step or (not step.get("replayed")
                        and not step.get("pending")):
            break
    audit = eng.auditor.stats()
    qos_stats = eng.qos_status() or {}
    eng.stop()

    gate_reasons = []
    if victim_survival_min < 0.99:
        gate_reasons.append(
            f"victim survival {victim_survival_min:.4f} < 0.99 "
            f"(gold {surv_rate['gold']:.4f}, "
            f"silver {surv_rate['silver']:.4f})")
    if lane_loaded_p99 > lane_budget_ms:
        gate_reasons.append(
            f"lane p99 under flood {lane_loaded_p99:.3f}ms > budget "
            f"{lane_budget_ms:.3f}ms (2x unloaded baseline "
            f"{lane_base_p99:.3f}ms / head-of-line allowance of "
            f"{hol_units} full-bucket dispatch units at "
            f"{bulk_p50:.3f}ms, floor {lane_floor_ms}ms)")
    if not w_share * 0.5 <= flood_admitted_share <= w_share * 1.5:
        gate_reasons.append(
            f"flooder admitted share {flood_admitted_share:.4f} outside "
            f"[{w_share * 0.5:.4f}, {w_share * 1.5:.4f}] — DRR did not "
            "confine it to its 1/7 weight")
    if audit["mismatched_rows"]:
        gate_reasons.append(
            f"parity: {audit['mismatched_rows']} mismatched rows at "
            "sampling 1.0 with QoS armed")
    if audit["checked_rows"] == 0:
        gate_reasons.append("auditor checked nothing")
    if not drained:
        gate_reasons.append("pipeline did not drain clean")

    if verbose:
        print(f"# tenants preset={preset} survival gold/silver="
              f"{surv_rate['gold']:.4f}/{surv_rate['silver']:.4f} "
              f"lane p99 base/loaded={lane_base_p99:.3f}/"
              f"{lane_loaded_p99:.3f}ms shares="
              f"{ {n: round(s, 3) for n, s in admitted_share.items()} } "
              f"flood sent/rejected={flood_sent}/{flood_rejected} "
              f"audit={audit['checked_rows']}/{audit['mismatched_rows']}",
              file=sys.stderr)

    return {
        "metric": "qos_mixed_tenant_cfg8",
        "value": round(victim_survival_min, 6),
        "unit": "victim_flow_survival",
        "vs_baseline": round(victim_survival_min / 0.99, 4),
        "preset": preset,
        "batch": batch,
        "victim_survival_min": round(victim_survival_min, 6),
        "lane_base_p99_ms": round(lane_base_p99, 3),
        "lane_e2e_p99_ms": round(lane_loaded_p99, 3),
        "flood_admitted_share": round(flood_admitted_share, 4),
        "survival": {t: {"rows": s["rows"], "allowed": s["allowed"],
                         "rate": round(surv_rate[t], 6)}
                     for t, s in survival.items()},
        "lane": {
            "rows": lane_rows,
            "probes_base": len(lane_base_ms),
            "probes_loaded": len(lane_loaded_ms),
            "base_p50_ms": round(float(np.percentile(lane_base_ms, 50)), 3)
            if lane_base_ms else 0.0,
            "loaded_p50_ms":
            round(float(np.percentile(lane_loaded_ms, 50)), 3)
            if lane_loaded_ms else 0.0,
            "bulk_dispatch_p50_ms": round(bulk_p50, 3),
            "budget_ms": round(lane_budget_ms, 3),
        },
        "flood": {
            "batches_submitted": flood_sent,
            "batches_rejected": flood_rejected,
            "per_iter": flood_per_iter,
            "iso_iters": iso_iters,
        },
        "shares": {
            "weights": {"gold": 4, "silver": 2, "bulk": 1},
            "window_s": share_window_s,
            "admitted_rows": share_rows,
            "admitted_share": {n: round(s, 4)
                               for n, s in admitted_share.items()},
            "submitted": share_sent,
            "rejected": share_rejected,
        },
        "tenants": qos_stats.get("tenants"),
        "audit": {
            "checked_rows": audit["checked_rows"],
            "checked_batches": audit["checked_batches"],
            "mismatched_rows": audit["mismatched_rows"],
            "skipped_batches": audit["skipped_batches"],
        },
        "drained": bool(drained),
        "qos_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


def fqdn_bench(preset: str, verbose: bool = False, batch: int = 256):
    """cfg9: toFQDNs policy under DNS churn at storm rates (ROADMAP item
    1b — the in-band DNS plane over the live pipelined engine).

    One endpoint serves an egress ``toFQDNs`` world: a matchPattern rule
    (``*.svc.example.com``, toPorts 443) plus the DNS L7 redirect class
    (UDP/53 to the resolver). Learning rides the WIRE shape: every tick
    submits a DNS batch through the pipeline, the verdict output marks
    the redirect rows, and the proxy tap (fqdn/proxy.observe_batch —
    the exact call the shim feeder makes at verdict-apply) decodes the
    harvested response payloads into the FQDN cache.

    Churn model, all on the cache's logical clock:

    - **stable names** re-resolve every tick with a long TTL — their
      identities must never flap; established flows to them are the
      survival population.
    - **churn names** arrive fresh every tick with a short TTL and die
      two ticks later through the fqdn-gc expiry — a steady
      grow-and-retire stream the delta path must absorb: every refresh
      is a coalesced rule refresh + identity growth + identity
      retirement through ``place_patch``, NEVER a full rebuild.

    The parity auditor rides at sampling 1.0 (retirement tombstones must
    be bit-identical to a fresh build under the oracle). ``fqdn_gate``
    fails the artifact (exit 4) on: any parity mismatch (or nothing
    checked), established survival < 99%, any full rebuild during
    steady churn, refresh p99 past the delta-path budget
    (max(25ms, 0.5x the measured full-build p50) — the patch path must
    beat half a rebuild or it isn't earning its complexity), zero
    learned/retired identities (the churn never actually exercised the
    plane), or an unclean drain."""
    from cilium_tpu.fqdn.dnsparse import encode_response
    from cilium_tpu.fqdn.proxy import DNSProxy
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine

    smoke = preset == "smoke"
    ticks = 16 if smoke else 48
    churn_per_tick = 3 if smoke else 8
    n_stable = 6
    stable_ttl, churn_ttl, tick_s = 10_000, 15, 7     # churn lives 2 ticks
    payload_w = 512
    cfg = DaemonConfig(
        ct_capacity=1 << 13, auto_regen=False, batch_size=batch,
        pipeline_flush_ms=5.0, pipeline_queue_batches=16,
        pipeline_block_timeout_s=0.05,
        audit_enabled=True, audit_sample_rate=1.0, audit_pool_batches=64,
        flowlog_mode="none",
        fqdn_proxy_enabled=True, fqdn_min_ttl=0)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.auditor.configure(sample_rate=1.0)
    L = [50_000]                       # logical clock (seconds)
    eng.ctx.fqdn_cache.clock = lambda: L[0]
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [
            # the DNS L7 redirect class: queries to the resolver carry
            # VERDICT_REDIRECT (allow-all L7 set — replies always flow)
            {"toCIDR": ["8.8.8.8/32"],
             "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}],
                          "rules": {"http": [{}]}}]},
            {"toFQDNs": [{"matchPattern": "*.svc.example.com"}],
             "toPorts": [{"ports": [{"port": "443",
                                     "protocol": "TCP"}]}]},
        ]}])
    eng.regenerate()
    eng.start_pipeline()
    proxy = DNSProxy(eng.ctx.fqdn_cache, metrics=eng.metrics,
                     min_ttl=cfg.fqdn_min_ttl, port=cfg.fqdn_proxy_port,
                     payload_width=payload_w)

    stable_ip = {i: f"20.0.{i}.1" for i in range(n_stable)}

    def dns_batch(answers):
        """One DNS exchange batch: egress UDP/53 query rows to the
        resolver, the harvested response payload riding the poll-buffer
        columns — the wire shape the feeder tap sees."""
        n = len(answers)
        b = _base_batch(n, direction=0)
        b["dst"][:, 3] = 0x08080808
        b["sport"][:] = 30000 + np.arange(n)
        b["dport"][:] = 53
        b["proto"][:] = 17
        b["tcp_flags"][:] = 0
        b["_dns_payload"] = np.zeros((n, payload_w), np.uint8)
        b["_dns_len"] = np.zeros((n,), np.int32)
        for i, (name, ip, ttl) in enumerate(answers):
            wire = encode_response(name, [ip], ttl=ttl)
            w = min(len(wire), payload_w)
            b["_dns_payload"][i, :w] = np.frombuffer(wire[:w], np.uint8)
            b["_dns_len"][i] = w
        return b

    def traffic_batch(n, syn):
        """Established-population flows to the STABLE learned IPs."""
        b = _base_batch(n, direction=0)
        idx = np.arange(n) % n_stable
        b["dst"][:, 3] = (0x14000001 + (idx << 8)).astype(np.uint32)
        b["sport"][:] = 41000 + np.arange(n) % 256
        b["dport"][:] = 443
        b["tcp_flags"][:] = 0x02 if syn else 0x10
        return b

    def learn(answers):
        """DNS batch through the pipeline; tap the verdict output."""
        b = dns_batch(answers)
        tk = eng.submit(b, now=L[0])
        out = tk.result(timeout=60.0)
        n_red = int(np.asarray(out["redirect"]).sum())
        proxy.observe_batch(b, out)
        return n_red

    # -- phase 0: seed + full-build baseline --------------------------------
    # learn the stable names, establish the survival flows, then measure
    # what a FULL rebuild of this world costs — the delta-path budget's
    # denominator
    for i in range(n_stable):
        learn([(f"s{i}.svc.example.com", stable_ip[i], stable_ttl)])
    eng.regenerate()
    tb = traffic_batch(min(batch, 128), syn=True)
    eng.submit(tb, now=L[0]).result(timeout=60.0)      # CT establishment
    full_ms = []
    for _ in range(3):
        t0 = time.monotonic()
        eng.regenerate(force=True)
        full_ms.append((time.monotonic() - t0) * 1e3)
    full_p50 = float(np.percentile(full_ms, 50))
    refresh_budget_ms = max(25.0, 0.5 * full_p50)
    eng.regenerate()                   # settle; re-seed the delta path

    # -- phase 1: steady churn ----------------------------------------------
    fulls0 = eng.metrics.counters.get("regen_full_total", 0)
    retired0 = eng.metrics.counters.get("fqdn_identities_retired_total", 0)
    created0 = eng.repo.fqdn_identities_created
    refresh_samples = []
    surv_rows = surv_allowed = 0
    dns_rows = redirect_rows = 0
    pending = []
    for tick in range(ticks):
        L[0] += tick_s
        # the tick's DNS storm: stable refreshes + fresh churn names
        answers = [(f"s{i}.svc.example.com", stable_ip[i], stable_ttl)
                   for i in range(n_stable)]
        for j in range(churn_per_tick):
            answers.append((f"c{tick}-{j}.svc.example.com",
                            f"20.1.{tick % 200}.{j + 1}", churn_ttl))
        redirect_rows += learn(answers)
        dns_rows += len(answers)
        # expiry: churn names from two ticks ago die here (fqdn-gc tick)
        eng.ctx.fqdn_cache.expire(L[0])
        # the refresh the gate times: coalesced flush + identity growth
        # AND retirement through the delta path, in one cycle
        t0 = time.monotonic()
        eng.regenerate()
        refresh_samples.append((time.monotonic() - t0) * 1e3)
        # established flows to stable names keep serving THROUGH the churn
        n = min(batch, 128)
        try:
            pending.append((eng.submit(traffic_batch(n, syn=False),
                                       now=L[0]), n))
        except Exception:
            surv_rows += n             # whole batch lost
        done = []
        for tk, rows in pending:
            if tk.done():
                done.append((tk, rows))
        for tk, rows in done:
            pending.remove((tk, rows))
            try:
                out = tk.result(timeout=0)
                surv_allowed += int(np.asarray(out["allow"]).sum())
            except Exception:
                pass
            surv_rows += rows
        eng.audit_step(budget=16)
    for tk, rows in pending:
        try:
            out = tk.result(timeout=60.0)
            surv_allowed += int(np.asarray(out["allow"]).sum())
        except Exception:
            pass
        surv_rows += rows

    # -- drain + audit ------------------------------------------------------
    drained = eng.drain(timeout=120)
    for _ in range(200):
        step = eng.audit_step(budget=128)
        if not step or (not step.get("replayed")
                        and not step.get("pending")):
            break
    audit = eng.auditor.stats()
    fulls_delta = eng.metrics.counters.get("regen_full_total", 0) - fulls0
    retired = eng.metrics.counters.get(
        "fqdn_identities_retired_total", 0) - retired0
    created = eng.repo.fqdn_identities_created - created0
    coalesced = eng.repo.fqdn_refresh_coalesced
    fqdn_doc = eng.fqdn_status()
    eng.stop()

    survival = surv_allowed / max(1, surv_rows)
    refresh_p50 = float(np.percentile(refresh_samples, 50))
    refresh_p99 = float(np.percentile(refresh_samples, 99))

    gate_reasons = []
    if audit["mismatched_rows"]:
        gate_reasons.append(
            f"parity: {audit['mismatched_rows']} mismatched rows at "
            "sampling 1.0 under FQDN churn")
    if audit["checked_rows"] == 0:
        gate_reasons.append("auditor checked nothing")
    if survival < 0.99:
        gate_reasons.append(
            f"established survival {survival:.4f} < 0.99 — stable-name "
            "flows lost verdicts during churn refreshes")
    if fulls_delta:
        gate_reasons.append(
            f"{fulls_delta} full rebuild(s) during steady churn — the "
            "delta path fell back")
    if refresh_p99 > refresh_budget_ms:
        gate_reasons.append(
            f"refresh p99 {refresh_p99:.3f}ms > delta budget "
            f"{refresh_budget_ms:.3f}ms (full build p50 {full_p50:.3f}ms)")
    if created == 0 or retired == 0:
        gate_reasons.append(
            f"churn exercised nothing (created={created} "
            f"retired={retired})")
    if redirect_rows == 0:
        gate_reasons.append("no DNS row ever carried the redirect class")
    if not drained:
        gate_reasons.append("pipeline did not drain clean")

    if verbose:
        print(f"# fqdn preset={preset} survival={survival:.4f} refresh "
              f"p50/p99={refresh_p50:.3f}/{refresh_p99:.3f}ms (budget "
              f"{refresh_budget_ms:.3f}ms, full {full_p50:.3f}ms) "
              f"created/retired={created}/{retired} fulls={fulls_delta} "
              f"audit={audit['checked_rows']}/{audit['mismatched_rows']}",
              file=sys.stderr)

    return {
        "metric": "fqdn_churn_cfg9",
        "value": round(refresh_p99, 3),
        "unit": "refresh_p99_ms",
        "vs_baseline": round(refresh_p99 / max(1e-9, refresh_budget_ms), 4),
        "preset": preset,
        "batch": batch,
        "refresh_p50_ms": round(refresh_p50, 3),
        "refresh_p99_ms": round(refresh_p99, 3),
        "established_survival": round(survival, 6),
        "refresh": {
            "samples": len(refresh_samples),
            "budget_ms": round(refresh_budget_ms, 3),
            "full_build_p50_ms": round(full_p50, 3),
            "full_rebuilds_in_churn": fulls_delta,
        },
        "churn": {
            "ticks": ticks,
            "names_per_tick": churn_per_tick,
            "stable_names": n_stable,
            "dns_rows": dns_rows,
            "redirect_rows": redirect_rows,
            "identities_created": created,
            "identities_retired": retired,
            "refreshes_coalesced": coalesced,
        },
        "survival": {"rows": surv_rows, "allowed": surv_allowed},
        "fqdn": fqdn_doc,
        "audit": {
            "checked_rows": audit["checked_rows"],
            "checked_batches": audit["checked_batches"],
            "mismatched_rows": audit["mismatched_rows"],
            "skipped_batches": audit["skipped_batches"],
        },
        "drained": bool(drained),
        "fqdn_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


def chiploss_bench(preset: str, verbose: bool = False, batch: int = 256,
                   shards: int = 4):
    """cfg10: chip-loss self-healing over the live pipelined engine
    (ISSUE 19 — the robustness counterpart to cfg9's control-plane
    churn).

    A ``shards``-device mesh serves a CT-gated reply world: the
    endpoint's egress policy allows the forward direction, ingress is
    enforced with nothing matching the servers — so a REPLY row passes
    ONLY on a conntrack hit. The established population is the survival
    metric: every reply verdict is a direct probe of CT continuity
    through the loss.

    Phases: establish + warm (the warm replies also stamp the
    established-fingerprint filter the grace window consults) → CT
    archive snapshot (the salvage floor) → baseline reply storm (fps
    denominator) → arm ``device.fail`` on one ordinal mid-storm → the
    dispatch error latches DEVICE_LOST and parks the pipeline → one
    ``remesh_step`` fences the wedged generation and re-meshes onto the
    survivors with CT salvage (surviving shards' entries re-steered into
    the n-1 geometry; the lost shard's flows ride the bounded grace
    window until forward traffic cold-learns them back) → degraded
    reply storm (fps numerator + survival) → disarm + heal re-mesh back
    to full width → healed storm.

    The parity auditor rides at sampling 1.0 the whole way — the grace
    flip is applied AFTER capture, so raw verdicts replay exactly and
    the oracle takes the captured CT status as table truth.
    ``chiploss_gate`` fails the artifact (exit 4) on: established
    survival < 99% over resolved post-loss replies (pipeline rejects in
    the loss window are sheds, not denials), any parity mismatch (or
    nothing checked), degraded throughput under 0.7x the ideal (n-1)/n
    scaling, anything but exactly one re-mesh in each direction, a
    grace window that never fired (the loss exercised nothing), a final
    mesh narrower than configured, or an unclean drain."""
    import shutil
    import tempfile

    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine
    from cilium_tpu.runtime.faults import FAULTS
    from cilium_tpu.utils import constants as C

    smoke = preset == "smoke"
    n = max(2, shards)
    victim = 1 % n
    n_flows = 384 if smoke else 1536
    ticks = 4 if smoke else 12          # storm ticks per measured phase
    snap_dir = tempfile.mkdtemp(prefix="cilium-tpu-ct-archive-")
    cfg = DaemonConfig(
        n_shards=n, ct_capacity=1 << 13, auto_regen=False,
        batch_size=batch, pipeline_flush_ms=5.0,
        pipeline_queue_batches=16, pipeline_block_timeout_s=0.05,
        audit_enabled=True, audit_sample_rate=1.0, audit_pool_batches=64,
        flowlog_mode="none",
        remesh_heal_hysteresis_s=0.0,   # the bench drives the heal tick
        remesh_grace_s=120.0,           # survives a slow smoke rig
        ct_snapshot_dir=snap_dir, checkpoint_max_age_s=300.0)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.auditor.configure(sample_rate=1.0)
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        # forward direction: allowed by policy — the cold-learn path
        # that re-creates CT on the survivor mesh after the loss
        "egress": [{"toCIDR": ["10.0.0.0/8"],
                    "toPorts": [{"ports": [{"port": "443",
                                            "protocol": "TCP"}]}]}],
        # ingress ENFORCED with nothing matching the servers: replies
        # pass only on a CT hit — each one probes CT continuity
        "ingress": [{"fromEndpoints": [
            {"matchLabels": {"role": "backoffice"}}]}],
    }])
    eng.regenerate()
    eng.start_pipeline()

    flow_ids = np.arange(n_flows)
    chunks = [flow_ids[i:i + batch] for i in range(0, n_flows, batch)]
    shed_rows = 0

    def fwd_batch(idx, flags):
        b = _base_batch(len(idx), direction=C.DIR_EGRESS)
        b["dst"][:, 3] = (0x0A000100 + idx).astype(np.uint32)
        b["sport"][:] = 20000 + idx
        b["tcp_flags"][:] = flags
        return b

    def rep_batch(idx):
        b = _base_batch(len(idx), direction=C.DIR_INGRESS)
        b["src"][:, 3] = (0x0A000100 + idx).astype(np.uint32)
        b["dst"][:, 3] = 0xC0A8000A
        b["sport"][:] = 443
        b["dport"][:] = 20000 + idx
        b["tcp_flags"][:] = C.TCP_ACK
        return b

    def pump(mk, count=None):
        """Submit every chunk, resolve every ticket. Submission or
        resolution failures (queue overflow while parked, the fenced
        wedged window) are capacity sheds, never denials — they leave
        the survival denominator."""
        nonlocal shed_rows
        tickets = []
        for idx in chunks:
            try:
                tickets.append((eng.submit(mk(idx)), len(idx)))
            except Exception:
                shed_rows += len(idx)
        for tk, rows in tickets:
            try:
                out = tk.result(timeout=60.0)
            except Exception:
                shed_rows += rows
                continue
            if count is not None:
                count["rows"] += rows
                count["allowed"] += int(np.asarray(out["allow"]).sum())

    def storm(n_ticks, count):
        """Forward-ACK + reply sweeps over the whole population; only
        the reply verdicts feed survival, both directions feed fps."""
        t0 = time.monotonic()
        rows = 0
        for _ in range(n_ticks):
            pump(lambda idx: fwd_batch(idx, C.TCP_ACK))
            pump(rep_batch, count=count)
            rows += 2 * n_flows
            eng.audit_step(budget=32)
        eng.drain(timeout=120)
        return rows / max(1e-9, time.monotonic() - t0)

    # -- phase 0: establish + warm ------------------------------------------
    pump(lambda idx: fwd_batch(idx, C.TCP_SYN))
    assert eng.drain(timeout=120)
    warm = {"rows": 0, "allowed": 0}
    pump(rep_batch, count=warm)        # stamps the fingerprint filter
    eng.drain(timeout=120)
    eng.ct_snapshot_step()             # the archive salvage floor
    warm_surv = warm["allowed"] / max(1, warm["rows"])

    # -- phase 1: baseline storm --------------------------------------------
    base = {"rows": 0, "allowed": 0}
    baseline_fps = storm(ticks, base)

    # -- phase 2: loss, detection, fenced re-mesh ---------------------------
    FAULTS.arm("device.fail", mode="fail", message=f"dev={victim}")
    t_loss0 = time.monotonic()
    deg = {"rows": 0, "allowed": 0}
    trip = []
    try:
        trip.append((eng.submit(rep_batch(chunks[0])), len(chunks[0])))
    except Exception:
        shed_rows += len(chunks[0])
    deadline = time.monotonic() + 60
    while (eng.pipeline_stats() or {}).get("state") != "device-lost" \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    detect_ms = (time.monotonic() - t_loss0) * 1e3
    down = eng.remesh_step() or {}
    down_ms = (time.monotonic() - t_loss0) * 1e3
    for tk, rows in trip:
        try:
            out = tk.result(timeout=30.0)
            deg["rows"] += rows        # raced the fence and resolved
            deg["allowed"] += int(np.asarray(out["allow"]).sum())
        except Exception:
            shed_rows += rows          # the fenced wedged window

    # -- phase 3: degraded storm --------------------------------------------
    grace0 = eng.metrics.counters.get("ct_salvage_grace_hits_total", 0)
    # first reply sweep BEFORE any forward traffic: the lost shard's
    # flows must ride the grace window (fingerprint hits) — the
    # forward ACKs of the storm then cold-learn their CT entries back
    pump(rep_batch, count=deg)
    degraded_fps = storm(ticks, deg)
    grace_hits = eng.metrics.counters.get(
        "ct_salvage_grace_hits_total", 0) - grace0

    # -- phase 4: heal ------------------------------------------------------
    FAULTS.disarm("device.fail")
    t_up0 = time.monotonic()
    up = eng.remesh_step() or {}
    up_ms = (time.monotonic() - t_up0) * 1e3
    healed = {"rows": 0, "allowed": 0}
    healed_fps = storm(max(1, ticks // 2), healed)

    # -- drain + audit ------------------------------------------------------
    drained = eng.drain(timeout=120)
    for _ in range(200):
        step = eng.audit_step(budget=128)
        if not step or (not step.get("replayed")
                        and not step.get("pending")):
            break
    audit = eng.auditor.stats()
    status = eng.remesh_status()
    ctr = eng.metrics.counters
    downs = ctr.get(f'datapath_remesh_total{{from="{n}",to="{n - 1}"}}', 0)
    ups = ctr.get(f'datapath_remesh_total{{from="{n - 1}",to="{n}"}}', 0)
    eng.stop()
    shutil.rmtree(snap_dir, ignore_errors=True)

    survival = (deg["allowed"] + healed["allowed"]) \
        / max(1, deg["rows"] + healed["rows"])
    ratio = degraded_fps / max(1e-9, baseline_fps)
    ideal = (n - 1) / n
    floor = 0.7 * ideal
    mesh = status.get("mesh") or {}

    gate_reasons = []
    if warm_surv < 0.999 or base["allowed"] < base["rows"]:
        gate_reasons.append(
            f"baseline replies leaked before any loss (warm "
            f"{warm_surv:.4f}, storm {base['allowed']}/{base['rows']}) — "
            "the CT-gated world is broken, survival would be vacuous")
    if survival < 0.99:
        gate_reasons.append(
            f"established survival {survival:.4f} < 0.99 — flows lost "
            "verdicts through the loss/heal cycle")
    if audit["mismatched_rows"]:
        gate_reasons.append(
            f"parity: {audit['mismatched_rows']} mismatched rows at "
            "sampling 1.0 across the re-mesh")
    if audit["checked_rows"] == 0:
        gate_reasons.append("auditor checked nothing")
    if ratio < floor:
        gate_reasons.append(
            f"degraded throughput {ratio:.3f}x baseline < "
            f"{floor:.3f}x (0.7 * ideal {ideal:.3f} for {n}->{n - 1})")
    if downs != 1:
        gate_reasons.append(
            f"{downs} loss re-mesh(es) {n}->{n - 1} — expected exactly 1")
    if ups != 1:
        gate_reasons.append(
            f"{ups} heal re-mesh(es) {n - 1}->{n} — expected exactly 1")
    if grace_hits == 0:
        gate_reasons.append(
            "the salvage grace window never fired — the loss exercised "
            "nothing (no lost-shard flow ever needed it)")
    if mesh.get("live") != mesh.get("configured"):
        gate_reasons.append(
            f"final mesh {mesh.get('live')}/{mesh.get('configured')} — "
            "the healed device never re-admitted")
    if not drained:
        gate_reasons.append("pipeline did not drain clean")

    if verbose:
        print(f"# chiploss preset={preset} shards={n} victim={victim} "
              f"survival={survival:.4f} fps base/deg/heal="
              f"{baseline_fps:.0f}/{degraded_fps:.0f}/{healed_fps:.0f} "
              f"detect={detect_ms:.1f}ms down={down_ms:.1f}ms "
              f"up={up_ms:.1f}ms grace={grace_hits} shed={shed_rows} "
              f"audit={audit['checked_rows']}/{audit['mismatched_rows']}",
              file=sys.stderr)

    return {
        "metric": "chiploss_recovery_cfg10",
        "value": round(ratio, 4),
        "unit": "degraded_fps_ratio",
        "vs_baseline": round(ratio / max(1e-9, ideal), 4),
        "preset": preset,
        "batch": batch,
        "shards": n,
        "victim": victim,
        "established_survival": round(survival, 6),
        "throughput": {
            "baseline_fps": round(baseline_fps, 1),
            "degraded_fps": round(degraded_fps, 1),
            "healed_fps": round(healed_fps, 1),
            "ideal_ratio": round(ideal, 4),
            "floor_ratio": round(floor, 4),
        },
        "loss": {
            "detect_ms": round(detect_ms, 3),
            "down_ms": round(down_ms, 3),
            "remesh": down.get("remesh"),
        },
        "heal": {
            "up_ms": round(up_ms, 3),
            "remesh": up.get("remesh"),
        },
        "salvage": {
            "grace_hits": grace_hits,
            "shed_rows": shed_rows,
        },
        "survival": {"warm": warm, "baseline": base, "degraded": deg,
                     "healed": healed},
        "mesh": status,
        "audit": {
            "checked_rows": audit["checked_rows"],
            "checked_batches": audit["checked_batches"],
            "mismatched_rows": audit["mismatched_rows"],
            "skipped_batches": audit["skipped_batches"],
        },
        "drained": bool(drained),
        "chiploss_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


def cluster_bench(n_nodes: int, preset: str, verbose: bool = False):
    """cfg7: multi-host serving over the clustermesh store (ISSUE 12 /
    ROADMAP item 3 — the horizontal-scale counterpart to cfg6's
    single-host overload ladder). N engine PROCESSES (runtime/cluster.py,
    spawn — real per-host isolation: own jax, own FAULTS, own identity
    numbering) share one store directory; each publishes its endpoints'
    (prefix, labels) and ingests its peers', so ordinary label policy
    selects remote pods.

    Phases: (1) converge — every node's remote view matches the union of
    its peers' ledgers, with the post-seed ingest riding the PR 9
    delta-patch path (``regen_incremental_total`` must move); (2) serve —
    cross-boundary traffic on every node, aggregate fps + per-node
    replication-lag p99, with the parity auditor armed at sampling 1.0
    (the oracle replay IS "the merged world" check); (3) chaos — store
    partition on one node (``clustermesh.store_list``: last-good serving,
    MESH_STALE past the budget, heal), peer kill + lease-expiry withdrawal
    + restart + re-convergence, conflicting prefix claims resolved
    identically on every observer (n >= 3), and a skewed publisher clock
    (entries survive, lag clamps at zero); (4) relay fan-in — every node's
    flowlog JSONL tailed into one FlowRelay, every node visible in the
    merged stream. ``cluster_gate`` fails the artifact (exit 4) on any
    violation: non-convergence, parity mismatches, fail-closed remote
    flows during partition, MESH_STALE missing/sticky, observer
    disagreement on a conflicting claim, a node missing from the relay."""
    import shutil
    import tempfile

    from cilium_tpu.observe.relay import FlowRelay, JsonlTailObserver
    from cilium_tpu.runtime.cluster import ClusterSupervisor

    smoke = preset == "smoke"
    datapath = os.environ.get("CILIUM_TPU_CLUSTER_DATAPATH", "jit")
    serve_batches = 20 if smoke else 80
    stale_after_s = 2.0
    staleness_budget_s = 1.0
    gate_reasons = []
    phases = {}

    def note(phase, **kw):
        phases[phase] = kw
        if verbose:
            print(f"# cluster phase {phase}: {kw}", file=sys.stderr)

    def gate(ok, reason):
        if not ok:
            gate_reasons.append(reason)
        return ok

    names = [f"node-{i}" for i in range(n_nodes)]
    work = tempfile.mkdtemp(prefix="cilium-tpu-cluster-")
    store = os.path.join(work, "store")
    flows_dir = os.path.join(work, "flows")
    os.makedirs(flows_dir)
    overrides = {
        name: {"cluster_stale_after_s": stale_after_s,
               "cluster_staleness_budget_s": staleness_budget_s,
               "flowlog_path": os.path.join(flows_dir, f"{name}.jsonl")}
        for name in names}

    def node_ip(i):
        return f"10.{i + 1}.0.10"

    def setup_node(sup, i):
        name = names[i]
        sup.add_endpoint(name, ["k8s:cluster=mesh", f"k8s:app=svc{i}"],
                         [node_ip(i)], ep_id=1)
        sup.nodes[name].call("policy", docs=[{
            "endpointSelector": {"matchLabels": {"app": f"svc{i}"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"cluster": "mesh"}}],
                "toPorts": [{"ports": [
                    {"port": "8080", "protocol": "TCP"}]}]}]}])
        sup.nodes[name].call("regen")   # seed the incremental compiler
                                        # BEFORE remote entries arrive

    def cross_flows(i, sport0=41000):
        """Flows node i serves: one allowed cross-boundary flow per peer
        (remote pod ip → local pod, the mesh-selected port) + junk drops
        (unknown world sources)."""
        flows = []
        for j in range(n_nodes):
            if j == i:
                continue
            flows.append({"src": node_ip(j), "dst": node_ip(i),
                          "sport": sport0 + j, "dport": 8080, "ep_id": 1})
        flows.append({"src": "203.0.113.9", "dst": node_ip(i),
                      "sport": sport0 + 99, "dport": 8080, "ep_id": 1})
        flows.append({"src": node_ip(i - 1 if i else n_nodes - 1),
                      "dst": node_ip(i), "sport": sport0 + 98,
                      "dport": 23, "ep_id": 1})   # wrong port → drop
        return flows

    def expect_cross(out, i):
        """allowed cross flows per peer, junk + wrong-port denied."""
        want = [True] * (n_nodes - 1) + [False, False]
        return list(out["allow"]) == want

    sup = ClusterSupervisor(store, names, overrides=overrides,
                            datapath=datapath)
    t_bench0 = time.monotonic()
    try:
        # -- phase 1: boot + converge (delta-patch ingest) ------------------
        for i in range(n_nodes):
            setup_node(sup, i)
        rounds = sup.converge(max_rounds=3 + n_nodes)
        statuses = sup.broadcast("status")
        delta_used = {n: statuses[n]["counters"].get(
            "regen_incremental_total", 0) for n in names}
        gate(all(v >= 1 for v in delta_used.values()),
             f"remote ingest did not ride the delta-patch path on every "
             f"node (regen_incremental_total={delta_used})")
        note("converge", rounds=rounds, delta_used=delta_used)

        # -- phase 2: serve + cross-boundary verdict spot-audit -------------
        per_node = {}
        for i, name in enumerate(names):
            res = sup.nodes[name].call(
                "serve", flows=cross_flows(i), batches=serve_batches,
                now=5000, timeout=600.0)
            per_node[name] = res
        agg_fps = sum(r["fps"] for r in per_node.values())
        spot_ok = {}
        for i, name in enumerate(names):
            out = sup.nodes[name].call("classify",
                                       flows=cross_flows(i, sport0=45000),
                                       now=6000)
            spot_ok[name] = expect_cross(out, i)
        gate(all(spot_ok.values()),
             f"cross-boundary verdict spot-audit failed: {spot_ok}")
        # flush every node's flowlog sink NOW: the kill phase below takes a
        # node down hard, and the relay must still see its served flows
        sup.broadcast("flush")
        note("serve", aggregate_fps=round(agg_fps, 1),
             per_node_fps={n: round(r["fps"], 1)
                           for n, r in per_node.items()})

        # -- phase 3a: store partition on node-0 ----------------------------
        victim = names[0]
        sup.nodes[victim].call("arm", point="clustermesh.store_list",
                               spec={"mode": "fail"})
        during = []
        for _ in range(3):
            sup.broadcast("step")
            out = sup.nodes[victim].call("classify",
                                         flows=cross_flows(0, 46000),
                                         now=7000)
            during.append(expect_cross(out, 0))
            time.sleep(0.45)
        gate(all(during),
             "partitioned node failed closed on established remote flows")
        st = sup.nodes[victim].call("status")
        gate(st["mesh"]["state"] == "MESH_STALE",
             f"partitioned node never reported MESH_STALE past the "
             f"{staleness_budget_s}s budget (state={st['mesh']['state']})")
        gate(st["health"]["state"] == "DEGRADED",
             f"health did not degrade on MESH_STALE "
             f"(state={st['health']['state']})")
        sup.nodes[victim].call("disarm", point="clustermesh.store_list")
        sup.broadcast("step")
        st = sup.nodes[victim].call("status")
        gate(st["mesh"]["state"] == "OK",
             f"MESH_STALE did not clear after heal "
             f"(state={st['mesh']['state']})")
        rounds_heal = sup.converge(max_rounds=4)
        note("partition", during_partition_served=all(during),
             healed_rounds=rounds_heal)

        # -- phase 3b: peer kill → lease expiry → restart → re-converge -----
        dead = names[-1]
        dead_idx = n_nodes - 1
        sup.nodes[dead].kill()
        survivors = names[:-1]
        dead_prefix = f"{node_ip(dead_idx)}/32"
        # detection latency is [stale_after, 2*stale_after): a survivor
        # that cached generation G-1 observes the dead node's final G on
        # its first post-kill sync as "progress" and renews the lease once
        # — withdrawal lands within one more lease window
        withdrawn = False
        expiry_deadline = time.monotonic() + 2 * stale_after_s + 2.0
        while not withdrawn and time.monotonic() < expiry_deadline:
            time.sleep(stale_after_s * 0.6)
            sup.broadcast("step", only=survivors)
            views = sup.views(only=survivors)
            withdrawn = all(dead_prefix not in views[n] for n in survivors)
        gate(withdrawn,
             f"dead peer's prefix {dead_prefix} not withdrawn after lease "
             f"expiry")
        # the withdrawn identity fails closed for NEW flows (stale IP must
        # not keep the old pod's permissions)
        out = sup.nodes[names[0]].call("classify", flows=[
            {"src": node_ip(dead_idx), "dst": node_ip(0),
             "sport": 47001, "dport": 8080, "ep_id": 1}], now=8000)
        gate(not out["allow"][0],
             "withdrawn remote identity still allowed after lease expiry")
        sup.restart(dead)
        setup_node(sup, dead_idx)
        rounds_back = sup.converge(max_rounds=4 + n_nodes)
        out = sup.nodes[names[0]].call("classify", flows=[
            {"src": node_ip(dead_idx), "dst": node_ip(0),
             "sport": 47002, "dport": 8080, "ep_id": 1}], now=8100)
        gate(bool(out["allow"][0]),
             "restarted peer's pod not re-admitted after re-convergence")
        # the restarted node serves again (feeds its auditor + flowlog —
        # the relay below must span the RESTARTED mesh, not just the
        # pre-kill one)
        sup.nodes[dead].call("serve", flows=cross_flows(dead_idx, 48000),
                             batches=max(5, serve_batches // 4), now=8200,
                             timeout=600.0)
        sup.nodes[dead].call("flush")
        note("kill_restart", withdrawn=withdrawn,
             reconverged_rounds=rounds_back)

        # -- phase 3c: conflicting claims (needs a third observer) ----------
        if n_nodes >= 3:
            cprefix = "10.77.0.7/32"
            sup.add_endpoint(names[0], ["k8s:app=moving"], ["10.77.0.7"],
                             ep_id=7)
            sup.add_endpoint(names[1], ["k8s:app=moving"], ["10.77.0.7"],
                             ep_id=7)
            for _ in range(2):
                sup.broadcast("step")
            observers = names[2:]
            winners = {}
            for name in observers:
                st = sup.nodes[name].call("status")
                conf = st["mesh"]["conflicts"].get(cprefix)
                winners[name] = conf["winner"] if conf else None
                gate(any(k.startswith("clustermesh_conflicts_total")
                         for k in st["counters"]),
                     f"{name}: conflicting claim not counted")
            gate(len(set(winners.values())) == 1
                 and None not in winners.values(),
                 f"observers disagree on the conflict winner: {winners}")
            # every observer ingested the prefix under exactly one claim
            views = sup.views(only=observers)
            gate(all(cprefix in views[n] for n in observers),
                 f"conflicted prefix not served by observers: "
                 f"{ {n: cprefix in views[n] for n in observers} }")
            sup.remove_endpoint(names[0], 7, ips=["10.77.0.7"])
            sup.remove_endpoint(names[1], 7, ips=["10.77.0.7"])
            rounds_conf = sup.converge(max_rounds=4)
            note("conflict", winners=winners, resolved_rounds=rounds_conf)
        else:
            note("conflict", skipped=f"needs >= 3 nodes, ran {n_nodes}")

        # -- phase 3d: skewed publisher clock -------------------------------
        skewed = names[1]
        sup.nodes[skewed].call("skew", seconds=3600.0)
        for _ in range(2):
            sup.broadcast("step")
        views = sup.views()
        skew_prefix = f"{node_ip(1)}/32"
        holders = [n for n in names if n != skewed]
        skew_ok = all(skew_prefix in views[n] for n in holders)
        gate(skew_ok, f"peers dropped a live publisher whose clock is "
                      f"3600s ahead (views={ {n: skew_prefix in views[n] for n in holders} })")
        lags = {n: sup.nodes[n].call("status")["mesh"]
                ["replication_lag_p99_s"] for n in holders}
        gate(all(v >= 0 for v in lags.values()),
             f"replication lag went negative under clock skew: {lags}")
        sup.nodes[skewed].call("skew", seconds=0.0)
        note("skewed_clock", entries_survive=skew_ok, lag_p99=lags)

        # -- phase 4: relay fan-in over the nodes' flowlog sinks ------------
        sup.broadcast("flush")
        relay = FlowRelay({name: JsonlTailObserver(
            os.path.join(flows_dir, f"{name}.jsonl")) for name in names})
        merged = relay.poll(limit=100_000)
        seen_nodes = {r.get("node") for r in merged["flows"]
                      if not r.get("gap")}
        gate(seen_nodes == set(names),
             f"relay fan-in missing nodes: saw {sorted(seen_nodes)} of "
             f"{names}")
        note("relay", merged_flows=len(merged["flows"]),
             nodes=sorted(seen_nodes),
             lag=merged["lag"], gaps=len(merged["gaps"]))

        # -- phase 5: final parity audit + lag p99 --------------------------
        audits = sup.broadcast("audit")
        mismatched = {n: a["mismatched_rows"] for n, a in audits.items()}
        checked = {n: a["checked_rows"] for n, a in audits.items()}
        gate(all(v == 0 for v in mismatched.values()),
             f"parity mismatches at sampling 1.0: {mismatched}")
        gate(all(v > 0 for v in checked.values()),
             f"auditor checked nothing on some node: {checked}")
        statuses = sup.broadcast("status")
        lag_p99 = {n: statuses[n]["mesh"]["replication_lag_p99_s"]
                   for n in names}
        note("audit", checked=checked, mismatched=mismatched)
    finally:
        try:
            sup.stop_all()
        finally:
            shutil.rmtree(work, ignore_errors=True)
    elapsed = time.monotonic() - t_bench0

    if verbose:
        print(f"# cluster n={n_nodes} preset={preset} agg_fps={agg_fps:.0f}"
              f" lag_p99={max(lag_p99.values()):.4f}s gate_reasons="
              f"{gate_reasons}", file=sys.stderr)

    return {
        "metric": f"cluster_mesh_serving_n{n_nodes}_cfg7",
        "value": round(agg_fps, 1),
        "unit": "aggregate_flows/sec",
        "vs_baseline": round(agg_fps / (PER_CHIP_TARGET * n_nodes), 6),
        "nodes": n_nodes,
        "preset": preset,
        "datapath": datapath,
        "elapsed_s": round(elapsed, 1),
        "aggregate_fps": round(agg_fps, 1),
        "per_node_fps": {n: round(r["fps"], 1)
                         for n, r in per_node.items()},
        "replication_lag_p99_s": lag_p99,
        "replication_lag_p99_max_s": max(lag_p99.values()),
        "audit": {"checked_rows": checked, "mismatched_rows": mismatched},
        "phases": phases,
        "cluster_gate": {
            "failed": bool(gate_reasons),
            **({"reasons": gate_reasons} if gate_reasons else {}),
        },
    }


BUILDERS = {1: build_config1, 2: build_config2, 3: build_config3,
            4: build_config4, 5: build_config5}
METRIC_NAMES = {
    1: "cfg1_l3_cidr_1k_rules",
    2: "cfg2_multi_identity_l3l4",
    3: "cfg3_lpm_heavy",
    4: "cfg4_l7_lite",
    5: "cfg5_conntrack_churn_50k_rules",
}


# --------------------------------------------------------------------------- #
# runner
# --------------------------------------------------------------------------- #
def run_bench(config: int, preset: str, batch: int, batches: int,
              verbose: bool = False, windows: int = 5,
              shards: int = 1, rule_shards: int = 1,
              profile_dir: str = ""):
    """One config → throughput dict.

    Pipeline modeled: packed wire batches (kernels/records.pack_batch — the
    single-buffer format the C++ shim emits) are device_put with one-batch
    prefetch (the next transfer overlaps the current classify), then the
    fused classify step runs with donated CT buffers. Transfers ARE included
    in the headline timing.

    Statistics (round-4 verdict item 3: the harness must detect its own
    noise): ``windows`` (>=5) timing windows run per mode, each calibrated
    to span >=~0.3s (short windows measure dispatch granularity — the
    kernel clears 65k records in ~100us), and the MEDIAN is reported with
    the IQR alongside — never best-of. Three numbers are measured:
    - ``value``: sustained transfer-included median (what a long-running
      AF_XDP pipeline sees). On this rig the host↔TPU tunnel is a token
      bucket — fast bursts, then a ~100-150MB/s sustained floor — so for
      configs run after the bucket drains this measures the LINK;
    - ``burst``: the bucket-fresh transfer rate (first pass);
    - ``compute_only``: device-resident batches — the framework's own
      throughput, reproducible run-to-run within a few percent. If
      ``value`` moves between runs but ``compute_only`` doesn't, the link
      moved, not the code.

    ``shards``/``rule_shards`` > 1 route the run through the production mesh
    path (parallel/mesh.make_sharded_classify_fn over a ('flows','rules')
    mesh): batches host-steered by flow hash, CT sharded per chip, verdict
    rows sharded + psum. Requires shards*rule_shards visible devices
    (JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N
    for a virtual mesh on a 1-chip rig).
    """
    import jax
    import jax.numpy as jnp
    from cilium_tpu.compile.ct_layout import make_ct_arrays
    from cilium_tpu.kernels.classify import make_classify_fn
    from cilium_tpu.kernels.records import pack_batch

    t0 = time.time()
    snap, gen, v4_only = BUILDERS[config](preset)
    compile_s = time.time() - t0

    rng = np.random.default_rng(7)
    wi = jnp.int32(snap.world_index)
    sharded = shards * rule_shards > 1

    # pre-generate host batches (generation excluded from the timed loop —
    # the shim does it in C++; transfer included, it is part of the real
    # pipeline). One packed width per config so a single jit serves.
    # Configs with a pcap source replay it through the shim ingest instead.
    host_dicts = None
    pcap_fn = getattr(gen, "pcap_replay", None)
    if pcap_fn is not None:
        host_dicts = pcap_fn(batch, min(batches, 16))
    if host_dicts is None:
        host_dicts = [gen(rng, batch) for _ in range(min(batches, 16))]
    from cilium_tpu.utils import constants as C
    from cilium_tpu.kernels.records import pack_batch_v4

    if sharded:
        from cilium_tpu.parallel.mesh import (
            flow_shard_of, make_mesh, make_sharded_classify_fn,
            pad_snapshot_tensors, shard_ct_arrays, steer_batch)
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = make_mesh(shards, rule_shards)
        tensors_np = pad_snapshot_tensors(snap.tensors(), rule_shards)
        vspec = NamedSharding(mesh, P(None, None, "rules", None))
        repl = NamedSharding(mesh, P())
        tensors = {k: jax.device_put(v, vspec if k == "verdict" else repl)
                   for k, v in tensors_np.items()}
        ct_host = shard_ct_arrays(
            make_ct_arrays(snap.ct_config), shards)
        ct_sharding = NamedSharding(mesh, P("flows"))
        ct = {k: jax.device_put(v, ct_sharding) for k, v in ct_host.items()}
        fn = make_sharded_classify_fn(mesh, v4_only=v4_only, donate_ct=True)
        # pre-steer (the C++ shim's flow_shard does this in production);
        # one uniform per-shard size across batches → single trace
        lb = snap.lb if snap.lb.n_frontends else None
        per = max(int(np.bincount(
            flow_shard_of(hb, shards, lb=lb), minlength=shards).max())
            for hb in host_dicts)
        per = 1 << (per - 1).bit_length()
        host_batches = [steer_batch(hb, shards, per_shard=per, lb=lb)[0]
                        for hb in host_dicts]
    else:
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct = {k: jnp.asarray(v)
              for k, v in make_ct_arrays(snap.ct_config).items()}
        fn = make_classify_fn(v4_only=v4_only, donate_ct=True, packed=True)
        # L7 presence must be decided across ALL pre-generated batches:
        # deciding from the first alone silently drops later batches'
        # http_path data (changing measured verdicts) whenever the first
        # happens to be L7-free.
        has_l7 = any(bool((hb["http_method"] != C.HTTP_METHOD_ANY).any()
                          or hb["http_path"].any()) for hb in host_dicts)
        has_v6 = any(bool(hb["is_v6"].any()) for hb in host_dicts)
        from cilium_tpu.kernels.records import (
            PACKA_EP_SLOT_MAX, _pad_dict_rows, pack_batch_addrdict)
        # addr-dict selection by BYTE COST vs the wire it would displace
        # (16B/record v4, or 44B/record full for v6): the dict only wins
        # when addresses repeat enough to pay for the dict rows
        u_max = 0 if has_l7 else max(
            np.unique(np.concatenate([hb["src"], hb["dst"]]),
                      axis=0).shape[0] for hb in host_dicts)
        u_pad = _pad_dict_rows(u_max, 1)
        addr_bytes = 12 * batch + 16 * u_pad
        alt_bytes = (44 if has_v6 else 16) * batch
        addr_ok = (not has_l7 and 0 < u_max <= 65536
                   and addr_bytes < alt_bytes
                   and all(not (hb["ep_slot"] > PACKA_EP_SLOT_MAX).any()
                           for hb in host_dicts))
        if addr_ok:
            # one dict row count across batches keeps a single trace
            host_batches = [pack_batch_addrdict(hb, min_addr_rows=u_pad)
                            for hb in host_dicts]
        elif not has_l7 and not has_v6:
            # compact 16B/record wire format — the transfer-bound fast path
            host_batches = [pack_batch_v4(hb) for hb in host_dicts]
        elif has_l7:
            # L7 dictionary wire: unique paths shipped once, 16-bit index
            # per record (~20B/record instead of 76-108B; the L7 path is
            # transfer-bound — compute-only runs >100M flows/s)
            from cilium_tpu.kernels.records import (
                _path_words_for, pack_batch_l7dict)
            pw = max(_path_words_for(hb) for hb in host_dicts)
            host_batches = [pack_batch_l7dict(hb, path_words=pw)
                            for hb in host_dicts]
        else:
            host_batches = [pack_batch(hb) for hb in host_dicts]

    # warmup / compile
    now = 10_000
    out, ct, counters = fn(tensors, ct,
                           jax.device_put(host_batches[0]),
                           jnp.uint32(now), wi)
    jax.block_until_ready(out)
    trace_s = time.time() - t0 - compile_s

    eff_batch = batch          # valid records per batch (steered pads aren't)

    # -- mode 1: transfer-included (headline) ------------------------------- #
    if profile_dir:
        # one profiled steady-state window → XProf trace (SURVEY §5)
        with jax.profiler.trace(profile_dir):
            for i in range(min(batches, 8)):
                now += 1
                out, ct, counters = fn(
                    tensors, ct, jax.device_put(host_batches[i % len(host_batches)]),
                    jnp.uint32(now), wi)
            jax.block_until_ready(out)
        print(f"# profiler trace written to {profile_dir}", file=sys.stderr)

    def _xfer_pass():
        nonlocal now, ct, out, counters
        nxt = jax.device_put(host_batches[0])
        for i in range(batches):
            cur = nxt
            nxt = jax.device_put(host_batches[(i + 1) % len(host_batches)])
            now += 1
            out, ct, counters = fn(tensors, ct, cur, jnp.uint32(now), wi)
        jax.block_until_ready(out)

    # calibration: the fused kernel clears 65k records in ~100us, so a
    # fixed-batch window can be milliseconds — measuring dispatch
    # granularity and single jitter bursts, not steady state (the round-4
    # "2.9x swing on identical code" failure). Repeat each window's pass
    # until it spans >= ~0.3s.
    t1 = time.time()
    _xfer_pass()
    first_pass_s = max(time.time() - t1, 1e-4)
    # the calibration pass doubles as the BURST rate probe: this rig's
    # host↔TPU tunnel has a token-bucket shape (fast bursts, then a
    # ~100-150MB/s sustained floor), so a short window measures the bucket
    # state, not the framework. `value` reports the sustained median;
    # `burst` the EARLY rate — first measured pass after warmup, so setup
    # transfers (tensor placement, the 1-batch warmup) have already drawn
    # on the bucket; read it as an upper-bound indicator, not an absolute.
    # Compute-only separates the kernels from the link entirely.
    burst_tp = batches * eff_batch / first_pass_s
    xfer_reps = max(1, min(50, int(0.3 / first_pass_s)))
    xfer_tp = []
    for _w in range(windows):
        t1 = time.time()
        for _r in range(xfer_reps):
            _xfer_pass()
        xfer_tp.append(xfer_reps * batches * eff_batch / (time.time() - t1))

    # -- mode 2: compute-only (device-resident batches) --------------------- #
    if sharded:
        # pre-shard onto the mesh: a plain device_put would commit to one
        # device and every call would re-distribute (still transfer-bound)
        batch_sharding = NamedSharding(mesh, P("flows"))
        dev_batches = [jax.device_put(hb, batch_sharding)
                       for hb in host_batches[:4]]
    else:
        dev_batches = [jax.device_put(hb) for hb in host_batches[:4]]
    jax.block_until_ready(dev_batches)

    def _comp_pass():
        nonlocal now, ct, out, counters
        for i in range(batches):
            now += 1
            out, ct, counters = fn(tensors, ct,
                                   dev_batches[i % len(dev_batches)],
                                   jnp.uint32(now), wi)
        jax.block_until_ready(out)

    t1 = time.time()
    _comp_pass()
    comp_reps = max(1, min(200, int(0.3 / max(time.time() - t1, 1e-4))))
    comp_tp = []
    for _w in range(windows):
        t1 = time.time()
        for _r in range(comp_reps):
            _comp_pass()
        comp_tp.append(comp_reps * batches * eff_batch / (time.time() - t1))

    def _stats(vals):
        v = np.asarray(vals, dtype=np.float64)
        q1, med, q3 = np.percentile(v, [25, 50, 75])
        return float(med), float(q3 - q1)

    xfer_med, xfer_iqr = _stats(xfer_tp)
    comp_med, comp_iqr = _stats(comp_tp)

    # per-batch latency distribution: synchronous dispatch (transfer +
    # classify + result fence per batch) — the per-batch time an enforcing
    # shim would wait for a verdict bitmap, deliberately unpipelined.
    lat_n = max(20, min(batches, 50))
    lat_ms = np.empty(lat_n)
    for i in range(lat_n):
        now += 1
        t1 = time.time()
        cur = jax.device_put(host_batches[i % len(host_batches)])
        out, ct, counters = fn(tensors, ct, cur, jnp.uint32(now), wi)
        jax.block_until_ready(out["allow"])
        lat_ms[i] = (time.time() - t1) * 1e3
    p50_ms = float(np.percentile(lat_ms, 50))
    p99_ms = float(np.percentile(lat_ms, 99))

    if verbose:
        by = np.asarray(counters["by_reason_dir"]).reshape(256, 2)
        print(f"# config={config} preset={preset} platform="
              f"{jax.devices()[0].platform} batch={batch} batches={batches}"
              f" windows={windows} shards={shards}x{rule_shards}\n"
              f"# compile={compile_s:.1f}s trace={trace_s:.1f}s\n"
              f"# transfer-incl windows (Mfl/s): "
              f"{[round(x / 1e6, 1) for x in xfer_tp]}\n"
              f"# compute-only windows (Mfl/s): "
              f"{[round(x / 1e6, 1) for x in comp_tp]}\n"
              f"# sync batch latency p50={p50_ms:.2f}ms p99={p99_ms:.2f}ms"
              f" last-batch reasons={ {int(r): int(by[r].sum()) for r in np.nonzero(by.sum(1))[0]} }",
              file=sys.stderr)
    n_chips = shards * rule_shards
    return {
        "metric": f"flow_classify_throughput_{METRIC_NAMES[config]}",
        # sharded runs measure the whole mesh: report honestly per chip
        "value": round(xfer_med / n_chips, 1),
        "unit": "flows/sec/chip",
        "vs_baseline": round(xfer_med / n_chips / PER_CHIP_TARGET, 4),
        "iqr": round(xfer_iqr / n_chips, 1),
        "burst": round(burst_tp / n_chips, 1),
        "compute_only": round(comp_med / n_chips, 1),
        "compute_only_iqr": round(comp_iqr / n_chips, 1),
        "windows": windows,
        "p50_batch_ms": round(p50_ms, 3),
        "p99_batch_ms": round(p99_ms, 3),
        "batch": batch,
        "preset": preset,
        **({"shards": shards, "rule_shards": rule_shards,
            "mesh_total": round(xfer_med, 1)} if sharded else {}),
    }


def _bench_bucket(cfg, batch: int, shards: int, mode: str) -> int:
    """Dispatch-shape parity between the RSS modes: a steered flush
    always ships the FULL n_shards*seg_cap layout (= batch * headroom
    rows, mostly valid under balanced traffic), so the unsteered ring
    sizes its bucket to the same aggregate rows — equal rows-per-dispatch
    and equal staging memory; anything else compares dispatch-overhead
    amortization, not steering."""
    if shards > 1 and mode == "device":
        return batch * cfg.pipeline_shard_headroom
    return batch


def _bench_pipeline(dispatch_fn, met, cfg, batch: int, shards: int,
                    mode: str, shard_fn=None):
    """The bench's serving Pipeline — ONE construction shared by the
    primary pipeline_bench measurement and the rss A/B, so the two sides
    of the steered-vs-unsteered comparison can never drift into
    differently configured pipelines. min_bucket == max_bucket: every
    coalesced dispatch is the one device-optimal shape (no trace
    proliferation); stall_timeout wide — a cold-shape XLA compile or a
    tunnel burst must not look like a device stall to the watchdog on
    this rig."""
    from cilium_tpu.pipeline import Pipeline
    sharded = shards > 1
    steered = sharded and mode == "host"
    bucket = _bench_bucket(cfg, batch, shards, mode)
    return Pipeline(dispatch_fn, metrics=met, max_bucket=bucket,
                    min_bucket=bucket,
                    queue_batches=max(64, cfg.pipeline_queue_batches),
                    admission="block", block_timeout_s=60.0,
                    flush_ms=cfg.pipeline_flush_ms,
                    inflight=cfg.pipeline_inflight,
                    stall_timeout_s=300.0,
                    n_shards=shards if steered else 1,
                    shard_fn=shard_fn if steered else None,
                    shard_headroom=cfg.pipeline_shard_headroom,
                    mesh_shards=shards if sharded else 0,
                    rss_mode=mode if sharded else "host")


def pipeline_bench(config: int, preset: str, batch: int, batches: int,
                   windows: int = 3, verbose: bool = False,
                   trace: bool = False, shards: int = 1,
                   rss: str = "host"):
    """Serial vs pipelined ingestion on one config, through the real
    ``DatapathBackend`` boundary (JITDatapath behind the Pipeline
    scheduler), over the same ingest stream: the shim's rx polls deliver
    sub-full chunks (``batch // 8`` records — an AF_XDP poll budget), and

    - **serial** classifies each chunk as it arrives with a blocking wait
      (today's per-poll serving path: build → transfer → classify →
      verdict fence, strictly sequential);
    - **pipelined** submits the same chunks to the scheduler, which
      coalesces them into full ``batch``-row buckets and keeps
      ``pipeline_inflight`` dispatches in flight via ``classify_async`` —
      host staging/transfer overlapped with the previous bucket's device
      compute, one device shape, 8x fewer dispatches.

    Same flows, same CT geometry, same kernel — the delta is scheduling.

    ``shards`` > 1 routes both modes through the flow-sharded mesh (one
    admission queue, steered staging, per-shard wire segments): serial
    classifies through the sync sharded path (steer at classify time),
    pipelined through the pre-steered staging ring. Requires ``shards``
    visible devices; tracing auto-enables so the artifact always carries
    the steer/scatter span split.

    ``rss="device"`` (with ``shards`` > 1) measures the device-side RSS
    path instead — arrival-order staging, the in-kernel ring ppermute CT
    exchange, no host steer/scatter anywhere (the schema check asserts
    those spans are ABSENT) — and appends a steered-vs-unsteered A/B
    (``rss_ab``): balanced traffic plus a skewed stream whose flows all
    hash to one CT shard, where the device path's win is structural
    (one segment serializes the steered mesh) rather than incremental.
    The ``rss_gate`` (exit 4) always arms the structural half — skew
    immunity (the steered path must degrade under skew by
    CILIUM_TPU_BENCH_RSS_SKEW_IMMUNITY_MIN more than the device path)
    plus zero device sheds — and arms the absolute throughput
    comparison (balanced within CILIUM_TPU_BENCH_RSS_AB_SLACK, strict
    win on skew) on TPU only: the CPU virtual mesh serializes the
    chips onto a couple of host cores, which inflates the exchange's
    per-chip CT redundancy ~n× in a way real hardware never sees (the
    same rig-unmeasurable-by-construction split as the --kernels
    fused gate).
    """
    from cilium_tpu.observe.trace import TRACER
    from cilium_tpu.pipeline import Pipeline
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.metrics import Metrics

    sharded = shards > 1
    device_rss = sharded and rss == "device"
    trace = trace or sharded
    if trace:
        # --trace: sample every submission so the per-stage summary in the
        # JSON artifact covers the whole run (admission/microbatch/dispatch/
        # finalize + the datapath's pack/transfer/compute split). This is
        # the diagnostic mode — production sampling is 1/64-style.
        TRACER.configure(sample_rate=1.0, capacity=65536)
        TRACER.reset()
    t0 = time.time()
    snap, gen, v4_only = BUILDERS[config](preset)
    compile_s = time.time() - t0
    cfg = DaemonConfig(ct_capacity=snap.ct_config.capacity,
                       probe_depth=snap.ct_config.probe_depth,
                       v4_only=v4_only, batch_size=batch, n_shards=shards,
                       rss_mode=rss if sharded else "host")
    dp = JITDatapath(cfg)
    placed = dp.place(snap)
    rng = np.random.default_rng(7)
    chunk = max(64, batch // 8)
    chunks = []
    for _ in range(min(batches, 8)):
        full = gen(rng, batch)
        chunks.extend({k: v[j:j + chunk] for k, v in full.items()}
                      for j in range(0, batch, chunk))
    now = [20_000]

    # warmup both device shapes (chunk for serial, full bucket for pipelined)
    dp.classify(placed, snap, dict(chunks[0]), now[0])
    dp.classify(placed, snap, gen(rng, batch), now[0])

    def serial_pass():
        for i in range(batches * (batch // chunk)):
            now[0] += 1
            dp.classify(placed, snap, chunks[i % len(chunks)], now[0])

    lb = snap.lb if snap.lb.n_frontends else None

    def shard_fn(b):
        from cilium_tpu.parallel.mesh import flow_shard_of
        return flow_shard_of(b, shards, lb=lb)

    def make_pipeline(met):
        mode = "device" if device_rss else "host"
        steered = sharded and not device_rss

        def dispatch_fn(b, n, steer_rev=None):
            # fixed snapshot for the whole run: a pre-steered bucket can
            # never be stale, whatever revision it was steered under
            fin = dp.classify_async(placed, snap, b, n,
                                    pre_steered=steered)
            return lambda: fin()[0]
        return _bench_pipeline(dispatch_fn, met, cfg, batch, shards, mode,
                               shard_fn=shard_fn)

    met = Metrics()
    pl = make_pipeline(met)        # long-lived, like a serving daemon's
    # pack attribution for the PIPELINED passes only — the serial
    # comparison mode classifies through the sync path, whose allocating
    # steer is counted "steered" by design and must not pollute the
    # steered-staging acceptance numbers
    pack_pipe = {k: 0 for k in dp.pack_stats}

    def pipe_pass():
        base = dict(dp.pack_stats)
        for i in range(batches * (batch // chunk)):
            now[0] += 1
            pl.submit(chunks[i % len(chunks)], now=now[0])
        assert pl.drain(timeout=600), "pipeline drain timed out"
        for k in pack_pipe:
            pack_pipe[k] += dp.pack_stats[k] - base.get(k, 0)

    serial_pass()                   # calibrate both modes on a warm link
    pipe_pass()
    serial_tp, pipe_tp = [], []
    for _w in range(windows):
        # alternate which mode runs first so CT-occupancy / link drift
        # across the run cannot systematically favor one mode
        order = ((serial_pass, serial_tp), (pipe_pass, pipe_tp))
        if _w % 2:
            order = order[::-1]
        for fn, acc in order:
            t1 = time.time()
            fn()
            acc.append(batches * batch / (time.time() - t1))

    def _med(vals):
        return float(np.percentile(np.asarray(vals, np.float64), 50))

    serial_med, pipe_med = _med(serial_tp), _med(pipe_tp)
    qw = met.histograms.get("pipeline_queue_wait_seconds")
    bl = met.histograms.get("pipeline_batch_latency_seconds")
    stats = pl.stats()
    pl.close(timeout=30)
    if verbose:
        print(f"# pipeline bench config={config} preset={preset} "
              f"batch={batch} batches={batches} compile={compile_s:.1f}s\n"
              f"# serial windows (Mfl/s): "
              f"{[round(x / 1e6, 1) for x in serial_tp]}\n"
              f"# pipelined windows (Mfl/s): "
              f"{[round(x / 1e6, 1) for x in pipe_tp]}", file=sys.stderr)
    doc = {
        "metric": f"pipeline_ingestion_{METRIC_NAMES[config]}",
        "value": round(pipe_med, 1),
        "unit": "flows/sec",
        "vs_baseline": round(pipe_med / PER_CHIP_TARGET, 4),
        "serial_flows_per_sec": round(serial_med, 1),
        "pipelined_flows_per_sec": round(pipe_med, 1),
        "speedup_vs_serial": round(pipe_med / max(serial_med, 1e-9), 3),
        "queue_wait_p50_ms": round(qw.quantile(0.5) * 1e3, 3) if qw else 0.0,
        "queue_wait_p99_ms": round(qw.quantile(0.99) * 1e3, 3) if qw else 0.0,
        "batch_latency_p50_ms": round(bl.quantile(0.5) * 1e3, 3)
        if bl else 0.0,
        "fill_ratio": stats["fill_ratio_avg"],
        "flush_reasons": stats["flush_reasons"],
        # guard-layer counters: overload/degradation behavior belongs in
        # the artifact (a healthy run shows zeros; a shedding or
        # breaker-tripping run is visibly not a clean number)
        "shed_total": stats.get("shed_total", 0),
        "shed_reasons": stats.get("shed_reasons", {}),
        "admission_drops": stats.get("admission_drops", 0),
        "breaker": stats.get("breaker", {}),
        "restarts": stats.get("restarts", 0),
        "pipeline_state": stats.get("state", "ok"),
        "inflight": cfg.pipeline_inflight,
        "ingest_chunk": chunk,
        "windows": windows,
        "batch": batch,
        "batches": batches,
        "preset": preset,
        # --trace: per-stage span summary (p50/p99/max per stage, ms)
        **({"trace_spans": TRACER.summary(),
            "trace_stats": TRACER.stats()} if trace else {}),
    }
    if sharded:
        doc.update({
            "shards": shards,
            "rss": "device" if device_rss else "host",
            "aggregate_flows_per_sec": round(pipe_med, 1),
            "per_chip_flows_per_sec": round(pipe_med / shards, 1),
            "vs_baseline": round(pipe_med / shards / PER_CHIP_TARGET, 4),
            "pack_stats": pack_pipe,
            "pack_stats_total": dict(dp.pack_stats),
            **({"shard_fill": stats.get("shard_fill"),
                "shard_rows_total": stats.get("shard_rows_total"),
                "shard_capacity": stats.get("shard_capacity")}
               if not device_rss else
               {"rss_exchange": dp.rss_exchange_stats()}),
        })
        spans = doc.get("trace_spans", {})
        doc["steer_split"] = {k: spans[k] for k in
                              ("pipeline.steer", "pipeline.stage_write",
                               "datapath.pack", "datapath.steer")
                              if k in spans}
        if device_rss:
            doc["rss_ab"] = _rss_ab(
                pipe_med, chunks, gen, snap, lb, cfg, batch, batches,
                chunk, shards, now, _med, verbose=verbose)
            import jax
            doc["rss_gate"] = _rss_gate(doc["rss_ab"],
                                        jax.devices()[0].platform)
        doc.update(_sharded_schema_check(doc, shards))
    return doc


def _rss_ab(device_balanced_fps, chunks, gen, snap, lb, cfg, batch,
            batches, chunk, shards, now, med, verbose=False):
    """The steered-vs-unsteered A/B the device-RSS artifact carries: the
    same balanced chunk stream through a HOST-steered mesh, plus a skewed
    stream — every flow hashing to ONE CT shard (rejection-sampled
    through the real steer hash) — through both modes. On skewed traffic
    the device path's win is structural: classify work spreads by arrival
    while host steering serializes the whole mesh behind one segment."""
    import time as _time
    from cilium_tpu.parallel.mesh import flow_shard_of
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.metrics import Metrics

    def skewed_stream(n_chunks):
        rng = np.random.default_rng(1123)
        need = n_chunks * chunk
        cols, got = None, 0
        while got < need:
            full = gen(rng, batch)
            sh = flow_shard_of(full, shards, lb=lb)
            keep = (sh == 0) & np.asarray(full["valid"], dtype=bool)
            if cols is None:
                cols = {k: [] for k in full}
            for k, v in full.items():
                cols[k].append(np.asarray(v)[keep])
            got += int(keep.sum())
        cat = {k: np.concatenate(v)[:need] for k, v in cols.items()}
        return [{k: v[j:j + chunk] for k, v in cat.items()}
                for j in range(0, need, chunk)]

    def build(mode):
        steered = mode == "host"
        cfg_m = DaemonConfig(ct_capacity=snap.ct_config.capacity,
                             probe_depth=snap.ct_config.probe_depth,
                             v4_only=cfg.v4_only,
                             batch_size=_bench_bucket(cfg, batch, shards,
                                                      mode),
                             n_shards=shards, rss_mode=mode)
        dp_m = JITDatapath(cfg_m)
        placed_m = dp_m.place(snap)

        def dispatch_fn(b, n, steer_rev=None):
            fin = dp_m.classify_async(placed_m, snap, b, n,
                                      pre_steered=steered)
            return lambda: fin()[0]
        return _bench_pipeline(
            dispatch_fn, Metrics(), cfg, batch, shards, mode,
            shard_fn=lambda b: flow_shard_of(b, shards, lb=lb))

    def one_pass(pl_m, chunk_list):
        for i in range(batches * (batch // chunk)):
            now[0] += 1
            pl_m.submit(chunk_list[i % len(chunk_list)], now=now[0])
        assert pl_m.drain(timeout=600), "rss A/B drain timed out"

    def measure_pair(chunk_list, n_windows=3):
        """Both modes over the same stream, windows INTERLEAVED with
        alternating order — rig drift (CPU freq, background load, CT
        aging) hits both modes instead of whichever ran second."""
        pls = {m: build(m) for m in ("host", "device")}
        for pl_m in pls.values():
            one_pass(pl_m, chunk_list)       # warm: traces + pools
        fps = {"host": [], "device": []}
        for w in range(n_windows):
            order = ("host", "device") if w % 2 == 0 else ("device", "host")
            for m in order:
                t1 = _time.time()
                one_pass(pls[m], chunk_list)
                fps[m].append(batches * batch / (_time.time() - t1))
        stats_pair = {m: pls[m].stats() for m in pls}
        for pl_m in pls.values():
            pl_m.close(timeout=30)
        return {m: med(v) for m, v in fps.items()}, stats_pair

    skewed = skewed_stream(max(4, min(8, len(chunks))))
    bal, _bal_st = measure_pair(chunks)
    sk, sk_st = measure_pair(skewed)
    if verbose:
        print(f"# rss A/B: balanced host={bal['host'] / 1e6:.2f} "
              f"device={bal['device'] / 1e6:.2f} Mfl/s "
              f"(primary device run: {device_balanced_fps / 1e6:.2f}); "
              f"skewed host={sk['host'] / 1e6:.2f} "
              f"device={sk['device'] / 1e6:.2f}", file=sys.stderr)
    return {
        "balanced": {
            "host_flows_per_sec": round(bal["host"], 1),
            "device_flows_per_sec": round(bal["device"], 1),
            "device_over_host": round(
                bal["device"] / max(bal["host"], 1e-9), 3),
        },
        "skewed": {
            "host_flows_per_sec": round(sk["host"], 1),
            "device_flows_per_sec": round(sk["device"], 1),
            "device_over_host": round(
                sk["device"] / max(sk["host"], 1e-9), 3),
            # the failure mode the device path retires: a steered mesh
            # under all-one-shard traffic sheds (steer_overflow) or
            # serializes — either shows here
            "host_shed_total": sk_st["host"].get("shed_total", 0),
            "device_shed_total": sk_st["device"].get("shed_total", 0),
        },
    }


#: balanced-traffic slack for the rss_gate's TPU-armed absolute half:
#: device mode must hold >= host/slack on balanced traffic and win
#: strictly on skew
RSS_AB_SLACK = float(os.environ.get("CILIUM_TPU_BENCH_RSS_AB_SLACK", "1.1"))
#: the always-armed structural gate: under the all-one-shard stream the
#: steered path must degrade at least this factor MORE than the device
#: path does (host_bal/host_sk vs dev_bal/dev_sk) — the skewed-flood
#: imbalance failure mode the exchange exists to retire, measurable on
#: any rig because it is a ratio of ratios
RSS_SKEW_IMMUNITY_MIN = float(os.environ.get(
    "CILIUM_TPU_BENCH_RSS_SKEW_IMMUNITY_MIN", "1.3"))


def _rss_gate(ab: dict, platform: str) -> dict:
    """Two-tier gate, mirroring the --kernels fused gate's platform
    split: the ABSOLUTE throughput comparison (device >= host/slack on
    balanced, strictly > on skew) arms only on TPU — on the CPU smoke
    rig the virtual mesh serializes every chip's work onto a couple of
    host cores, so the exchange's per-chip CT redundancy (the price of
    shedless skew tolerance with static shapes) inflates ~n_shards×
    in wall clock in a way n real chips never see; gating fps there
    measures the rig, not the code. The STRUCTURAL half — skew
    immunity + zero device sheds — is a ratio of ratios and always
    arms: steered throughput must collapse under the all-one-shard
    stream while the device path holds, or the whole point of the
    mode is missing."""
    reasons = []
    bal, sk = ab["balanced"], ab["skewed"]
    eps = 1e-9
    host_deg = bal["host_flows_per_sec"] / max(sk["host_flows_per_sec"],
                                               eps)
    dev_deg = bal["device_flows_per_sec"] / max(
        sk["device_flows_per_sec"], eps)
    immunity = host_deg / max(dev_deg, eps)
    if immunity < RSS_SKEW_IMMUNITY_MIN:
        reasons.append(
            f"skew immunity {immunity:.2f} < {RSS_SKEW_IMMUNITY_MIN}: "
            f"steered degrades {host_deg:.2f}x under skew vs device "
            f"{dev_deg:.2f}x — the structural win is missing")
    if sk["device_shed_total"]:
        reasons.append(
            f"skewed: device path shed {sk['device_shed_total']} "
            "submissions (no shed class should exist without steering)")
    throughput_armed = platform == "tpu"
    if throughput_armed:
        if bal["device_over_host"] < 1.0 / RSS_AB_SLACK:
            reasons.append(
                f"balanced: device {bal['device_flows_per_sec']} < host "
                f"{bal['host_flows_per_sec']}/{RSS_AB_SLACK}")
        if sk["device_over_host"] <= 1.0:
            reasons.append(
                f"skewed: device {sk['device_flows_per_sec']} <= host "
                f"{sk['host_flows_per_sec']}")
    return {
        "failed": bool(reasons),
        "slack": RSS_AB_SLACK,
        "skew_immunity_min": RSS_SKEW_IMMUNITY_MIN,
        "host_skew_degradation": round(host_deg, 3),
        "device_skew_degradation": round(dev_deg, 3),
        "skew_immunity_ratio": round(immunity, 3),
        # False = this artifact came from a rig whose absolute fps
        # comparison is unmeasurable by construction (see docstring);
        # the ROADMAP item-6 v5e pass arms it
        "throughput_gate_armed": throughput_armed,
        **({"reasons": reasons} if reasons else {}),
    }


#: max tolerated per-shard traffic skew, expressed as a multiple of the
#: fair share (1/shards of all rows) one shard may carry before the
#: artifact is failed — a healthy flow hash over uniform traffic sits
#: near 1x; one saturated shard means the mesh throughput number is a lie
SHARD_SKEW_LIMIT = float(os.environ.get(
    "CILIUM_TPU_BENCH_SHARD_SKEW_LIMIT", "3"))


def _sharded_schema_check(doc: dict, shards: int) -> dict:
    """Artifact self-check for sharded runs: the per-chip/aggregate fields
    must be present, the steer/scatter attribution must be in the split,
    the steered path must not have fallen back to allocating packs, and —
    the real balance check — every flow shard must actually have carried
    traffic within SHARD_SKEW_LIMIT of the mean (`shard_rows_total` is
    counted independently at ingest, so a steering bug that parks the work
    on one chip fails the artifact loudly instead of hiding inside an
    aggregate headline).

    Device-RSS artifacts (``doc["rss"] == "device"``) invert the span
    contract: the host ``pipeline.steer``/``datapath.steer`` spans must
    be ABSENT (their presence means the host tax the mode exists to
    delete is still being paid), and the per-shard balance check does
    not apply (rows never group by shard on the host — that is the
    point). This is what keeps steered and unsteered artifacts
    comparable under ``--compare`` without tripping the
    span-attribution gate."""
    problems = []
    rss = doc.get("rss", "host")
    if doc.get("aggregate_flows_per_sec", 0) <= 0 \
            or doc.get("per_chip_flows_per_sec", 0) <= 0:
        problems.append("missing per-chip/aggregate throughput")
    spans = {}
    spans.update(doc.get("stage_split") or {})
    spans.update(doc.get("steer_split") or {})
    spans.update(doc.get("trace_spans") or {})
    if rss == "device":
        for sp in ("pipeline.steer", "datapath.steer"):
            if sp in spans:
                problems.append(
                    f"{sp} span present in a device-RSS artifact "
                    "(host steering still running)")
    elif "pipeline.steer" not in doc.get("steer_split", {}) \
            and "pipeline.steer" not in doc.get("stage_split", {}):
        problems.append("steer span missing from the stage split")
    pack = doc.get("pack_stats") or {}
    if pack.get("pack_fallback_steered", 0):
        problems.append(
            f'pack_fallback{{reason="steered"}} = '
            f'{pack["pack_fallback_steered"]} on the steered path')
    rows = doc.get("shard_rows_total")
    if rss == "device":
        pass            # no host-side per-shard grouping exists to judge
    elif not rows or len(rows) != shards:
        problems.append("shard_rows_total missing from pipeline stats")
    elif sum(rows) >= 64 * shards:       # enough traffic to judge balance
        total = sum(rows)
        # judged as max SHARE of total vs the fair share 1/shards: the
        # max-share threshold is capped at 0.95 so the check stays live
        # for every mesh size (a max/mean formulation is mathematically
        # dead whenever the limit reaches the shard count — a 2-shard
        # mesh can never exceed 2x its mean)
        share_limit = min(0.95, SHARD_SKEW_LIMIT / shards)
        max_share = max(rows) / total
        if min(rows) == 0:
            problems.append(f"idle shard(s): shard_rows_total={rows}")
        elif max_share > share_limit:
            problems.append(
                f"shard skew: one shard carries {max_share:.0%} of rows "
                f"(> {share_limit:.0%} = {SHARD_SKEW_LIMIT}x fair share): "
                f"shard_rows_total={rows}")
    return {"schema_check": "ok" if not problems else "failed",
            **({"schema_check_problems": problems} if problems else {})}


#: BENCH_r05 reference points for the single-chip regression gate (the
#: CPU smoke rig numbers the zero-copy PR shipped with); override via env
#: when re-baselining on different hardware. NOISE_FACTOR is deliberately
#: generous — the gate exists to catch the steered-staging refactor
#: regressing the single-shard path wholesale, not 5% jitter.
REF_PACK_P50_MS = float(os.environ.get(
    "CILIUM_TPU_BENCH_REF_PACK_P50_MS", "0.116"))
REF_INGEST_FPS = float(os.environ.get(
    "CILIUM_TPU_BENCH_REF_INGEST_FPS", "0"))       # 0 = unknown, skip
BENCH_NOISE_FACTOR = float(os.environ.get(
    "CILIUM_TPU_BENCH_NOISE_FACTOR", "1.75"))


def _single_chip_regression_gate(spans: dict, fps: float) -> dict:
    """--shards 1 gate: the steered-staging refactor must not tax the
    single-chip path — fail the artifact when pack p50 (or, with a known
    reference, end-to-end fps) regresses beyond noise vs BENCH_r05."""
    gate = {
        "pack_p50_ms": spans.get("datapath.pack", {}).get("p50_ms"),
        "ref_pack_p50_ms": REF_PACK_P50_MS,
        "steer_p50_ms": spans.get("pipeline.steer", {}).get("p50_ms", 0.0),
        "fps": round(fps, 1),
        "ref_fps": REF_INGEST_FPS or None,
        "noise_factor": BENCH_NOISE_FACTOR,
        # the default reference is the BENCH_r05 CPU smoke rig: a `failed`
        # verdict from a different-speed machine with no env-pinned
        # baseline is a rig mismatch, not a regression — consumers can
        # tell from this field
        "ref_source": "env" if "CILIUM_TPU_BENCH_REF_PACK_P50_MS"
                      in os.environ else "BENCH_r05-default",
    }
    reasons = []
    p50 = gate["pack_p50_ms"]
    if p50 is not None and REF_PACK_P50_MS > 0 \
            and p50 > REF_PACK_P50_MS * BENCH_NOISE_FACTOR:
        reasons.append(f"pack p50 {p50}ms > "
                       f"{REF_PACK_P50_MS}*{BENCH_NOISE_FACTOR}ms")
    if REF_INGEST_FPS > 0 and fps < REF_INGEST_FPS / BENCH_NOISE_FACTOR:
        reasons.append(f"fps {fps:.0f} < "
                       f"{REF_INGEST_FPS}/{BENCH_NOISE_FACTOR}")
    gate["failed"] = bool(reasons)
    if reasons:
        gate["reasons"] = reasons
    return gate


def ingest_bench(preset: str, batch: int, n_frames: int = 0,
                 verbose: bool = False, shards: int = 1,
                 observer: bool = False, rss: str = "host"):
    """Shim→verdict end-to-end over the mock rings: frames are injected
    NIC-side into the rx ring, the async feeder (shim/feeder.py) harvests
    on a budget into reusable poll buffers, the pipeline coalesces and
    dispatches through ``classify_async`` with in-place pack + pinned
    staging, and verdicts apply FIFO back into the shim (forwarded frames
    drain from the tx ring). Tracing runs at sampling 1.0 so the JSON
    artifact carries the full harvest/stage/pack/transfer/compute split
    plus staging-ring occupancy — where the remaining gap lives."""
    from cilium_tpu.observe.trace import TRACER
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import JITDatapath
    from cilium_tpu.runtime.engine import Engine
    from cilium_tpu.shim.bindings import LIB_PATH, FlowShim, build_frame

    if not os.path.exists(LIB_PATH):
        return {"metric": "ingest_shim_to_verdict", "value": 0,
                "unit": "frames/sec", "vs_baseline": 0,
                "error": f"{LIB_PATH} not built (make shim)"}
    if n_frames <= 0:
        n_frames = 10_000 if preset == "smoke" else 100_000
    TRACER.configure(sample_rate=1.0, capacity=65536)
    TRACER.reset()
    from cilium_tpu.model.rules import parse_rule
    cfg = DaemonConfig(ct_capacity=1 << (14 if preset == "smoke" else 18),
                       auto_regen=False, batch_size=batch,
                       pipeline_flush_ms=1.0, pipeline_queue_batches=256,
                       ingest_pool_batches=8,
                       # the observer A/B soak needs the columnar ring
                       # armed in BOTH windows (the flowlog predates this
                       # bench; what's measured is the observe machinery)
                       flowlog_mode="all" if observer else "none",
                       n_shards=shards,
                       rss_mode=rss if shards > 1 else "host")
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    # a non-trivial ruleset so classification isn't a no-op: cfg1-style
    # CIDR allow/deny slice
    rules = []
    for i in range(200):
        a, b = 1 + (i % 200), (i * 7) % 256
        block = {"toCIDR": [f"{a}.{b}.0.0/16"]}
        key = "egressDeny" if i % 3 == 2 else "egress"
        rules.append(parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            key: [block]}))
    eng.repo.add(rules)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toCIDR": ["10.0.0.0/8"],
                    "toPorts": [{"ports": [{"port": "443",
                                            "protocol": "TCP"}]}]}]}])
    eng.regenerate()

    shim_batch = min(256, batch)
    shim = FlowShim(batch_size=shim_batch, timeout_us=200)
    shim.register_endpoint("192.168.1.10", 1)
    shim.mock_rings_init(ring_size=256, frame_size=2048, n_frames=256)
    feeder = eng.start_feeder(shim)

    # pre-build the frame set (frame crafting is not the measured path)
    rng = np.random.default_rng(11)
    pool = [build_frame("192.168.1.10",
                        f"10.{rng.integers(0, 4)}.2.{rng.integers(1, 250)}"
                        if i % 4 else f"{1 + i % 200}.9.9.9",
                        40000 + (i % 20000),
                        443 if i % 4 else 80)
            for i in range(512)]
    # warmup: the first dispatches JIT-compile the classify shapes
    for f in pool[:64]:
        shim.mock_rx_inject(f)
    deadline = time.time() + 120
    while time.time() < deadline:
        shim.mock_tx_drain(256)
        st = shim.stats()
        if st["verdict_passes"] + st["verdict_drops"] >= 64:
            break
        time.sleep(0.005)
    base = shim.stats()
    done_base = base["verdict_passes"] + base["verdict_drops"] \
        + base["tx_full_drops"]
    TRACER.reset()     # drop warmup spans (cold XLA compile) from the split
    # e2e baseline for the same reason: the p50/p99 split is computed from
    # the DELTA bucket counts over the measured window, so the cold-compile
    # warmup batches can't dominate the tail
    _e2e = eng.metrics.histograms.get("ingest_e2e_latency_seconds")
    e2e_base = list(_e2e.snapshot()[1]) if _e2e is not None else None
    slo_base = feeder.slo_burns          # same window discipline for burns

    t0 = time.time()
    injected = 0
    stalls = 0
    deadline = time.time() + 600
    while injected < n_frames and time.time() < deadline:
        if shim.mock_rx_inject(pool[injected % len(pool)]) == 0:
            injected += 1
        else:
            shim.mock_tx_drain(256)
            stalls += 1
            if stalls % 64 == 0:
                time.sleep(0.0002)
    timed_out = True
    while time.time() < deadline:
        shim.mock_tx_drain(256)
        st = shim.stats()
        if st["verdict_passes"] + st["verdict_drops"] \
                + st["tx_full_drops"] - done_base >= injected:
            timed_out = False
            break
        time.sleep(0.002)
    elapsed = time.time() - t0
    fps = injected / max(elapsed, 1e-9)

    pstats = eng.pipeline_stats() or {}
    fstats = feeder.stats()
    pack_stats = dict(eng.datapath.pack_stats)
    # measured-window e2e split (delta bucket counts vs the post-warmup
    # baseline; EMPTY_QUANTILE → 0.0 when nothing applied in the window)
    from cilium_tpu.runtime.metrics import quantile_from, quantile_is_empty
    e2e_p50_ms = e2e_p99_ms = 0.0
    hist = eng.metrics.histograms.get("ingest_e2e_latency_seconds")
    if hist is not None:
        hb, hc, _ht, _hn = hist.snapshot()
        if e2e_base is not None:
            hc = [a - b for a, b in zip(hc, e2e_base)]
        p50, p99 = quantile_from(hb, hc, 0.5), quantile_from(hb, hc, 0.99)
        if not quantile_is_empty(p50):
            e2e_p50_ms = round(p50 * 1e3, 3)
            e2e_p99_ms = round(p99 * 1e3, 3)
    spans = TRACER.summary()
    keep = ("shim.harvest", "pipeline.steer", "pipeline.stage_write",
            "pipeline.microbatch", "pipeline.dispatch", "pipeline.finalize",
            "datapath.pack", "datapath.steer", "datapath.transfer",
            "datapath.compute")

    # -- observer overhead attestation (ISSUE 11 acceptance): D/A/D/A
    # windows over the warm engine — disarmed vs a live follow-mode
    # observer polling every 5ms with a compound filter armed (verdict +
    # port + CIDR; selective, so matched rows are payload, not noise).
    # Best-of-two per arm absorbs rig noise; the <2% budget is recorded
    # (and gated by `make observe-smoke`) in the artifact.
    observer_doc = None
    if observer:
        import threading as _threading

        from cilium_tpu.observe.observer import (FlowFilter, FlowObserver,
                                                 FollowCursor)
        obs_filters = [FlowFilter(verdict="DROPPED", dports=(9999,),
                                  dst_cidrs=("10.0.0.0/8",))]

        def _window(n, armed):
            stop_evt = _threading.Event()
            fstat = {"polls": 0, "matched": 0, "gaps": 0, "dropped": 0,
                     "poll_busy_s": 0.0}

            samples = []

            def _follow():
                cur = FollowCursor(FlowObserver(eng.flowlog),
                                   allow=obs_filters)
                # Per-poll durations are sampled and summarized as
                # median x count: a raw wall-time sum would bill GIL /
                # scheduler descheduling (10ms quanta) to a ~20us poll,
                # and thread_time's granularity is coarser than the polls
                # themselves. 5ms cadence is already 60x the CLI
                # follower's 300ms poll; per-tick cost scales with
                # throughput (records since last tick), not cadence.
                while not stop_evt.is_set():
                    p_t0 = time.perf_counter()
                    for r in cur.poll(limit=8192):
                        if r.get("gap"):
                            fstat["gaps"] += 1
                            fstat["dropped"] += r["dropped"]
                        else:
                            fstat["matched"] += 1
                    samples.append(time.perf_counter() - p_t0)
                    fstat["polls"] += 1
                    time.sleep(0.005)

            th = None
            if armed:
                th = _threading.Thread(target=_follow, daemon=True)
                th.start()
            st0 = shim.stats()
            done0 = st0["verdict_passes"] + st0["verdict_drops"] \
                + st0["tx_full_drops"]
            w_t0 = time.time()
            inj = stl = 0
            w_dl = time.time() + 240
            while inj < n and time.time() < w_dl:
                if shim.mock_rx_inject(pool[inj % len(pool)]) == 0:
                    inj += 1
                else:
                    shim.mock_tx_drain(256)
                    stl += 1
                    if stl % 64 == 0:
                        time.sleep(0.0002)
            while time.time() < w_dl:
                shim.mock_tx_drain(256)
                s = shim.stats()
                if s["verdict_passes"] + s["verdict_drops"] \
                        + s["tx_full_drops"] - done0 >= inj:
                    break
                time.sleep(0.002)
            w_elapsed = max(time.time() - w_t0, 1e-9)
            if th is not None:
                stop_evt.set()
                th.join(5)
            if samples:
                med = sorted(samples)[len(samples) // 2]
                fstat["poll_p50_us"] = round(med * 1e6, 1)
                fstat["poll_busy_s"] = med * fstat["polls"]
            fstat["elapsed_s"] = round(w_elapsed, 4)
            fstat["poll_busy_s"] = round(fstat["poll_busy_s"], 5)
            return inj / w_elapsed, fstat

        # The GATED overhead is the observer's measured serving-time share
        # during the armed windows (summed in-poll time / window time):
        # deterministic where wall-clock fps windows on a shared CPU rig
        # swing 2-3x from CT drift / GC ticks / scheduler noise — far
        # above a 2% signal. The D/A fps windows still ride the artifact
        # as context (best-of per arm), with a loose 25% sanity ratio.
        w_n = max(1500, n_frames // 8)
        _window(w_n, False)              # warmup (not recorded)
        # calibrate the per-poll cost synchronously on the LIVE ring (a
        # representative 64-record backlog, filters armed): in-window
        # wall samples bill GIL handoffs — time the pipeline is actually
        # serving — to the observer, so the attested overhead is
        # calibrated-cost x observed polls over armed serving time (the
        # audit-smoke attestation form, executed in the bench)
        cal = FollowCursor(FlowObserver(eng.flowlog), allow=obs_filters)
        cal_newest = eng.flowlog.newest_seq
        cal_durs = []
        for _ in range(200):
            cal.cursor = max(0, cal_newest - 64)
            c_t0 = time.perf_counter()
            cal.poll(limit=8192)
            cal_durs.append(time.perf_counter() - c_t0)
        per_poll_s = sorted(cal_durs)[len(cal_durs) // 2]
        obs_runs = []
        for armed in (False, True) * 4:
            w_fps, fstat = _window(w_n, armed)
            obs_runs.append({"armed": armed, "fps": round(w_fps, 1),
                             **(fstat if armed else {})})
        fps_dis = max(r["fps"] for r in obs_runs if not r["armed"])
        fps_arm = max(r["fps"] for r in obs_runs if r["armed"])
        polls_total = sum(r.get("polls", 0) for r in obs_runs)
        span = sum(r["elapsed_s"] for r in obs_runs if r["armed"])
        busy = per_poll_s * polls_total
        ovh = busy / max(span, 1e-9)
        fps_ratio = fps_arm / max(fps_dis, 1e-9)
        observer_doc = {
            "windows": obs_runs, "frames_per_window": w_n,
            "fps_armed": fps_arm, "fps_disarmed": fps_dis,
            "fps_ratio": round(fps_ratio, 4),
            "calibrated_poll_us": round(per_poll_s * 1e6, 1),
            "polls": polls_total,
            "poll_busy_s": round(busy, 5),
            "armed_elapsed_s": round(span, 4),
            "overhead_pct": round(ovh * 100, 2),
            "budget_pct": 2.0,
            # the gate: calibrated observer cost share < 2%, plus a
            # catastrophic-only fps guard — best-of-4 windows on a shared
            # rig still swing ~30% from CT drift and scheduler noise, so
            # anything tighter than 2x would gate on the rig, not the code
            "ok": bool(ovh < 0.02 and fps_ratio > 0.5),
        }
    eng.stop()
    st = shim.stats()
    shim.close()
    if verbose:
        print(f"# ingest bench preset={preset} frames={injected} "
              f"elapsed={elapsed:.2f}s fps={fps / 1e6:.3f}M "
              f"passes={st['verdict_passes']} drops={st['verdict_drops']} "
              f"tx_full={st['tx_full_drops']}", file=sys.stderr)
    doc = {
        "metric": "ingest_shim_to_verdict",
        "value": round(fps, 1),
        "unit": "frames/sec",
        "vs_baseline": round(fps / PER_CHIP_TARGET, 4),
        "frames": injected,
        "elapsed_s": round(elapsed, 3),
        # a wedged pipeline must be distinguishable from a clean run —
        # with this set, `value` is a floor, not a measurement
        **({"timed_out": True} if timed_out else {}),
        "verdict_passes": int(st["verdict_passes"]),
        "verdict_drops": int(st["verdict_drops"]),
        "tx_full_drops": int(st["tx_full_drops"]),
        "shim_batch": shim_batch,
        "batch": batch,
        "preset": preset,
        # the per-stage attribution the issue asks for: where host time
        # goes between the rx ring and the verdict bitmap
        "stage_split": {k: spans[k] for k in keep if k in spans},
        # the TRUE ingest→verdict split (harvest stamp → verdict apply,
        # through queue + staging + device + FIFO head-of-line): per-stage
        # spans above attribute it, these two numbers ARE it — computed
        # over the measured window only (warmup-compile batches excluded)
        "e2e_p50_ms": e2e_p50_ms,
        "e2e_p99_ms": e2e_p99_ms,
        "slo_burns": fstats.get("slo_burns", 0) - slo_base,
        "staging_free": pstats.get("staging_free"),
        "staging_slots": pstats.get("staging_slots"),
        "fill_ratio": pstats.get("fill_ratio_avg"),
        "flush_reasons": pstats.get("flush_reasons"),
        "shed_reasons": pstats.get("shed_reasons"),
        "pack_stats": pack_stats,
        "feeder": fstats,
        **({"observer_soak": observer_doc} if observer_doc else {}),
    }
    if shards > 1:
        doc.update({
            "shards": shards,
            "rss": rss,
            "aggregate_frames_per_sec": round(fps, 1),
            "per_chip_frames_per_sec": round(fps / shards, 1),
            "aggregate_flows_per_sec": round(fps, 1),
            "per_chip_flows_per_sec": round(fps / shards, 1),
            **({"shard_fill": pstats.get("shard_fill"),
                "shard_rows_total": pstats.get("shard_rows_total"),
                "shard_capacity": pstats.get("shard_capacity")}
               if rss != "device" else {}),
        })
        doc.update(_sharded_schema_check(doc, shards))
    else:
        # satellite gate: the refactored (shard-capable) staging path must
        # stay within noise of BENCH_r05 on the single-chip configuration
        doc["regression_gate"] = _single_chip_regression_gate(
            doc["stage_split"], fps)
    return doc


def kernels_bench(config: int, preset: str, batch: int, batches: int,
                  verbose: bool = False, fused_mode: str = "auto"):
    """Per-kernel compute-only microbench of the classify interior
    (ROADMAP item 2 attribution): the LPM stride walk, the CT probe pair,
    the policy ladder + L7 matcher + verdict composition, and the full
    classify step — each as its own jitted program over device-resident
    batches, timed through the observe tracer's per-kernel span names
    (``datapath.kernel.*``) so the artifact's p50/p99 flow through the same
    machinery as the serving-path stage split.

    Executors: the jnp reference always runs. The fused Pallas path
    (kernels/fused.py) is timed only where it actually compiles —
    ``fused_mode`` resolved exactly like the serving selector
    (``DaemonConfig.fused_kernels``) — because interpret-mode wall time
    measures the Pallas *interpreter*, not the kernel. Off-TPU the fused
    path is instead PARITY-checked in interpret mode (bit-identical outputs
    + CT against the jnp reference over every pre-generated batch), so the
    artifact still proves the fused interior before a TPU ever runs it;
    the cfg3/cfg4 compute_only movement toward the cfg2 ceiling is the
    v5e-8 expectation this artifact exists to verify (ROADMAP item 5).
    """
    import jax
    import jax.numpy as jnp
    from cilium_tpu.compile.ct_layout import make_ct_arrays
    from cilium_tpu.kernels import conntrack as ctk
    from cilium_tpu.kernels import fused as fk
    from cilium_tpu.kernels.classify import (classify_interior_core,
                                             classify_step)
    from cilium_tpu.kernels.lpm import lpm_lookup_batch
    from cilium_tpu.observe.trace import (KERNEL_SPAN_CT_PROBE,
                                          KERNEL_SPAN_FULL, KERNEL_SPAN_LPM,
                                          KERNEL_SPAN_POLICY_L7, Tracer)
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.datapath import resolve_fused
    from cilium_tpu.utils import constants as C

    t0 = time.time()
    snap, gen, v4_only = BUILDERS[config](preset)
    compile_s = time.time() - t0
    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    make_ct = lambda: {k: jnp.asarray(v)  # noqa: E731
                       for k, v in make_ct_arrays(snap.ct_config).items()}
    ct = make_ct()
    rng = np.random.default_rng(7)
    host = [gen(rng, batch) for _ in range(min(batches, 8))]
    dev = [{k: jnp.asarray(v) for k, v in hb.items()} for hb in host]
    jax.block_until_ready(dev)
    wi = jnp.int32(snap.world_index)

    fused_active, interpret = resolve_fused(
        DaemonConfig(fused_kernels=fused_mode))
    plan = fk.fuse_plan(tensors, ct, v4_only=v4_only)
    time_fused = fused_active and not interpret   # compiled Pallas only

    def _stage_fns(use_fused):
        """One jitted program per interior stage; ``use_fused`` swaps the
        executor, nothing else. The fuse_plan geometry gate applies per
        stage exactly as classify_step applies it in serving — a gated
        stage times its real executor (the jnp reference), never a
        kernel the serving path would refuse."""
        def lpm_fn(tensors, b, wi):
            rw = jnp.where((b["direction"] == C.DIR_EGRESS)[:, None],
                           b["dst"], b["src"])
            if use_fused and plan.lpm:
                return fk.lpm_lookup_fused(
                    tensors["lpm_v4"], tensors["lpm_v6"], rw, b["is_v6"],
                    wi, v4_only=v4_only, interpret=interpret)
            return lpm_lookup_batch(tensors["lpm_v4"], tensors["lpm_v6"],
                                    rw, b["is_v6"], default_index=wi,
                                    v4_only=v4_only)

        def ct_fn(ct, b, now):
            fwd, rev = ctk.ct_key_words_pair(b)
            if use_fused and plan.ct:
                return fk.ct_probe_pair_fused(
                    ct, fwd, rev, now, snap.ct_config.probe_depth,
                    interpret=interpret)
            return (ctk.ct_probe(ct, fwd, now, snap.ct_config.probe_depth),
                    ctk.ct_probe(ct, rev, now, snap.ct_config.probe_depth))

        def pol_fn(tensors, b, id_idx, est, reply):
            args = (tensors, b["ep_slot"], b["direction"], id_idx,
                    b["proto"], b["dport"], b["http_method"],
                    b["http_path"], est, reply, b["valid"])
            if use_fused and plan.policy:
                return fk.policy_verdict_fused(*args, interpret=interpret)
            return classify_interior_core(*args)

        def full_fn(tensors, ct, b, now, wi):
            return classify_step(tensors, ct, b, now, wi,
                                 probe_depth=snap.ct_config.probe_depth,
                                 v4_only=v4_only, fused=use_fused,
                                 fused_interpret=interpret)
        return {
            KERNEL_SPAN_LPM: jax.jit(lpm_fn),
            KERNEL_SPAN_CT_PROBE: jax.jit(ct_fn),
            KERNEL_SPAN_POLICY_L7: jax.jit(pol_fn),
            KERNEL_SPAN_FULL: jax.jit(full_fn, donate_argnums=(1,)),
        }

    # staged inputs shared by the lpm/ct/policy micro-stages: id_idx from a
    # reference LPM pass; est/reply against the empty table (all-new flows
    # — the ladder cost is est-independent, it is branch-free)
    ref = _stage_fns(False)
    id_idx0 = [ref[KERNEL_SPAN_LPM](tensors, b, wi) for b in dev]
    n = batch
    false_col = jnp.zeros((n,), dtype=bool)
    jax.block_until_ready(id_idx0)

    tracer = Tracer(sample_rate=1.0, capacity=1 << 14)
    now_ctr = [20_000]

    def _run(span_name, fns, reps):
        """Time one stage ``reps`` times through the tracer (span per
        call, device-fenced). The full step threads donated CT."""
        nonlocal ct
        calls = {
            KERNEL_SPAN_LPM:
                lambda i: fns[KERNEL_SPAN_LPM](
                    tensors, dev[i % len(dev)], wi),
            KERNEL_SPAN_CT_PROBE:
                lambda i: fns[KERNEL_SPAN_CT_PROBE](
                    ct, dev[i % len(dev)], jnp.uint32(now_ctr[0])),
            KERNEL_SPAN_POLICY_L7:
                lambda i: fns[KERNEL_SPAN_POLICY_L7](
                    tensors, dev[i % len(dev)], id_idx0[i % len(dev)],
                    false_col, false_col),
        }
        if span_name == KERNEL_SPAN_FULL:
            def call(i):
                nonlocal ct
                now_ctr[0] += 1
                out, ct, _ = fns[KERNEL_SPAN_FULL](
                    tensors, ct, dev[i % len(dev)],
                    jnp.uint32(now_ctr[0]), wi)
                return out
        else:
            call = calls[span_name]
        jax.block_until_ready(call(0))               # warmup/compile
        for r in range(reps):
            tid = tracer.maybe_sample()
            with tracer.span(tid, span_name):
                jax.block_until_ready(call(r))

    reps = max(8, min(100, batches * 4))
    stage_names = (KERNEL_SPAN_LPM, KERNEL_SPAN_CT_PROBE,
                   KERNEL_SPAN_POLICY_L7, KERNEL_SPAN_FULL)
    for name in stage_names:
        _run(name, ref, reps)
    jnp_summary = tracer.summary()

    fused_summary = None
    if time_fused:
        tracer.reset()
        tracer.configure(sample_rate=1.0)
        ct = make_ct()
        fus = _stage_fns(True)
        for name in stage_names:
            _run(name, fus, reps)
        fused_summary = tracer.summary()

    def _stage_doc(summary):
        out = {}
        for name in stage_names:
            s = summary.get(name)
            if s is None:
                continue
            key = name.rsplit(".", 1)[1]
            out[key] = {
                "p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"],
                "flows_per_s": round(batch / (s["p50_ms"] / 1e3), 1),
            }
        return out

    # interpret-mode parity: the CPU-CI proof that the fused interior is
    # bit-identical (outputs + CT + counters) to the jnp reference
    parity = None
    if fused_active and interpret:
        ct_a, ct_b = make_ct(), make_ct()
        rows = 0
        for i, b in enumerate(dev):
            now = jnp.uint32(30_000 + i)
            out_a, ct_a, cnt_a = classify_step(
                tensors, ct_a, b, now, wi, v4_only=v4_only)
            out_b, ct_b, cnt_b = classify_step(
                tensors, ct_b, b, now, wi, v4_only=v4_only,
                fused=True, fused_interpret=True)
            for k in out_a:
                np.testing.assert_array_equal(
                    np.asarray(out_a[k]), np.asarray(out_b[k]), k)
            for k in ct_a:
                np.testing.assert_array_equal(
                    np.asarray(ct_a[k]), np.asarray(ct_b[k]), k)
            for k in cnt_a:
                np.testing.assert_array_equal(
                    np.asarray(cnt_a[k]), np.asarray(cnt_b[k]), k)
            rows += int(np.asarray(b["valid"]).shape[0])
        parity = {"ok": True, "batches": len(dev), "rows": rows}

    kernels = _stage_doc(jnp_summary)
    full = kernels.get("full_step", {})
    result = {
        "metric": f"kernel_compute_only_{METRIC_NAMES[config]}",
        "value": full.get("flows_per_s", 0.0),
        "unit": "flows/sec/chip",
        "vs_baseline": round(full.get("flows_per_s", 0.0)
                             / PER_CHIP_TARGET, 4),
        "compute_only": full.get("flows_per_s", 0.0),
        "batch": batch,
        "preset": preset,
        "reps": reps,
        "compile_s": round(compile_s, 1),
        "kernels": kernels,
        "fused": {
            "mode": fused_mode,
            "active": fused_active,
            "interpret": interpret,
            "plan": {"lpm": plan.lpm, "ct": plan.ct, "policy": plan.policy},
            **({"interpret_parity": parity} if parity is not None else {}),
        },
    }
    if fused_summary is not None:
        fdoc = _stage_doc(fused_summary)
        result["kernels_fused"] = fdoc
        # the no-regression gate: a compiled fused kernel slower than the
        # reference it replaces fails the artifact (main exits 4)
        gate = {}
        regressions = []
        for key, ref_doc in kernels.items():
            fd = fdoc.get(key)
            if fd is None or ref_doc["p50_ms"] <= 0:
                continue
            ratio = fd["p50_ms"] / ref_doc["p50_ms"]
            gate[key] = round(ratio, 4)
            if ratio > 1.05:
                regressions.append(
                    f"{key}: fused p50 {fd['p50_ms']}ms > jnp "
                    f"{ref_doc['p50_ms']}ms")
        result["fused_gate"] = {
            "p50_ratio_fused_over_jnp": gate,
            "failed": bool(regressions),
            **({"regressions": regressions} if regressions else {}),
        }
    if verbose:
        print(f"# kernels config={config} preset={preset} batch={batch} "
              f"reps={reps} fused_active={fused_active} "
              f"interpret={interpret} plan={plan}", file=sys.stderr)
        for key, d in kernels.items():
            print(f"#   {key}: p50={d['p50_ms']}ms p99={d['p99_ms']}ms "
                  f"({d['flows_per_s'] / 1e6:.1f} Mfl/s)", file=sys.stderr)
    return result


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=5, choices=sorted(BUILDERS))
    ap.add_argument("--preset", default="auto",
                    choices=["auto", "smoke", "full"])
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--batches", type=int, default=0)
    ap.add_argument("--only", action="store_true",
                    help="run just --config (default: all five, with "
                         "--config as the headline metric)")
    ap.add_argument("--pipeline", action="store_true",
                    help="pipelined-ingestion mode: serial vs overlapped "
                         "(pipeline/scheduler.py) throughput on --config, "
                         "one JSON line with queue-wait and fill-ratio")
    ap.add_argument("--trace", action="store_true",
                    help="with --pipeline: record observe/trace spans at "
                         "sampling 1.0 and emit the per-stage p50/p99 "
                         "summary in the JSON artifact")
    ap.add_argument("--ingest", action="store_true",
                    help="shim→verdict end-to-end over mock rings through "
                         "the async feeder + pipeline (shim/feeder.py): "
                         "one JSON line with the harvest/stage/pack/"
                         "transfer/compute split and staging-ring "
                         "occupancy")
    ap.add_argument("--frames", type=int, default=0,
                    help="with --ingest: frames to push (default "
                         "10k smoke / 100k full)")
    ap.add_argument("--observer", action="store_true",
                    help="with --ingest: append a D/A/D/A observer "
                         "overhead soak (flowlog armed, a 5ms-cadence "
                         "follow observer with compound filters vs "
                         "disarmed) and record the <2%% attestation in "
                         "the artifact as `observer_soak`")
    ap.add_argument("--update-storm", action="store_true",
                    help="live policy patching under pipelined traffic: "
                         "rule add/remove p50/p99 with the host/device "
                         "span split, parity-audited at sampling 1.0, "
                         "plus the overlapped-CT-GC on/off churn "
                         "comparison; gate failures exit 4")
    ap.add_argument("--updates", type=int, default=0,
                    help="with --update-storm: rule toggles to time "
                         "(default 40 smoke / 120 full)")
    ap.add_argument("--ddos", action="store_true",
                    help="cfg6 adversarial drop-storm: a randomized-source "
                         "SYN flood saturates a small CT table over the "
                         "live pipelined engine while established flows "
                         "keep serving — reports survival rate, legit e2e "
                         "p99, CT occupancy trajectory, overload-ladder "
                         "dwell times; auditor at sampling 1.0; gate "
                         "failures exit 4")
    ap.add_argument("--tenants", action="store_true",
                    help="cfg8 mixed-tenant QoS isolation: gold (lane) + "
                         "silver victims keep serving while a weight-1 "
                         "bulk tenant replays the cfg6 SYN storm through "
                         "the same pipeline — reports victim survival, "
                         "lane e2e p99 vs unloaded baseline, and the DRR "
                         "admitted-row shares vs the 4:2:1 weights; "
                         "auditor at sampling 1.0; gate failures exit 4")
    ap.add_argument("--fqdn", action="store_true",
                    help="cfg9 FQDN churn: toFQDNs policy under a DNS "
                         "storm on the pipelined engine — stable names "
                         "keep their established flows serving while "
                         "short-TTL churn names grow AND retire "
                         "identities through the delta path every tick; "
                         "reports refresh p50/p99 vs the delta budget, "
                         "established survival, full-rebuild count "
                         "(must be 0); auditor at sampling 1.0; gate "
                         "failures exit 4")
    ap.add_argument("--chiploss", action="store_true",
                    help="cfg10 chip-loss: kill one mesh device mid-"
                         "storm, fenced re-mesh onto survivors with CT "
                         "salvage + grace window, then heal back to "
                         "full width (gated by chiploss_gate, exit 4; "
                         "--shards picks the mesh width, default 4)")
    ap.add_argument("--cluster", type=int, default=0, metavar="N",
                    help="cfg7 multi-host serving: N engine PROCESSES over "
                         "one clustermesh store (runtime/cluster.py) — "
                         "converge (delta-patch ingest), cross-boundary "
                         "serve with the auditor at 1.0, chaos (store "
                         "partition / peer kill+restart / conflicting "
                         "claims / skewed clock), relay fan-in over the "
                         "nodes' flowlogs; reports aggregate fps + "
                         "replication-lag p99; gate failures exit 4")
    ap.add_argument("--kernels", action="store_true",
                    help="per-kernel compute-only microbench of the "
                         "classify interior (lpm / ct_probe / policy_l7 / "
                         "full_step p50+p99 via the datapath.kernel.* "
                         "spans); times the fused Pallas path where it "
                         "compiles and parity-checks it in interpret mode "
                         "elsewhere")
    ap.add_argument("--fused", default="auto", choices=["auto", "on", "off"],
                    help="with --kernels: fused-kernel selector resolved "
                         "exactly like DaemonConfig.fused_kernels")
    ap.add_argument("--hbm-report", metavar="VERIFY.json",
                    help="embed a `cilium-tpu verify --report` sweep's HBM "
                         "budget summary into the artifact's provenance "
                         "(offline --max-hbm-bytes verification and the "
                         "live HBM ledger citing the same numbers)")
    ap.add_argument("--compare", metavar="OLD.json",
                    help="diff this run against a prior JSON artifact "
                         "(pack/fps/e2e ratio-checked against "
                         "CILIUM_TPU_BENCH_COMPARE_FACTOR, default 1.75); "
                         "a regression past the factor fails the run "
                         "(exit 4)")
    ap.add_argument("--shards", type=int, default=1,
                    help="flow shards (data-parallel mesh axis); >1 routes "
                         "through the production multi-chip path — with "
                         "--pipeline/--ingest: steered staging + per-shard "
                         "wire segments behind one admission queue, "
                         "reporting per-chip AND aggregate flows/s plus "
                         "the steer/scatter span split")
    ap.add_argument("--rule-shards", type=int, default=1,
                    help="verdict-row shards (rule-space mesh axis)")
    ap.add_argument("--rss", default="host", choices=["host", "device"],
                    help="with --shards > 1: where flow→shard resolution "
                         "runs — 'host' = the steered staging path, "
                         "'device' = the in-kernel ring ppermute CT "
                         "exchange (no host steer/scatter; with "
                         "--pipeline the artifact carries a "
                         "steered-vs-unsteered A/B incl. a skewed-"
                         "traffic case, gated by rss_gate)")
    ap.add_argument("--windows", type=int, default=5,
                    help="timing windows per mode (median+IQR reported)")
    ap.add_argument("--profile", default="", metavar="DIR",
                    help="write an XProf trace of one steady-state window "
                         "to DIR (jax.profiler.trace)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.hbm_report:
        with open(args.hbm_report) as f:
            _HBM_REPORT["budget"] = json.load(f).get("budget")

    import os

    if args.chiploss and args.shards <= 1:
        args.shards = 4                # the cfg10 default mesh width
    need = args.shards * args.rule_shards
    if need > 1 and not os.environ.get("CILIUM_TPU_BENCH_REAL_MESH"):
        # a virtual CPU mesh on a 1-chip rig. The env vars must land
        # BEFORE the first jax import (jax < 0.5 has no
        # jax_num_cpu_devices config; XLA_FLAGS is the only knob) — and
        # the config.update below still runs as a belt-and-braces for
        # images whose sitecustomize TPU-plugin registration imports jax
        # first. On a real multi-chip rig set CILIUM_TPU_BENCH_REAL_MESH=1
        # to use the live TPU devices instead.
        import re
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={need}").strip()
        elif int(m.group(1)) < need:
            # an inherited flag (e.g. the Makefile's 8) smaller than the
            # requested mesh would die later in make_mesh — raise it
            os.environ["XLA_FLAGS"] = flags.replace(
                m.group(0),
                f"--xla_force_host_platform_device_count={need}")
    import jax
    if need > 1 and not os.environ.get("CILIUM_TPU_BENCH_REAL_MESH"):
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_num_cpu_devices", need)
        except Exception:
            pass                       # backend already live; make_mesh checks
    platform = jax.devices()[0].platform
    preset = args.preset
    if preset == "auto":
        preset = "smoke" if platform == "cpu" else "full"
    # 64k records ≈ 2.9MB packed — big enough to amortize dispatch, small
    # enough to stay under the transport's fast-path transfer size
    batch = args.batch or (4096 if preset == "smoke" else 65536)
    batches = args.batches or (10 if preset == "smoke" else 40)

    def _finish(result) -> None:
        """Shared artifact tail: provenance stamp, optional --compare gate
        (exit 4 on regression past the factor), one JSON line. Device-RSS
        A/B deltas ride into the provenance block so a later --compare
        against this artifact carries the steered-vs-unsteered evidence."""
        result["provenance"] = _provenance(argv)
        if result.get("rss_ab"):
            result["provenance"]["rss_ab"] = result["rss_ab"]
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("rss_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)

    _start_watchdog(METRIC_NAMES[args.config])
    if args.cluster:
        if args.cluster < 2:
            ap.error("--cluster needs N >= 2")
        result = cluster_bench(args.cluster, preset, verbose=args.verbose)
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("cluster_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.kernels:
        result = kernels_bench(args.config, preset, batch, batches,
                               verbose=args.verbose, fused_mode=args.fused)
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("fused_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.update_storm:
        result = update_storm_bench(preset, updates=args.updates,
                                    verbose=args.verbose)
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("storm_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.tenants:
        result = tenants_bench(preset, verbose=args.verbose,
                               batch=min(batch, 256))
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("qos_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.fqdn:
        result = fqdn_bench(preset, verbose=args.verbose,
                            batch=min(batch, 256))
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("fqdn_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.chiploss:
        result = chiploss_bench(preset, verbose=args.verbose,
                                batch=min(batch, 256), shards=args.shards)
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("chiploss_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.ddos:
        result = ddos_bench(preset, verbose=args.verbose,
                            batch=min(batch, 256))
        result["provenance"] = _provenance(argv)
        rc = 0
        if args.compare:
            result["compare"] = _compare_artifacts(result, args.compare)
            if result["compare"]["failed"]:
                rc = 4
        if result.get("ddos_gate", {}).get("failed"):
            rc = 4
        _progress["headline"] = result
        print(json.dumps(result))
        if rc:
            sys.exit(rc)
        return
    if args.ingest:
        result = ingest_bench(preset, batch, n_frames=args.frames,
                              verbose=args.verbose, shards=args.shards,
                              observer=args.observer, rss=args.rss)
        _finish(result)
        return
    if args.pipeline:
        result = pipeline_bench(args.config, preset, batch, batches,
                                windows=max(3, args.windows - 2),
                                verbose=args.verbose, trace=args.trace,
                                shards=args.shards, rss=args.rss)
        _finish(result)
        return
    result = run_bench(args.config, preset, batch, batches,
                       verbose=args.verbose, windows=args.windows,
                       shards=args.shards, rule_shards=args.rule_shards,
                       profile_dir=args.profile)
    _progress["headline"] = result
    if args.shards * args.rule_shards > 1:
        args.only = True       # the sweep is a single-chip comparison series
    if not args.only:
        configs = {METRIC_NAMES[args.config]: {
            "value": result["value"], "vs_baseline": result["vs_baseline"],
            "p50_batch_ms": result["p50_batch_ms"],
            "p99_batch_ms": result["p99_batch_ms"]}}
        for cfg in sorted(BUILDERS):
            if cfg == args.config:
                continue
            # non-headline configs: fewer timed batches and windows
            # (visibility, not the headline number) — bounds the sweep
            res = run_bench(cfg, preset, batch, max(10, batches // 2),
                            verbose=args.verbose,
                            windows=max(3, args.windows - 2))
            print(json.dumps(res), file=sys.stderr)
            configs[METRIC_NAMES[cfg]] = {
                "value": res["value"], "vs_baseline": res["vs_baseline"],
                "p50_batch_ms": res["p50_batch_ms"],
                "p99_batch_ms": res["p99_batch_ms"]}
            _progress["configs"] = configs
        result["configs"] = configs
        result["update_latency"] = update_latency_bench(preset)
    _finish(result)


if __name__ == "__main__":
    main()
