#!/usr/bin/env python3
"""Serving-path exception-hygiene lint (ISSUE 19 satellite).

The self-healing plane only works if failures SURFACE: device-loss
detection reads dispatch exceptions, the flight recorder narrates them,
and the health document folds them in. A ``except Exception: pass``
anywhere on the serving path silently eats exactly the evidence that
machinery runs on — the classic way a dead chip serves garbage for an
hour before anyone notices.

This AST walk enforces, over ``cilium_tpu/{pipeline,runtime,shim}``:

- **no swallowed broad catches**: a handler for ``Exception`` /
  ``BaseException`` / bare ``except:`` whose body is only ``pass`` (or
  ``...``) is an error unless the handler line carries an explicit
  ``# noqa: BLE001``-style label stating why swallowing is safe;
- **no unlabelled broad catches**: every other broad handler must either
  re-raise somewhere in its body, make at least one call (accounting:
  ``log.exception``, a counter bump, the device-loss triage, ...), or
  carry an explicit ``# noqa: BLE001``-style label on the handler line —
  the repo's convention for "never-raise by design, accounted".

Narrow catches (``except OSError:`` etc.) are out of scope: naming the
exception IS the label. Exit 0 clean, 1 with findings, 2 on usage/parse
errors — wired as ``make lint-serving``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

#: serving-path packages, relative to the repo root
SERVING_DIRS = ("cilium_tpu/pipeline", "cilium_tpu/runtime",
                "cilium_tpu/shim")

BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                                  # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD
                   for e in t.elts)
    return False


def _pass_only(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value is Ellipsis:
            continue
        return False
    return True


def _reraises(body: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Raise)
               for stmt in body for n in ast.walk(stmt))


def _has_call(body: List[ast.stmt]) -> bool:
    """At least one call anywhere in the handler body — the accounting
    floor (a log line, a counter bump, a triage helper)."""
    return any(isinstance(n, ast.Call)
               for stmt in body for n in ast.walk(stmt))


def _labelled(lines: List[str], handler: ast.ExceptHandler) -> bool:
    """noqa/BLE001 marker on the handler's header line(s): from the
    ``except`` keyword through the line before the first body statement
    (multi-line headers keep their label visible)."""
    first_body = handler.body[0].lineno if handler.body else handler.lineno
    for ln in range(handler.lineno, first_body + 1):
        if ln - 1 >= len(lines):
            break
        text = lines[ln - 1]
        if "noqa" in text or "BLE001" in text:
            return True
    return False


def lint_file(path: str) -> List[Tuple[int, str]]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    findings: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
            continue
        what = ast.unparse(node.type) if node.type is not None else "<bare>"
        labelled = _labelled(lines, node)
        if _pass_only(node.body):
            if not labelled:
                findings.append((
                    node.lineno,
                    f"swallowed broad catch (except {what}: pass) — "
                    f"failures on the serving path must surface, be "
                    f"accounted, or carry a `# noqa: BLE001 — <why>` "
                    f"label"))
            continue
        if _reraises(node.body) or labelled:
            continue
        if not _has_call(node.body):
            findings.append((
                node.lineno,
                f"unlabelled broad catch (except {what}) with no re-raise "
                f"and no accounting call — add the handling, or label it "
                f"`# noqa: BLE001 — <why never-raise is safe here>`"))
    return findings


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [os.path.join(root, d) for d in SERVING_DIRS]
    missing = [t for t in targets if not os.path.isdir(t)]
    if missing:
        print(f"lint-serving: missing serving dirs: {missing}",
              file=sys.stderr)
        return 2
    total = 0
    for tdir in targets:
        for dirpath, _dirnames, filenames in sorted(os.walk(tdir)):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                for lineno, msg in lint_file(path):
                    rel = os.path.relpath(path, root)
                    print(f"{rel}:{lineno}: {msg}")
                    total += 1
    if total:
        print(f"lint-serving: {total} finding(s)", file=sys.stderr)
        return 1
    print("lint-serving: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
