"""Mesh self-healing tests (ISSUE 19): device-loss detection, the fenced
re-mesh onto survivors, CT salvage (device gather → archive floor → cold)
with the bounded established-fingerprint grace window, and hysteretic
re-admission — the tier-1 subset behind ``make chiploss-smoke`` (the
full-scale acceptance rides ``bench.py --chiploss``, cfg10).

Layers covered here:

- the dead-device classifier (``runtime/datapath.dead_device_of``): real
  runtime signatures vs transient dispatch errors, ordinal attribution;
- the shared established-fingerprint filter (``shim/feeder``): stamp /
  lookup discipline both consumers (feeder priority classing, the engine
  grace window) rely on;
- the CT archive helpers (``runtime/checkpoint``): atomic timestamped
  writes, retention pruning, age accounting, corrupt-file fail-closed;
- the engine protocol (``Engine.remesh_step`` / ``_remesh_to`` over
  ``Pipeline.remesh`` + ``JITDatapath.remesh``): loss → park → fenced
  shrink (wedged window rejected, queued submissions survive) → degraded
  serving → probe-canary heal with hysteresis, plus every operator
  surface the cycle feeds (health detail, mesh_width ledger row,
  counters, flight-recorder freeze kinds);
- the ct-snapshot controller tick: archive flow, CHECKPOINT_STALE
  folding, the ``device.collective`` chaos point, and the archive as the
  re-mesh's salvage floor when the device gather dies.
"""

import os
import time
import zipfile

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.pipeline.guard import DeviceLost, PipelineError
from cilium_tpu.runtime import checkpoint as ckpt
from cilium_tpu.runtime.datapath import dead_device_of
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.shim.feeder import EstablishedFingerprints
from cilium_tpu.utils import constants as C
from tests.test_datapath import pkt
from tests.test_sharded_pipeline import jit_pipeline_engine


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _mk(slot_of, n, start, dst_octet=2):
    recs = [pkt("192.168.1.10", f"10.0.{dst_octet}.{(i % 200) + 1}",
                52000 + start + i, 443) for i in range(n)]
    return batch_from_records(recs, slot_of)


def _replies(slot_of, n, start, dst_octet=2):
    recs = [pkt(f"10.0.{dst_octet}.{(i % 200) + 1}", "192.168.1.10",
                443, 52000 + start + i, flags=C.TCP_ACK,
                direction=C.DIR_INGRESS) for i in range(n)]
    return batch_from_records(recs, slot_of)


# --------------------------------------------------------------------------- #
# dead-device classifier
# --------------------------------------------------------------------------- #
class TestDeadDeviceClassifier:
    def test_attributed_signature(self):
        e = RuntimeError("DEVICE_UNAVAILABLE: chip fell off ici dev=3")
        assert dead_device_of(e) == 3

    def test_unattributed_signature(self):
        assert dead_device_of(RuntimeError("hardware failure")) == -1

    def test_drill_signature(self):
        assert dead_device_of(
            FaultInjected("injected fault at device.fail: dev=1")) == 1

    def test_transient_is_none(self):
        assert dead_device_of(ValueError("bad batch geometry")) is None

    def test_mention_of_devices_is_not_a_loss(self):
        # case-sensitive literal tokens only: a user exception that
        # merely talks about devices must stay breaker territory
        assert dead_device_of(
            RuntimeError("all devices are fine, dev=2 ok")) is None


# --------------------------------------------------------------------------- #
# the shared established-fingerprint filter
# --------------------------------------------------------------------------- #
class TestEstablishedFingerprints:
    def _buf(self, n):
        b = {k: np.zeros((n,), np.int32)
             for k in ("sport", "dport", "proto", "direction")}
        b["src"] = np.zeros((n, 4), np.uint32)
        b["dst"] = np.zeros((n, 4), np.uint32)
        b["valid"] = np.ones((n,), bool)
        b["src"][:, 3] = 0xC0A8010A
        b["dst"][:, 3] = 0x0A000200 + np.arange(n)
        b["sport"][:] = 40000 + np.arange(n)
        b["dport"][:] = 443
        b["proto"][:] = 6
        return b

    def test_only_allowed_established_rows_stamp(self):
        fp = EstablishedFingerprints(slots=1 << 12)
        b = self._buf(4)
        out = {"allow": np.array([True, True, False, True]),
               "status": np.array([int(C.CTStatus.ESTABLISHED),
                                   int(C.CTStatus.NEW),
                                   int(C.CTStatus.ESTABLISHED),
                                   int(C.CTStatus.REPLY)], np.int32)}
        fp.note(b, out)
        hits = fp.hits(b)
        # allowed-EST and allowed-REPLY stamp; allowed-NEW and denied-EST
        # do not — the filter only ever vouches for proven flows
        assert hits.tolist() == [True, False, False, True]

    def test_unknown_flow_never_hits(self):
        fp = EstablishedFingerprints(slots=1 << 12)
        assert not fp.hits(self._buf(8)).any()

    def test_note_never_raises(self):
        fp = EstablishedFingerprints(slots=1 << 12)
        fp.note({}, {})                 # missing columns: swallowed

    def test_slots_must_be_pow2(self):
        with pytest.raises(ValueError):
            EstablishedFingerprints(slots=48)


# --------------------------------------------------------------------------- #
# CT archive helpers
# --------------------------------------------------------------------------- #
class TestCTArchive:
    def _arrays(self, cap=64, live=5):
        from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
        a = make_ct_arrays(CTConfig(capacity=cap))
        a["expiry"][:live] = 10_000 + np.arange(live)
        return a

    def test_roundtrip_and_prune(self, tmp_path):
        d = str(tmp_path)
        assert ckpt.newest_ct_archive(d) is None
        assert ckpt.ct_archive_age_s(d) is None
        paths = [ckpt.save_ct_archive(d, self._arrays(live=i + 1), keep=2)
                 for i in range(3)]
        kept = ckpt.list_ct_archives(d)
        assert len(kept) == 2                      # pruned to keep
        assert ckpt.newest_ct_archive(d) == paths[-1]
        got = ckpt.load_ct_archive(paths[-1])
        assert got is not None
        assert int((got["expiry"] > 0).sum()) == 3
        assert "__ct_format__" not in got          # normalized out
        assert ckpt.ct_archive_age_s(d) >= 0.0

    def test_corrupt_archive_loads_as_none(self, tmp_path):
        d = str(tmp_path)
        p = ckpt.save_ct_archive(d, self._arrays(), keep=2)
        with open(p, "wb") as f:
            f.write(b"not a zip at all")
        assert ckpt.load_ct_archive(p) is None
        # a valid zip that is not a CT checkpoint also fails closed
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("garbage.npy", b"xx")
        assert ckpt.load_ct_archive(p) is None


# --------------------------------------------------------------------------- #
# the engine protocol: loss -> fenced shrink -> degraded -> heal
# --------------------------------------------------------------------------- #
class TestEngineRemesh:
    @pytest.mark.slow
    def test_loss_remesh_degraded_then_heal(self):
        eng = jit_pipeline_engine(4, remesh_heal_hysteresis_s=0.0)
        slot_of = eng.active.snapshot.ep_slot_of
        try:
            t = eng.submit(_mk(slot_of, 32, 0))
            assert eng.drain(timeout=30)
            assert int(np.asarray(t.result(5)["allow"]).sum()) == 32
            rev0 = eng.active.revision

            FAULTS.arm("device.fail", mode="fail", message="dev=1")
            trip = eng.submit(_mk(slot_of, 16, 1000))
            deadline = time.monotonic() + 30
            while (eng.pipeline_stats() or {}).get("state") \
                    != "device-lost" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng.pipeline_stats()["state"] == "device-lost"
            # queued while parked: must survive the fenced re-mesh
            queued = eng.submit(_mk(slot_of, 8, 2000))

            doc = eng.remesh_step()
            assert doc["remesh"]["from"] == 4
            assert doc["remesh"]["to"] == 3
            assert doc["remesh"]["reason"] == "device-loss"
            assert eng.drain(timeout=30)
            # the wedged in-flight window is rejected attributably...
            with pytest.raises(PipelineError):
                trip.result(timeout=5)
            # ...but the queued submission rode through onto survivors
            assert int(np.asarray(queued.result(5)["allow"]).sum()) == 8
            # the steering fence: a NEW revision (stale pre-binned
            # ``_shard`` stamps hashed mod the old width must not be
            # trusted against the 3-wide mesh)
            assert eng.active.revision > rev0

            # operator surfaces while degraded
            h = eng.health()
            assert h["state"] == C.HEALTH_DEGRADED
            assert h["devices"]["detail"] == C.DEVICE_LOST
            assert h["devices"]["dead"] == [1]
            width = eng._res_datapath()["mesh_width"]
            assert width[0] == 4 and width[1] == 3
            assert width[2] == pytest.approx(0.25)
            mh = eng.datapath.mesh_health()
            assert mh["live_ordinals"] == [0, 2, 3]
            assert mh["devices"][1]["state"] == "dead"
            # degraded serving with the fault STILL armed (the dead
            # chip cannot hurt a mesh it is no longer part of)
            t2 = eng.submit(_mk(slot_of, 16, 3000))
            assert eng.drain(timeout=30)
            assert int(np.asarray(t2.result(5)["allow"]).sum()) == 16

            # heal: disarm = the probe canary passes; hysteresis 0
            FAULTS.disarm("device.fail")
            doc = eng.remesh_step()
            assert doc["remesh"]["from"] == 3
            assert doc["remesh"]["to"] == 4
            assert doc["remesh"]["reason"] == "heal"
            assert eng.drain(timeout=30)
            t3 = eng.submit(_mk(slot_of, 16, 4000))
            assert eng.drain(timeout=30)
            assert int(np.asarray(t3.result(5)["allow"]).sum()) == 16
            assert eng.health()["state"] == C.HEALTH_OK

            ctr = eng.metrics.counters
            assert ctr['device_loss_total{device="1"}'] == 1
            assert ctr['datapath_remesh_total{from="4",to="3"}'] == 1
            assert ctr['datapath_remesh_total{from="3",to="4"}'] == 1
            assert ctr["pipeline_remesh_total"] == 2
            # each re-meshed generation restarted canary-first, and the
            # canary never leaked into submission accounting
            assert ctr.get("pipeline_canary_ok_total", 0) >= 2
            # the flight recorder narrated the loss (first freeze wins:
            # the loss bundle is the root-cause record)
            bb = eng.blackbox.stats()
            assert bb["frozen"]
            assert bb["frozen_reason"].startswith("device-loss")
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_grace_window_covers_lost_shard_then_expires(self):
        eng = jit_pipeline_engine(4, remesh_heal_hysteresis_s=0.0,
                                  remesh_grace_s=60.0)
        slot_of = eng.active.snapshot.ep_slot_of
        n = 64
        try:
            eng.submit(_mk(slot_of, n, 0))
            assert eng.drain(timeout=30)
            # warm pass: replies ride CT (REPLY) and stamp the
            # established-fingerprint filter — BEFORE any loss
            t = eng.submit(_replies(slot_of, n, 0))
            assert eng.drain(timeout=30)
            out = t.result(5)
            assert int(np.asarray(out["allow"]).sum()) == n
            assert (np.asarray(out["status"])[:n]
                    == int(C.CTStatus.REPLY)).all()

            FAULTS.arm("device.fail", mode="fail", message="dev=1")
            try:
                eng.submit(_mk(slot_of, 4, 9000)).result(timeout=30)
            except PipelineError:
                pass                       # the tripping window
            deadline = time.monotonic() + 30
            while (eng.pipeline_stats() or {}).get("state") \
                    != "device-lost" and time.monotonic() < deadline:
                time.sleep(0.02)
            doc = eng.remesh_step()
            assert doc["remesh"]["to"] == 3
            lost = doc["remesh"]["ct_lost"]
            assert lost > 0                # the dropped shard held flows
            assert eng.drain(timeout=30)

            # inside the window: EVERY reply still passes — survivors by
            # salvaged CT, the lost shard's flows by the grace flip
            t = eng.submit(_replies(slot_of, n, 0))
            assert eng.drain(timeout=30)
            assert int(np.asarray(t.result(5)["allow"]).sum()) == n
            hits = eng.metrics.counters.get("ct_salvage_grace_hits_total",
                                            0)
            assert hits > 0
            assert eng.remesh_status()["salvage_grace_remaining_s"] > 0

            # window closed: the flip stops, the uncovered flows fail
            # closed again (no forward traffic cold-learned them back)
            eng._salvage_until = 0.0
            t = eng.submit(_replies(slot_of, n, 0))
            assert eng.drain(timeout=30)
            allowed = int(np.asarray(t.result(5)["allow"]).sum())
            assert allowed < n
            assert allowed >= n - lost     # only lost-shard flows denied
            assert eng.remesh_status()["salvage_grace_remaining_s"] == 0.0

            # cold-learn: forward packets (policy-allowed) re-create the
            # entries on the survivor mesh; replies pass again with NO
            # grace window
            eng.submit(_mk(slot_of, n, 0))
            assert eng.drain(timeout=30)
            t = eng.submit(_replies(slot_of, n, 0))
            assert eng.drain(timeout=30)
            assert int(np.asarray(t.result(5)["allow"]).sum()) == n
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_heal_hysteresis_defers_and_flap_resets(self):
        eng = jit_pipeline_engine(4, remesh_heal_hysteresis_s=600.0)
        slot_of = eng.active.snapshot.ep_slot_of
        try:
            eng.submit(_mk(slot_of, 8, 0))
            assert eng.drain(timeout=30)
            FAULTS.arm("device.fail", mode="fail", message="dev=2")
            try:
                eng.submit(_mk(slot_of, 4, 500)).result(timeout=30)
            except PipelineError:
                pass
            deadline = time.monotonic() + 30
            while (eng.pipeline_stats() or {}).get("state") \
                    != "device-lost" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng.remesh_step()["remesh"]["to"] == 3
            assert eng.drain(timeout=30)

            # probe passes but the streak is younger than the
            # hysteresis: no re-admission yet
            FAULTS.disarm("device.fail")
            doc = eng.remesh_step()
            assert doc["remesh"] is None
            assert doc["heal_ok_s"] >= 0
            assert eng.datapath.mesh_health()["live"] == 3
            # a flap (fresh loss signal) zeroes the streak
            eng._on_device_loss(2, "flap drill")
            assert eng._heal_ok_since is None
        finally:
            eng.stop()

    def test_no_survivors_refuses_remesh(self):
        eng = jit_pipeline_engine(2)
        try:
            for o in (0, 1):
                eng.datapath.note_device_loss(o, reason="drill")
            doc = eng.remesh_step()
            assert doc["remesh"] == "no-survivors"
            assert eng.datapath.mesh_health()["live"] == 2  # unchanged
        finally:
            eng.stop()

    def test_remesh_disabled_is_inert(self):
        eng = jit_pipeline_engine(2, remesh_enabled=False)
        try:
            eng.datapath.note_device_loss(1, reason="drill")
            assert eng.remesh_step() is None
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# the ct-snapshot controller tick + the archive as salvage floor
# --------------------------------------------------------------------------- #
class TestCTSnapshotController:
    def test_snapshot_age_gauge_and_stale_health(self, tmp_path):
        eng = jit_pipeline_engine(2, ct_snapshot_dir=str(tmp_path),
                                  checkpoint_max_age_s=300.0)
        slot_of = eng.active.snapshot.ep_slot_of
        try:
            # no archive yet: DEGRADED with CHECKPOINT_STALE, gauge -1
            h = eng.health()
            assert h["state"] == C.HEALTH_DEGRADED
            assert h["checkpoint"]["detail"] == C.CHECKPOINT_STALE
            eng.submit(_mk(slot_of, 16, 0))
            assert eng.drain(timeout=30)
            doc = eng.ct_snapshot_step()
            assert doc["entries"] == 16
            assert eng.metrics.gauges["checkpoint_age_seconds"] >= 0.0
            assert eng.health()["state"] == C.HEALTH_OK
            # age the archive past the budget (mtime is the clock so the
            # age survives restarts): stale again
            old = time.time() - 10_000
            os.utime(doc["path"], (old, old))
            h = eng.health()
            assert h["state"] == C.HEALTH_DEGRADED
            assert h["checkpoint"]["detail"] == C.CHECKPOINT_STALE
            assert h["checkpoint"]["age_s"] > 300.0
        finally:
            eng.stop()

    def test_collective_fault_fails_tick_but_keeps_gauge(self, tmp_path):
        eng = jit_pipeline_engine(2, ct_snapshot_dir=str(tmp_path))
        try:
            FAULTS.arm("device.collective", mode="fail")
            with pytest.raises(FaultInjected):
                eng.ct_snapshot_step()     # controller supervision backs off
            # the finally kept the age gauge honest: no archive = -1
            assert eng.metrics.gauges["checkpoint_age_seconds"] == -1.0
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_archive_is_the_salvage_floor_when_gather_dies(self, tmp_path):
        eng = jit_pipeline_engine(4, remesh_heal_hysteresis_s=0.0,
                                  ct_snapshot_dir=str(tmp_path))
        slot_of = eng.active.snapshot.ep_slot_of
        try:
            eng.submit(_mk(slot_of, 32, 0))
            assert eng.drain(timeout=30)
            assert eng.ct_snapshot_step()["entries"] == 32
            # the chip died holding the collective: device gather fails,
            # the re-mesh falls back to the bounded-staleness archive
            FAULTS.arm("device.collective", mode="fail")
            eng.datapath.note_device_loss(1, reason="drill")
            doc = eng.remesh_step()
            assert doc["remesh"]["salvage_source"] == "archive"
            assert doc["remesh"]["ct_salvaged"] > 0
            assert eng.datapath.remesh_stats["remesh_gather_failures"] == 1
            FAULTS.disarm("device.collective")
            # the salvaged floor actually serves: established flows from
            # the archive still hit CT on the survivor mesh
            t = eng.submit(_replies(slot_of, 32, 0))
            assert eng.drain(timeout=30)
            out = t.result(5)
            n_reply = int((np.asarray(out["status"])
                           == int(C.CTStatus.REPLY)).sum())
            assert n_reply > 0
        finally:
            eng.stop()
