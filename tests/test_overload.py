"""Overload degradation ladder (ISSUE 10): OK → PRESSURE → OVERLOAD →
SHED-NEW, priority shedding, SHED-NEW harvest shedding, the blackbox
shed-reason split, and the labeled-metrics scrape race.

The contract: the ladder is an explicit, hysteresis-latched state machine
fed by queue/shed/CT pressure; PRESSURE arms priority shedding at the
admission queue (established-class batches displace flood batches, counted
``pipeline_shed_total{reason="priority"}``, FIFO-safe for everything that
survives); OVERLOAD additionally fails admission fast; SHED-NEW makes the
feeder drop non-established rows at harvest without ever submitting them.
Ladder transitions and CT-emergency events are flight-recorder events that
never freeze, and deliberate-shed spikes are judged against a relaxed
threshold so a commanded storm cannot blind the recorder.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.observe.blackbox import FlightRecorder
from cilium_tpu.pipeline import Pipeline, PipelineDrop
from cilium_tpu.pipeline.guard import (OVERLOAD_OVERLOAD, OVERLOAD_PRESSURE,
                                       OVERLOAD_SHED_NEW, PRIO_ESTABLISHED,
                                       PRIO_NEW, PRIO_UNKNOWN,
                                       OverloadLadder)
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.shim.feeder import shed_new_rows
from tests.test_pipeline import EchoDispatch, sub_batch


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# --------------------------------------------------------------------------- #
# the ladder state machine
# --------------------------------------------------------------------------- #
class TestOverloadLadder:
    def mk(self, **kw):
        kw.setdefault("up_ticks", 2)
        kw.setdefault("down_ticks", 3)
        return OverloadLadder(queue_high=0.75, queue_low=0.25,
                              shed_high=50.0, shed_low=5.0,
                              ct_high=0.85, ct_low=0.6, **kw)

    def test_single_signal_holds_pressure(self):
        lad = self.mk()
        for _ in range(10):
            state, _ = lad.observe(0.9, 0.0, 0.0)
        assert state == OVERLOAD_PRESSURE     # one lit signal caps at 1

    def test_two_signals_escalate_to_shed_new(self):
        lad = self.mk()
        states = [lad.observe(0.9, 100.0, 0.0)[0] for _ in range(8)]
        assert states[-1] == OVERLOAD_SHED_NEW
        assert OVERLOAD_OVERLOAD in states    # ramped, rung by rung

    def test_hysteresis_latch_keeps_signal_lit_between_thresholds(self):
        lad = self.mk(up_ticks=1)
        lad.observe(0.9, 0.0, 0.0)            # queue lights at 0.9
        for _ in range(10):
            state, _ = lad.observe(0.5, 0.0, 0.0)   # between low and high
        assert state == OVERLOAD_PRESSURE     # still lit — no flap
        for _ in range(10):
            state, _ = lad.observe(0.1, 0.0, 0.0)   # below low: clears
        assert state == 0

    def test_descent_is_slow(self):
        lad = self.mk(up_ticks=1, down_ticks=4)
        for _ in range(6):
            lad.observe(0.9, 100.0, 0.9)
        assert lad.state == OVERLOAD_SHED_NEW
        downs = [lad.observe(0.0, 0.0, 0.0)[0] for _ in range(12)]
        assert downs[-1] == 0
        assert downs[2] == OVERLOAD_SHED_NEW   # held through early calm
        # one rung at a time on the way down
        assert sorted(set(downs), reverse=True) == \
            sorted(set(downs), reverse=True)

    def test_dwell_and_trail_recorded(self):
        lad = self.mk(up_ticks=1)
        time.sleep(0.02)                      # dwell accrues in OK first
        lad.observe(0.9, 100.0, 0.0)
        time.sleep(0.02)
        lad.observe(0.9, 100.0, 0.0)
        st = lad.status()
        assert st["level"] >= OVERLOAD_PRESSURE
        assert st["dwell_s"]["ok"] > 0
        assert st["transitions"] >= 1
        assert st["trail"][0]["frm"] == "ok"
        assert st["inputs"]["severity"] >= 2

    def test_bad_thresholds_rejected(self):
        with pytest.raises(ValueError):
            OverloadLadder(queue_high=0.2, queue_low=0.5)
        with pytest.raises(ValueError):
            OverloadLadder(up_ticks=0)


# --------------------------------------------------------------------------- #
# priority shedding at the admission queue
# --------------------------------------------------------------------------- #
def prio_batch(n_rows, start, prio):
    b = sub_batch(n_rows, start)
    b["_prio"] = np.full((n_rows,), prio, dtype=np.int8)
    return b


class TestPriorityShed:
    def mk_pipeline(self, d, **kw):
        kw.setdefault("max_bucket", 16)
        kw.setdefault("min_bucket", 1)
        kw.setdefault("queue_batches", 3)
        kw.setdefault("flush_ms", 2.0)
        kw.setdefault("block_timeout_s", 0.05)
        return Pipeline(d, **kw)

    def test_established_batch_displaces_flood_batch(self):
        d = EchoDispatch()
        d.gate.clear()                        # stall the worker
        pl = self.mk_pipeline(d)
        pl.set_overload_state(OVERLOAD_PRESSURE)
        try:
            flood = [pl.submit(prio_batch(4, 100 + 10 * i, PRIO_NEW))
                     for i in range(4)]      # fills worker + queue(3)
            legit = pl.submit(prio_batch(4, 900, PRIO_ESTABLISHED))
            assert not legit.dropped          # admitted by displacement
            victims = [t for t in flood if t.done()]
            assert len(victims) == 1
            with pytest.raises(PipelineDrop):
                victims[0].result(timeout=1)
            assert pl.metrics.counters[
                'pipeline_shed_total{reason="priority"}'] == 1
            assert pl.stats()["shed_reasons"] == {"priority": 1}
            # the NEWEST flood batch was the victim: FIFO history survives
            assert victims[0] is flood[-1]
            d.gate.set()
            assert pl.drain(timeout=10)
            # every survivor resolves with its own rows, in order
            for t in flood[:-1] + [legit]:
                t.result(timeout=5)
            assert d.sports_seen == [100, 101, 102, 103, 110, 111, 112,
                                     113, 120, 121, 122, 123, 900, 901,
                                     902, 903]
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_same_class_keeps_fifo_admission(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = self.mk_pipeline(d)
        pl.set_overload_state(OVERLOAD_PRESSURE)
        try:
            for i in range(4):
                pl.submit(prio_batch(4, 100 + 10 * i, PRIO_NEW))
            t = pl.submit(prio_batch(4, 900, PRIO_NEW))   # same class
            assert t.dropped                  # block timeout → plain drop
            assert pl.metrics.counters.get(
                'pipeline_shed_total{reason="priority"}', 0) == 0
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_overload_level_fails_fast_without_blocking(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = self.mk_pipeline(d, block_timeout_s=5.0)
        pl.set_overload_state(OVERLOAD_OVERLOAD)
        try:
            # with the worker wedged in the gated dispatch, at most
            # 1 staged + 3 queued submissions can be absorbed — submit
            # until one fails fast. Racing a fixed count against the
            # worker's own pop schedule flaked under full-suite load;
            # the invariant is WHICH outcome, not which submission.
            t0 = time.monotonic()
            dropped = None
            for i in range(8):
                t = pl.submit(prio_batch(4, 100 + 10 * i, PRIO_NEW))
                if t.dropped:
                    dropped = t
                    break
            assert dropped is not None
            assert time.monotonic() - t0 < 1.0   # no 5s blocking waits
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_level_zero_changes_nothing(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = self.mk_pipeline(d)
        try:
            for i in range(4):
                pl.submit(prio_batch(4, 100 + 10 * i, PRIO_NEW))
            t = pl.submit(prio_batch(4, 900, PRIO_ESTABLISHED))
            assert t.dropped                  # no ladder: plain admission
            assert pl.metrics.counters.get(
                'pipeline_shed_total{reason="priority"}', 0) == 0
        finally:
            d.gate.set()
            pl.close(timeout=5)


# --------------------------------------------------------------------------- #
# SHED-NEW harvest shedding + priority classing
# --------------------------------------------------------------------------- #
class TestShedNew:
    def test_shed_new_rows_drops_exactly_the_low_prio(self):
        b = sub_batch(8, 100)
        b["_prio"] = np.asarray(
            [PRIO_ESTABLISHED, PRIO_NEW, PRIO_UNKNOWN, PRIO_ESTABLISHED,
             PRIO_NEW, PRIO_NEW, PRIO_ESTABLISHED, PRIO_UNKNOWN],
            dtype=np.int8)
        shed = shed_new_rows(b)
        assert shed == 5
        assert b["valid"].tolist() == [True, False, False, True, False,
                                       False, True, False]

    def test_shed_new_events_ride_the_relaxed_spike_class(self):
        """The feeder narrates SHED-NEW harvest drops to the flight
        recorder as reason="shed-new" events — judged against the RELAXED
        spike threshold, so a commanded storm records without freezing."""
        from types import SimpleNamespace
        from cilium_tpu.shim.feeder import ShimFeeder
        fr = FlightRecorder(shed_spike=4, shed_window_s=60.0,
                            shed_spike_relaxed=1000)
        m = Metrics()
        ns = SimpleNamespace(metrics=m, prio_shed_rows=0,
                             _event_sink=fr.record_event)
        for _ in range(20):
            b = sub_batch(8, 100)
            b["_prio"] = np.full((8,), PRIO_NEW, dtype=np.int8)
            assert ShimFeeder._shed_new(ns, b) == 8
        assert ns.prio_shed_rows == 160
        assert m.counters[
            'feeder_prio_shed_rows_total{class="new"}'] == 160
        st = fr.stats()
        assert st["events_total"] == 20           # narrated, every batch
        assert st["frozen"] is False              # relaxed: no freeze

    def test_engine_ladder_propagates_to_pipeline_and_health(self):
        """Drive the engine's overload controller to SHED-NEW (shed + CT
        signals) and assert propagation: pipeline overload level, gauges,
        transition counters, blackbox events (recorded, not frozen), and
        health DEGRADED at >= OVERLOAD."""
        cfg = DaemonConfig(ct_capacity=1024, auto_regen=False,
                           overload_up_ticks=1, overload_down_ticks=2,
                           overload_shed_rate_high=10.0,
                           overload_shed_rate_low=1.0)
        eng = Engine(cfg, datapath=FakeDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.regenerate()
        try:
            pl = eng.start_pipeline()
            assert eng.overload_step()["level"] == 0
            # light the shed + CT signals (two signals → escalation)
            eng.metrics.set_gauge("ct_occupancy", 0.95)
            for _ in range(5):
                pl.shed_total += 500          # test-internal: shed storm
                st = eng.overload_step()
            assert st["level"] == OVERLOAD_SHED_NEW
            assert pl.stats()["overload_level"] == OVERLOAD_SHED_NEW
            assert eng.metrics.gauges["overload_state"] == \
                OVERLOAD_SHED_NEW
            assert eng.metrics.counters[
                'overload_transitions_total{to="shed-new"}'] == 1
            health = eng.health()
            assert health["overload"]["state"] == "shed-new"
            assert health["state"] == "DEGRADED"
            kinds = [e["kind"] for e in eng.blackbox._events]
            assert kinds.count("overload") >= 3   # one per rung
            assert eng.blackbox.stats()["frozen"] is False
            # calm: the ladder descends and health recovers
            eng.metrics.set_gauge("ct_occupancy", 0.0)
            for _ in range(12):
                st = eng.overload_step()
            assert st["level"] == 0
            assert eng.health()["state"] == "OK"
            # the status surface carries the ladder
            from cilium_tpu.runtime.api import status_doc
            assert status_doc(eng)["overload"]["state"] == "ok"
        finally:
            eng.stop()

    def test_overload_decide_fault_leaves_state_standing(self):
        cfg = DaemonConfig(ct_capacity=1024, auto_regen=False,
                           overload_up_ticks=1,
                           overload_shed_rate_high=10.0,
                           overload_shed_rate_low=1.0)
        eng = Engine(cfg, datapath=FakeDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.regenerate()
        try:
            pl = eng.start_pipeline()
            eng.metrics.set_gauge("ct_occupancy", 0.95)
            for _ in range(4):
                pl.shed_total += 500
                eng.overload_step()
            level = eng.overload_status()["level"]
            assert level >= OVERLOAD_OVERLOAD
            FAULTS.arm("overload.decide", mode="fail", times=3)
            for _ in range(3):
                with pytest.raises(FaultInjected):
                    eng.overload_step()       # the controller would back off
            # the last propagated state stands — no flap to OK
            assert pl.stats()["overload_level"] == level
            assert eng.overload_status()["level"] == level
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# blackbox shed-reason split
# --------------------------------------------------------------------------- #
class TestBlackboxShedSplit:
    def test_relaxed_reasons_do_not_freeze_at_strict_threshold(self):
        fr = FlightRecorder(shed_spike=4, shed_window_s=60.0,
                            shed_spike_relaxed=1000)
        for i in range(100):
            fr.record_event("shed", reason="priority", seq=i)
        for i in range(100):
            fr.record_event("shed", reason="shed-new", seq=i)
        assert fr.stats()["frozen"] is False
        # strict reasons still freeze at the strict threshold
        for i in range(4):
            fr.record_event("shed", reason="flush", seq=i)
        st = fr.stats()
        assert st["frozen"] is True
        assert st["frozen_reason"] == "shed-spike"

    def test_relaxed_spike_still_freezes_eventually(self):
        fr = FlightRecorder(shed_spike=1000, shed_window_s=60.0,
                            shed_spike_relaxed=8)
        for i in range(8):
            fr.record_event("shed", reason="priority", seq=i)
        assert fr.stats()["frozen"] is True

    def test_ladder_events_record_without_freezing(self):
        fr = FlightRecorder()
        fr.record_event("overload", state="shed-new", queue_frac=1.0)
        fr.record_event("ct-emergency", action="enter", occupancy=0.9)
        assert fr.stats()["frozen"] is False
        kinds = [e["kind"] for e in fr._events]
        assert kinds == ["overload", "ct-emergency"]


# --------------------------------------------------------------------------- #
# labeled-metrics scrape race (extends the PR 7 concurrent-scrape test)
# --------------------------------------------------------------------------- #
class TestLabeledScrapeRace:
    def test_priority_and_class_label_families_race_render(self):
        """The new {reason="priority"} / {class=...} counter families and
        a labeled histogram racing continuous render_metrics scrapes
        during simulated ladder transitions: no exception, each rendered
        document has exactly one TYPE line per base metric, and the final
        counts land."""
        m = Metrics()
        stop = threading.Event()
        errors = []
        renders = []

        def scraper():
            try:
                while not stop.is_set():
                    renders.append(m.render_prometheus())
            except Exception as e:   # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=scraper) for _ in range(2)]
        for t in threads:
            t.start()
        n = 400
        for i in range(n):
            m.inc_counter('pipeline_shed_total{reason="priority"}')
            m.inc_counter('pipeline_shed_total{reason="ingest"}')
            m.inc_counter('feeder_prio_shed_rows_total{class="new"}', 3)
            m.inc_counter(
                'feeder_prio_shed_rows_total{class="unknown"}', 1)
            m.inc_counter(f'overload_transitions_total{{to='
                          f'"{("pressure", "overload")[i % 2]}"}}')
            m.set_gauge("overload_state", i % 4)
            m.histogram(
                'ingest_e2e_latency_seconds{shard="0"}').observe(1e-4)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors
        final = m.render_prometheus()
        for base in ("pipeline_shed_total", "feeder_prio_shed_rows_total",
                     "overload_transitions_total"):
            assert final.count(f"# TYPE ciliumtpu_{base} counter") == 1
        assert f'pipeline_shed_total{{reason="priority"}} {n}' in final
        assert f'feeder_prio_shed_rows_total{{class="new"}} {3 * n}' \
            in final
        assert final.count(
            "# TYPE ciliumtpu_ingest_e2e_latency_seconds histogram") == 1
        assert f'ingest_e2e_latency_seconds_count{{shard="0"}} {n}' \
            in final
        # every mid-race render parsed as one-TYPE-per-base too
        for doc in renders[:: max(1, len(renders) // 16)]:
            for base in ("pipeline_shed_total",
                         "overload_transitions_total"):
                assert doc.count(f"# TYPE ciliumtpu_{base} counter") <= 1
