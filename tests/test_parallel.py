"""Multi-chip tests on the 8-fake-device CPU mesh (SURVEY.md §4: the standard
JAX idiom for testing shard_map without TPUs): steering invariants, DP
classify parity vs single-device, rule-axis sharding parity."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.classify import classify_step
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.parallel.mesh import (
    flow_shard_of, make_mesh, make_sharded_classify_fn, pad_snapshot_tensors,
    steer_batch, unsteer_outputs,
)
from cilium_tpu.utils import constants as C
from tests.test_parity import (
    build_world, extract_device_ct, oracle_live_ct, random_packet,
)
from oracle import Oracle


@pytest.fixture(scope="module")
def world():
    ctx, repo, eps = build_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
    return ctx, snap


class TestSteering:
    def test_directions_agree(self, world):
        ctx, snap = world
        rng = random.Random(3)
        packets = [random_packet(rng, []) for _ in range(64)]
        fwd = batch_from_records(packets, snap.ep_slot_of)
        # reversed packets: swap addrs/ports, flip direction
        rev = dict(fwd)
        rev = {k: v.copy() for k, v in fwd.items()}
        rev["src"], rev["dst"] = fwd["dst"].copy(), fwd["src"].copy()
        rev["sport"], rev["dport"] = fwd["dport"].copy(), fwd["sport"].copy()
        rev["direction"] = 1 - fwd["direction"]
        np.testing.assert_array_equal(flow_shard_of(fwd, 4),
                                      flow_shard_of(rev, 4))

    def test_steer_roundtrip(self, world):
        ctx, snap = world
        rng = random.Random(4)
        packets = [random_packet(rng, []) for _ in range(50)]
        batch = batch_from_records(packets, snap.ep_slot_of, pad_to=64)
        steered, scatter, per = steer_batch(batch, 4)
        # every valid packet lands in its shard's region
        shard = flow_shard_of(batch, 4)
        for i in range(64):
            if batch["valid"][i]:
                assert steered["valid"][scatter[i]]
                assert scatter[i] // per == shard[i]
        # fake outputs roundtrip
        out = {"x": np.arange(steered["valid"].shape[0], dtype=np.int64)}
        back = unsteer_outputs(out, scatter)
        for i in range(64):
            if batch["valid"][i]:
                assert back["x"][i] == scatter[i]


def _run_mesh_parity(n_flow, n_rule, seed=5, n_batches=4, batch=96):
    """Sharded classify over the mesh vs the oracle."""
    rng = random.Random(seed)
    ctx, repo, eps = build_world()
    cap = 4096
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=cap))
    mesh = make_mesh(n_flow, n_rule)
    tensors_np = pad_snapshot_tensors(snap.tensors(), n_rule)
    tensors = {k: jnp.asarray(v) for k, v in tensors_np.items()}
    ct = {k: jnp.asarray(v) for k, v in
          make_ct_arrays(CTConfig(capacity=cap)).items()}
    fn = make_sharded_classify_fn(mesh, donate_ct=False)
    oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                    ctx.ipcache.snapshot())
    prior = []
    now = 1000
    for bi in range(n_batches):
        packets = [random_packet(rng, prior) for _ in range(batch)]
        want = oracle.classify_batch_snapshot(packets, now)
        raw = batch_from_records(packets, snap.ep_slot_of)
        steered, scatter, per = steer_batch(raw, n_flow, per_shard=batch)
        dev_batch = {k: jnp.asarray(v) for k, v in steered.items()}
        out, ct, counters = fn(tensors, ct, dev_batch, jnp.uint32(now),
                               jnp.int32(snap.world_index))
        out_np = unsteer_outputs({k: np.asarray(v) for k, v in out.items()},
                                 scatter)
        for i, v in enumerate(want):
            assert bool(out_np["allow"][i]) == v.allow, (n_flow, n_rule, bi, i)
            assert int(out_np["reason"][i]) == int(v.drop_reason), \
                (n_flow, n_rule, bi, i)
            assert int(out_np["status"][i]) == int(v.ct_status), \
                (n_flow, n_rule, bi, i)
        # device CT across all shards == oracle live entries
        assert extract_device_ct(ct, now) == oracle_live_ct(oracle, now)
        # counters replicated + correct total
        by = np.asarray(counters["by_reason_dir"]).reshape(256, 2)
        n_valid = sum(1 for p in packets)
        assert int(by.sum()) == n_valid
        prior.extend(p for p, v in zip(packets, want)
                     if v.allow and v.ct_status == C.CTStatus.NEW)
        prior = prior[-150:]
        now += 40


class TestMeshParity:
    def test_dp_4x1(self):
        _run_mesh_parity(4, 1)

    def test_dp_8x1(self):
        _run_mesh_parity(8, 1, seed=6)

    def test_rule_sharded_1x8(self):
        _run_mesh_parity(1, 8, seed=7)

    def test_combined_4x2(self):
        _run_mesh_parity(4, 2, seed=8)


# --------------------------------------------------------------------------- #
# Production path: Engine + JITDatapath honoring n_shards/rule_shards
# (round-4 verdict item 1: the mesh must be reachable from the Engine, not
# just the dryrun). Runs on the conftest-provisioned 8-fake-device CPU mesh.
# --------------------------------------------------------------------------- #
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath, JITDatapath
from cilium_tpu.parallel.mesh import rehash_ct_arrays
from tests.test_datapath import TRAFFIC, fixture_engine


def _sharded_cfg(**kw):
    base = dict(ct_capacity=2048, auto_regen=False, n_shards=4,
                rule_shards=2)
    base.update(kw)
    return DaemonConfig(**base)


class TestShardedEngine:
    def test_engine_sharded_parity_vs_fake(self):
        """DaemonConfig(n_shards=4, rule_shards=2) engine serves through the
        mesh and produces verdicts identical to the oracle-backed fake —
        including CT continuity across batches (flow→shard steering must be
        direction-stable)."""
        eng_mesh = fixture_engine(JITDatapath(_sharded_cfg()))
        eng_fake = fixture_engine(FakeDatapath(DaemonConfig(ct_capacity=2048)))
        slots = eng_mesh.active.snapshot.ep_slot_of
        assert slots == eng_fake.active.snapshot.ep_slot_of
        now = 1000
        for rep in range(3):          # repeats exercise ESTABLISHED via CT
            batch = batch_from_records(TRAFFIC, slots)
            out_m = eng_mesh.classify(dict(batch), now=now + rep * 5)
            out_f = eng_fake.classify(dict(batch), now=now + rep * 5)
            for k in ("allow", "reason", "status", "remote_identity",
                      "redirect", "svc", "rnat"):
                np.testing.assert_array_equal(
                    np.asarray(out_f[k]), np.asarray(out_m[k]), (rep, k))
        assert (np.asarray(out_m["status"])[0] == C.CTStatus.ESTABLISHED)
        assert eng_mesh.ct_stats(now) == eng_fake.ct_stats(now)

    def test_engine_sharded_random_traffic_parity(self):
        """Random mixed traffic (both directions, replies of prior flows)
        through the meshed engine == fake engine, multiple batches."""
        rng = random.Random(11)
        eng_mesh = fixture_engine(JITDatapath(_sharded_cfg()))
        eng_fake = fixture_engine(FakeDatapath(DaemonConfig(ct_capacity=2048)))
        slots = eng_mesh.active.snapshot.ep_slot_of
        prior = []
        now = 2000
        for bi in range(4):
            packets = [random_packet(rng, prior) for _ in range(100)]
            batch = batch_from_records(packets, slots)
            out_m = eng_mesh.classify(dict(batch), now=now)
            out_f = eng_fake.classify(dict(batch), now=now)
            for k in ("allow", "reason", "status", "remote_identity"):
                np.testing.assert_array_equal(
                    np.asarray(out_f[k]), np.asarray(out_m[k]), (bi, k))
            prior.extend(p for i, p in enumerate(packets)
                         if out_f["allow"][i]
                         and out_f["status"][i] == C.CTStatus.NEW)
            prior = prior[-120:]
            now += 30

    def test_ct_checkpoint_across_shard_layouts(self):
        """CT exported from a sharded backend restores into a single-chip
        backend and vice versa: flows stay ESTABLISHED (rehash_ct_arrays
        re-places entries for the importing geometry)."""
        eng_mesh = fixture_engine(JITDatapath(_sharded_cfg()))
        slots = eng_mesh.active.snapshot.ep_slot_of
        batch = batch_from_records(TRAFFIC, slots)
        out0 = eng_mesh.classify(dict(batch), now=1000)
        live = eng_mesh.ct_stats(1000)["live"]
        assert live > 0
        arrays = eng_mesh.ct_arrays()

        # mesh → single chip
        eng_one = fixture_engine(JITDatapath(DaemonConfig(
            ct_capacity=2048, auto_regen=False)))
        eng_one.load_ct_arrays(arrays)
        assert eng_one.ct_stats(1000)["live"] == live
        out1 = eng_one.classify(dict(batch), now=1005)
        allowed = np.asarray(out0["allow"])
        assert (np.asarray(out1["status"])[allowed]
                == C.CTStatus.ESTABLISHED).all()

        # single chip → mesh (different flow-shard count: 2)
        arrays1 = eng_one.ct_arrays()
        eng_mesh2 = fixture_engine(JITDatapath(_sharded_cfg(n_shards=2,
                                                            rule_shards=1)))
        eng_mesh2.load_ct_arrays(arrays1)
        out2 = eng_mesh2.classify(dict(batch), now=1010)
        assert (np.asarray(out2["status"])[allowed]
                == C.CTStatus.ESTABLISHED).all()

    def test_rehash_preserves_entries(self):
        """rehash round trip: every live entry survives (ample probe room)
        and lands where the importing geometry's probe expects it —
        asserted behaviorally above, structurally here."""
        rng = np.random.default_rng(5)
        from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
        arrays = make_ct_arrays(CTConfig(capacity=1024))
        n = 200
        arrays["keys"][:n] = rng.integers(0, 2**32, (n, 10), dtype=np.uint32)
        arrays["keys"][:n, 9] = (arrays["keys"][:n, 9] & ~np.uint32(0xFF)) \
            | (arrays["keys"][:n, 9] & 1)          # direction ∈ {0,1}
        arrays["expiry"][:n] = 5000
        arrays["pkts_fwd"][:n] = np.arange(n)
        re4, dropped = rehash_ct_arrays(arrays, 4)
        assert dropped == 0
        assert int((re4["expiry"] > 0).sum()) == n
        # entry payloads survive keyed by key (slots differ)
        src = {tuple(arrays["keys"][i]): int(arrays["pkts_fwd"][i])
               for i in range(n)}
        for s in np.nonzero(re4["expiry"] > 0)[0]:
            assert src[tuple(re4["keys"][s])] == int(re4["pkts_fwd"][s])
