"""Multi-chip tests on the 8-fake-device CPU mesh (SURVEY.md §4: the standard
JAX idiom for testing shard_map without TPUs): steering invariants, DP
classify parity vs single-device, rule-axis sharding parity."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.classify import classify_step
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.parallel.mesh import (
    flow_shard_of, make_mesh, make_sharded_classify_fn, pad_snapshot_tensors,
    steer_batch, unsteer_outputs,
)
from cilium_tpu.utils import constants as C
from tests.test_parity import (
    build_world, extract_device_ct, oracle_live_ct, random_packet,
)
from oracle import Oracle


@pytest.fixture(scope="module")
def world():
    ctx, repo, eps = build_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
    return ctx, snap


class TestSteering:
    def test_directions_agree(self, world):
        ctx, snap = world
        rng = random.Random(3)
        packets = [random_packet(rng, []) for _ in range(64)]
        fwd = batch_from_records(packets, snap.ep_slot_of)
        # reversed packets: swap addrs/ports, flip direction
        rev = dict(fwd)
        rev = {k: v.copy() for k, v in fwd.items()}
        rev["src"], rev["dst"] = fwd["dst"].copy(), fwd["src"].copy()
        rev["sport"], rev["dport"] = fwd["dport"].copy(), fwd["sport"].copy()
        rev["direction"] = 1 - fwd["direction"]
        np.testing.assert_array_equal(flow_shard_of(fwd, 4),
                                      flow_shard_of(rev, 4))

    def test_steer_roundtrip(self, world):
        ctx, snap = world
        rng = random.Random(4)
        packets = [random_packet(rng, []) for _ in range(50)]
        batch = batch_from_records(packets, snap.ep_slot_of, pad_to=64)
        steered, scatter, per = steer_batch(batch, 4)
        # every valid packet lands in its shard's region
        shard = flow_shard_of(batch, 4)
        for i in range(64):
            if batch["valid"][i]:
                assert steered["valid"][scatter[i]]
                assert scatter[i] // per == shard[i]
        # fake outputs roundtrip
        out = {"x": np.arange(steered["valid"].shape[0], dtype=np.int64)}
        back = unsteer_outputs(out, scatter)
        for i in range(64):
            if batch["valid"][i]:
                assert back["x"][i] == scatter[i]


def _run_mesh_parity(n_flow, n_rule, seed=5, n_batches=4, batch=96):
    """Sharded classify over the mesh vs the oracle."""
    rng = random.Random(seed)
    ctx, repo, eps = build_world()
    cap = 4096
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=cap))
    mesh = make_mesh(n_flow, n_rule)
    tensors_np = pad_snapshot_tensors(snap.tensors(), n_rule)
    tensors = {k: jnp.asarray(v) for k, v in tensors_np.items()}
    ct = {k: jnp.asarray(v) for k, v in
          make_ct_arrays(CTConfig(capacity=cap)).items()}
    fn = make_sharded_classify_fn(mesh, donate_ct=False)
    oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                    ctx.ipcache.snapshot())
    prior = []
    now = 1000
    for bi in range(n_batches):
        packets = [random_packet(rng, prior) for _ in range(batch)]
        want = oracle.classify_batch_snapshot(packets, now)
        raw = batch_from_records(packets, snap.ep_slot_of)
        steered, scatter, per = steer_batch(raw, n_flow, per_shard=batch)
        dev_batch = {k: jnp.asarray(v) for k, v in steered.items()}
        out, ct, counters = fn(tensors, ct, dev_batch, jnp.uint32(now),
                               jnp.int32(snap.world_index))
        out_np = unsteer_outputs({k: np.asarray(v) for k, v in out.items()},
                                 scatter)
        for i, v in enumerate(want):
            assert bool(out_np["allow"][i]) == v.allow, (n_flow, n_rule, bi, i)
            assert int(out_np["reason"][i]) == int(v.drop_reason), \
                (n_flow, n_rule, bi, i)
            assert int(out_np["status"][i]) == int(v.ct_status), \
                (n_flow, n_rule, bi, i)
        # device CT across all shards == oracle live entries
        assert extract_device_ct(ct, now) == oracle_live_ct(oracle, now)
        # counters replicated + correct total
        by = np.asarray(counters["by_reason_dir"]).reshape(256, 2)
        n_valid = sum(1 for p in packets)
        assert int(by.sum()) == n_valid
        prior.extend(p for p, v in zip(packets, want)
                     if v.allow and v.ct_status == C.CTStatus.NEW)
        prior = prior[-150:]
        now += 40


class TestMeshParity:
    def test_dp_4x1(self):
        _run_mesh_parity(4, 1)

    def test_dp_8x1(self):
        _run_mesh_parity(8, 1, seed=6)

    def test_rule_sharded_1x8(self):
        _run_mesh_parity(1, 8, seed=7)

    def test_combined_4x2(self):
        _run_mesh_parity(4, 2, seed=8)
