"""Pipelined ingestion scheduler tests (pipeline/scheduler.py).

Unit tests drive a raw Pipeline against a recording dispatch function
(echoing each row's sport through ``reason`` so slice plumbing is
checkable row-by-row): admission backpressure + drop accounting, deadline
vs full vs drain flushes, direct-dispatch bypass, FIFO ordering,
``pipeline.dispatch`` fault retries, supervised dispatch-error rejection,
and clean shutdown with queued work.

Integration tests go through ``Engine.submit`` and pin pipeline verdicts
bit-identical to the serial ``classify`` path on the same submissions —
the serial path is already oracle-pinned (test_parity.py), so equality
here extends the parity chain to the pipelined path. The ``slow``-marked
soak (``make pipeline-smoke``) pushes 10k submissions through an engine
on FakeDatapath with ``pipeline.dispatch`` faults armed and asserts
nothing is lost or reordered.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records, empty_batch
from cilium_tpu.pipeline import (Pipeline, PipelineClosed, PipelineDrop,
                                 PipelineError)
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def sub_batch(n_rows, start, n_valid=None):
    """A submission whose rows carry ``sport = start + i`` as an identity
    tag; the first ``n_valid`` rows are valid."""
    b = empty_batch(n_rows)
    b["sport"][:] = np.arange(start, start + n_rows, dtype=np.int32)
    b["valid"][: n_rows if n_valid is None else n_valid] = True
    return b


class EchoDispatch:
    """Stands in for the datapath: records the valid-row sports of every
    dispatched batch (FIFO order proof) and echoes each row's sport back
    through ``reason`` (slice-plumbing proof)."""

    def __init__(self):
        self.batches = []            # list of [sport, ...] per dispatch
        self.gate = threading.Event()
        self.gate.set()              # clear() to stall the worker
        self.fail_next = None        # exception to raise on next call

    def __call__(self, batch, now):
        self.gate.wait(timeout=10)
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc
        valid = np.asarray(batch["valid"])
        self.batches.append(np.asarray(batch["sport"])[valid].tolist())
        out = {
            "allow": valid.copy(),
            "reason": np.asarray(batch["sport"], np.int32).copy(),
            "status": np.zeros(valid.shape[0], np.int32),
            "remote_identity": np.zeros(valid.shape[0], np.int32),
        }
        return lambda: out

    @property
    def sports_seen(self):
        return [s for b in self.batches for s in b]


class TestPipelineUnit:
    def test_direct_dispatch_bypass(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1000.0)
        try:
            t = pl.submit(sub_batch(4, start=100))
            out = t.result(timeout=5)
            assert out["reason"].tolist() == [100, 101, 102, 103]
            assert pl.flush_reasons["direct"] == 1
            assert d.batches == [[100, 101, 102, 103]]
        finally:
            pl.close(timeout=5)

    def test_coalesce_full_flush_and_slice_mapping(self):
        """Three 3-valid-row submissions into max_bucket=8: the third
        overflows the stage, forcing a 'full' flush of the first two (6
        rows → bucket 8); each ticket's rows come back in its own
        geometry."""
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=8, flush_ms=1000.0)
        try:
            t1 = pl.submit(sub_batch(5, start=10, n_valid=3))
            t2 = pl.submit(sub_batch(3, start=20))
            t3 = pl.submit(sub_batch(3, start=30))
            out1, out2 = t1.result(timeout=5), t2.result(timeout=5)
            assert pl.flush_reasons["full"] >= 1
            assert d.batches[0] == [10, 11, 12, 20, 21, 22]
            # t1: 5 rows, 3 valid — echoed on valid rows, zero elsewhere
            assert out1["reason"].tolist() == [10, 11, 12, 0, 0]
            assert out1["allow"].tolist() == [True, True, True, False, False]
            assert out2["reason"].tolist() == [20, 21, 22]
            pl.drain(timeout=5)
            assert t3.result(timeout=5)["reason"].tolist() == [30, 31, 32]
        finally:
            pl.close(timeout=5)

    def test_deadline_flush(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=64, flush_ms=30.0)
        try:
            t = pl.submit(sub_batch(3, start=1))
            out = t.result(timeout=5)     # resolves via the deadline alone
            assert out["reason"].tolist() == [1, 2, 3]
            assert pl.flush_reasons["deadline"] == 1
        finally:
            pl.close(timeout=5)

    def test_drain_flushes_immediately(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=64, flush_ms=60_000.0)
        try:
            t = pl.submit(sub_batch(3, start=1))
            assert pl.drain(timeout=5)
            assert t.done() and pl.flush_reasons["drain"] == 1
        finally:
            pl.close(timeout=5)

    def test_fifo_ordering_across_mixed_shapes(self):
        """Valid rows hit the dispatch function in exact submission order
        no matter how submissions coalesce, bypass, or split."""
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1.0)
        try:
            rng = np.random.default_rng(3)
            want, start = [], 0
            for _ in range(60):
                n = int(rng.integers(1, 12))
                pl.submit(sub_batch(n, start=start))
                want.extend(range(start, start + n))
                start += n
            assert pl.drain(timeout=30)
            assert d.sports_seen == want
        finally:
            pl.close(timeout=5)

    def test_admission_drop_mode_accounts(self):
        d = EchoDispatch()
        d.gate.clear()                       # stall dispatch: queue backs up
        pl = Pipeline(d, min_bucket=4, max_bucket=4, queue_batches=2,
                      admission="drop", flush_ms=1000.0)
        try:
            tickets = [pl.submit(sub_batch(4, start=4 * i))
                       for i in range(8)]
            dropped = [t for t in tickets if t.dropped]
            assert dropped and pl.admission_drops == len(dropped)
            for t in dropped:
                with pytest.raises(PipelineDrop):
                    t.result(timeout=1)
            assert pl.metrics.counters[
                "pipeline_admission_drops_total"] == len(dropped)
            d.gate.set()
            assert pl.drain(timeout=10)
            for t in tickets:
                if not t.dropped:
                    t.result(timeout=5)
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_admission_block_timeout_drops(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = Pipeline(d, min_bucket=4, max_bucket=4, queue_batches=1,
                      admission="block", block_timeout_s=0.05,
                      flush_ms=1000.0)
        try:
            for i in range(8):
                last = pl.submit(sub_batch(4, start=4 * i))
            assert last.dropped and pl.admission_drops >= 1
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_zero_valid_resolves_without_dispatch(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16)
        try:
            out = pl.submit(sub_batch(6, start=0, n_valid=0)).result(
                timeout=5)
            assert out["allow"].shape == (6,) and not out["allow"].any()
            assert d.batches == []
        finally:
            pl.close(timeout=5)

    def test_dispatch_fault_retried_not_lost(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1000.0)
        try:
            FAULTS.arm("pipeline.dispatch", mode="fail", times=3)
            out = pl.submit(sub_batch(4, start=7)).result(timeout=10)
            assert out["reason"].tolist() == [7, 8, 9, 10]
            assert pl.dispatch_faults == 3
            assert pl.metrics.counters["pipeline_dispatch_faults_total"] == 3
        finally:
            pl.close(timeout=5)

    def test_dispatch_error_rejects_only_affected(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1000.0)
        try:
            d.fail_next = ValueError("device fell over")
            bad = pl.submit(sub_batch(4, start=0))
            with pytest.raises(PipelineError):
                bad.result(timeout=5)
            ok = pl.submit(sub_batch(4, start=50))
            assert ok.result(timeout=5)["reason"].tolist() == [50, 51, 52, 53]
            assert pl.dispatch_errors == 1
        finally:
            pl.close(timeout=5)

    def test_close_completes_queued_work(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = Pipeline(d, min_bucket=4, max_bucket=4, queue_batches=32,
                      flush_ms=1000.0)
        tickets = [pl.submit(sub_batch(4, start=4 * i)) for i in range(6)]
        d.gate.set()
        pl.close(timeout=10)
        for t in tickets:
            assert t.result(timeout=1)["allow"].all()
        with pytest.raises(PipelineClosed):
            pl.submit(sub_batch(4, start=0))
        pl.close(timeout=1)                 # idempotent

    def test_worker_crash_restarts_supervised(self):
        """A submission that crashes the worker mid-staging (malformed
        batch: missing columns) must come back rejected — not strand its
        ticket forever — and the watchdog-supervised restart keeps the
        pipeline serving (guard layer: crash → bounded restart, not a
        permanently dead pipeline)."""
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1000.0,
                      restart_backoff_s=0.01)
        bad = {"valid": np.ones(3, bool),
               "sport": np.arange(3, dtype=np.int32)}   # not a full batch
        t = pl.submit(bad)
        with pytest.raises(PipelineError):
            t.result(timeout=5)
        assert pl.drain(timeout=5)          # outstanding went back to zero
        # supervised restart: a fresh worker picks up where the dead one
        # wedged — new submissions still serve
        ok = pl.submit(sub_batch(4, start=0))
        assert ok.result(timeout=5)["allow"].all()
        assert pl.stats()["restarts"] == 1
        pl.close(timeout=5)
        with pytest.raises(PipelineClosed):
            pl.submit(sub_batch(4, start=0))

    def test_stats_shape(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=8, flush_ms=1.0)
        try:
            pl.submit(sub_batch(3, start=0))
            assert pl.drain(timeout=5)
            s = pl.stats()
            assert s["submitted"] == 1 and s["outstanding"] == 0
            assert 0 < s["fill_ratio_avg"] <= 1.0
            assert s["queue_wait_p99_ms"] >= 0.0
            text = pl.metrics.render_prometheus()
            assert "pipeline_queue_wait_seconds_bucket" in text
            assert 'le="+Inf"' in text
        finally:
            pl.close(timeout=5)


def pkt(src, dst, sp, dp, flags=C.TCP_SYN, ep_id=1):
    s16, _ = parse_addr(src)
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, C.PROTO_TCP, flags, False, ep_id,
                        C.DIR_EGRESS, C.HTTP_METHOD_ANY, b"")


def fake_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    kw.setdefault("batch_size", 64)
    cfg = DaemonConfig(**kw)
    return Engine(cfg, datapath=FakeDatapath(cfg))


def mk_chunks(slot_of, n_chunks, rows_per_chunk, seed=11, repeats=False):
    """An ingest stream of sub-full chunks: fresh SYNs to a mix of allowed
    (10/8:443) and denied (ports 80/22, off-prefix) destinations. With
    ``repeats`` every later chunk also revisits an early flow with an ACK,
    exercising CT continuity across batches."""
    rng = np.random.default_rng(seed)
    chunks = []
    for c in range(n_chunks):
        recs = []
        for r in range(rows_per_chunk):
            if repeats and c >= 2 and r == rows_per_chunk - 1:
                recs.append(pkt("192.168.1.10", "10.1.2.3", 41000, 443,
                                flags=C.TCP_ACK))
                continue
            dp = int(rng.choice([443, 443, 80, 22]))
            dst = f"10.{rng.integers(0, 2)}.2.{rng.integers(1, 250)}"
            sp = 42000 + c * rows_per_chunk + r
            flags = C.TCP_SYN
            if (c, r) == (0, 0):             # the flow later ACKs revisit
                sp, dp, dst = 41000, 443, "10.1.2.3"
            recs.append(pkt("192.168.1.10", dst, sp, dp, flags=flags))
        chunks.append(batch_from_records(recs, slot_of))
    return chunks


OUT_KEYS = ("allow", "reason", "status", "remote_identity", "svc",
            "nat_dst", "nat_dport", "rnat", "rnat_src", "rnat_sport")


def _mk_engine_pair(**kw):
    engines = []
    for _ in range(2):
        eng = fake_engine(**kw)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        engines.append(eng)
    return engines


def _assert_parity(ser, pipe, chunks):
    serial_outs = [ser.classify(dict(ch), now=100 + i)
                   for i, ch in enumerate(chunks)]
    tickets = [pipe.submit(dict(ch), now=100 + i)
               for i, ch in enumerate(chunks)]
    assert pipe.drain(timeout=30)
    for i, (t, want) in enumerate(zip(tickets, serial_outs)):
        got = t.result(timeout=5)
        for k in OUT_KEYS:
            np.testing.assert_array_equal(
                got[k], want[k],
                err_msg=f"chunk {i} field {k} diverged from serial")
    # same flows, same order → identical CT occupancy and drop counters
    assert pipe.ct_stats(now=200)["live"] == ser.ct_stats(now=200)["live"]
    assert pipe.metrics.packets_total == ser.metrics.packets_total
    np.testing.assert_array_equal(pipe.metrics.by_reason_dir,
                                  ser.metrics.by_reason_dir)


class TestEnginePipelineParity:
    def test_direct_path_bit_identical_with_ct_continuity(self):
        """Bucket-shaped submissions ride the zero-copy direct path, so the
        device sees the exact same batches as the serial engine — verdicts
        must be bit-identical including established-flow CT hits spanning
        batches (the acceptance contract: same batches → same tensors)."""
        ser, pipe = _mk_engine_pair(pipeline_min_bucket=16)
        chunks = mk_chunks(ser.active.snapshot.ep_slot_of, n_chunks=12,
                           rows_per_chunk=16, repeats=True)
        _assert_parity(ser, pipe, chunks)
        stats = pipe.pipeline_stats()
        assert stats["flush_reasons"]["direct"] == len(chunks)
        pipe.stop()
        ser.stop()

    def test_coalesced_path_matches_serial(self):
        """Sub-full chunks coalesce into buckets; per-row verdicts must
        still match the serial per-chunk path. (Flows here are unique per
        row — under the kernel's CT snapshot-batch semantics that is
        exactly the regime where batch composition cannot matter, which is
        what makes coalescing a legal scheduling choice.)"""
        ser, pipe = _mk_engine_pair(pipeline_min_bucket=16,
                                    pipeline_flush_ms=1.0)
        chunks = mk_chunks(ser.active.snapshot.ep_slot_of, n_chunks=24,
                           rows_per_chunk=5)
        _assert_parity(ser, pipe, chunks)
        stats = pipe.pipeline_stats()
        assert stats["submitted"] == len(chunks)
        assert stats["dispatched_batches"] < len(chunks)   # it did coalesce
        assert ser.pipeline_stats() is None    # never started on this one
        pipe.stop()
        ser.stop()

    def test_engine_status_doc_carries_pipeline(self):
        from cilium_tpu.runtime.api import status_doc
        eng = fake_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        assert status_doc(eng)["pipeline"] is None
        eng.submit(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)],
            eng.active.snapshot.ep_slot_of), now=100)
        assert eng.drain(timeout=10)
        doc = status_doc(eng)
        assert doc["pipeline"]["submitted"] == 1
        eng.stop()
        assert eng.pipeline_stats() is None    # stop() tears the pipeline down
        with pytest.raises(PipelineClosed):    # and bars lazy resurrection
            eng.submit(batch_from_records(
                [pkt("192.168.1.10", "10.1.2.3", 40001, 443)],
                eng.active.snapshot.ep_slot_of), now=101)


@pytest.mark.slow
class TestPipelineSoak:
    def test_soak_10k_submissions_with_faults(self):
        """`make pipeline-smoke` soak: 10k submissions through an engine on
        FakeDatapath with a 2% `pipeline.dispatch` fault storm armed the
        whole time — every ticket resolves, valid rows reach the datapath
        exactly once in submission order, nothing lost or reordered."""
        eng = fake_engine(pipeline_flush_ms=0.5, pipeline_queue_batches=256)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of

        seen = []
        real_async = eng.datapath.classify_async

        def recording_async(placed, snap, batch, now):
            seen.extend(np.asarray(batch["sport"])
                        [np.asarray(batch["valid"])].tolist())
            return real_async(placed, snap, batch, now)

        eng.datapath.classify_async = recording_async
        FAULTS.arm("pipeline.dispatch", mode="prob", prob=0.02, seed=99)

        n_sub, want = 10_000, []
        tickets = []
        for i in range(n_sub):
            n = 1 + (i % 3)
            recs = [pkt("192.168.1.10", "10.1.2.3", 40000 + i, 443)
                    for _ in range(n)]
            b = batch_from_records(recs, slot_of)
            b["sport"][:n] = np.arange(i * 4, i * 4 + n)   # unique tags
            want.extend(range(i * 4, i * 4 + n))
            tickets.append(eng.submit(b, now=100 + i))
        assert eng.drain(timeout=120)
        unresolved = sum(1 for t in tickets if not t.done())
        assert unresolved == 0
        for t in tickets[:100] + tickets[-100:]:
            t.result(timeout=1)
        assert seen == want, "valid rows lost or reordered under faults"
        stats = eng.pipeline_stats()
        assert stats["submitted"] == n_sub
        assert stats["dispatch_faults"] > 0     # the storm actually fired
        assert stats["admission_drops"] == 0
        eng.stop()
