"""FQDN policy (pkg/fqdn analog): selector matching, cache TTL semantics,
toFQDNs materialization into CIDR identities, learn/expire → policy
recompute, datapath verdicts, checkpoint persistence."""

import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.model.fqdn import FQDNCache, FQDNSelector
from cilium_tpu.model.rules import RuleParseError, parse_rules
from cilium_tpu.runtime.checkpoint import restore, save
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord


class TestSelector:
    def test_match_name(self):
        s = FQDNSelector(match_name="API.example.com.")
        assert s.matches("api.example.com")
        assert s.matches("api.EXAMPLE.com.")
        assert not s.matches("xapi.example.com")
        assert not s.matches("example.com")

    def test_match_pattern(self):
        s = FQDNSelector(match_pattern="*.example.com")
        assert s.matches("api.example.com")
        assert s.matches("a.b.example.com")  # '*' spans dots (upstream)
        assert not s.matches("example.com")
        assert not s.matches("api.example.org")

    def test_pattern_middle_star(self):
        s = FQDNSelector(match_pattern="api-*.prod.svc")
        assert s.matches("api-1.prod.svc")
        assert not s.matches("web-1.prod.svc")

    def test_exactly_one_of(self):
        with pytest.raises(ValueError):
            FQDNSelector()
        with pytest.raises(ValueError):
            FQDNSelector(match_name="a.com", match_pattern="*.com")


class TestCache:
    def test_observe_and_lookup(self):
        c = FQDNCache()
        assert c.observe("api.example.com", ["1.2.3.4"], ttl=60, now=100)
        # TTL refresh alone: no change notification needed
        assert not c.observe("api.example.com", ["1.2.3.4"], ttl=60, now=110)
        assert c.observe("api.example.com", ["1.2.3.5"], ttl=60, now=110)
        sel = FQDNSelector(match_name="api.example.com")
        assert c.lookup_selector(sel, now=120) == ["1.2.3.4", "1.2.3.5"]
        # expired IPs filtered from lookup even before GC
        assert c.lookup_selector(sel, now=1000) == []

    def test_expire_notifies(self):
        c = FQDNCache()
        events = []
        c.add_observer(lambda: events.append(1))
        c.observe("a.com", ["9.9.9.9"], ttl=50, now=0)
        assert len(events) == 1
        assert c.expire(now=10) == 0
        assert c.expire(now=60) == 1
        assert len(events) == 2
        assert len(c) == 0

    def test_relearn_after_expiry_notifies(self):
        c = FQDNCache()
        events = []
        c.observe("a.com", ["9.9.9.9"], ttl=50, now=0)
        c.add_observer(lambda: events.append(1))
        # expired but not GC'd, then refreshed: policy may lack the IP
        assert c.observe("a.com", ["9.9.9.9"], ttl=50, now=100)
        assert len(events) == 1

    def test_garbage_ips_skipped(self):
        """Unparseable IPs from a resolver must not poison the cache (they
        would crash rule materialization inside the change observer)."""
        c = FQDNCache()
        assert not c.observe("a.com", ["999.999.1.1", "nonsense"],
                             ttl=60, now=0)
        assert len(c) == 0
        assert c.observe("a.com", ["999.999.1.1", "1.2.3.4"], ttl=60, now=0)
        assert c.lookup_selector(FQDNSelector(match_name="a.com"),
                                 now=10) == ["1.2.3.4"]

    def test_null_matchname_rejected_cleanly(self):
        with pytest.raises(RuleParseError):
            parse_rules([{
                "endpointSelector": {},
                "egress": [{"toFQDNs": [{"matchName": None,
                                         "matchPattern": None}]}],
            }])

    def test_min_ttl(self):
        c = FQDNCache(min_ttl=300)
        c.observe("a.com", ["1.1.1.1"], ttl=1, now=0)
        assert c.lookup_selector(FQDNSelector(match_name="a.com"),
                                 now=200) == ["1.1.1.1"]


class TestRuleParsing:
    def test_tofqdns_parses(self):
        [r] = parse_rules([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toFQDNs": [{"matchName": "api.example.com"},
                                    {"matchPattern": "*.cdn.net"}],
                        "toPorts": [{"ports": [{"port": "443",
                                                "protocol": "TCP"}]}]}],
        }])
        assert len(r.egress[0].peer.fqdns) == 2

    def test_tofqdns_ingress_rejected(self):
        with pytest.raises(RuleParseError):
            parse_rules([{
                "endpointSelector": {},
                "ingress": [{"toFQDNs": [{"matchName": "a.com"}]}],
            }])

    def test_tofqdns_deny_rejected(self):
        with pytest.raises(RuleParseError):
            parse_rules([{
                "endpointSelector": {},
                "egressDeny": [{"toFQDNs": [{"matchName": "a.com"}]}],
            }])


FQDN_POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toFQDNs": [{"matchName": "api.example.com"}],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


def _engine(policy=FQDN_POLICY):
    """Engine with a test-controlled FQDN clock: rule materialization reads
    the cache through ``fqdn_cache.clock``, so tests that use synthetic
    ``now`` values must drive that clock too."""
    eng = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False))
    clock = {"t": 100}
    eng.ctx.fqdn_cache.clock = lambda: clock["t"]
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(policy)
    return eng, clock


def _pkt(dst, dport=443):
    s16, _ = parse_addr("192.168.1.10")
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, 40000, dport, C.PROTO_TCP, C.TCP_SYN,
                        False, 1, C.DIR_EGRESS)


class TestEndToEnd:
    def test_learn_allow_expire_deny(self):
        eng, clock = _engine()
        # before any DNS answer: default-deny (enforced egress, no peer)
        out = eng.classify(batch_from_records(
            [_pkt("20.1.2.3")], eng.active.snapshot.ep_slot_of), now=100)
        assert not bool(out["allow"][0])
        assert int(out["reason"][0]) == C.DropReason.POLICY

        # DNS answer learned → rule re-materializes → traffic allowed
        assert eng.observe_dns("api.example.com", ["20.1.2.3"], ttl=600,
                               now=100)
        out = eng.classify(batch_from_records(
            [_pkt("20.1.2.3", dport=443)], eng.active.snapshot.ep_slot_of),
            now=101)
        assert bool(out["allow"][0])
        # but only on the allowed port
        out = eng.classify(batch_from_records(
            [_pkt("20.1.2.3", dport=80)], eng.active.snapshot.ep_slot_of),
            now=102)
        assert not bool(out["allow"][0])

        # TTL expiry + GC → identity revoked → NEW flows denied again
        clock["t"] = 1000
        eng.ctx.fqdn_cache.expire(now=1000)
        out = eng.classify(batch_from_records(
            [_pkt("20.1.2.3", dport=443)], eng.active.snapshot.ep_slot_of),
            now=1001)
        assert not bool(out["allow"][0])

    def test_pattern_learns_multiple_names(self):
        eng, clock = _engine(policy=[{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toFQDNs": [{"matchPattern": "*.cdn.net"}]}],
        }])
        eng.observe_dns("a.cdn.net", ["30.0.0.1"], now=100)
        eng.observe_dns("b.cdn.net", ["30.0.0.2"], now=100)
        eng.observe_dns("evil.org", ["30.0.0.3"], now=100)
        slot_of = eng.active.snapshot.ep_slot_of
        out = eng.classify(batch_from_records(
            [_pkt("30.0.0.1"), _pkt("30.0.0.2"), _pkt("30.0.0.3")],
            slot_of), now=101)
        assert bool(out["allow"][0]) and bool(out["allow"][1])
        assert not bool(out["allow"][2])

    def test_checkpoint_persists_dns_cache(self, tmp_path):
        eng, clock = _engine()
        # expiry (= now + ttl) must beat the REAL clock: the restored engine
        # materializes rules with wall time
        eng.observe_dns("api.example.com", ["20.1.2.3"], ttl=10**10, now=100)
        eng.active
        save(eng, str(tmp_path / "s"))
        eng2 = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False))
        restore(eng2, str(tmp_path / "s"))
        assert len(eng2.ctx.fqdn_cache) == 1
        out = eng2.classify(batch_from_records(
            [_pkt("20.1.2.3")], eng2.active.snapshot.ep_slot_of), now=105)
        assert bool(out["allow"][0])

    def test_cli_fqdn_cache(self, tmp_path, capsys):
        from cilium_tpu.cli.main import main as cli_main
        import json
        eng, clock = _engine()
        eng.observe_dns("api.example.com", ["20.1.2.3"], ttl=500, now=100)
        eng.active
        save(eng, str(tmp_path / "s"))
        rc = cli_main(["fqdn", "cache", "--state-dir", str(tmp_path / "s"),
                       "-o", "json"])
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc[0]["name"] == "api.example.com"
        assert "20.1.2.3" in doc[0]["ips"]
