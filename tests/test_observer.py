"""ISSUE 11: the vectorized flow-observe engine (observe/observer.py), the
hubble-relay-style fan-in (observe/relay.py), per-rule hit counters, and the
explainable-flow surface (API route, CLI, blackbox provenance).

Pinned here:
- FlowFilter mask composition (allow-OR / deny-subtract / field-AND) over
  the columnar ring, including CIDR matching on v4-mapped words
- one-shot vs follow read modes; follow NEVER loses records silently —
  every ring wraparound past a cursor is an explicit structured gap
  (acceptance criterion), including under a live writer race
- relay fan-in: k-way merge ordering, node tags, per-source cursors/lag,
  gap re-emission; the 4-engine fan-in phase `make observe-smoke` runs
- per-rule hit/drop counters {rule=} with capped cardinality, scraped
  concurrently with a sharded soak (the satellite race test)
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.observe.observer import (FlowFilter, FlowObserver,
                                         FollowCursor, compose_mask,
                                         parse_filters)
from cilium_tpu.observe.relay import FlowRelay
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.runtime.flowlog import FlowLog
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr

from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine

from tests.test_audit import setup_web, sharded_audited_engine, web_batch
from tests.test_pipeline import POLICY, fake_engine, mk_chunks, pkt


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _words(addr: str) -> np.ndarray:
    a16, _ = parse_addr(addr)
    return np.frombuffer(a16, dtype=">u4").astype(np.uint32)


def mk_batch_out(n, *, allow=True, reason=0, rule=3, pfx=0x10A, pre=1,
                 ident=1234, sport0=40000, dport=443, proto=C.PROTO_TCP,
                 direction=C.DIR_EGRESS, src="192.168.1.10", dst="10.1.2.3"):
    """Synthetic (batch, out) pair in the kernels/records column layout —
    enough surface for the flowlog/observer to extract."""
    batch = {
        "valid": np.ones(n, dtype=bool),
        "ep_slot": np.zeros(n, dtype=np.int32),
        "src": np.tile(_words(src), (n, 1)),
        "dst": np.tile(_words(dst), (n, 1)),
        "sport": np.arange(sport0, sport0 + n, dtype=np.uint32),
        "dport": np.full(n, dport, dtype=np.uint32),
        "proto": np.full(n, proto, dtype=np.int32),
        "direction": np.full(n, direction, dtype=np.int32),
    }
    out = {
        "allow": np.full(n, allow, dtype=bool),
        "reason": np.full(n, reason, dtype=np.int32),
        "status": np.full(n, pre, dtype=np.int32),
        "matched_rule": np.full(n, rule, dtype=np.int32),
        "lpm_prefix": np.full(n, pfx, dtype=np.int32),
        "ct_state_pre": np.full(n, pre, dtype=np.int32),
        "remote_identity": np.full(n, ident, dtype=np.int32),
    }
    return batch, out


def fill(log, n, **kw):
    now = kw.pop("now", 1)
    batch, out = mk_batch_out(n, **kw)
    log.append_batch(batch, out, now=now, ep_ids=(1,))


# --------------------------------------------------------------------------- #
# filter mask composition
# --------------------------------------------------------------------------- #
class TestFilterMasks:
    def _cols(self):
        log = FlowLog(capacity=64, mode="all")
        fill(log, 4, allow=True, rule=3, dport=443)
        fill(log, 3, allow=False, reason=int(C.DropReason.POLICY_DENY),
             rule=7, dport=80, dst="10.9.0.5")
        fill(log, 2, allow=False, reason=int(C.DropReason.CT_INVALID),
             rule=-1, pfx=-1, dst="172.16.3.9", proto=C.PROTO_UDP,
             dport=53, direction=C.DIR_INGRESS)
        cols, _, _ = log.snapshot_columns()
        return cols

    def test_verdict_reason_and_allow_or(self):
        cols = self._cols()
        m = FlowFilter(verdict="FORWARDED").mask(cols)
        assert int(m.sum()) == 4
        m = FlowFilter(
            reasons=(int(C.DropReason.POLICY_DENY),)).mask(cols)
        assert int(m.sum()) == 3
        # allowlist ORs its filters
        m = compose_mask(cols, allow=[
            FlowFilter(verdict="FORWARDED"),
            FlowFilter(reasons=(int(C.DropReason.CT_INVALID),))])
        assert int(m.sum()) == 6

    def test_deny_subtracts_and_fields_and(self):
        cols = self._cols()
        # empty allowlist = everything; denylist subtracts
        m = compose_mask(cols, deny=[FlowFilter(verdict="DROPPED")])
        assert int(m.sum()) == 4
        # fields inside one filter AND: dropped AND udp = the CT_INVALID rows
        m = compose_mask(cols, allow=[
            FlowFilter(verdict="DROPPED", protos=(C.PROTO_UDP,))])
        assert int(m.sum()) == 2

    def test_rule_identity_direction_ports(self):
        cols = self._cols()
        assert int(FlowFilter(rules=(7,)).mask(cols).sum()) == 3
        assert int(FlowFilter(rules=(3, 7)).mask(cols).sum()) == 7
        assert int(FlowFilter(identities=(1234,)).mask(cols).sum()) == 9
        assert int(FlowFilter(
            direction=C.DIR_INGRESS).mask(cols).sum()) == 2
        assert int(FlowFilter(dports=(80,)).mask(cols).sum()) == 3
        # port matches src OR dst
        assert int(FlowFilter(ports=(443,)).mask(cols).sum()) == 4

    def test_cidr_matching_v4_mapped(self):
        cols = self._cols()
        assert int(FlowFilter(dst_cidrs=("10.0.0.0/8",)).mask(cols).sum()) \
            == 7
        assert int(FlowFilter(
            dst_cidrs=("172.16.0.0/12",)).mask(cols).sum()) == 2
        assert int(FlowFilter(
            src_cidrs=("192.168.1.0/24",)).mask(cols).sum()) == 9
        # any-direction cidr: src OR dst
        assert int(FlowFilter(cidrs=("10.9.0.0/16",)).mask(cols).sum()) == 3
        # OR within the cidr list
        assert int(FlowFilter(
            dst_cidrs=("10.9.0.0/16", "172.16.0.0/12")).mask(cols).sum()) \
            == 5

    def test_parse_filters(self):
        allow, deny = parse_filters({
            "verdict": "dropped", "reason": "POLICY_DENY,6",
            "proto": "TCP", "rule": "3,7", "not_dport": "53",
            "last": "10"})                 # non-filter keys ignored
        assert len(allow) == 1 and len(deny) == 1
        f = allow[0]
        assert f.verdict == "DROPPED"
        assert int(C.DropReason.POLICY_DENY) in f.reasons and 6 in f.reasons
        assert f.protos == (C.PROTO_TCP,) and f.rules == (3, 7)
        assert deny[0].dports == (53,)
        # each not_* KEY is its own deny filter (independent exclusions
        # OR via compose_mask; one AND-ed filter would deny almost nothing)
        _, deny = parse_filters({"not_verdict": "FORWARDED",
                                 "not_dport": "53,80"})
        assert len(deny) == 2
        assert {f.verdict for f in deny} == {"FORWARDED", None}
        assert (53, 80) in {f.dports for f in deny}
        with pytest.raises(ValueError):
            parse_filters({"reason": "NO_SUCH_REASON"})
        with pytest.raises(ValueError):
            parse_filters({"verdict": "MAYBE"})
        # value validation covers the DENYLIST too, and CIDRs fail at
        # parse time (a 400), not inside the scan (a 500)
        with pytest.raises(ValueError):
            parse_filters({"not_verdict": "MAYBE"})
        with pytest.raises(ValueError):
            parse_filters({"cidr": "banana"})
        # repeated scalar --not flags reach the parser comma-joined (the
        # API accumulates duplicate not_* keys); each part denies alone
        _, deny = parse_filters({"not_verdict": "FORWARDED,DROPPED"})
        assert {f.verdict for f in deny} == {"FORWARDED", "DROPPED"}
        # an unknown not_* key is a typo'd exclusion: silently dropping it
        # would fail OPEN (streaming the very flows the operator excluded)
        with pytest.raises(ValueError):
            parse_filters({"not_identty": "123"})

    def test_monitor_follower_handles_gap_records(self):
        """The legacy `monitor --api -f` surface: gap markers render as a
        line (not a TypeError on missing flow fields) and pass every
        client-side filter — loss is never hidden."""
        from cilium_tpu.cli.commands import _flow_line, _flow_matches
        gap = {"gap": True, "dropped": 7, "resume_seq": 42}
        line = _flow_line(gap)
        assert "7" in line and "42" in line and "gap" in line

        class _Args:
            verdict = "DROPPED"
            endpoint = 3
            ip = "1.2.3.4"
            port = 80
        assert _flow_matches(gap, _Args())


# --------------------------------------------------------------------------- #
# observe read modes
# --------------------------------------------------------------------------- #
class TestObserveModes:
    def test_oneshot_last_window_newest(self):
        log = FlowLog(capacity=64, mode="all")
        fill(log, 10)
        obs = FlowObserver(log)
        res = obs.observe(last=3)
        assert [r["seq"] for r in res["flows"]] == [8, 9, 10]
        assert res["matched"] == 10 and res["scanned"] == 10
        assert res["gap"] is None and res["cursor"] == 10

    def test_follow_truncation_resumes_without_loss(self):
        log = FlowLog(capacity=64, mode="all")
        fill(log, 10)
        cur = FollowCursor(FlowObserver(log))
        seqs = []
        for _ in range(5):
            seqs += [r["seq"] for r in cur.poll(limit=4)]
        assert seqs == list(range(1, 11))
        assert cur.poll(limit=4) == []     # drained

    def test_follow_gap_marker_counter_and_metrics(self):
        m = Metrics()
        log = FlowLog(capacity=8, mode="all", metrics=m)
        fill(log, 20)                      # ring keeps 13..20
        cur = FollowCursor(FlowObserver(log, metrics=m), cursor=5)
        out = cur.poll()
        assert out[0] == {"gap": True, "dropped": 7, "resume_seq": 13}
        assert [r["seq"] for r in out[1:]] == list(range(13, 21))
        assert cur.gaps == 1 and cur.dropped == 7
        assert log.follow_gaps == 1 and log.follow_gap_records == 7
        assert m.counters["flowlog_follow_gaps_total"] == 1
        assert m.counters["flowlog_follow_gap_records_total"] == 7

    def test_fresh_attach_is_not_a_gap(self):
        log = FlowLog(capacity=8, mode="all")
        fill(log, 20)
        res = FlowObserver(log).observe(since=0)
        assert res["gap"] is None
        assert [r["seq"] for r in res["flows"]] == list(range(13, 21))

    def test_filters_apply_in_follow_mode(self):
        log = FlowLog(capacity=64, mode="all")
        fill(log, 4, allow=True)
        fill(log, 3, allow=False, reason=int(C.DropReason.POLICY_DENY))
        cur = FollowCursor(FlowObserver(log),
                           allow=[FlowFilter(verdict="DROPPED")])
        out = cur.poll()
        assert len(out) == 3
        assert all(r["verdict"] == "DROPPED" for r in out)
        assert cur.cursor == 7             # advanced past non-matching too


# --------------------------------------------------------------------------- #
# follow-mode racing ring wraparound (acceptance: no silent loss)
# --------------------------------------------------------------------------- #
class TestFollowRacesWraparound:
    def test_live_writer_race_accounts_every_record(self):
        """A writer wrapping a small ring at full speed vs a follower with
        a small poll page: every appended record is either DELIVERED or
        covered by an explicit gap marker — seqs delivered strictly
        increasing, delivered + dropped == appended, nothing silent."""
        log = FlowLog(capacity=64, mode="all")
        n_batches, per = 150, 7
        stop = threading.Event()

        def writer():
            for i in range(n_batches):
                fill(log, per, now=i)
                if i % 10 == 0:
                    time.sleep(0.001)
            stop.set()

        cur = FollowCursor(FlowObserver(log))
        delivered = []
        t = threading.Thread(target=writer)
        t.start()
        while not (stop.is_set() and cur.cursor >= log.newest_seq):
            for r in cur.poll(limit=16):
                if not r.get("gap"):
                    delivered.append(r["seq"])
        t.join()
        total = n_batches * per
        assert log.newest_seq == total
        # a guaranteed lap (scheduling-independent): one burst larger than
        # the whole ring lands between two polls — also exercises the
        # single-batch-bigger-than-capacity trim path
        fill(log, 200, now=999)
        for r in cur.poll():
            if not r.get("gap"):
                delivered.append(r["seq"])
        total += 200
        # strictly increasing — no duplicates, no reordering
        assert all(a < b for a, b in zip(delivered, delivered[1:]))
        # explicit accounting: what wasn't delivered was declared dropped
        assert len(delivered) + cur.dropped == total
        # the ring provably wrapped past the follower and said so
        assert cur.gaps >= 1 and cur.dropped >= 136


# --------------------------------------------------------------------------- #
# relay fan-in
# --------------------------------------------------------------------------- #
class TestRelay:
    def _three(self):
        logs = {f"node{i}": FlowLog(capacity=64, mode="all")
                for i in range(3)}
        # interleaved times across sources: node0 t=1, node1 t=2, node2 t=3,
        # then node0 again at t=9 (newest globally)
        fill(logs["node0"], 2, now=1)
        fill(logs["node1"], 2, now=2)
        fill(logs["node2"], 2, now=3)
        fill(logs["node0"], 1, now=9)
        return logs

    def test_oneshot_merge_orders_and_tags(self):
        relay = FlowRelay(self._three())
        res = relay.observe()
        flows = res["flows"]
        assert len(flows) == 7
        times = [r["time"] for r in flows]
        assert times == sorted(times)
        assert flows[-1]["node"] == "node0" and flows[-1]["time"] == 9
        assert set(res["sources"]) == {"node0", "node1", "node2"}
        # last= is a GLOBAL window, not per-source
        res = relay.observe(last=3)
        assert len(res["flows"]) == 3
        assert res["flows"][-1]["time"] == 9

    def test_oneshot_last_zero_is_the_full_retained_window(self):
        """last=0 must not silently truncate a source to the observer's
        default one-shot cap: every retained record fans in."""
        log = FlowLog(capacity=8192, mode="all")
        for _ in range(3):             # 6000 retained > the default 4096
            fill(log, 2000, now=1)     # one-shot limit, under the per-
        relay = FlowRelay({"big": log})   # append extract cap
        res = relay.observe()
        assert len(res["flows"]) == 6000
        assert res["sources"]["big"]["matched"] == 6000

    def test_poll_cursors_lag_and_gap_reemission(self):
        m = Metrics()
        logs = self._three()
        relay = FlowRelay(logs, metrics=m)
        res = relay.poll()
        assert len(res["flows"]) == 7 and res["gaps"] == []
        assert all(v == 0 for v in res["lag"].values())
        assert relay.cursors()["node0"] == 3
        # wrap node1 past its cursor: 70 records through a 64-slot ring
        for i in range(10):
            fill(logs["node1"], 7, now=20 + i)
        res = relay.poll()
        assert len(res["gaps"]) == 1
        g = res["gaps"][0]
        # node1's cursor sat at seq 2; 70 appends through a 64-slot ring
        # retain 9..72 — seqs 3..8 are the declared loss
        assert g["node"] == "node1" and g["dropped"] == 6
        # the gap marker leads its source's run in the merged stream
        node1_rows = [r for r in res["flows"] if r["node"] == "node1"]
        assert node1_rows[0].get("gap") is True
        assert len(node1_rows) == 1 + 64
        assert m.counters["relay_source_gaps_total"] == 1
        assert 'relay_source_lag{source="node1"}' in m.gauges

    def test_poll_truncation_shows_lag(self):
        logs = {"a": FlowLog(capacity=256, mode="all")}
        fill(logs["a"], 100)
        relay = FlowRelay(logs)
        res = relay.poll(limit=30)
        assert len(res["flows"]) == 30
        assert res["lag"]["a"] == 70       # behind by what it didn't page
        res = relay.poll(limit=100)
        assert res["lag"]["a"] == 0

    def test_fan_in_over_four_engines(self):
        """The single-host stand-in for ROADMAP item 3's multi-host tier:
        four engines classify disjoint flows; one relay merges their rings
        with node attribution and loses nothing."""
        engines = []
        try:
            for i in range(4):
                eng = setup_web(fake_engine(flowlog_mode="all"))
                slot_of = eng.active.snapshot.ep_slot_of
                recs = [pkt("192.168.1.10", f"10.{i}.2.{j + 1}",
                            41000 + 10 * i + j, 443) for j in range(3)]
                eng.classify(batch_from_records(recs, slot_of),
                             now=100 + i)
                engines.append(eng)
            relay = FlowRelay({f"host{i}": e.flowlog
                               for i, e in enumerate(engines)})
            res = relay.poll()
            assert len(res["flows"]) == 12 and not res["gaps"]
            by_node = {n: sum(1 for r in res["flows"] if r["node"] == n)
                       for n in relay.cursors()}
            assert by_node == {f"host{i}": 3 for i in range(4)}
            # provenance rides through the fan-in
            assert all(r["matched_rule"] >= 0 and r["lpm_prefix"] >= 0
                       for r in res["flows"])
            # filtered fan-in: a rule filter applies on every source
            rule = res["flows"][0]["matched_rule"]
            res2 = relay.observe(allow=[FlowFilter(rules=(rule,))])
            assert len(res2["flows"]) == 12
        finally:
            for e in engines:
                e.stop()


# --------------------------------------------------------------------------- #
# engine integration: provenance columns, rule counters, explain
# --------------------------------------------------------------------------- #
class TestEngineObserver:
    def test_observe_and_explain_through_engine(self):
        eng = setup_web(fake_engine(flowlog_mode="all"))
        try:
            eng.classify(web_batch(eng), now=100)   # 443 allow, 80/22 drop
            res = eng.observer.observe(
                allow=[FlowFilter(verdict="DROPPED")])
            assert res["matched"] == 2
            fwd = eng.observer.observe(
                allow=[FlowFilter(verdict="FORWARDED")])["flows"]
            assert len(fwd) == 1
            r = fwd[0]
            # the allowed flow names its evidence
            assert r["matched_rule"] >= 0 and r["lpm_prefix"] >= 0
            assert r["ct_state_pre"] == "NEW"
            legend = eng.explain_provenance(fwd)
            rinfo = legend["rules"][str(r["matched_rule"])]
            assert rinfo["resolved"]
            pinfo = legend["prefixes"][str(r["lpm_prefix"])]
            assert pinfo["resolved"] and "10.0.0.0" in pinfo["prefix"]
            # rule filter round-trips: every flow this cell decided
            again = eng.observer.observe(
                allow=[FlowFilter(rules=(r["matched_rule"],),
                                  verdict="FORWARDED")])
            assert again["matched"] == 1
        finally:
            eng.stop()

    def test_rule_hit_counters_render(self):
        eng = setup_web(fake_engine(flowlog_mode="all"))
        try:
            for i in range(3):
                eng.classify(web_batch(eng), now=100 + i)
            text = eng.render_metrics()
            hit_lines = [ln for ln in text.splitlines()
                         if "policy_rule_hits_total{rule=" in ln]
            drop_lines = [ln for ln in text.splitlines()
                          if "policy_rule_drops_total{rule=" in ln]
            assert hit_lines and drop_lines
            # 3 batches x 1 allowed row through the ladder
            assert sum(int(float(ln.rsplit(" ", 1)[1]))
                       for ln in hit_lines) == 3
            # 3 batches x 2 denied rows (80 + 22)
            assert sum(int(float(ln.rsplit(" ", 1)[1]))
                       for ln in drop_lines) == 6
            # labels resolve to the ic/pc[/id] tag form
            assert 'rule="ic' in hit_lines[0]
        finally:
            eng.stop()

    def test_rule_label_cardinality_cap(self):
        eng = setup_web(fake_engine(flowlog_mode="all",
                                    rule_metrics_max=1))
        try:
            eng.classify(web_batch(eng), now=100)   # ≥2 distinct cells
            text = eng.render_metrics()
            labels = {ln.split('rule="')[1].split('"')[0]
                      for ln in text.splitlines()
                      if "policy_rule_" in ln and "rule=" in ln}
            assert "other" in labels
            assert len(labels - {"other"}) <= 1
        finally:
            eng.stop()

    def test_rule_counters_disabled(self):
        eng = setup_web(fake_engine(flowlog_mode="all", rule_metrics_max=0))
        try:
            eng.classify(web_batch(eng), now=100)
            assert "policy_rule_" not in eng.render_metrics()
        finally:
            eng.stop()

    def test_blackbox_verdict_summary_carries_provenance(self):
        eng = setup_web(fake_engine(flowlog_mode="all"))
        try:
            eng.classify(web_batch(eng), now=100)
            bundle = eng.debug_bundle()
            vs = bundle["verdict_summaries"][-1]
            assert vs["dropped"] == 2
            assert vs["top_drop_rules"] and vs["top_drop_prefixes"]
            assert vs["drop_ct_states"]
        finally:
            eng.stop()

    def test_api_observe_route(self, tmp_path):
        from cilium_tpu.runtime.api import APIServer, UnixAPIClient
        eng = setup_web(fake_engine(flowlog_mode="all"))
        sock = str(tmp_path / "api.sock")
        srv = APIServer(eng, sock)
        srv.start()
        try:
            eng.classify(web_batch(eng), now=100)
            client = UnixAPIClient(sock)
            code, res = client.get(
                "/v1/flows/observe?verdict=DROPPED&explain=1")
            assert code == 200 and res["matched"] == 2
            assert all(r["verdict"] == "DROPPED" for r in res["flows"])
            assert "legend" in res and res["legend"]["revision"] >= 0
            # follow from the returned cursor: drained, then new records
            cursor = res["cursor"]
            code, res = client.get(f"/v1/flows/observe?since={cursor}")
            assert code == 200 and res["flows"] == []
            eng.classify(web_batch(eng), now=101)
            code, res = client.get(f"/v1/flows/observe?since={cursor}")
            assert code == 200 and len(res["flows"]) == 3
            # denylist param
            code, res = client.get("/v1/flows/observe?not_verdict=DROPPED")
            assert code == 200
            assert all(r["verdict"] == "FORWARDED" for r in res["flows"])
            # bad filter → 400, not 500
            code, res = client.get("/v1/flows/observe?reason=BOGUS")
            assert code == 400
            # ... including bad CIDRs and bad DENYLIST verdicts (which
            # must never silently filter as the wrong polarity)
            code, res = client.get("/v1/flows/observe?cidr=banana")
            assert code == 400
            code, res = client.get("/v1/flows/observe?not_verdict=FORWARD")
            assert code == 400
            # percent-encoded values decode (the CLI quotes '/' in CIDRs)
            code, res = client.get(
                "/v1/flows/observe?dst_cidr=10.0.0.0%2F8")
            assert code == 200 and res["matched"] == 6
            # repeated not_* keys accumulate (repeatable --not flags) and
            # independent deny KEYS each exclude on their own (OR, not AND)
            code, res = client.get(
                "/v1/flows/observe?not_dport=80&not_dport=22")
            assert code == 200
            assert {r["dst_port"] for r in res["flows"]} == {443}
            code, res = client.get(
                "/v1/flows/observe?not_verdict=FORWARDED&not_dport=22")
            assert code == 200 and res["flows"]
            assert all(r["verdict"] == "DROPPED" and r["dst_port"] == 80
                       for r in res["flows"])
            # observer counters surfaced in /v1/status
            code, st = client.get("/v1/status")
            assert code == 200 and st["observer"]["queries"] >= 4
        finally:
            srv.stop()
            eng.stop()

    def test_cli_observe(self, tmp_path, capsys):
        from cilium_tpu.cli.main import main as cli_main
        from cilium_tpu.runtime.api import APIServer
        eng = setup_web(fake_engine(flowlog_mode="all"))
        sock = str(tmp_path / "api.sock")
        srv = APIServer(eng, sock)
        srv.start()
        try:
            eng.classify(web_batch(eng), now=100)
            rc = cli_main(["observe", "--api", sock,
                           "--verdict", "DROPPED"])
            out = capsys.readouterr().out
            assert rc == 0
            lines = [ln for ln in out.splitlines() if ln]
            assert len(lines) == 2
            # the one-line provenance rendering: verdict + evidence
            assert all("because rule" in ln and "/ CT " in ln
                       for ln in lines)
            assert all("DROPPED" in ln for ln in lines)
            # allowed flow resolves its winning prefix in the legend
            rc = cli_main(["observe", "--api", sock,
                           "--verdict", "FORWARDED"])
            out = capsys.readouterr().out
            assert rc == 0 and "prefix 10.0.0.0/8" in out
            # json mode emits records
            rc = cli_main(["observe", "--api", sock, "-o", "json",
                           "--not", "verdict=DROPPED"])
            out = capsys.readouterr().out
            assert rc == 0
            import json as _json
            recs = [_json.loads(ln) for ln in out.splitlines() if ln]
            assert all(r["verdict"] == "FORWARDED" for r in recs)
        finally:
            srv.stop()
            eng.stop()


# --------------------------------------------------------------------------- #
# concurrent {rule=} scrape during a sharded soak + follower racing wrap
# --------------------------------------------------------------------------- #
class TestScrapeRaceRuleLabels:
    def test_rule_family_scrape_races_sharded_soak_with_follower(self):
        """The satellite race: an 8-shard soak (auditor armed at 1.0 — the
        provenance columns are part of the audited surface) while (a) two
        scrapers hammer render_metrics asserting every exposition parses
        with the {rule=} family present and one TYPE per base, and (b) a
        follow-mode observer races the deliberately tiny flowlog ring —
        wraparound under load must surface as explicit gaps, with
        delivered + dropped == appended."""
        eng = sharded_audited_engine(flowlog_mode="all",
                                     flowlog_capacity=128)
        setup_web(eng)
        chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=16,
                           rows_per_chunk=8)
        errors = []
        stop = threading.Event()

        def scraper():
            seen_rule_family = False
            while not stop.is_set():
                try:
                    text = eng.render_metrics()
                    types = set()
                    for ln in text.splitlines():
                        if ln.startswith("# TYPE"):
                            assert "{" not in ln, f"labeled TYPE: {ln}"
                            base = ln.split()[2]
                            assert base not in types, f"dup TYPE {base}"
                            types.add(base)
                    seen_rule_family |= "policy_rule_hits_total{" in text
                except Exception as e:   # noqa: BLE001
                    errors.append(e)
                    return
            if not seen_rule_family:
                errors.append(AssertionError("no {rule=} family scraped"))

        cur = FollowCursor(FlowObserver(eng.flowlog))
        delivered = [0]

        def follower():
            try:
                while not stop.is_set() or cur.cursor < eng.flowlog.newest_seq:
                    for r in cur.poll(limit=32):
                        if not r.get("gap"):
                            delivered[0] += 1
                    time.sleep(0.002)
            except Exception as e:   # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=scraper, daemon=True)
                   for _ in range(2)]
        threads.append(threading.Thread(target=follower, daemon=True))
        for t in threads:
            t.start()
        try:
            eng.start_pipeline()
            for round_ in range(3):
                tickets = [eng.submit(dict(ch), now=100 + i)
                           for i, ch in enumerate(chunks)]
                assert eng.drain(timeout=30)
                for tk in tickets:
                    tk.result(timeout=5)
            eng.audit_step(budget=None)
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0 and st["mismatched_rows"] == 0
        finally:
            stop.set()
            for t in threads:
                t.join(10)
            eng.stop()
        assert not errors, errors[:1]
        # follower accounting over the whole soak (ring wrapped ~3x)
        total = eng.flowlog.newest_seq
        assert total > eng.flowlog.capacity
        assert delivered[0] + cur.dropped == total


# --------------------------------------------------------------------------- #
# slow soaks: the observe-smoke attestation + relay fan-in phase
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestObserverOverheadSoak:
    def test_follow_filters_armed_under_two_percent(self):
        """The <2% contract in the PR 3 attestation form: (1) the precise,
        deterministic measurement — incremental follow-mode polling with a
        compound filter armed (verdict + ports + CIDR: the masks, the
        since-cursor column slice, and the matched-row rendering) costs,
        per appended batch, under 2% of the measured per-submission
        pipeline cost; (2) an interleaved end-to-end soak with a live
        follower thread as a loose gross-regression bound (wall-clock on
        a multi-threaded pipeline carries scheduler noise above 2%)."""
        import gc
        # 64-row chunks: the representative serving shape (the pipeline
        # coalesces toward batch_size=64 buckets) — an 8-row toy chunk
        # would understate the submit path the 2% is measured against
        eng = setup_web(fake_engine(flowlog_mode="all",
                                    pipeline_min_bucket=16))
        chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=8,
                           rows_per_chunk=64)
        # armed-but-selective (the needle case a follow filter exists
        # for): the full mask set runs every poll, but almost nothing
        # matches — matched rows are delivered PAYLOAD the consumer asked
        # for, not overhead, so the overhead contract measures the scan
        filters = [FlowFilter(verdict="DROPPED", dports=(9999,),
                              dst_cidrs=("10.0.0.0/8",))]

        def one_pass(n_rounds=4):
            t0 = time.perf_counter()
            n = 0
            for _r in range(n_rounds):
                for i, ch in enumerate(chunks):
                    eng.submit(dict(ch), now=1000 + i)
                    n += 1
                assert eng.drain(timeout=60)
            return (time.perf_counter() - t0) / n

        # micro: append+incremental-poll vs append-only, same ring geometry
        # and per-batch row count as the pipeline soak. The follower polls
        # once per 4 appended batches — the bench's 1ms wall cadence sees
        # well over 4 batches per tick at soak throughput, so this is the
        # conservative end of the realistic cadence range. The armed
        # filter is selective (the needle case a follow filter exists
        # for): one row per poll window matches and pays its rendering.
        log = FlowLog(capacity=eng.config.flowlog_capacity, mode="all")
        b_plain, o_plain = mk_batch_out(
            64, allow=False, reason=int(C.DropReason.POLICY_DENY), dport=80)
        b_hit, o_hit = mk_batch_out(
            64, allow=False, reason=int(C.DropReason.POLICY_DENY), dport=80)
        b_hit["dport"][0] = 22           # the needle
        micro_filters = [FlowFilter(verdict="DROPPED", dports=(22,),
                                    dst_cidrs=("10.0.0.0/8",))]
        cur = FollowCursor(FlowObserver(log), allow=micro_filters)
        reps = 600

        def micro_pass(poll):
            t0 = time.perf_counter()
            for _ in range(reps):
                for bb, oo in ((b_plain, o_plain), (b_plain, o_plain),
                               (b_plain, o_plain), (b_hit, o_hit)):
                    log.append_batch(bb, oo, now=1, ep_ids=(1,))
                if poll:
                    cur.poll()
            return (time.perf_counter() - t0) / (reps * 4)

        one_pass(2)                      # warmup the pipeline path
        micro_pass(True)                 # warmup the micro path
        gc_was = gc.isenabled()
        gc.disable()
        try:
            micro_off = min(micro_pass(False) for _ in range(5))
            micro_on = min(micro_pass(True) for _ in range(5))

            off, on = [], []
            for _i in range(3):          # interleaved A/B windows
                off.append(one_pass())
                stop = threading.Event()
                fcur = FollowCursor(FlowObserver(eng.flowlog),
                                    allow=filters)

                def follow():
                    while not stop.is_set():
                        fcur.poll(limit=4096)
                        time.sleep(0.001)

                th = threading.Thread(target=follow, daemon=True)
                th.start()
                try:
                    on.append(one_pass())
                finally:
                    stop.set()
                    th.join(5)
        finally:
            if gc_was:
                gc.enable()
        per_submit = min(off)
        delta = micro_on - micro_off     # true per-batch follow cost
        frac = delta / per_submit
        assert frac < 0.02, \
            f"filters-armed follow adds {delta * 1e6:.1f}us/batch = " \
            f"{frac:.2%} of the {per_submit * 1e6:.1f}us submit path " \
            f"(budget 2%)"
        # the gross bound is LOOSE by design: the oracle-backed fake
        # engine is GIL-bound pure Python, so a concurrent poll thread
        # costs wall-clock far beyond its measured CPU (scheduler ping-
        # pong) — the precise 2% contract is the micro above, and the
        # real-datapath fps gate lives in `bench.py --ingest --observer`
        # (device compute releases the GIL there). This guards against
        # catastrophic regressions only (a lock held across the scan, a
        # render of unmatched rows).
        assert min(on) <= min(off) * 1.6, \
            f"end-to-end regression: off={min(off) * 1e6:.1f}us " \
            f"on={min(on) * 1e6:.1f}us"
        eng.stop()


class _Sharded4(FakeDatapath):
    pipeline_shards = 4


@pytest.mark.slow
class TestRelayFanInPhase:
    def test_relay_follows_live_4shard_mesh_plus_peers(self):
        """The observe-smoke fan-in phase: one 4-shard mesh engine under
        pipelined load + three plain engines classifying, all four rings
        fanned in by one live-polling relay. Every source's records are
        either merged (node-tagged, time-ordered per poll) or declared in
        a gap; the sharded engine's auditor (sampling 1.0 — provenance is
        part of the audited surface) stays clean throughout."""
        cfg = DaemonConfig(ct_capacity=4096, auto_regen=False,
                           batch_size=64, audit_enabled=True,
                           audit_sample_rate=1.0, flowlog_mode="all",
                           flowlog_capacity=256)
        mesh_eng = Engine(cfg, datapath=_Sharded4(cfg))
        setup_web(mesh_eng)
        peers = [setup_web(fake_engine(flowlog_mode="all"))
                 for _ in range(3)]
        engines = [mesh_eng] + peers
        relay = FlowRelay(
            {f"host{i}": e.flowlog for i, e in enumerate(engines)})
        delivered = {f"host{i}": 0 for i in range(4)}
        merged_ok = [True]
        stop = threading.Event()

        def pump_relay():
            while True:
                res = relay.poll(limit=64)
                for r in res["flows"]:
                    if not r.get("gap"):
                        delivered[r["node"]] += 1
                # per-poll merge ordering: (time, seq) nondecreasing per
                # node run is guaranteed by ring order; check global time
                # ordering of the merged page
                times = [r["time"] for r in res["flows"] if "time" in r]
                if times != sorted(times):
                    merged_ok[0] = False
                if stop.is_set() and not res["flows"]:
                    return
                time.sleep(0.002)

        th = threading.Thread(target=pump_relay, daemon=True)
        th.start()
        try:
            pl = mesh_eng.start_pipeline()
            assert pl.stats()["n_shards"] == 4
            chunks = mk_chunks(mesh_eng.active.snapshot.ep_slot_of,
                               n_chunks=16, rows_per_chunk=8)
            for round_ in range(3):
                tickets = [mesh_eng.submit(dict(ch), now=100 + i)
                           for i, ch in enumerate(chunks)]
                for peer in peers:
                    peer.classify(web_batch(peer), now=200 + round_)
                assert mesh_eng.drain(timeout=30)
                for tk in tickets:
                    tk.result(timeout=5)
            mesh_eng.audit_step(budget=None)
            st = mesh_eng.auditor.stats()
            assert st["checked_rows"] > 0 and st["mismatched_rows"] == 0
        finally:
            stop.set()
            th.join(15)
            for e in engines:
                e.stop()
        assert merged_ok[0], "merged page left time order"
        # fan-in accounting per source: delivered + declared-dropped ==
        # appended (no silent loss through the relay either)
        cursors = relay.cursors()
        for i, e in enumerate(engines):
            assert cursors[f"host{i}"] == e.flowlog.newest_seq
        got = sum(delivered.values())
        appended = sum(e.flowlog.newest_seq for e in engines)
        dropped = sum(
            o.flowlog.follow_gap_records
            for o in relay.observers.values())
        assert got + dropped == appended
        # the mesh engine's ring (256 slots vs ~384 rows) must have lapped
        # at least once if the follower ever fell behind — either way the
        # equality above proves nothing vanished silently
        assert delivered["host1"] == delivered["host2"] == \
            delivered["host3"]


# --------------------------------------------------------------------------- #
# JSONL file-tail source (ISSUE 12: the cross-process relay transport)
# --------------------------------------------------------------------------- #
def _jsonl_rec(seq, t=100, allow=True, dport=443, src="10.1.0.5",
               dst="10.2.0.9"):
    """A record in the flowlog JSONL sink's wire format (render_flow)."""
    return {"time": t, "verdict": "FORWARDED" if allow else "DROPPED",
            "drop_reason": 0 if allow else 133, "ct_state": "NEW",
            "src_ip": src, "dst_ip": dst, "src_port": 40000 + seq,
            "dst_port": dport, "proto": "TCP", "direction": "ingress",
            "endpoint_id": 1, "remote_identity": 1234,
            "matched_rule": 3, "lpm_prefix": 0, "ct_state_pre": "NEW",
            "seq": seq}


def _append(path, recs):
    import json as _json
    with open(path, "a") as f:
        for r in recs:
            f.write(_json.dumps(r) + "\n")


class TestJsonlTail:
    def test_tail_incremental_and_follow(self, tmp_path):
        from cilium_tpu.observe.relay import JsonlTailObserver
        p = str(tmp_path / "n0.jsonl")
        _append(p, [_jsonl_rec(s, t=100 + s) for s in range(1, 4)])
        obs = JsonlTailObserver(p)
        res = obs.observe()
        assert [r["seq"] for r in res["flows"]] == [1, 2, 3]
        cursor = res["cursor"]
        # nothing new: empty page, cursor stable
        res = obs.observe(since=cursor)
        assert res["flows"] == [] and res["cursor"] == cursor
        # appended bytes picked up mid-file, only the new records paged
        _append(p, [_jsonl_rec(s, t=100 + s) for s in range(4, 6)])
        res = obs.observe(since=cursor)
        assert [r["seq"] for r in res["flows"]] == [4, 5]

    def test_partial_line_and_garbage(self, tmp_path):
        """A torn trailing line (writer mid-append) is held until its
        newline arrives; a garbage line is counted, not fatal."""
        from cilium_tpu.observe.relay import JsonlTailObserver
        import json as _json
        p = str(tmp_path / "n0.jsonl")
        obs = JsonlTailObserver(p)
        with open(p, "w") as f:
            f.write(_json.dumps(_jsonl_rec(1)) + "\n")
            f.write('{"seq": 2, "torn')     # no newline yet
        assert obs.poll_file() == 1
        with open(p, "a") as f:             # the rest of the line lands
            f.write('": true, "time": 5}\n')
            f.write("not json at all\n")
            f.write(_json.dumps(_jsonl_rec(3)) + "\n")
        obs.poll_file()
        assert [r["seq"] for r in obs.observe()["flows"]] == [1, 2, 3]
        assert obs.parse_errors == 1

    def test_truncation_resyncs_from_top(self, tmp_path):
        from cilium_tpu.observe.relay import JsonlTailObserver
        p = str(tmp_path / "n0.jsonl")
        _append(p, [_jsonl_rec(s) for s in range(1, 4)])
        obs = JsonlTailObserver(p)
        obs.poll_file()
        # rotation: the file is replaced with a shorter one, same writer
        # session continuing its seq counter
        os_mod = __import__("os")
        os_mod.unlink(p)
        _append(p, [_jsonl_rec(4)])
        obs.poll_file()
        assert obs.newest_seq == 4
        seqs = [r["seq"] for r in obs.observe()["flows"]]
        assert seqs == [1, 2, 3, 4]

    def test_writer_restart_rebases_seq(self, tmp_path):
        """A restarted engine's ring starts over at seq 1. The tail keeps
        its own stream monotonic by rebasing — new-session records are
        kept, never dropped as duplicates."""
        from cilium_tpu.observe.relay import JsonlTailObserver
        p = str(tmp_path / "n0.jsonl")
        _append(p, [_jsonl_rec(s) for s in range(1, 4)])
        obs = JsonlTailObserver(p)
        obs.poll_file()
        _append(p, [_jsonl_rec(1, t=500), _jsonl_rec(2, t=501)])
        obs.poll_file()
        assert obs.writer_restarts == 1
        seqs = [r["seq"] for r in obs.observe()["flows"]]
        assert seqs == [1, 2, 3, 4, 5]      # rebased, strictly increasing

    def test_bounded_window_gaps_and_filters(self, tmp_path):
        from cilium_tpu.observe.relay import JsonlTailObserver
        p = str(tmp_path / "n0.jsonl")
        _append(p, [_jsonl_rec(s, allow=s % 2 == 0) for s in range(1, 11)])
        obs = JsonlTailObserver(p, capacity=4)   # retains seqs 7..10
        res = obs.observe(since=2)
        assert res["gap"] == {"gap": True, "dropped": 4, "resume_seq": 7}
        assert [r["seq"] for r in res["flows"]] == [7, 8, 9, 10]
        # the same FlowFilter surface the in-memory observer serves
        res = obs.observe(allow=(FlowFilter(verdict="DROPPED"),))
        assert all(r["verdict"] == "DROPPED" for r in res["flows"])
        assert [r["seq"] for r in res["flows"]] == [7, 9]
        res = obs.observe(allow=(FlowFilter(dports=(443,),
                                            cidrs=("10.1.0.0/16",)),))
        assert res["matched"] == 4

    def test_relay_fans_in_tailed_files(self, tmp_path):
        """Two nodes' JSONL sinks → one merged node-tagged stream: the
        multi-host transport under the same FlowRelay merge."""
        from cilium_tpu.observe.relay import FlowRelay, JsonlTailObserver
        pa = str(tmp_path / "a.jsonl")
        pb = str(tmp_path / "b.jsonl")
        _append(pa, [_jsonl_rec(s, t=100 + 2 * s) for s in range(1, 4)])
        _append(pb, [_jsonl_rec(s, t=101 + 2 * s) for s in range(1, 4)])
        relay = FlowRelay({"node-a": JsonlTailObserver(pa),
                           "node-b": JsonlTailObserver(pb)})
        res = relay.poll()
        assert len(res["flows"]) == 6
        times = [r["time"] for r in res["flows"]]
        assert times == sorted(times)
        assert {r["node"] for r in res["flows"]} == {"node-a", "node-b"}
        # live append on one node: only its new records page in
        _append(pb, [_jsonl_rec(4, t=200)])
        res = relay.poll()
        assert [(r["node"], r["seq"]) for r in res["flows"]] \
            == [("node-b", 4)]
