"""REST API over a unix socket (SURVEY.md §1 layer 7 slim REST analog +
§3.1 "api server up (unix socket REST)") and the CLI's --api live mode."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.api import APIServer, UnixAPIClient
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord


@pytest.fixture
def live_engine(tmp_path):
    sock = str(tmp_path / "cilium-tpu.sock")
    cfg = DaemonConfig(ct_capacity=1024, auto_regen=False,
                       api_socket=sock, flowlog_mode="all")
    eng = Engine(cfg, datapath=FakeDatapath(DaemonConfig(ct_capacity=1024)))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.add_endpoint(["k8s:role=fe"], ips=("192.168.1.30",), ep_id=3)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}],
                     "toPorts": [{"ports": [
                         {"port": "443", "protocol": "TCP"}]}]}]}])
    eng.regenerate()
    # classify some traffic so ct/flows have content
    s16, _ = parse_addr("192.168.1.30")
    d16, _ = parse_addr("192.168.1.10")
    pkts = [PacketRecord(s16, d16, 40000, 443, C.PROTO_TCP, C.TCP_SYN,
                         False, 1, C.DIR_INGRESS),
            PacketRecord(s16, d16, 40001, 80, C.PROTO_TCP, C.TCP_SYN,
                         False, 1, C.DIR_INGRESS)]
    eng.classify(batch_from_records(pkts, eng.active.snapshot.ep_slot_of))
    eng.start_background()
    yield eng, sock
    eng.stop()


class TestAPIServer:
    def test_healthz_and_status(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, doc = client.get("/v1/healthz")
        assert code == 200 and doc["status"] == "ok"
        code, st = client.get("/v1/status")
        assert code == 200
        assert st["endpoints"] == 2 and st["rules"] == 1
        assert st["conntrack"]["live"] >= 1

    def test_endpoints_and_identities(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, eps = client.get("/v1/endpoints")
        assert code == 200 and [e["ep_id"] for e in eps] == [1, 3]
        code, one = client.get("/v1/endpoints/1")
        assert code == 200 and one["ingress"]["enforced"]
        code, _ = client.get("/v1/endpoints/99")
        assert code == 404
        code, ids = client.get("/v1/identities")
        assert code == 200 and len(ids) > 2

    def test_policy_roundtrip_and_trace(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, rules = client.get("/v1/policy")
        assert code == 200 and len(rules) == 1
        # live apply through the API → revision bumps, verdicts change
        code, doc = client.post("/v1/policy", [{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [
                {"port": "80", "protocol": "TCP"}]}]}]}])
        assert code == 200 and doc["revision"] > 1
        code, tr = client.post("/v1/policy/trace", {
            "ep": 1, "direction": "ingress", "remote": "192.168.1.30",
            "dport": 80, "proto": "TCP"})
        assert code == 200 and tr["verdict"] == "ALLOWED"
        code, tr = client.post("/v1/policy/trace", {
            "ep": 1, "direction": "ingress", "remote": "192.168.1.30",
            "dport": 22, "proto": "TCP"})
        assert code == 200 and tr["verdict"] == "DENIED"

    def test_ct_flows_metrics(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, ct = client.get("/v1/ct?limit=8")
        assert code == 200 and len(ct) >= 1
        assert ct[0]["dport"] == 443
        code, flows = client.get("/v1/flows?last=10")
        assert code == 200 and len(flows) == 2
        code, text = client.get("/v1/metrics")
        assert code == 200 and "cilium_tpu" in text or "policy_revision" in text

    def test_config_patch_enforcement(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, cfgdoc = client.get("/v1/config")
        assert code == 200 and cfgdoc["enforcement_mode"] == "default"
        code, _ = client.patch("/v1/config", {"enforcement_mode": "never"})
        assert code == 200
        assert eng.ctx.enforcement_mode == "never"
        # never-mode: previously denied traffic now allowed
        code, tr = client.post("/v1/policy/trace", {
            "ep": 1, "direction": "ingress", "remote": "192.168.1.30",
            "dport": 22})
        assert tr["verdict"] == "ALLOWED"
        code, err = client.patch("/v1/config", {"enforcement_mode": "bogus"})
        assert code == 400
        code, err = client.patch("/v1/config", {"batch_size": 1})
        assert code == 400

    def test_health_probe_route(self, live_engine):
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, doc = client.get("/v1/health")
        assert code == 200
        assert set(doc) == {"1", "3", "engine"} or set(doc) == {1, 3, "engine"}
        assert doc["engine"]["state"] == C.HEALTH_OK

    def test_stale_socket_is_replaced(self, live_engine, tmp_path):
        eng, sock = live_engine
        eng.stop()
        assert not os.path.exists(sock)
        # a stale file at the path must not block a restart
        with open(sock, "w") as f:
            f.write("stale")
        eng2 = Engine(DaemonConfig(ct_capacity=1024, auto_regen=False,
                                   api_socket=sock),
                      datapath=FakeDatapath(DaemonConfig(ct_capacity=1024)))
        eng2.start_background()
        code, _ = UnixAPIClient(sock).get("/v1/healthz")
        assert code == 200
        eng2.stop()


class TestCLILive:
    def _run(self, argv):
        return subprocess.run(
            [sys.executable, "-m", "cilium_tpu.cli.main"] + argv,
            capture_output=True, text=True, timeout=60, cwd="/root/repo",
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_cli_live_commands(self, live_engine):
        eng, sock = live_engine
        out = self._run(["status", "--api", sock])
        assert out.returncode == 0, out.stderr
        assert "Endpoints:        2" in out.stdout
        out = self._run(["endpoint", "list", "--api", sock, "-o", "json"])
        assert out.returncode == 0
        assert [e["ep_id"] for e in json.loads(out.stdout)] == [1, 3]
        out = self._run(["policy", "trace", "--api", sock, "--ep", "1",
                         "--direction", "ingress",
                         "--remote", "192.168.1.30", "--dport", "443"])
        assert out.returncode == 0 and "ALLOWED" in out.stdout
        out = self._run(["ct", "list", "--api", sock])
        assert out.returncode == 0 and "443" in out.stdout
        out = self._run(["monitor", "--api", sock, "-o", "json"])
        assert out.returncode == 0
        assert len(out.stdout.strip().splitlines()) == 2
        out = self._run(["metrics", "--api", sock])
        assert out.returncode == 0 and "policy_revision" in out.stdout

    def test_cli_requires_a_source(self, live_engine):
        out = self._run(["status"])
        assert out.returncode != 0
