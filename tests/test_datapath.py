"""The Datapath plugin boundary (SURVEY.md §1 layer 3, §4 control-plane
tests): the Engine must depend only on DatapathBackend, a fake must slot in
exactly like pkg/datapath/fake, and control-plane fixtures replayed against
the fake must produce the same verdicts the jit backend produces."""

import subprocess
import sys

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath, JITDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from oracle import PacketRecord
from cilium_tpu.utils.ip import parse_addr

FIXTURE_RULES = [
    {
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [
            {"toCIDRSet": [{"cidr": "10.0.0.0/8",
                            "except": ["10.96.0.0/12"]}],
             "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]},
        ],
        "egressDeny": [{"toCIDR": ["10.66.0.0/16"]}],
        "ingress": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}]}],
    },
]


def fixture_engine(datapath):
    eng = Engine(DaemonConfig(ct_capacity=2048, auto_regen=False,
                              flowlog_mode="all"), datapath=datapath)
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.add_endpoint(["k8s:role=fe"], ips=("192.168.1.30",), ep_id=3)
    eng.apply_policy(FIXTURE_RULES)
    return eng


def pkt(src, dst, sp, dp, proto=C.PROTO_TCP, flags=C.TCP_SYN, ep_id=1,
        direction=C.DIR_EGRESS):
    s16, sv6 = parse_addr(src)
    d16, dv6 = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, proto, flags, sv6 or dv6,
                        ep_id, direction)


TRAFFIC = [
    pkt("192.168.1.10", "10.1.2.3", 40000, 443),      # allow (CIDRSet)
    pkt("192.168.1.10", "10.96.0.1", 40001, 443),     # drop (except)
    pkt("192.168.1.10", "10.66.1.1", 40002, 443),     # drop (deny wins)
    pkt("192.168.1.10", "10.1.2.3", 40003, 80),       # drop (port)
    pkt("192.168.1.30", "192.168.1.10", 40004, 22,    # allow (fromEndpoints)
        ep_id=1, direction=C.DIR_INGRESS),
]


class TestFakeDatapath:
    def test_control_plane_replay_records_placements(self):
        """pkg/datapath/fake pattern: replay fixtures, assert what would be
        programmed (placed snapshot + tensor images), no device involved."""
        fake = FakeDatapath()
        eng = fixture_engine(fake)
        eng.regenerate()
        assert len(fake.placed) == 1
        snap, tensors = fake.placed[0]
        assert snap.revision == eng.active.revision
        # "map contents": the verdict image must contain at least one DENY
        # cell (the egressDeny rule) and one ALLOW cell
        decisions = tensors["verdict"] & C.VERDICT_DECISION_MASK
        assert (decisions == C.VERDICT_DENY).any()
        assert (decisions == C.VERDICT_ALLOW).any()
        # a second regenerate with a new rule records a second placement
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["11.0.0.0/8"]}]}])
        eng.regenerate()
        assert len(fake.placed) == 2
        assert fake.placed[1][0].revision > snap.revision

    def test_fake_matches_jit_verdicts(self):
        """The two backends implement the same semantics contract: identical
        fixture + traffic → bit-identical verdict columns and CT stats."""
        eng_fake = fixture_engine(FakeDatapath(DaemonConfig(ct_capacity=2048)))
        eng_jit = fixture_engine(JITDatapath(DaemonConfig(
            ct_capacity=2048, auto_regen=False)))
        slots = eng_fake.active.snapshot.ep_slot_of
        assert slots == eng_jit.active.snapshot.ep_slot_of
        batch = batch_from_records(TRAFFIC, slots)
        now = 1000
        out_f = eng_fake.classify(dict(batch), now=now)
        out_j = eng_jit.classify(dict(batch), now=now)
        for k in ("allow", "reason", "status", "remote_identity",
                  "redirect", "svc", "rnat"):
            np.testing.assert_array_equal(
                np.asarray(out_f[k]), np.asarray(out_j[k]), k)
        # nat/rnat rewrite columns are only meaningful where svc/rnat is set
        # (device convention; see kernels/classify.py out docstring)
        svc = np.asarray(out_j["svc"])
        rnat = np.asarray(out_j["rnat"])
        np.testing.assert_array_equal(np.asarray(out_f["nat_dport"])[svc],
                                      np.asarray(out_j["nat_dport"])[svc])
        np.testing.assert_array_equal(np.asarray(out_f["rnat_sport"])[rnat],
                                      np.asarray(out_j["rnat_sport"])[rnat])
        assert eng_fake.ct_stats(now) == eng_jit.ct_stats(now)
        # established repeat flows agree too (CT persisted in both backends)
        out_f2 = eng_fake.classify(dict(batch), now=now + 5)
        out_j2 = eng_jit.classify(dict(batch), now=now + 5)
        np.testing.assert_array_equal(out_f2["status"], out_j2["status"])
        assert (np.asarray(out_f2["status"])[0]
                == C.CTStatus.ESTABLISHED)

    def test_ct_arrays_roundtrip(self):
        """Fake CT export/import preserves entries (checkpoint path)."""
        fake = FakeDatapath(DaemonConfig(ct_capacity=2048))
        eng = fixture_engine(fake)
        eng.classify(batch_from_records(
            TRAFFIC, eng.active.snapshot.ep_slot_of), now=1000)
        before = fake.ct_stats(1000)
        assert before["live"] > 0
        arrays = fake.ct_arrays()
        fake2 = FakeDatapath(DaemonConfig(ct_capacity=2048))
        fake2.load_ct_arrays(arrays)
        assert fake2.ct_stats(1000) == before
        assert fake2._ct_table.entries == fake._ct_table.entries

    def test_sweep_reclaims(self):
        fake = FakeDatapath()
        eng = fixture_engine(fake)
        eng.classify(batch_from_records(
            TRAFFIC, eng.active.snapshot.ep_slot_of), now=1000)
        assert fake.ct_stats(1000)["live"] > 0
        reclaimed = eng.sweep(now=10**9)
        assert reclaimed > 0
        assert fake.ct_stats(10**9)["live"] == 0


class TestJaxFreeBoundary:
    def test_engine_with_fake_never_imports_jax(self):
        """The boundary is real only if an Engine(FakeDatapath) session runs
        with jax imports poisoned. Subprocess because conftest pre-imports
        jax in this process."""
        code = r"""
import sys
sys.modules["jax"] = None          # any 'import jax' now raises ImportError
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

eng = Engine(DaemonConfig(ct_capacity=1024, auto_regen=False),
             datapath=FakeDatapath())
eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
eng.apply_policy([{"endpointSelector": {"matchLabels": {"app": "web"}},
                   "egress": [{"toCIDR": ["10.0.0.0/8"]}]}])
s16, _ = parse_addr("192.168.1.10")
d16, _ = parse_addr("10.1.2.3")
p = PacketRecord(s16, d16, 40000, 443, 6, 0x02, False, 1, 0)
out = eng.classify(batch_from_records([p], eng.active.snapshot.ep_slot_of),
                   now=100)
assert bool(out["allow"][0]), out
assert eng.ct_stats(100)["live"] == 1
print("JAXFREE_OK")
"""
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=120,
                              cwd="/root/repo")
        assert proc.returncode == 0, proc.stderr
        assert "JAXFREE_OK" in proc.stdout


class TestWireFlagReset:
    """Satellite pin: the sticky _wire_l7/_wire_wide widening flags reset
    in place() when the NEW snapshot provably has no L7/v6 surface, so a
    transient L7/v6 burst doesn't permanently tax every future batch with
    the wide pack path — while verdicts stay correct throughout."""

    L7_POLICY = [{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{"toPorts": [{
            "ports": [{"port": "80", "protocol": "TCP"}],
            "rules": {"http": [{"method": "GET", "path": "/api"}]}}]}],
        "egress": [{"toCIDR": ["10.0.0.0/8"]}],
    }]
    PLAIN_POLICY = [{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [{"toCIDR": ["10.0.0.0/8"]}],
    }]

    def _l7_batch(self, eng):
        from cilium_tpu.kernels.records import batch_from_records
        recs = [pkt("192.168.1.30", "192.168.1.10", 50000 + i, 80,
                    direction=C.DIR_INGRESS) for i in range(4)]
        b = batch_from_records(recs, eng.active.snapshot.ep_slot_of)
        b["http_method"][:] = 0
        b["http_path"][:, :4] = np.frombuffer(b"/api", np.uint8)
        return b

    def _v4_batch(self, eng):
        from cilium_tpu.kernels.records import batch_from_records
        recs = [pkt("192.168.1.10", "10.1.2.3", 51000 + i, 443)
                for i in range(4)]
        return batch_from_records(recs, eng.active.snapshot.ep_slot_of)

    def test_l7_burst_unsticks_after_l7_free_snapshot(self):
        cfg = DaemonConfig(ct_capacity=2048, auto_regen=False,
                           device="cpu", batch_size=32)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.add_endpoint(["k8s:role=fe"], ips=("192.168.1.30",), ep_id=3)
        eng.apply_policy(self.L7_POLICY)
        eng.regenerate()
        out = eng.classify(self._l7_batch(eng), now=100)
        assert bool(out["allow"].all())
        assert eng.datapath._wire_l7          # the burst widened the wire

        # drop every L7 rule: the new snapshot has no L7 surface
        eng.repo.clear()
        eng.apply_policy(self.PLAIN_POLICY)
        eng.regenerate(force=True)
        assert not eng.datapath._wire_l7      # place() reset the flag
        assert eng.datapath.pack_stats["wire_flag_resets"] >= 1
        # subsequent traffic rides the compact wire AND verdicts stay
        # correct (allowed CIDR flow)
        out = eng.classify(self._v4_batch(eng), now=200)
        assert bool(out["allow"].all())
        assert not eng.datapath._wire_l7
        eng.stop()

    def test_v6_burst_unsticks_after_clean_run(self):
        from cilium_tpu.runtime.datapath import WIRE_RESET_CLEAN_BATCHES
        cfg = DaemonConfig(ct_capacity=2048, auto_regen=False,
                           device="cpu", batch_size=32)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(self.PLAIN_POLICY)
        eng.regenerate()
        b = self._v4_batch(eng)
        b["is_v6"][0] = True                  # one stray v6 record
        eng.classify(b, now=100)
        assert eng.datapath._wire_wide
        # a regen right after the burst must NOT narrow (hysteresis: with
        # recent wide traffic a reset would retrace on the next v6 batch)
        eng.regenerate(force=True)
        assert eng.datapath._wire_wide
        # after a clean run of v4-only batches the next regen narrows
        for i in range(WIRE_RESET_CLEAN_BATCHES):
            eng.classify(self._v4_batch(eng), now=110 + i)
        eng.regenerate(force=True)
        assert not eng.datapath._wire_wide
        assert eng.datapath.pack_stats["wire_flag_resets"] >= 1
        out = eng.classify(self._v4_batch(eng), now=300)
        assert bool(out["allow"].all())
        eng.stop()

    def test_stale_staging_tail_does_not_pin_wide(self):
        """A reused staging slot must not leak an earlier flush's v6 rows
        into later batches' wire-format probes: after one coalesced v6
        batch, subsequent v4-only coalesced batches through the SAME slot
        must advance the clean-batch counter (else the wide wire could
        never narrow on the serving path)."""
        cfg = DaemonConfig(ct_capacity=2048, auto_regen=False,
                           device="cpu", batch_size=64,
                           pipeline_flush_ms=1.0)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(self.PLAIN_POLICY)
        eng.regenerate()
        from cilium_tpu.kernels.records import batch_from_records
        recs = [pkt("192.168.1.10", "10.1.2.3", 52000 + i, 443)
                for i in range(40)]
        big = batch_from_records(recs, eng.active.snapshot.ep_slot_of)
        big["is_v6"][:] = False
        big["is_v6"][5] = True                # one v6 row mid-batch
        eng.submit(big, now=100)              # 40 rows: coalesced path
        assert eng.drain(timeout=30)
        assert eng.datapath._wire_wide
        small = batch_from_records(recs[:8],
                                   eng.active.snapshot.ep_slot_of)
        for i in range(5):                    # 8 rows: same slots reused
            eng.submit(dict(small), now=200 + i)
            assert eng.drain(timeout=30)
        assert eng.datapath._batches_since_wide >= 5, \
            "stale staging tail re-tripped the wide probe"
        eng.stop()

    def test_tokens_without_l7_policy_never_widen(self):
        """Policy-gated L7 widening: with zero L7 rule sets, http tokens
        cannot affect verdicts — the wire stays compact under tokenized
        traffic (no per-regen reset/re-widen retrace flap), and verdicts
        still match the oracle, which does see the tokens."""
        cfg = DaemonConfig(ct_capacity=2048, auto_regen=False,
                           device="cpu", batch_size=32)
        jit = Engine(cfg, datapath=JITDatapath(cfg))
        fake = Engine(cfg, datapath=FakeDatapath(cfg))
        for eng in (jit, fake):
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",),
                             ep_id=1)
            eng.apply_policy(self.PLAIN_POLICY)
            eng.regenerate()
        b = self._v4_batch(jit)
        b["http_method"][:] = 0               # shim tokenizes plain HTTP
        b["http_path"][:, :4] = np.frombuffer(b"/idx", np.uint8)
        out_j = jit.classify(dict(b), now=100)
        out_f = fake.classify(dict(b), now=100)
        for k in ("allow", "reason", "status", "remote_identity"):
            np.testing.assert_array_equal(out_j[k], out_f[k])
        assert not jit.datapath._wire_l7      # tokens never widened it
        jit.stop()
        fake.stop()
