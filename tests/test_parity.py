"""END-TO-END PARITY: the fused device kernel vs the semantics oracle.

This is the build's core obligation (SURVEY.md §4 "Parity testing"): for
randomized (rules × packet streams), the jitted classify step must produce
verdicts bit-identical to the oracle's snapshot batch mode, and the device
CT table must hold exactly the oracle's live entries (flags, expiry,
counters). Batch-size-1 equals the sequential (eBPF-equivalent) mode, which
the oracle test suite separately pins to snapshot mode.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.classify import classify_step
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rules
from cilium_tpu.policy import PolicyContext, Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr, words_to_addr
from oracle import ConntrackTable, Oracle, PacketRecord

RULES = [
    {   # web: egress to 10/8 except 10.96/12 on 443+8080-8090; ingress l7 80
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "egress": [
            {"toCIDRSet": [{"cidr": "10.0.0.0/8", "except": ["10.96.0.0/12"]}],
             "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"},
                                    {"port": "8080", "endPort": 8090,
                                     "protocol": "TCP"}]}]},
            {"toEntities": ["world"],
             "toPorts": [{"ports": [{"port": "53", "protocol": "ANY"}]}]},
            {"toCIDR": ["2001:db8::/32"],
             "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]},
            {"toCIDR": ["10.200.0.0/16"],
             "icmps": [{"fields": [{"type": 8, "family": "IPv4"}]}]},
        ],
        "egressDeny": [
            {"toCIDR": ["10.66.0.0/16"]},
        ],
        "ingress": [
            {"toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                          "rules": {"http": [{"method": "GET", "path": "/api"},
                                             {"path": "/public"}]}}]},
            {"fromEndpoints": [{"matchLabels": {"role": "fe"}}]},
        ],
    },
    {   # db: ingress only from web pods on 5432
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "web"}}],
                     "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]}],
    },
]


def build_world():
    alloc = IdentityAllocator()
    ipc = IPCache()
    ctx = PolicyContext(allocator=alloc, selector_cache=SelectorCache(alloc),
                        ipcache=ipc)
    repo = Repository(ctx)
    eps = []
    for ep_id, (labels, ip) in enumerate(
            [(("k8s:app=web",), "192.168.1.10"),
             (("k8s:app=db",), "192.168.1.20"),
             (("k8s:role=fe",), "192.168.1.30")], start=1):
        lbls = Labels.parse(labels)
        ident = alloc.allocate(lbls)
        ep = Endpoint(ep_id=ep_id, labels=lbls, identity_id=ident.id, ips=(ip,))
        ipc.upsert(f"{ip}/32", ident.id)
        eps.append(ep)
    repo.add(parse_rules(RULES))
    return ctx, repo, eps


DST_POOL = [
    "10.1.2.3", "10.5.5.5", "10.96.0.1", "10.100.3.9", "10.66.1.1",
    "10.200.1.1", "8.8.8.8", "1.1.1.1", "192.168.1.20", "192.168.1.30",
    "2001:db8::77", "2001:db9::1",
]
PORT_POOL = [443, 8080, 8085, 8090, 8091, 80, 53, 5432, 22, 0]
PATHS = [b"/api/users", b"/public/x", b"/admin", b"/ap", b""]


def random_packet(rng, prior):
    """Either a brand-new random flow, a repeat, or a reply of a prior one."""
    r = rng.random()
    if prior and r < 0.30:
        p = rng.choice(prior)     # repeat (established)
        flags = rng.choice([C.TCP_ACK, C.TCP_ACK | C.TCP_PSH, C.TCP_FIN,
                            C.TCP_RST]) if p.proto == C.PROTO_TCP else 0
        return PacketRecord(p.src_addr, p.dst_addr, p.src_port, p.dst_port,
                            p.proto, flags, p.is_ipv6, p.ep_id, p.direction,
                            p.http_method, p.http_path)
    if prior and r < 0.45:
        p = rng.choice(prior)     # reply
        flags = (C.TCP_SYN | C.TCP_ACK) if p.proto == C.PROTO_TCP else 0
        return PacketRecord(p.dst_addr, p.src_addr, p.dst_port, p.src_port,
                            p.proto, flags, p.is_ipv6, p.ep_id,
                            1 - p.direction, C.HTTP_METHOD_ANY, b"")
    ep_id = rng.choice([1, 1, 1, 2, 3])
    direction = rng.choice([C.DIR_EGRESS, C.DIR_EGRESS, C.DIR_INGRESS])
    dst = rng.choice(DST_POOL)
    src_ip = {1: "192.168.1.10", 2: "192.168.1.20", 3: "192.168.1.30"}[ep_id]
    if direction == C.DIR_INGRESS:
        src, dstip = dst, src_ip
    else:
        src, dstip = src_ip, dst
    s16, sv6 = parse_addr(src)
    d16, dv6 = parse_addr(dstip)
    proto = rng.choice([C.PROTO_TCP] * 5 + [C.PROTO_UDP, C.PROTO_ICMP])
    if proto == C.PROTO_ICMP:
        sport, dport, flags = 0, rng.choice([0, 8]), 0
    else:
        sport = rng.randrange(30000, 60000)
        dport = rng.choice(PORT_POOL)
        flags = C.TCP_SYN if proto == C.PROTO_TCP else 0
    method, path = C.HTTP_METHOD_ANY, b""
    if proto == C.PROTO_TCP and dport == 80 and rng.random() < 0.5:
        method = rng.choice([C.HTTP_METHOD_IDS["GET"], C.HTTP_METHOD_IDS["POST"]])
        path = rng.choice(PATHS)
        flags = C.TCP_ACK
    return PacketRecord(s16, d16, sport, dport, proto, flags, sv6 or dv6,
                        ep_id, direction, method, path)


def extract_device_ct(ct_dev, now):
    """Device table → {CTKey: (flags, expiry, pkts_fwd, pkts_rev)} for live
    entries."""
    keys = np.asarray(ct_dev["keys"])
    expiry = np.asarray(ct_dev["expiry"])
    flags = np.asarray(ct_dev["flags"])
    fwd = np.asarray(ct_dev["pkts_fwd"])
    rev = np.asarray(ct_dev["pkts_rev"])
    rnat = np.asarray(ct_dev["rev_nat"])
    out = {}
    for slot in np.nonzero(expiry > now)[0]:
        w = keys[slot]
        src = words_to_addr(w[0:4])
        dst = words_to_addr(w[4:8])
        sport = int(w[8]) >> 16
        dport = int(w[8]) & 0xFFFF
        proto = int(w[9]) >> 8
        d = int(w[9]) & 0xFF
        key = (src, dst, sport, dport, proto, d)
        out[key] = (int(flags[slot]), int(expiry[slot]),
                    int(fwd[slot]), int(rev[slot]), int(rnat[slot]))
    return out


def oracle_live_ct(oracle, now):
    out = {}
    for key, e in oracle.ct.entries.items():
        if e.expiry > now:
            out[key] = (e.flags, e.expiry, e.pkts_fwd, e.pkts_rev, e.rev_nat)
    return out


def test_packed_path_bit_identical():
    """The packed wire format (single uint32 array) must produce the exact
    same outputs and CT state as the dict path — it is the production
    transfer path (bench + shim)."""
    import jax
    from cilium_tpu.kernels.classify import make_classify_fn
    from cilium_tpu.kernels.records import pack_batch, unpack_batch_jnp

    rng = random.Random(11)
    ctx, repo, eps = build_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    make_ct = lambda: {k: jnp.asarray(v) for k, v in  # noqa: E731
                       make_ct_arrays(CTConfig(capacity=4096)).items()}
    ct_a, ct_b = make_ct(), make_ct()
    fn_dict = make_classify_fn(donate_ct=False)
    fn_packed = make_classify_fn(donate_ct=False, packed=True)
    prior = []
    now = 500
    for bi in range(3):
        packets = [random_packet(rng, prior) for _ in range(64)]
        raw = batch_from_records(packets, snap.ep_slot_of)
        # roundtrip: pack → device unpack reproduces every column
        unpacked = unpack_batch_jnp(jnp.asarray(pack_batch(raw, l7=True)))
        for k in raw:
            np.testing.assert_array_equal(
                np.asarray(unpacked[k]).astype(raw[k].dtype), raw[k], k)
        out_a, ct_a, ca = fn_dict(
            tensors, ct_a, {k: jnp.asarray(v) for k, v in raw.items()},
            jnp.uint32(now), jnp.int32(snap.world_index))
        out_b, ct_b, cb = fn_packed(
            tensors, ct_b, jnp.asarray(pack_batch(raw)),
            jnp.uint32(now), jnp.int32(snap.world_index))
        for k in out_a:
            np.testing.assert_array_equal(np.asarray(out_a[k]),
                                          np.asarray(out_b[k]), k)
        for k in ct_a:
            np.testing.assert_array_equal(np.asarray(ct_a[k]),
                                          np.asarray(ct_b[k]), k)
        prior.extend(packets)
        prior = prior[-100:]
        now += 40


def run_parity(seed, n_batches=6, batch=96, cap=4096, time_step=40,
               classify_kwargs=None):
    """``classify_kwargs`` forwards extra static options to classify_step —
    tests/test_fused.py reruns this exact suite with
    {"fused": True, "fused_interpret": True} to pin the Pallas megakernel
    path against the oracle."""
    rng = random.Random(seed)
    ctx, repo, eps = build_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=cap))
    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    ct_dev = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=cap)).items()}
    # for_snapshot wires the provenance tables — the matched_rule /
    # lpm_prefix / ct_state_pre columns below are part of the parity
    # contract (ISSUE 11), pinned against the oracle like the verdicts
    oracle = Oracle.for_snapshot(snap)
    prior = []
    now = 1000
    for bi in range(n_batches):
        packets = [random_packet(rng, prior) for _ in range(batch)]
        want = oracle.classify_batch_snapshot(packets, now)
        b = {k: jnp.asarray(v) for k, v in
             batch_from_records(packets, snap.ep_slot_of).items()}
        out, ct_dev, counters = classify_step(
            tensors, ct_dev, b, jnp.uint32(now),
            world_index=snap.world_index, **(classify_kwargs or {}))
        got_allow = np.asarray(out["allow"])
        got_reason = np.asarray(out["reason"])
        got_status = np.asarray(out["status"])
        got_rid = np.asarray(out["remote_identity"])
        got_rule = np.asarray(out["matched_rule"])
        got_pfx = np.asarray(out["lpm_prefix"])
        got_pre = np.asarray(out["ct_state_pre"])
        for i, (p, v) in enumerate(zip(packets, want)):
            assert bool(got_allow[i]) == v.allow, \
                f"seed={seed} batch={bi} pkt={i}: allow {bool(got_allow[i])} != {v.allow} ({p})"
            assert int(got_reason[i]) == int(v.drop_reason), \
                f"seed={seed} batch={bi} pkt={i}: reason {int(got_reason[i])} != {int(v.drop_reason)} ({p})"
            assert int(got_status[i]) == int(v.ct_status), \
                f"seed={seed} batch={bi} pkt={i}: status {int(got_status[i])} != {int(v.ct_status)} ({p})"
            assert int(got_rid[i]) == v.remote_identity, \
                f"seed={seed} batch={bi} pkt={i}: rid {int(got_rid[i])} != {v.remote_identity}"
            assert int(got_rule[i]) == v.matched_rule, \
                f"seed={seed} batch={bi} pkt={i}: matched_rule " \
                f"{int(got_rule[i])} != {v.matched_rule} ({p})"
            assert int(got_pfx[i]) == v.lpm_prefix, \
                f"seed={seed} batch={bi} pkt={i}: lpm_prefix " \
                f"{int(got_pfx[i])} != {v.lpm_prefix} ({p})"
            assert int(got_pre[i]) == int(v.ct_status), \
                f"seed={seed} batch={bi} pkt={i}: ct_state_pre " \
                f"{int(got_pre[i])} != {int(v.ct_status)} ({p})"
        dev_ct = extract_device_ct(ct_dev, now)
        ora_ct = oracle_live_ct(oracle, now)
        assert dev_ct == ora_ct, (
            f"seed={seed} batch={bi}: CT divergence\n"
            f"only-device: { {k: v for k, v in dev_ct.items() if ora_ct.get(k) != v} }\n"
            f"only-oracle: { {k: v for k, v in ora_ct.items() if dev_ct.get(k) != v} }")
        prior.extend(p for p, v in zip(packets, want)
                     if v.allow and v.ct_status == C.CTStatus.NEW)
        prior = prior[-200:]
        now += time_step


class TestKernelOracleParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_randomized_multibatch(self, seed):
        run_parity(seed)

    @pytest.mark.parametrize("mode", [C.ENFORCEMENT_NEVER, C.ENFORCEMENT_ALWAYS])
    def test_enforcement_modes(self, mode):
        """Regression: unenforced directions must bypass DENY/REDIRECT cells
        on the device path exactly as the oracle skips the ladder."""
        rng = random.Random(11)
        ctx, repo, eps = build_world()
        ctx.enforcement_mode = mode
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=2048))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct_dev = {k: jnp.asarray(v) for k, v in
                  make_ct_arrays(CTConfig(capacity=2048)).items()}
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot())
        prior = []
        now = 100
        for bi in range(3):
            packets = [random_packet(rng, prior) for _ in range(64)]
            want = oracle.classify_batch_snapshot(packets, now)
            b = {k: jnp.asarray(v) for k, v in
                 batch_from_records(packets, snap.ep_slot_of).items()}
            out, ct_dev, _ = classify_step(tensors, ct_dev, b, jnp.uint32(now),
                                           world_index=snap.world_index)
            for i, v in enumerate(want):
                assert bool(np.asarray(out["allow"])[i]) == v.allow, (mode, bi, i)
                assert int(np.asarray(out["reason"])[i]) == int(v.drop_reason), \
                    (mode, bi, i)
            assert extract_device_ct(ct_dev, now) == oracle_live_ct(oracle, now)
            prior.extend(p for p, v in zip(packets, want)
                         if v.allow and v.ct_status == C.CTStatus.NEW)
            now += 40

    def test_per_endpoint_enforcement_override(self):
        ctx, repo, eps = build_world()
        eps[2].enforcement = C.ENFORCEMENT_ALWAYS  # fe endpoint: default-deny
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct_dev = {k: jnp.asarray(v) for k, v in
                  make_ct_arrays(CTConfig(capacity=1024)).items()}
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot())
        s16, _ = parse_addr("192.168.1.30")
        d16, _ = parse_addr("8.8.8.8")
        p = PacketRecord(s16, d16, 40000, 443, C.PROTO_TCP, C.TCP_SYN,
                         False, 3, C.DIR_EGRESS)
        v = oracle.classify(p, 100)
        b = {k: jnp.asarray(a) for k, a in
             batch_from_records([p], snap.ep_slot_of).items()}
        out, ct_dev, _ = classify_step(tensors, ct_dev, b, jnp.uint32(100),
                                       world_index=snap.world_index)
        assert not v.allow  # always-mode, no rules for fe → default deny
        assert bool(np.asarray(out["allow"])[0]) == v.allow
        assert int(np.asarray(out["reason"])[0]) == int(v.drop_reason)

    def test_long_horizon_with_expiry(self):
        # large time steps force SYN-timeout expiries and slot reuse
        run_parity(seed=99, n_batches=8, batch=64, time_step=90)

    def test_batch_of_one_matches_sequential(self):
        rng = random.Random(7)
        ctx, repo, eps = build_world()
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct_dev = {k: jnp.asarray(v) for k, v in
                  make_ct_arrays(CTConfig(capacity=1024)).items()}
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot())
        prior = []
        now = 500
        for i in range(40):
            p = random_packet(rng, prior)
            v = oracle.classify(p, now)          # SEQUENTIAL mode
            b = {k: jnp.asarray(a) for k, a in
                 batch_from_records([p], snap.ep_slot_of).items()}
            out, ct_dev, _ = classify_step(tensors, ct_dev, b, jnp.uint32(now),
                                           world_index=snap.world_index)
            assert bool(np.asarray(out["allow"])[0]) == v.allow, (i, p)
            assert int(np.asarray(out["reason"])[0]) == int(v.drop_reason), (i, p)
            assert int(np.asarray(out["status"])[0]) == int(v.ct_status), (i, p)
            if v.allow and v.ct_status == C.CTStatus.NEW:
                prior.append(p)
            now += 13
        assert extract_device_ct(ct_dev, now) == oracle_live_ct(oracle, now)


def test_addrdict_wire_bit_identical():
    """The address-dictionary wire (12B/record + shared unique-address
    table) must match the dict path exactly — outputs and CT state — for
    mixed v4/v6 and for L7-token traffic (the 4-word variant)."""
    from cilium_tpu.kernels.classify import make_classify_fn
    from cilium_tpu.kernels.records import (
        pack_batch_addrdict, unpack_batch_addrdict_jnp)

    rng = random.Random(12)
    ctx, repo, eps = build_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    make_ct = lambda: {k: jnp.asarray(v) for k, v in  # noqa: E731
                       make_ct_arrays(CTConfig(capacity=4096)).items()}
    ct_a, ct_b = make_ct(), make_ct()
    fn_dict = make_classify_fn(donate_ct=False)
    fn_packed = make_classify_fn(donate_ct=False, packed=True)
    prior = []
    now = 700
    for bi in range(3):
        packets = [random_packet(rng, prior) for _ in range(64)]
        raw = batch_from_records(packets, snap.ep_slot_of)
        # roundtrip incl. L7 variant
        parts = pack_batch_addrdict(raw, l7=True)
        unpacked = unpack_batch_addrdict_jnp(
            *(jnp.asarray(p) for p in parts))
        for k in raw:
            np.testing.assert_array_equal(
                np.asarray(unpacked[k]).astype(raw[k].dtype), raw[k], k)
        out_a, ct_a, _ = fn_dict(
            tensors, ct_a, {k: jnp.asarray(v) for k, v in raw.items()},
            jnp.uint32(now), jnp.int32(snap.world_index))
        wire = pack_batch_addrdict(raw)
        out_b, ct_b, _ = fn_packed(
            tensors, ct_b, tuple(jnp.asarray(p) for p in wire),
            jnp.uint32(now), jnp.int32(snap.world_index))
        for k in out_a:
            np.testing.assert_array_equal(np.asarray(out_a[k]),
                                          np.asarray(out_b[k]), k)
        for k in ct_a:
            np.testing.assert_array_equal(np.asarray(ct_a[k]),
                                          np.asarray(ct_b[k]), k)
        prior.extend(packets)
        prior = prior[-100:]
        now += 40
