"""Device-side RSS tests: the in-kernel ring ppermute CT exchange
(parallel/exchange.py, ``DaemonConfig.rss_mode="device"``).

Unit tests pin the ring primitives (all-gather / reduce-scatter over
explicit ppermute hops) and the exchange's bit-identity to the steered
mesh at the raw classify-fn level — including a saturating flood where
CT_FULL fail-closed verdicts AND the tail-evict victim order must match
slot-for-slot (the gathered request set preserves global row order, and
the owner-side CT stage is classify_step's own ct_update_stage).

Integration tests run the device-RSS engine behind the pipeline against
the host-steered mesh and the oracle-backed serial path (the sharded
parity suite's acceptance bar, steering off), drive the skewed/adversarial
arrival patterns that host steering sheds or serializes on
(all-rows-one-shard, alternating-shard, a cfg6-form randomized storm)
asserting NO shed class fires and verdicts match the bounded oracle, pin
the steer-revision fence degradation (a regen between stage and dispatch
must not trip re-steer logic that no longer applies — the plain revision
stamp check / StalePlacement retry is the whole fence), and check the
operator surfaces: the ``rss_exchange`` ledger row + ``exchange`` HBM
group exist, while the steer-balance gauges and the ``steer_overflow``
shed reason are swept from the export instead of reporting frozen zeros.
"""

import os

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.pipeline import Pipeline
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from tests.test_datapath import pkt
from tests.test_sharded_pipeline import (_mk_phase, _run_phase,
                                         fake_serial_engine,
                                         jit_pipeline_engine)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# --------------------------------------------------------------------------- #
# Unit: the ring primitives
# --------------------------------------------------------------------------- #
class TestRingPrimitives:
    def _mesh(self, n):
        from cilium_tpu.parallel.mesh import make_mesh
        return make_mesh(n, 1)

    def test_ring_all_gather_orders_by_origin(self):
        import jax
        import jax.numpy as jnp
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        import inspect
        from jax.sharding import PartitionSpec as P
        from cilium_tpu.parallel.exchange import ring_all_gather
        n = 4
        mesh = self._mesh(n)
        kw = {("check_vma" if "check_vma"
               in inspect.signature(shard_map).parameters
               else "check_rep"): False}

        def body(x):
            return ring_all_gather(x, "flows", n)
        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("flows"),
            out_specs=P("flows"), **kw))
        x = np.arange(n * 3, dtype=np.uint32).reshape(n * 3, 1)
        out = np.asarray(fn(jnp.asarray(x)))
        # each chip's [n, L, 1] block (stacked along dim 0 by the out
        # spec) must hold ALL chips' rows indexed by origin
        out = out.reshape(n, n, 3, 1)
        for chip in range(n):
            np.testing.assert_array_equal(
                out[chip].reshape(n * 3, 1), x,
                err_msg=f"chip {chip} gathered a reordered request set")

    def test_ring_reduce_scatter_routes_chunks_home(self):
        import jax
        import jax.numpy as jnp
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        import inspect
        from jax.sharding import PartitionSpec as P
        from cilium_tpu.parallel.exchange import ring_reduce_scatter
        n = 4
        mesh = self._mesh(n)
        kw = {("check_vma" if "check_vma"
               in inspect.signature(shard_map).parameters
               else "check_rep"): False}

        def body(x):
            # every chip contributes chunk c = 1000*my + c per element;
            # chip c must end with sum over chips of (1000*chip + c)
            my = jax.lax.axis_index("flows")
            parts = (jnp.arange(n, dtype=jnp.uint32)[:, None, None]
                     + jnp.uint32(1000) * my.astype(jnp.uint32))
            parts = jnp.broadcast_to(parts, (n, 2, 1))
            return ring_reduce_scatter(parts, "flows", n)
        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("flows"), out_specs=P("flows"),
            **kw))
        out = np.asarray(fn(jnp.zeros((n * 2, 1), np.uint32)))
        out = out.reshape(n, 2, 1)
        base = 1000 * sum(range(n))
        for c in range(n):
            assert (out[c] == base + n * c).all(), \
                f"chip {c} chunk mis-routed: {out[c].ravel()}"


# --------------------------------------------------------------------------- #
# Unit: exchange vs steered bit-identity at the raw classify-fn level
# --------------------------------------------------------------------------- #
class TestExchangeBitIdentity:
    def _world(self, ct_capacity):
        from cilium_tpu.runtime.datapath import FakeDatapath
        from cilium_tpu.runtime.engine import Engine
        cfg = DaemonConfig(ct_capacity=ct_capacity, auto_regen=False,
                           flowlog_mode="none")
        eng = Engine(cfg, datapath=FakeDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"]}],
        }])
        eng.regenerate()
        snap = eng.active.snapshot
        eng.stop()
        return snap

    def test_saturating_flood_ct_full_and_evict_order_identical(self):
        """The acceptance pin the steered parity suite cannot see: under
        a flood that saturates the per-shard CT tables, the exchange path
        must produce the SAME CT_FULL fail-closed verdicts, the SAME
        eviction counters, and byte-identical CT tables — the tail-evict
        victim order survives the ring exchange because the gathered
        request set preserves global row order."""
        import jax.numpy as jnp
        from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
        from cilium_tpu.parallel.mesh import (
            make_mesh, make_sharded_classify_fn, make_unsteered_classify_fn,
            shard_ct_arrays, steer_batch, unsteer_outputs)
        snap = self._world(ct_capacity=128)
        slot_of = snap.ep_slot_of
        n_shards = 4
        mesh = make_mesh(n_shards, 1)
        ct_host = make_ct_arrays(CTConfig(128, 8))
        shard_ct_arrays(ct_host, n_shards)
        ct_s = {k: jnp.asarray(v) for k, v in ct_host.items()}
        ct_d = {k: jnp.asarray(v) for k, v in ct_host.items()}
        tn = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        steer_fn = make_sharded_classify_fn(mesh, donate_ct=False)
        dev_fn = make_unsteered_classify_fn(mesh, donate_ct=False)

        rows = 128
        tot_full = 0
        for i in range(6):                 # 6*128 rows >> 128 CT slots
            rng = np.random.default_rng(i)
            recs = [pkt("192.168.1.10",
                        f"10.{rng.integers(0, 200)}.{rng.integers(0, 250)}"
                        f".{rng.integers(1, 250)}",
                        int(1024 + rng.integers(0, 60000)), 443)
                    for _ in range(rows)]
            b = batch_from_records(recs, slot_of, pad_to=rows)
            now = 1000 + i
            sb, scatter, _per = steer_batch(b, n_shards, round_to_pow2=True)
            out_s, ct_s, ctr_s = steer_fn(
                tn, ct_s, {k: jnp.asarray(v) for k, v in sb.items()},
                jnp.uint32(now), jnp.int32(snap.world_index))
            out_s = unsteer_outputs(
                {k: np.asarray(v) for k, v in out_s.items()}, scatter)
            out_d, ct_d, ctr_d = dev_fn(
                tn, ct_d, {k: jnp.asarray(v) for k, v in b.items()},
                jnp.uint32(now), jnp.int32(snap.world_index))
            out_d = {k: np.asarray(v) for k, v in out_d.items()}
            v = np.asarray(b["valid"], dtype=bool)
            for k in out_s:
                np.testing.assert_array_equal(
                    out_s[k][v], out_d[k][v],
                    err_msg=f"batch {i} out[{k}] diverged")
            for k in ctr_s:
                np.testing.assert_array_equal(
                    np.asarray(ctr_s[k]), np.asarray(ctr_d[k]),
                    err_msg=f"batch {i} counter {k} diverged")
            tot_full += int(out_d["ct_full"][v].sum())
        for k in ct_s:
            np.testing.assert_array_equal(
                np.asarray(ct_s[k]), np.asarray(ct_d[k]),
                err_msg=f"CT table {k} diverged (evict order)")
        assert tot_full > 0, "flood never saturated — the pin is vacuous"


# --------------------------------------------------------------------------- #
# Integration: the device-RSS engine behind the pipeline
# --------------------------------------------------------------------------- #
class TestDeviceRSSEngine:
    def test_device_parity_vs_steered_and_oracle(self):
        """The acceptance bar: the same submission stream through the
        host-steered 4-shard mesh and the device-RSS 4-shard mesh is
        bit-identical — and both match the oracle-backed serial path —
        including CT continuity in both directions across drained
        phases."""
        serial = fake_serial_engine()
        host = jit_pipeline_engine(4)
        dev = jit_pipeline_engine(4, rss_mode="device")
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            assert dev.datapath.rss_state == {
                "mode": "device", "shards": 4, "active": True}
            assert dev.datapath.pipeline_shards == 1   # no pre-steering
            ch1 = _mk_phase(slot_of, 5, (1, 5, 17, 9, 23), seed=21)
            _run_phase(serial, [host, dev], ch1, now0=1000)
            est = [pkt("192.168.1.10", "10.0.2.7", 48200 + i, 443)
                   for i in range(4)]
            _run_phase(serial, [host, dev],
                       [batch_from_records(est, slot_of)], now0=1200)
            reply = [pkt("10.0.2.7", "192.168.1.10", 443, 48200 + i,
                         flags=C.TCP_ACK, direction=C.DIR_INGRESS)
                     for i in range(4)]
            outs = _run_phase(
                serial, [host, dev],
                [batch_from_records(reply, slot_of, pad_to=6)], now0=1210)
            assert (np.asarray(outs[0]["status"])[:4]
                    == int(C.CTStatus.REPLY)).all()
            live = serial.ct_stats(now=1500)["live"]
            assert host.ct_stats(now=1500)["live"] == live
            assert dev.ct_stats(now=1500)["live"] == live
            # the device path staged unsharded, packed in place, never
            # paid an allocating steer, never shed
            ps = dev.pipeline_stats()
            assert ps["n_shards"] == 1 and ps["mesh_shards"] == 4
            assert ps["rss_mode"] == "device"
            assert ps["shed_total"] == 0
            assert dev.datapath.pack_stats["pack_fallback_steered"] == 0
            assert dev.datapath.pack_stats["pack_inplace"] > 0
        finally:
            for e in (serial, host, dev):
                e.stop()

    def test_sync_classify_pads_arbitrary_row_counts(self):
        """Control-plane entries (health probes, CLI classify) arrive at
        arbitrary sizes: the device path pads to an equal pow2 per-chip
        slice and truncates on finalize — verdicts match the oracle."""
        serial = fake_serial_engine()
        dev = jit_pipeline_engine(4, rss_mode="device")
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            odd = batch_from_records(
                [pkt("192.168.1.10", f"10.1.9.{i + 1}", 51000 + i, 443)
                 for i in range(5)], slot_of)
            o1 = serial.classify(dict(odd), now=1600)
            o2 = dev.classify(dict(odd), now=1600)
            assert o2["allow"].shape[0] == 5    # padding truncated
            for k in ("allow", "reason", "status", "remote_identity"):
                np.testing.assert_array_equal(o1[k], o2[k], err_msg=k)
        finally:
            serial.stop()
            dev.stop()

    def test_skewed_and_alternating_arrivals_no_shed(self):
        """The arrival patterns host steering sheds (steer_overflow) or
        serializes on: every valid row hashing to ONE CT shard, and a
        strict alternating two-shard pattern — through the device path
        nothing sheds, no steer_overflow class exists, and verdicts match
        the bounded oracle bit-for-bit."""
        from cilium_tpu.parallel.mesh import flow_shard_of
        serial = fake_serial_engine()
        dev = jit_pipeline_engine(4, rss_mode="device")
        slot_of = serial.active.snapshot.ep_slot_of
        n_shards = 4
        try:
            # rejection-sample flows by their REAL steer hash
            by_shard = {s: [] for s in range(n_shards)}
            rng = np.random.default_rng(5)
            while min(len(v) for v in by_shard.values()) < 24:
                recs = [pkt("192.168.1.10",
                            f"10.{rng.integers(0, 2)}.2."
                            f"{rng.integers(1, 250)}",
                            int(42000 + rng.integers(0, 20000)), 443)
                        for _ in range(64)]
                b = batch_from_records(recs, slot_of)
                sh = flow_shard_of(b, n_shards)
                for i, s in enumerate(sh):
                    by_shard[int(s)].append(recs[i])
            # all-rows-one-shard x2 waves, then alternating-shard
            chunks = [batch_from_records(by_shard[0][:24], slot_of),
                      batch_from_records(by_shard[0][24:48]
                                         or by_shard[0][:24], slot_of)]
            alt = [r for pair in zip(by_shard[1][:16], by_shard[2][:16])
                   for r in pair]
            chunks.append(batch_from_records(alt, slot_of))
            _run_phase(serial, [dev], chunks, now0=3000)
            ps = dev.pipeline_stats()
            assert ps["shed_total"] == 0
            assert "steer_overflow" not in ps["shed_reasons"]
        finally:
            serial.stop()
            dev.stop()

    def test_cfg6_form_storm_matches_bounded_oracle(self):
        """A cfg6-form randomized-source SYN/junk storm through the
        device path: no shed class fires and every verdict matches the
        bounded oracle bit-for-bit (CT kept un-saturated so the
        single-table oracle and the sharded mesh agree on placement)."""
        serial = fake_serial_engine()
        dev = jit_pipeline_engine(4, rss_mode="device")
        slot_of = serial.active.snapshot.ep_slot_of
        rng = np.random.default_rng(17)
        try:
            chunks = []
            for c in range(6):
                recs = []
                for r in range(48):
                    proto = int(rng.choice(
                        [C.PROTO_TCP, C.PROTO_TCP, C.PROTO_UDP]))
                    recs.append(pkt(
                        "192.168.1.10",
                        f"10.{rng.integers(0, 2)}.{rng.integers(0, 250)}"
                        f".{rng.integers(1, 250)}",
                        int(1024 + rng.integers(0, 60000)),
                        int(rng.choice([443, 80, 53, 22])), proto=proto,
                        flags=C.TCP_SYN if proto == C.PROTO_TCP else 0))
                chunks.append(batch_from_records(recs, slot_of,
                                                 pad_to=48 + (c % 3)))
            _run_phase(serial, [dev], chunks, now0=4000)
            ps = dev.pipeline_stats()
            assert ps["shed_total"] == 0 and ps["admission_drops"] == 0
        finally:
            serial.stop()
            dev.stop()

    def test_regen_between_stage_and_dispatch_plain_stamp_check(self):
        """The steer-revision fence satellite: with device RSS active, a
        policy regen landing between stage-write and dispatch must NOT
        trip the re-steer logic (there is nothing to re-steer — rows
        carry no placement) — the fence degrades to the plain revision
        stamp check (ep-slot remap + the StalePlacement retry), and the
        batch classifies correctly under the NEW snapshot."""
        dev = jit_pipeline_engine(4, rss_mode="device",
                                  pipeline_flush_ms=250.0)
        slot_of = dev.active.snapshot.ep_slot_of
        try:
            b = batch_from_records(
                [pkt("192.168.1.10", "10.1.77.1", 45001, 443)], slot_of)
            t = dev.submit(dict(b), now=5000)     # parks in staging 250ms
            # regen lands while staged: the delta patch donates the old
            # placed handle — dispatch must retry via the stamp check,
            # never attempt a re-steer
            dev.apply_policy([{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egressDeny": [{"toCIDR": ["10.1.77.0/24"]}],
            }])
            dev.regenerate()
            assert dev.drain(timeout=60)
            out = t.result(timeout=10)
            # the new deny applied: classified under the post-regen world
            assert not out["allow"][0]
            assert out["reason"][0] == int(C.DropReason.POLICY_DENY)
            # no steered fallback ran — there is no steering to redo
            assert dev.datapath.pack_stats["pack_fallback_steered"] == 0
        finally:
            dev.stop()

    def test_ledger_and_gauge_surfaces(self):
        """Satellites: the exchange buffers register in the resource
        ledger (+ the HBM ledger's ``exchange`` group), the unsteered
        staging ring keeps its ring row, and the steer-balance gauges /
        steer_overflow shed class are ABSENT from the export rather than
        frozen at zero."""
        dev = jit_pipeline_engine(4, rss_mode="device")
        slot_of = dev.active.snapshot.ep_slot_of
        try:
            t = dev.submit(batch_from_records(
                [pkt("192.168.1.10", "10.0.2.3", 40000, 443)], slot_of),
                now=100)
            assert dev.drain(timeout=30)
            t.result(timeout=5)
            dev.resource_step()
            rep = dev.resources()
            assert "rss_exchange" in rep["resources"]
            assert "staging_ring" in rep["resources"]
            # steered-only row must not exist on an unsharded ring
            assert "staging_segment_peak" not in rep["resources"]
            ex = dev.datapath.rss_exchange_stats()
            assert ex["in_use"] > 0 and ex["capacity"] >= ex["peak"] > 0
            assert dev.datapath.hbm_ledger()["groups"]["exchange"] > 0
            text = dev.render_metrics()
            assert "ciliumtpu_pipeline_mesh_shards 4" in text
            assert 'pipeline_staged_rows{shard=' not in text
            assert "steer_overflow" not in text
            h = dev.health()
            assert h["pipeline"]["shards"] == 4
            assert h["pipeline"]["rss_mode"] == "device"
            from cilium_tpu.runtime.api import status_doc
            assert status_doc(dev)["rss"]["mode"] == "device"
        finally:
            dev.stop()

    def test_audit_clean_at_sampling_one(self):
        """The shadow-oracle auditor at sampling 1.0 over the device
        path: every finalized batch replays clean against the oracle —
        the ISSUE's parity bar with steering off."""
        dev = jit_pipeline_engine(4, rss_mode="device",
                                  audit_enabled=True, audit_sample_rate=1.0)
        slot_of = dev.active.snapshot.ep_slot_of
        try:
            chunks = _mk_phase(slot_of, 4, (7, 13, 5, 22), seed=31)
            for i, ch in enumerate(chunks):
                dev.submit(dict(ch), now=6000 + i)
            assert dev.drain(timeout=60)
            dev.audit_step()
            st = dev.auditor.stats()
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0, list(dev.auditor.mismatches)
            assert st["replay_errors"] == 0
        finally:
            dev.stop()

    def test_min_bucket_clamped_to_mesh(self):
        """Buckets must divide the mesh's flow axis: an engine configured
        with a min bucket below the shard count clamps it up."""
        dev = jit_pipeline_engine(8, rss_mode="device",
                                  pipeline_min_bucket=1)
        slot_of = dev.active.snapshot.ep_slot_of
        try:
            t = dev.submit(batch_from_records(
                [pkt("192.168.1.10", "10.0.2.3", 40001, 443)], slot_of),
                now=100)
            assert dev.drain(timeout=30)
            assert t.result(timeout=5)["allow"].shape[0] == 1
            assert dev._pipeline.min_bucket >= 8
        finally:
            dev.stop()


class TestPipelineRSSValidation:
    def test_device_mode_refuses_sharded_staging(self):
        with pytest.raises(ValueError, match="rss_mode='device'"):
            Pipeline(lambda b, n: (lambda: {}), n_shards=4,
                     shard_fn=lambda b: np.zeros(1), rss_mode="device")

    def test_bad_rss_mode_rejected(self):
        with pytest.raises(ValueError, match="bad rss_mode"):
            Pipeline(lambda b, n: (lambda: {}), rss_mode="bogus")
        with pytest.raises(ValueError, match="bad rss_mode"):
            DaemonConfig(rss_mode="bogus")


# --------------------------------------------------------------------------- #
# Slow soak (`make rss-smoke`): 10k skewed rows through the device mesh
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestDeviceRSSSoak:
    def test_soak_10k_skewed_device(self):
        """10k rows whose flows ALL hash to one CT shard — the storm that
        breaks host steering structurally (one segment serializes the
        mesh; past headroom it sheds steer_overflow) — through the
        device-RSS 4-shard mesh: every submission resolves, nothing
        sheds, the guard never restarts, and the CT table holds exactly
        the unique flows."""
        from cilium_tpu.parallel.mesh import flow_shard_of
        dev = jit_pipeline_engine(4, rss_mode="device", batch_size=256,
                                  ct_capacity=1 << 15,
                                  pipeline_queue_batches=256,
                                  pipeline_flush_ms=0.5)
        slot_of = dev.active.snapshot.ep_slot_of
        try:
            # build one shard-0-only pool of flows, then stream 10k rows
            pool = []
            rng = np.random.default_rng(77)
            while len(pool) < 2048:
                recs = [pkt("192.168.1.10",
                            f"10.{rng.integers(0, 2)}."
                            f"{rng.integers(0, 250)}.{rng.integers(1, 250)}",
                            int(1024 + rng.integers(0, 60000)), 443)
                        for _ in range(256)]
                b = batch_from_records(recs, slot_of)
                sh = flow_shard_of(b, 4)
                pool.extend(r for r, s in zip(recs, sh) if s == 0)
            tickets = []
            n_rows = 0
            i = 0
            while n_rows < 10_000:
                take = pool[(i * 64) % len(pool):][:64] or pool[:64]
                tickets.append(dev.submit(
                    batch_from_records(take, slot_of), now=7000 + i))
                n_rows += len(take)
                i += 1
            assert dev.drain(timeout=300)
            for t in tickets:
                t.result(timeout=10)
            ps = dev.pipeline_stats()
            assert ps["shed_total"] == 0
            assert ps["restarts"] == 0
            assert ps["state"] == "ok"
            assert dev.datapath.pack_stats["pack_fallback_steered"] == 0
        finally:
            dev.stop()


# --------------------------------------------------------------------------- #
# Degraded survivor geometry under device-side RSS (ISSUE 19): the n-1
# ring exchange is the same verdict machine, just narrower
# --------------------------------------------------------------------------- #
class TestDeviceRSSDegradedMesh:
    @pytest.mark.slow
    def test_device_rss_n_minus_1_parity_and_audit_clean(self):
        """Both rss modes shrink 4 -> 3 BEFORE any traffic; the degraded
        device-RSS mesh (ppermute ring over 3 chips) must stay
        bit-identical to the degraded host-steered mesh and to the
        oracle-backed serial path — including CT continuity in both
        directions — with the shadow auditor at sampling 1.0 clean on
        the device engine."""
        FAULTS.reset()
        serial = fake_serial_engine()
        host = jit_pipeline_engine(4)
        dev = jit_pipeline_engine(4, rss_mode="device",
                                  audit_enabled=True,
                                  audit_sample_rate=1.0,
                                  audit_pool_batches=64)
        dev.auditor.configure(sample_rate=1.0)
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            for eng in (host, dev):
                eng.datapath.note_device_loss(2, reason="drill")
                doc = eng.remesh_step()
                assert doc["remesh"]["to"] == 3
            assert dev.datapath.rss_state["shards"] == 3
            assert dev.datapath.pipeline_shards == 1   # no pre-steering
            assert host.datapath.pipeline_shards == 3

            ch1 = _mk_phase(slot_of, 4, (1, 5, 17, 9), seed=91)
            _run_phase(serial, [host, dev], ch1, now0=3000)
            est = [pkt("192.168.1.10", "10.0.2.7", 49500 + i, 443)
                   for i in range(4)]
            _run_phase(serial, [host, dev],
                       [batch_from_records(est, slot_of)], now0=3200)
            reply = [pkt("10.0.2.7", "192.168.1.10", 443, 49500 + i,
                         flags=C.TCP_ACK, direction=C.DIR_INGRESS)
                     for i in range(4)]
            outs = _run_phase(
                serial, [host, dev],
                [batch_from_records(reply, slot_of, pad_to=6)],
                now0=3210)
            assert (np.asarray(outs[0]["status"])[:4]
                    == int(C.CTStatus.REPLY)).all()

            live = serial.ct_stats(now=4000)["live"]
            assert host.ct_stats(now=4000)["live"] == live
            assert dev.ct_stats(now=4000)["live"] == live
            for _ in range(100):
                step = dev.audit_step(budget=128)
                if not step or (not step.get("replayed")
                                and not step.get("pending")):
                    break
            st = dev.auditor.stats()
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0
        finally:
            for eng in (serial, host, dev):
                eng.stop()
