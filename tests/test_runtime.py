"""Runtime tests: engine end-to-end (vs oracle), policy update fencing,
checkpoint/resume flow survival, config layering, controllers, metrics,
flow log."""

import json
import os
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.checkpoint import restore, save
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.controller import Controller, Trigger
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import Oracle, PacketRecord

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
}]


def small_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    kw.setdefault("flowlog_mode", "all")
    return Engine(DaemonConfig(**kw))


def pkt(src, dst, sp, dp, proto=C.PROTO_TCP, flags=C.TCP_SYN, ep_id=1,
        direction=C.DIR_EGRESS, method=C.HTTP_METHOD_ANY, path=b""):
    s16, sv6 = parse_addr(src)
    d16, dv6 = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, proto, flags, sv6 or dv6, ep_id,
                        direction, method, path)


class TestEngine:
    def test_end_to_end_matches_oracle(self):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        active = eng.active
        oracle = Oracle(dict(zip(active.snapshot.ep_ids,
                                 active.snapshot.policies)),
                        eng.ctx.ipcache.snapshot())
        packets = [
            pkt("192.168.1.10", "10.1.2.3", 40000, 443),
            pkt("192.168.1.10", "10.1.2.3", 40000, 443, flags=C.TCP_ACK),
            pkt("192.168.1.10", "10.1.2.3", 40001, 80),
            pkt("192.168.1.10", "8.8.8.8", 40002, 443),
        ]
        want = oracle.classify_batch_snapshot(packets, 100)
        out = eng.classify(batch_from_records(packets, active.snapshot.ep_slot_of),
                           now=100)
        for i, v in enumerate(want):
            assert bool(out["allow"][i]) == v.allow
            assert int(out["reason"][i]) == int(v.drop_reason)
        assert eng.ct_stats(now=100)["live"] == 1
        assert eng.metrics.packets_total == 4

    def test_policy_update_revision_fence(self):
        """Snapshot swap: new rules take effect for NEW flows; established
        flows keep passing via CT (the connection-survival contract)."""
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        snap0 = eng.active
        slot_of = snap0.snapshot.ep_slot_of
        # establish a flow on 443
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)], slot_of), now=100)
        assert bool(out["allow"][0])
        rev0 = snap0.revision
        # replace policy: now only port 80 is allowed
        eng.repo.clear()
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}]}],
        }])
        snap1 = eng.active
        assert snap1.revision > rev0
        # established flow still forwarded (CT bypass)
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443, flags=C.TCP_ACK)],
            slot_of), now=101)
        assert bool(out["allow"][0])
        assert int(out["status"][0]) == C.CTStatus.ESTABLISHED
        # a NEW flow to 443 now drops; to 80 passes
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 41000, 443),
             pkt("192.168.1.10", "10.1.2.3", 41001, 80)], slot_of), now=102)
        assert not bool(out["allow"][0]) and bool(out["allow"][1])

    def test_sweep_controller(self):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)], slot_of), now=100)
        assert eng.sweep(now=100 + C.CT_LIFETIME_SYN + 1) == 1
        assert eng.ct_stats(now=200)["live"] == 0

    def test_flowlog_and_metrics(self):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443),
             pkt("192.168.1.10", "10.1.2.3", 40001, 22)], slot_of), now=100)
        logs = eng.flowlog.tail()
        assert len(logs) == 2
        drop = [l for l in logs if l["verdict"] == "DROPPED"][0]
        assert drop["dst_port"] == 22 and drop["drop_reason_desc"] == "POLICY"
        text = eng.metrics.render_prometheus()
        assert 'reason="OK",direction="egress"} 1' in text
        assert 'reason="POLICY"' in text

    def test_unenforced_endpoint_allows(self):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=lonely"], ips=("192.168.1.99",), ep_id=5)
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.99", "8.8.8.8", 40000, 443, ep_id=5)],
            eng.active.snapshot.ep_slot_of), now=100)
        assert bool(out["allow"][0])


class TestEngineServices:
    def test_service_lb_through_engine(self):
        from cilium_tpu.model.services import Backend, Frontend, Service
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.upsert_service(Service(
            name="api", namespace="prod",
            frontends=(Frontend("172.30.0.1", 443, C.PROTO_TCP),),
            lb_backends=(Backend("10.7.0.1", 443), Backend("10.7.0.2", 443)),
        ))
        active = eng.active
        assert active.snapshot.lb.n_frontends == 1
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "172.30.0.1", 40000, 443)],
            active.snapshot.ep_slot_of), now=100)
        assert bool(out["allow"][0]) and bool(out["svc"][0])
        assert int(out["nat_dport"][0]) == 443
        # deleting the service recompiles; VIP traffic now hits world/deny
        eng.delete_service("prod", "api")
        out2 = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "172.30.0.1", 40001, 443)],
            eng.active.snapshot.ep_slot_of), now=101)
        assert not bool(out2["svc"][0])

    def test_rnat_stable_across_service_churn(self):
        """Rev-NAT ids are stable: adding a service that sorts earlier must
        not re-point old CT entries at the new VIP, and deleting a service
        leaves its stale CT entries failing closed (no rewrite)."""
        from cilium_tpu.model.services import Backend, Frontend, Service
        from cilium_tpu.utils.ip import words_to_addr
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.upsert_service(Service(
            name="api", namespace="zzz",
            frontends=(Frontend("172.30.0.1", 443, C.PROTO_TCP),),
            lb_backends=(Backend("10.7.0.1", 443),)))
        slot_of = eng.active.snapshot.ep_slot_of
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "172.30.0.1", 40000, 443)], slot_of),
            now=100)
        assert bool(out["svc"][0])
        # a service that sorts FIRST re-orders frontend indices
        eng.upsert_service(Service(
            name="aaa", namespace="aaa",
            frontends=(Frontend("172.31.0.9", 443, C.PROTO_TCP),),
            lb_backends=(Backend("10.8.0.1", 443),)))
        reply = pkt("10.7.0.1", "192.168.1.10", 443, 40000,
                    flags=C.TCP_SYN | C.TCP_ACK, direction=C.DIR_INGRESS)
        out2 = eng.classify(batch_from_records(
            [reply], eng.active.snapshot.ep_slot_of), now=105)
        assert bool(out2["rnat"][0])
        vip16, _ = parse_addr("172.30.0.1")   # the ORIGINAL vip, not aaa's
        assert words_to_addr(out2["rnat_src"][0]) == vip16
        # delete the original service: stale CT entry → no rewrite at all
        eng.delete_service("zzz", "api")
        out3 = eng.classify(batch_from_records(
            [reply], eng.active.snapshot.ep_slot_of), now=110)
        assert int(out3["status"][0]) == C.CTStatus.REPLY
        assert not bool(out3["rnat"][0])

    def test_service_flow_survives_restart(self, tmp_path):
        from cilium_tpu.model.services import Backend, Frontend, Service
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.upsert_service(Service(
            name="api", namespace="prod",
            frontends=(Frontend("172.30.0.1", 443, C.PROTO_TCP),),
            lb_backends=(Backend("10.7.0.1", 443),),
        ))
        slot_of = eng.active.snapshot.ep_slot_of
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "172.30.0.1", 40000, 443)], slot_of),
            now=100)
        assert bool(out["svc"][0])
        save(eng, str(tmp_path / "ckpt"))

        eng2 = small_engine()
        restore(eng2, str(tmp_path / "ckpt"))
        # service survives, and the reply still rev-NATs through the
        # restored CT entry (rev_nat column round-trips)
        reply = pkt("10.7.0.1", "192.168.1.10", 443, 40000,
                    flags=C.TCP_SYN | C.TCP_ACK, direction=C.DIR_INGRESS)
        out2 = eng2.classify(batch_from_records(
            [reply], eng2.active.snapshot.ep_slot_of), now=105)
        assert int(out2["status"][0]) == C.CTStatus.REPLY
        assert bool(out2["rnat"][0])
        vip16, _ = parse_addr("172.30.0.1")
        from cilium_tpu.utils.ip import words_to_addr
        assert words_to_addr(out2["rnat_src"][0]) == vip16
        assert int(out2["rnat_sport"][0]) == 443


class TestHealth:
    def test_health_probe(self):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.add_endpoint(["k8s:app=db"], ips=("192.168.1.20",), ep_id=2)
        # web: unenforced ingress (no ingress rules) → reachable;
        # db: enforced ingress that does NOT allow health → unreachable
        eng.apply_policy(POLICY + [{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [{"matchLabels":
                                            {"app": "web"}}]}],
        }])
        rep = eng.health_probe(now=100)
        assert rep[1]["reachable"] is True
        assert rep[2]["reachable"] is False
        assert rep[2]["reason"] == "POLICY"
        # whitelist health → reachable (the upstream remediation)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEntities": ["health"]}],
        }])
        rep = eng.health_probe(now=200)
        assert rep[2]["reachable"] is True
        assert eng.metrics.gauges["health_reachable_endpoints"] == 2


class TestCheckpoint:
    def test_flows_survive_restart(self, tmp_path):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)], slot_of), now=100)
        cidr_id = eng.ctx.ipcache.lookup("10.1.2.3")
        save(eng, str(tmp_path / "ckpt"))

        eng2 = small_engine()
        restore(eng2, str(tmp_path / "ckpt"))
        # identity numbering stable
        assert eng2.ctx.ipcache.lookup("10.1.2.3") == cidr_id
        # the established flow survives the "restart": ACK is ESTABLISHED,
        # not NEW (the pinned-map analog)
        out = eng2.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443, flags=C.TCP_ACK)],
            eng2.active.snapshot.ep_slot_of), now=105)
        assert bool(out["allow"][0])
        assert int(out["status"][0]) == C.CTStatus.ESTABLISHED

    def test_restore_requires_fresh_engine(self, tmp_path):
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ep_id=1)
        save(eng, str(tmp_path / "c"))
        eng2 = small_engine()
        eng2.add_endpoint(["k8s:app=other"], ep_id=9)
        with pytest.raises(ValueError):
            restore(eng2, str(tmp_path / "c"))


class TestConfig:
    def test_env_overrides_file(self, tmp_path):
        cfg_file = tmp_path / "cfg.json"
        cfg_file.write_text(json.dumps({"ct_capacity": 4096,
                                        "enforcement_mode": "default"}))
        cfg = DaemonConfig.load(
            config_file=str(cfg_file),
            env={"CILIUM_TPU_ENFORCEMENT_MODE": "always"},
            argv=["--batch-size", "128"])
        assert cfg.ct_capacity == 4096
        assert cfg.enforcement_mode == "always"
        assert cfg.batch_size == 128

    def test_rejects_unknown_keys(self, tmp_path):
        cfg_file = tmp_path / "cfg.json"
        cfg_file.write_text(json.dumps({"bogus": 1}))
        with pytest.raises(ValueError):
            DaemonConfig.load(config_file=str(cfg_file), env={})

    def test_validation(self):
        with pytest.raises(ValueError):
            DaemonConfig(ct_capacity=1000)
        with pytest.raises(ValueError):
            DaemonConfig(enforcement_mode="sometimes")


class TestControllers:
    def test_retry_with_backoff_counts(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("boom")

        ctrl = Controller("test", flaky, interval=0.01, backoff_base=0.001)
        for _ in range(3):
            ctrl.run_once()
        assert ctrl.status.failure_count == 2
        assert ctrl.status.success_count == 1
        assert ctrl.status.consecutive_failures == 0

    def test_trigger_debounce(self):
        fired = []
        trig = Trigger(lambda: fired.append(1), min_interval=0.05)
        for _ in range(10):
            trig()
        time.sleep(0.15)
        assert len(fired) == 1
        assert trig.folds == 9


class TestFlowLogSinkCap:
    def test_sink_buf_bounded_drop_oldest(self, tmp_path, monkeypatch):
        """Without a flush controller the pending sink buffer must stay
        bounded (drop-oldest, counted) instead of growing without limit."""
        from cilium_tpu.runtime import flowlog as fl
        monkeypatch.setattr(fl, "SINK_BUF_MAX", 10)
        log = fl.FlowLog(capacity=4, mode="all",
                         sink_path=str(tmp_path / "flows.jsonl"))
        batch = {
            "src": np.zeros((3, 4), np.uint32), "dst": np.zeros((3, 4), np.uint32),
            "sport": np.zeros(3, np.uint32), "dport": np.zeros(3, np.uint32),
            "proto": np.full(3, 6, np.uint32), "direction": np.zeros(3, np.uint32),
            "ep_slot": np.zeros(3, np.uint32), "valid": np.ones(3, bool),
        }
        out = {
            "allow": np.ones(3, bool), "reason": np.zeros(3, np.uint32),
            "status": np.zeros(3, np.uint32),
            "remote_identity": np.zeros(3, np.uint32),
        }
        for t in range(8):
            log.append_batch(batch, out, now=t, ep_ids=(1,))
        assert len(log._sink_buf) <= 10
        assert log.sink_dropped == 8 * 3 - 10
        # flush drains what's left; ring tail unaffected
        assert log.flush_sink() == 10
        assert log._sink_buf == []

    @staticmethod
    def _mk_batch_out(n):
        batch = {
            "src": np.zeros((n, 4), np.uint32),
            "dst": np.zeros((n, 4), np.uint32),
            "sport": np.arange(n, dtype=np.uint32),
            "dport": np.zeros(n, np.uint32),
            "proto": np.full(n, 6, np.uint32),
            "direction": np.zeros(n, np.uint32),
            "ep_slot": np.zeros(n, np.uint32), "valid": np.ones(n, bool),
        }
        out = {
            "allow": np.ones(n, bool), "reason": np.zeros(n, np.uint32),
            "status": np.zeros(n, np.uint32),
            "remote_identity": np.zeros(n, np.uint32),
        }
        return batch, out

    def test_sink_rotation_at_rotate_bytes(self, tmp_path, monkeypatch):
        """Past SINK_ROTATE_BYTES the sink rotates to <path>.1 (keep one
        generation); new lines land in a fresh file."""
        from cilium_tpu.runtime import flowlog as fl
        monkeypatch.setattr(fl, "SINK_ROTATE_BYTES", 256)
        path = tmp_path / "flows.jsonl"
        log = fl.FlowLog(capacity=8, mode="all", sink_path=str(path))
        batch, out = self._mk_batch_out(3)
        log.append_batch(batch, out, now=1, ep_ids=(1,))
        log.flush_sink()
        assert path.stat().st_size > 256   # one flush already past the cap
        first_gen = path.read_text()
        log.append_batch(batch, out, now=2, ep_ids=(1,))
        log.flush_sink()                   # this flush must rotate first
        rotated = tmp_path / "flows.jsonl.1"
        assert rotated.exists() and rotated.read_text() == first_gen
        fresh = [json.loads(line) for line in
                 path.read_text().strip().splitlines()]
        assert len(fresh) == 3 and all(r["time"] == 2 for r in fresh)

    def test_extract_capped_keeps_newest(self, monkeypatch):
        """A drop-storm batch larger than APPEND_BATCH_MAX only extracts
        the newest rows; the shed remainder is counted, and the ring still
        sees every extracted record."""
        from cilium_tpu.runtime import flowlog as fl
        monkeypatch.setattr(fl, "APPEND_BATCH_MAX", 5)
        log = fl.FlowLog(capacity=16, mode="all")
        batch, out = self._mk_batch_out(12)
        log.append_batch(batch, out, now=1, ep_ids=(1,))
        assert log.extract_shed == 12 - 5
        assert log.total_seen == 12
        tail = log.tail()
        assert [r["src_port"] for r in tail] == list(range(7, 12))


class TestFlowLogFollowEdges:
    """Live-follow edge cases: the since() seq cursor across ring
    wraparound, and tail()/since() exact-match filter typing (int vs str
    field values must not cross-match)."""

    @staticmethod
    def _fill(log, n, start_port=0):
        batch, out = TestFlowLogSinkCap._mk_batch_out(n)
        batch["sport"] = np.arange(start_port, start_port + n,
                                   dtype=np.uint32)
        log.append_batch(batch, out, now=1, ep_ids=(1,))

    def test_since_cursor_across_wraparound(self):
        from cilium_tpu.runtime import flowlog as fl
        log = fl.FlowLog(capacity=8, mode="all")
        self._fill(log, 20)               # seqs 1..20; ring keeps 13..20
        # a cursor inside the retained range follows without loss
        got = log.since(15)
        assert [r["seq"] for r in got] == [16, 17, 18, 19, 20]
        # oldest-first ordering holds across the physical wrap point
        got = log.since(0)
        assert [r["seq"] for r in got] == list(range(13, 21))
        # a cursor that fell off the ring gets an EXPLICIT structured gap
        # marker (records 6..12 are gone), then resumes at the oldest
        # retained record — loss is a record in the stream, not an
        # inference left to seq arithmetic
        got = log.since(5)
        assert got[0] == {"gap": True, "dropped": 7, "resume_seq": 13}
        assert got[1]["seq"] == 13
        assert log.follow_gaps == 1 and log.follow_gap_records == 7
        # cursor at the head: nothing new
        assert log.since(20) == []
        # limit caps oldest-first (the poll page)
        got = log.since(0, limit=3)
        assert [r["seq"] for r in got] == [13, 14, 15]

    def test_since_filters_apply_before_limit_cursor_advances(self):
        from cilium_tpu.runtime import flowlog as fl
        log = fl.FlowLog(capacity=16, mode="all")
        self._fill(log, 10)
        got = log.since(0, src_port=7)
        assert len(got) == 1 and got[0]["src_port"] == 7
        # filtered follow: cursor from the last *returned* record still
        # sees later matches only
        assert log.since(got[0]["seq"], src_port=7) == []

    def test_tail_filter_typing_int_vs_str(self):
        from cilium_tpu.runtime import flowlog as fl
        log = fl.FlowLog(capacity=16, mode="all")
        self._fill(log, 6)
        # src_port is stored as int: an int filter matches...
        assert len(log.tail(src_port=3)) == 1
        # ...a string of the same digits must NOT (exact typed match, the
        # documented semantics — no coercion surprises for API callers)
        assert log.tail(src_port="3") == []
        # string-valued fields match strings only
        assert len(log.tail(verdict="FORWARDED")) == 6
        assert log.tail(verdict=True) == []
        # unknown filter key matches nothing rather than everything
        assert log.tail(no_such_field=1) == []
        # combined typed filters AND together
        assert len(log.tail(verdict="FORWARDED", src_port=3)) == 1

    def test_since_typed_filters_across_wrap(self):
        from cilium_tpu.runtime import flowlog as fl
        log = fl.FlowLog(capacity=4, mode="all")
        self._fill(log, 10)               # ring keeps sports 6..9
        assert [r["src_port"] for r in log.since(0, src_port=8)] == [8]
        assert log.since(0, src_port="8") == []


class TestMetricsHistogram:
    def test_observe_quantile_and_render(self):
        from cilium_tpu.runtime.metrics import Histogram, Metrics
        m = Metrics()
        h = m.histogram("pipeline_queue_wait_seconds")
        assert m.histogram("pipeline_queue_wait_seconds") is h  # idempotent
        for v in (0.0002, 0.0002, 0.003, 0.02, 7.0):
            h.observe(v)
        assert h.count == 5 and h.total == pytest.approx(7.0234)
        assert 0.0001 <= h.quantile(0.5) <= 0.005
        assert h.quantile(0.999) == h.buckets[-1]   # past last finite bound
        text = m.render_prometheus()
        assert ("# TYPE ciliumtpu_pipeline_queue_wait_seconds histogram"
                in text)
        assert 'pipeline_queue_wait_seconds_bucket{le="+Inf"} 5' in text
        assert "pipeline_queue_wait_seconds_count 5" in text
        assert Histogram().quantile(0.5) == 0.0     # empty histogram

    def test_counter_geometry_from_constants(self):
        from cilium_tpu.runtime.metrics import Metrics
        m = Metrics()
        assert m.by_reason_dir.shape == (C.DROP_REASON_BINS
                                         * C.N_DIRECTIONS,)
        bad = {"by_reason_dir": np.zeros(512 + 2, np.uint32),
               "insert_fail": np.uint32(0)}
        with pytest.raises(ValueError, match="geometry"):
            m.add_batch(bad, n_valid=0)


class TestRegenFailureVisibility:
    def test_regen_failure_logged_and_counted(self, caplog):
        """A failing auto-regen must not be silent: it logs and bumps
        regen_failures_total exactly once so operators see stale device
        state (supervised degradation: serving continues on last-good)."""
        import logging as _logging

        from cilium_tpu.runtime.faults import FAULTS
        eng = small_engine(auto_regen=True)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        _ = eng.active                             # last-good exists
        eng._regen_trigger.cancel()                # no async timer racing us
        try:
            FAULTS.arm("regen.compile", mode="fail", times=1)
            with caplog.at_level(_logging.WARNING,
                                 logger="cilium_tpu.engine"):
                eng._mark_dirty_and_regen()
        finally:
            FAULTS.reset()
        assert eng.metrics.counters.get("regen_failures_total") == 1
        assert any("regeneration failed" in r.message
                   for r in caplog.records)
        assert "regen_failures_total 1" in eng.metrics.render_prometheus()


class TestDebugChecksHarness:
    def test_classify_under_debug_nans_and_checks(self):
        """SURVEY §5 race-detection/sanitizer row: the datapath program must
        be clean under jax_debug_nans + checking config (the eBPF-verifier
        -strictness analog for numerics) — NaN-producing ops or invalid
        indexing in the fused kernel would raise here."""
        import jax
        from cilium_tpu.kernels.records import batch_from_records
        from cilium_tpu.runtime.config import DaemonConfig
        from cilium_tpu.runtime.datapath import JITDatapath
        from cilium_tpu.runtime.engine import Engine
        from cilium_tpu.utils.ip import parse_addr
        from oracle import PacketRecord

        jax.config.update("jax_debug_nans", True)
        try:
            eng = Engine(DaemonConfig(ct_capacity=1024, auto_regen=False),
                         datapath=JITDatapath(DaemonConfig(
                             ct_capacity=1024, auto_regen=False)))
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.5.10",), ep_id=1)
            eng.apply_policy([{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [{"toCIDR": ["10.0.0.0/8"],
                            "toPorts": [{"ports": [
                                {"port": "443", "protocol": "TCP"}]}]}]}])
            eng.regenerate()
            s16, _ = parse_addr("192.168.5.10")
            d16, _ = parse_addr("10.3.2.1")
            pkts = [PacketRecord(s16, d16, 40000 + i, 443, C.PROTO_TCP,
                                 C.TCP_SYN, False, 1, C.DIR_EGRESS)
                    for i in range(32)]
            out = eng.classify(batch_from_records(
                pkts, eng.active.snapshot.ep_slot_of), now=100)
            assert bool(out["allow"][0])
        finally:
            jax.config.update("jax_debug_nans", False)
