"""Zero-copy ingestion tests: reusable poll buffers, the async
shim→pipeline feeder (shim/feeder.py), and the steady-state zero-alloc
contract of the pack/stage path.

The FIFO proof rides frame *lengths*: mock_tx_drain returns forwarded
frames in tx-push order, and tx pushes happen in apply_verdicts order, so
injecting frames with strictly increasing payload sizes and asserting the
drained length sequence is exactly the injected one pins
harvest-order == verdict-order end to end — including under armed
``shim.rx_ring`` faults.
"""

import gc
import os
import time
import tracemalloc

import numpy as np
import pytest

from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.shim.bindings import LIB_PATH, FlowShim, build_frame

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB_PATH),
    reason="libflowshim.so not built (make -C cilium_tpu/shim)")

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]

BASE_LEN = 54       # eth(14) + ipv4(20) + tcp(20): payload i → len 54+i


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def fake_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    kw.setdefault("batch_size", 64)
    kw.setdefault("pipeline_flush_ms", 1.0)
    cfg = DaemonConfig(**kw)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(POLICY)
    eng.regenerate()
    return eng


def mk_shim(batch_size=16, rings=True):
    shim = FlowShim(batch_size=batch_size, timeout_us=100)
    shim.register_endpoint("192.168.1.10", 1)
    if rings:
        shim.mock_rings_init(ring_size=64, frame_size=2048, n_frames=64)
    return shim


def inject_all(shim, frames, drain_to=None, deadline_s=10.0):
    """NIC-side producer: push every frame, recycling tx as needed."""
    end = time.time() + deadline_s
    for f in frames:
        while shim.mock_rx_inject(f) != 0:
            if drain_to is not None:
                drain_to.extend(shim.mock_tx_drain(64))
            else:
                shim.mock_tx_drain(64)
            if time.time() > end:
                raise TimeoutError("mock rx ring never drained")
            time.sleep(0.0005)


def wait_verdicts(shim, want, deadline_s=20.0, drain_to=None):
    end = time.time() + deadline_s
    while time.time() < end:
        if drain_to is not None:
            drain_to.extend(shim.mock_tx_drain(64))
        else:
            shim.mock_tx_drain(64)
        st = shim.stats()
        if st["verdict_passes"] + st["verdict_drops"] \
                + st["tx_full_drops"] >= want:
            return st
        time.sleep(0.005)
    raise TimeoutError(f"verdicts never reached {want}: {shim.stats()}")


class TestPollBatchOut:
    def test_out_reuse_matches_fresh_poll(self):
        """poll_batch(out=) must be column-identical to an allocating poll
        of the same frames, including the reset tail of a dirty reused
        buffer."""
        shim = mk_shim(batch_size=8, rings=False)
        frames = [build_frame("192.168.1.10", "10.0.0.1", 41000 + i, 443,
                              payload=b"x" * i) for i in range(5)]
        for f in frames:
            shim.feed_frame(f)
        fresh = shim.poll_batch(force=True)
        assert fresh is not None
        shim.apply_verdicts(np.zeros(8, bool))

        for f in frames:
            shim.feed_frame(f)
        buf = shim.make_poll_buffer()
        for col in buf.values():            # dirty the buffer thoroughly
            col[:] = np.iinfo(col.dtype).max if col.dtype != bool else True
        reused = shim.poll_batch(force=True, out=buf)
        assert reused is buf
        for k in fresh:
            if k == "_frame_idx":
                continue          # monotone across polls by design
            np.testing.assert_array_equal(
                reused[k], fresh[k], err_msg=f"column {k} diverged")
        np.testing.assert_array_equal(reused["_frame_idx"][:5],
                                      fresh["_frame_idx"][:5] + 5)
        shim.apply_verdicts(np.zeros(8, bool))
        shim.close()


class TestFeederEndToEnd:
    def test_fifo_verdict_order_mock_rings(self):
        """Frames with strictly increasing lengths, all allowed: the tx
        drain sequence must be exactly the injection sequence (verdicts
        applied FIFO, nothing lost, nothing reordered)."""
        eng = fake_engine()
        shim = mk_shim()
        eng.start_feeder(shim)
        n = 120
        frames = [build_frame("192.168.1.10", "10.1.2.3", 40000 + i, 443,
                              payload=b"p" * i) for i in range(n)]
        drained = []
        inject_all(shim, frames, drain_to=drained)
        st = wait_verdicts(shim, n, drain_to=drained)
        eng.stop()
        drained.extend(shim.mock_tx_drain(64))
        assert st["verdict_passes"] == n and st["verdict_drops"] == 0
        lens = [ln for _a, ln in drained]
        assert lens == [BASE_LEN + i for i in range(n)], \
            "forwarded frames out of order — verdict FIFO broken"
        fd_stats = eng.metrics.counters
        assert fd_stats["feeder_harvest_batches_total"] >= 1
        shim.close()

    def test_mixed_verdicts_and_counts(self):
        eng = fake_engine()
        shim = mk_shim()
        feeder = eng.start_feeder(shim)
        n = 90
        frames = [build_frame("192.168.1.10", "10.1.2.3", 42000 + i,
                              443 if i % 3 else 80) for i in range(n)]
        n_allow = sum(1 for i in range(n) if i % 3)
        inject_all(shim, frames)
        st = wait_verdicts(shim, n)
        stats = feeder.stats()
        eng.stop()
        assert st["verdict_passes"] == n_allow
        assert st["verdict_drops"] == n - n_allow
        assert stats["harvested_records"] == n
        assert stats["rejected_batches"] == 0
        shim.close()

    def test_rings_attach_with_exhausted_fill_ring(self):
        """Every umem descriptor parked in the rx ring BEFORE the
        feeder's first ring probe: the fill level reads zero exactly
        when the ring drain is most needed, and only the drain recycles
        addresses — a probe that mistook that for "no rings" deadlocked
        ingestion permanently (producer: full rx ring; harvester: never
        looks). The same race fired intermittently when a fast producer
        out-injected the feeder thread's startup."""
        eng = fake_engine()
        shim = mk_shim()                      # ring 64 / 64 umem frames
        frames = [build_frame("192.168.1.10", "10.1.2.3", 47000 + i, 443)
                  for i in range(64)]
        for f in frames:
            assert shim.mock_rx_inject(f) == 0
        assert shim.ring_fill_level() == 0    # the trap state
        eng.start_feeder(shim)
        st = wait_verdicts(shim, 64)
        eng.stop()
        assert st["verdict_passes"] == 64
        shim.close()

    def test_rx_ring_faults_tolerated(self):
        """An armed shim.rx_ring fault storm fails individual polls; the
        frames stay queued and every verdict still lands FIFO."""
        eng = fake_engine()
        shim = mk_shim()
        feeder = eng.start_feeder(shim)
        FAULTS.arm("shim.rx_ring", mode="prob", prob=0.3, seed=7)
        n = 80
        frames = [build_frame("192.168.1.10", "10.1.2.3", 43000 + i, 443,
                              payload=b"q" * i) for i in range(n)]
        drained = []
        inject_all(shim, frames, drain_to=drained)
        st = wait_verdicts(shim, n, drain_to=drained)
        FAULTS.reset()
        eng.stop()
        drained.extend(shim.mock_tx_drain(64))
        assert st["verdict_passes"] == n
        assert [ln for _a, ln in drained] == \
            [BASE_LEN + i for i in range(n)]
        assert feeder.stats()["harvest_faults"] > 0   # the storm fired
        shim.close()

    def test_pipeline_unavailable_applies_fail_closed(self):
        """When the pipeline rejects work (dispatch fault storm → breaker
        open), the feeder must still consume a verdict slot per harvested
        batch — all-drop, in FIFO position — or later verdicts would
        enforce on the wrong frames. Frames allowed BEFORE the storm must
        still come out in exact order (a rejected-at-submit batch may
        never jump the pending queue and consume an older batch's
        FrameRefs)."""
        eng = fake_engine(pipeline_breaker_threshold=2,
                          pipeline_breaker_cooldown_s=30.0)
        shim = mk_shim()
        feeder = eng.start_feeder(shim)
        n_good = 40
        good = [build_frame("192.168.1.10", "10.1.2.3", 44000 + i, 443,
                            payload=b"g" * i) for i in range(n_good)]
        drained = []
        inject_all(shim, good, drain_to=drained)
        wait_verdicts(shim, n_good, drain_to=drained)

        FAULTS.arm("pipeline.dispatch", mode="fail")
        n_bad = 48
        bad = [build_frame("192.168.1.10", "10.1.2.3", 45000 + i, 443)
               for i in range(n_bad)]
        inject_all(shim, bad, drain_to=drained)
        st = wait_verdicts(shim, n_good + n_bad, deadline_s=30.0,
                           drain_to=drained)
        FAULTS.reset()
        stats = feeder.stats()
        eng.stop()
        drained.extend(shim.mock_tx_drain(64))
        assert st["verdict_passes"] == n_good     # pre-storm traffic only
        assert st["verdict_drops"] == n_bad       # storm fail-closed
        assert [ln for _a, ln in drained] == \
            [BASE_LEN + i for i in range(n_good)], \
            "pre-storm frames reordered across the rejection boundary"
        assert stats["rejected_batches"] > 0
        assert stats["applied_batches"] == stats["harvested_batches"]
        shim.close()

    def test_oversized_shim_batch_rejected_at_start(self):
        """A harvest batch that can't fit the pipeline's largest bucket
        would fail-close 100% of traffic while looking healthy — the
        misconfig must fail fast at attach time instead."""
        eng = fake_engine(batch_size=64)
        shim = FlowShim(batch_size=128, timeout_us=100)
        try:
            with pytest.raises(ValueError, match="max bucket"):
                eng.start_feeder(shim)
        finally:
            shim.close()
            eng.stop()

    def test_sparse_ep_ids_use_dict_mapping(self, monkeypatch):
        """One huge ep_id must not make the slot LUT rebuild allocate
        id-space-sized arrays: past DENSE_LUT_MAX the mapping falls back
        to per-row dict lookups with identical verdicts."""
        from cilium_tpu.shim.feeder import ShimFeeder
        monkeypatch.setattr(ShimFeeder, "DENSE_LUT_MAX", 1024)
        eng = fake_engine()
        big_id = 1 << 16                     # far past the patched cap
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.20",),
                         ep_id=big_id)
        eng.regenerate(force=True)
        shim = mk_shim()
        shim.register_endpoint("192.168.1.20", big_id)
        feeder = eng.start_feeder(shim)
        n = 30
        frames = [build_frame("192.168.1.20", "10.1.2.3", 46000 + i,
                              443 if i % 2 else 80) for i in range(n)]
        inject_all(shim, frames)
        st = wait_verdicts(shim, n)
        eng.stop()
        assert feeder._slot_lut is None      # dict path actually taken
        assert st["verdict_passes"] == n // 2
        assert st["verdict_drops"] == n - n // 2
        shim.close()

    def test_stop_drains_pending_fifo(self):
        """stop() force-harvests what the batcher still holds and applies
        every pending verdict — no stranded FrameRefs."""
        eng = fake_engine()
        shim = mk_shim(batch_size=32)
        eng.start_feeder(shim)
        n = 11                                   # sub-batch leftovers
        frames = [build_frame("192.168.1.10", "10.1.2.3", 45000 + i, 443)
                  for i in range(n)]
        inject_all(shim, frames)
        time.sleep(0.1)
        eng.stop()                               # feeder drains through here
        st = shim.stats()
        assert st["verdict_passes"] + st["verdict_drops"] == n
        assert not shim._pending_counts          # nothing unverdicted
        shim.close()


class TestDispatchRemap:
    def test_stale_harvest_mapping_remapped_at_dispatch(self):
        """Slots are re-enumerated on regen: a batch mapped at harvest
        time can go stale in the queue. Shim-fed batches carry ``_ep_raw``
        and Engine._pipeline_dispatch re-maps them onto the snapshot it
        actually classifies with — the stale slot must not enforce another
        endpoint's policy."""
        cfg = DaemonConfig(ct_capacity=4096, auto_regen=False,
                           batch_size=64, pipeline_flush_ms=1.0)
        eng = Engine(cfg, datapath=FakeDatapath(cfg))
        eng.add_endpoint(["k8s:app=block"], ips=("192.168.1.5",), ep_id=1)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=2)
        eng.apply_policy(POLICY + [{
            "endpointSelector": {"matchLabels": {"app": "block"}},
            "egressDeny": [{"toCIDR": ["0.0.0.0/0"]}]}])
        eng.regenerate()
        from cilium_tpu.kernels.records import batch_from_records
        from cilium_tpu.utils.ip import parse_addr
        from oracle import PacketRecord
        from cilium_tpu.utils import constants as C
        s16, _ = parse_addr("192.168.1.10")
        d16, _ = parse_addr("10.1.2.3")
        recs = [PacketRecord(s16, d16, 40000 + i, 443, C.PROTO_TCP,
                             C.TCP_SYN, False, 2, C.DIR_EGRESS)
                for i in range(4)]
        b = batch_from_records(recs, eng.active.snapshot.ep_slot_of)
        assert (b["ep_slot"][:4] == 1).all()     # web is slot 1 pre-regen
        b["_ep_raw"] = np.where(b["valid"], 2, 0).astype(np.int64)
        # endpoint 1 goes away; regen re-enumerates: web is now slot 0
        eng.remove_endpoint(1)
        eng.regenerate(force=True)
        assert eng.active.snapshot.ep_slot_of == {2: 0}
        out = eng.submit(b, now=100).result(timeout=10)
        assert out["allow"][:4].all(), \
            "stale slot survived to dispatch — wrong endpoint's policy"
        eng.stop()


class TestZeroAllocSoak:
    def test_pack_stage_path_steady_state_zero_alloc(self):
        """Acceptance pin: over >=1k pipelined batches through the JIT
        datapath, the pack/stage path (records.py, scheduler.py,
        datapath.py) shows no steady-state Python-heap growth — the wire
        rings, staging views, and upload cache make it allocation-free
        modulo transient temporaries the soak nets out to ~zero."""
        from cilium_tpu.runtime.datapath import JITDatapath
        from cilium_tpu.kernels.records import empty_batch

        cfg = DaemonConfig(ct_capacity=4096, auto_regen=False,
                           batch_size=64, device="cpu",
                           pipeline_flush_ms=0.5,
                           pipeline_queue_batches=256,
                           flowlog_mode="none")
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.regenerate()

        # one reusable sub-full chunk: submissions only read it
        chunk = empty_batch(32)
        chunk["src"][:, 2] = 0xFFFF
        chunk["src"][:, 3] = 0xC0A8010A
        chunk["dst"][:, 2] = 0xFFFF
        chunk["dst"][:, 3] = 0x0A010203
        chunk["sport"][:] = np.arange(40000, 40032)
        chunk["dport"][:] = 443
        chunk["proto"][:] = 6
        chunk["tcp_flags"][:] = 0x02
        chunk["valid"][:] = True

        def run(batches):
            for i in range(batches):
                eng.submit(chunk, now=100 + i)
                if i % 128 == 127:
                    assert eng.drain(timeout=60)
            assert eng.drain(timeout=60)

        run(128)                        # warmup: traces, views, histograms
        gc.collect()
        tracemalloc.start()
        snap1 = tracemalloc.take_snapshot()
        run(1024)
        gc.collect()
        snap2 = tracemalloc.take_snapshot()
        tracemalloc.stop()
        flt = [tracemalloc.Filter(
            True, f"*{os.sep}{name}") for name in
            ("records.py", "scheduler.py", "datapath.py", "feeder.py")]
        diff = snap2.filter_traces(flt).compare_to(
            snap1.filter_traces(flt), "lineno")
        growth = sum(d.size_diff for d in diff)
        stats = eng.pipeline_stats()
        eng.stop()
        assert stats["completed_batches"] >= 512   # it really coalesced
        # net growth ~0: tracemalloc bookkeeping noise only (no per-batch
        # buffer, dict, or device-destination allocation survived)
        assert growth < 64 * 1024, \
            f"pack/stage path grew {growth}B over 1k batches:\n" + \
            "\n".join(str(d) for d in diff[:10])
        assert eng.datapath.pack_stats["pack_inplace"] > 0


@pytest.mark.slow
class TestFeederSoak:
    def test_soak_10k_frames_with_faults(self):
        """`make ingest-smoke` soak: 10k frames through the mock rings
        with shim.rx_ring faults armed the whole run — every frame gets a
        verdict, forwarded frames leave in exact injection order, and the
        feeder/pipeline account for every batch.

        ct_capacity is sized ABOVE the 10k distinct flows: this soak pins
        FIFO under rx faults, not table exhaustion — at a saturated table
        the insert-when-full contract (tests/test_ctfull.py) would
        legitimately deny the overflow flows with CT_FULL."""
        eng = fake_engine(pipeline_queue_batches=256,
                          ingest_pool_batches=8,
                          ct_capacity=1 << 15)
        shim = mk_shim(batch_size=64)
        feeder = eng.start_feeder(shim)
        FAULTS.arm("shim.rx_ring", mode="prob", prob=0.05, seed=31)
        n = 10_000
        drained = []
        end = time.time() + 120
        for i in range(n):
            f = build_frame("192.168.1.10", "10.1.2.3",
                            40000 + (i % 20000), 443,
                            payload=b"s" * (i % 512))
            while shim.mock_rx_inject(f) != 0:
                drained.extend(shim.mock_tx_drain(64))
                if time.time() > end:
                    raise TimeoutError("rx ring wedged")
                time.sleep(0.0002)
        st = wait_verdicts(shim, n, deadline_s=120.0, drain_to=drained)
        FAULTS.reset()
        stats = feeder.stats()
        eng.stop()
        drained.extend(shim.mock_tx_drain(64))
        assert st["verdict_passes"] + st["tx_full_drops"] == n
        assert st["verdict_drops"] == 0
        # FIFO: drained lengths replay the injected payload cycle exactly
        lens = [ln for _a, ln in drained]
        want = [BASE_LEN + (i % 512) for i in range(n)]
        assert len(lens) == st["verdict_passes"]
        # tx_full drops (NIC backpressure) can gap the sequence; with the
        # producer draining continuously there should be none — assert the
        # strict replay when that holds, else at least monotone cycling
        if st["tx_full_drops"] == 0:
            assert lens == want, "forwarded frames out of order"
        assert stats["harvested_records"] == n
        assert stats["applied_batches"] == stats["harvested_batches"]
        assert feeder.stats()["pending"] == 0
        shim.close()
