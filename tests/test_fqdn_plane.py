"""The in-band DNS plane (ISSUE 18): the vectorized response decoder
(fqdn/dnsparse.py), the fail-open learning tap on the feeder's
verdict-apply path (fqdn/proxy.py), cache bounds/eviction, refresh
coalescing, delta-path identity retirement, and checkpoint pruning.

The wire-path tests ride a DNS-capable shim stand-in: the native C++
shim has no payload channel, so a FlowShim subclass fills the
``_dns_payload``/``_dns_len`` poll-buffer columns the way a
payload-capturing harvest would — harvest order is feed order, so the
response bytes attach to their query row deterministically.
"""

import os
import time
from collections import deque

import numpy as np
import pytest

from cilium_tpu.fqdn.dnsparse import (decode_batch, encode_name,
                                      encode_response, parse_frame)
from cilium_tpu.fqdn.proxy import DNSProxy
from cilium_tpu.model.fqdn import FQDNCache, FQDNSelector
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# --------------------------------------------------------------------------- #
# dnsparse: the vectorized decoder
# --------------------------------------------------------------------------- #
class TestDNSParse:
    def test_roundtrip_a(self):
        wire = encode_response("api.example.com",
                               ["20.1.2.3", "20.1.2.4"], ttl=300)
        got = parse_frame(np.frombuffer(wire, dtype=np.uint8))
        assert got is not None
        qname, ips, ttl = got
        assert qname == "api.example.com"
        assert sorted(ips) == ["20.1.2.3", "20.1.2.4"]
        assert ttl == 300

    def test_roundtrip_aaaa(self):
        wire = encode_response("v6.example.com", ["2001:db8::1"], ttl=60)
        got = parse_frame(np.frombuffer(wire, dtype=np.uint8))
        assert got is not None
        _, ips, _ = got
        assert ips == ["2001:db8::1"]

    def test_min_ttl_across_answers(self):
        # per-record TTLs differ → the LEARNED ttl is the minimum
        w1 = encode_response("a.com", ["1.1.1.1"], ttl=500)
        w2 = encode_response("a.com", ["1.1.1.2"], ttl=20)
        # splice: take w1's single answer and w2's, bump ancount to 2
        buf = bytearray(w1) + bytes(w2[len(w1) - 16 + 6:])  # not valid: skip
        # (hand-splicing compressed records is fragile; drive the real
        # multi-answer path through encode_response instead)
        wire = encode_response("a.com", ["1.1.1.1", "1.1.1.2"], ttl=77)
        got = parse_frame(np.frombuffer(wire, dtype=np.uint8))
        assert got[2] == 77
        del buf

    def test_compression_pointer(self):
        wire = encode_response("deep.sub.example.com", ["9.9.9.9"],
                               ttl=60, compress=True)
        # the answer owner is a 2-byte pointer back into the question
        assert b"\xc0\x0c" in wire
        got = parse_frame(np.frombuffer(wire, dtype=np.uint8))
        assert got[0] == "deep.sub.example.com"

    def test_forward_pointer_rejected(self):
        """A pointer at/after its own offset (loop fuel) is malformed —
        the decompression walk only ever jumps BACKWARD."""
        wire = bytearray(encode_response("a.com", ["1.1.1.1"], ttl=60))
        off = wire.find(b"\xc0\x0c")
        assert off > 0
        wire[off:off + 2] = bytes([0xC0 | (off >> 8) & 0x3F, off & 0xFF])
        with pytest.raises(ValueError):
            parse_frame(np.frombuffer(bytes(wire), dtype=np.uint8))

    def test_truncated_frame_rejected(self):
        wire = encode_response("a.com", ["1.1.1.1"], ttl=60)
        with pytest.raises(ValueError):
            parse_frame(np.frombuffer(wire[:len(wire) - 3],
                                      dtype=np.uint8))

    def test_nxdomain_is_unlearnable_not_malformed(self):
        wire = encode_response("gone.example.com", [], ttl=0, rcode=3)
        assert parse_frame(np.frombuffer(wire, dtype=np.uint8)) is None

    def test_query_is_unlearnable(self):
        # flip QR off: a query reaching the tap must not learn anything
        wire = bytearray(encode_response("a.com", ["1.1.1.1"], ttl=60))
        wire[2] &= 0x7F
        assert parse_frame(np.frombuffer(bytes(wire),
                                         dtype=np.uint8)) is None

    def test_encode_name_label_bounds(self):
        with pytest.raises(ValueError):
            encode_name("x" * 64 + ".com")          # label > 63
        with pytest.raises(ValueError):
            encode_name(".".join(["abcdefgh"] * 32))  # name > 255

    def test_decode_batch_mixed(self):
        W = 512
        good = encode_response("ok.example.com", ["5.5.5.5"], ttl=60)
        payload = np.zeros((4, W), dtype=np.uint8)
        lens = np.zeros((4,), dtype=np.int32)
        payload[0, :len(good)] = np.frombuffer(good, dtype=np.uint8)
        lens[0] = len(good)
        # plausible header, garbage body: passes the vectorized screen,
        # fails the walk (0xFF reads as a forward compression pointer)
        payload[1, :12] = np.frombuffer(good[:12], dtype=np.uint8)
        payload[1, 12:40] = 0xFF
        lens[1] = 40
        lens[2] = 6                                 # shorter than a header
        payload[3, :len(good)] = np.frombuffer(good, dtype=np.uint8)
        lens[3] = len(good)
        results, malformed = decode_batch(payload, lens,
                                          np.arange(4))
        rows = sorted(r for r, _q, _i, _t in results)
        assert rows == [0, 3]
        assert malformed == 2


# --------------------------------------------------------------------------- #
# proxy: the fail-open learning tap
# --------------------------------------------------------------------------- #
def _tap_batch(payloads, dport=53, redirect=True, proto=C.PROTO_UDP):
    """(buf, out) pair shaped like the feeder's verdict-apply arguments:
    one row per payload, all marked DNS-redirect unless told otherwise."""
    n = max(1, len(payloads))
    W = 512
    buf = {
        "valid": np.ones((n,), bool),
        "proto": np.full((n,), proto, np.uint8),
        "sport": np.full((n,), 40000, np.uint16),
        "dport": np.full((n,), dport, np.uint16),
        "_dns_payload": np.zeros((n, W), np.uint8),
        "_dns_len": np.zeros((n,), np.int32),
    }
    for i, pl in enumerate(payloads):
        buf["_dns_payload"][i, :len(pl)] = np.frombuffer(pl, np.uint8)
        buf["_dns_len"][i] = len(pl)
    out = {"allow": np.ones((n,), bool),
           "redirect": np.full((n,), bool(redirect))}
    return buf, out


class TestProxyTap:
    def _cache(self):
        c = FQDNCache()
        c.clock = lambda: 100
        return c

    def test_learns_redirected_rows(self):
        cache = self._cache()
        px = DNSProxy(cache)
        wire = encode_response("api.example.com", ["20.1.2.3"], ttl=600)
        buf, out = _tap_batch([wire])
        assert px.observe_batch(buf, out) == 1
        sel = FQDNSelector(match_name="api.example.com")
        assert cache.lookup_selector(sel, now=101) == ["20.1.2.3"]
        st = px.stats()
        assert st["frames"] == 1 and st["observed"] == 1
        assert st["parse_errors"] == 0

    def test_non_redirect_rows_ignored(self):
        cache = self._cache()
        px = DNSProxy(cache)
        wire = encode_response("api.example.com", ["20.1.2.3"], ttl=600)
        buf, out = _tap_batch([wire], redirect=False)
        assert px.observe_batch(buf, out) == 0
        buf, out = _tap_batch([wire], dport=443)     # not the DNS port
        assert px.observe_batch(buf, out) == 0
        buf, out = _tap_batch([wire], proto=C.PROTO_TCP)
        assert px.observe_batch(buf, out) == 0
        assert len(cache) == 0

    def test_malformed_counted_never_raises(self):
        cache = self._cache()
        px = DNSProxy(cache)
        # response header, garbage body: survives the vectorized screen,
        # violates the wire grammar in the per-row walk
        hdr = encode_response("a.com", ["1.1.1.1"], ttl=60)[:12]
        buf, out = _tap_batch([hdr + b"\xff" * 52])
        assert px.observe_batch(buf, out) == 0
        assert px.stats()["parse_errors"] == 1
        assert len(cache) == 0

    def test_fault_fail_open(self):
        """fqdn.parse armed: learning stops and is COUNTED; the call never
        raises (the caller's verdict-apply path is invariant)."""
        cache = self._cache()
        px = DNSProxy(cache)
        wire = encode_response("api.example.com", ["20.1.2.3"], ttl=600)
        FAULTS.arm("fqdn.parse", mode="fail", times=1)
        buf, out = _tap_batch([wire])
        assert px.observe_batch(buf, out) == 0
        assert px.stats()["parse_errors"] == 1
        assert len(cache) == 0
        # fault expired: the next batch learns normally
        assert px.observe_batch(buf, out) == 1
        assert len(cache) == 1

    def test_missing_columns_noop(self):
        cache = self._cache()
        px = DNSProxy(cache)
        buf, out = _tap_batch([])
        del buf["_dns_payload"]
        assert px.observe_batch(buf, out) == 0
        assert px.observe_batch({"valid": np.ones(1, bool)}, None) == 0

    def test_min_ttl_floor(self):
        cache = self._cache()
        px = DNSProxy(cache, min_ttl=400)
        wire = encode_response("api.example.com", ["20.1.2.3"], ttl=5)
        buf, out = _tap_batch([wire])
        px.observe_batch(buf, out)
        sel = FQDNSelector(match_name="api.example.com")
        assert cache.lookup_selector(sel, now=300) == ["20.1.2.3"]


# --------------------------------------------------------------------------- #
# cache bounds (satellite 1)
# --------------------------------------------------------------------------- #
class TestCacheBounds:
    def test_per_name_ip_cap_evicts_oldest_expiry(self):
        c = FQDNCache(max_ips_per_name=2)
        c.observe("a.com", ["1.1.1.1"], ttl=100, now=0)   # exp 100
        c.observe("a.com", ["1.1.1.2"], ttl=500, now=0)   # exp 500
        c.observe("a.com", ["1.1.1.3"], ttl=300, now=0)   # exp 300
        ips = c.lookup_selector(FQDNSelector(match_name="a.com"), now=1)
        assert ips == ["1.1.1.2", "1.1.1.3"]              # exp-100 shed
        st = c.stats(now=1)
        assert st["ips"] == 2 and st["evictions"] == 1
        assert st["high_water"] >= 2

    def test_name_cap_evicts_soonest_dying_name(self):
        c = FQDNCache(max_names=2)
        c.observe("old.com", ["1.0.0.1"], ttl=50, now=0)
        c.observe("mid.com", ["1.0.0.2"], ttl=500, now=0)
        c.observe("new.com", ["1.0.0.3"], ttl=10, now=0)  # freshest observe
        names = [n for n, _ in c.names()]
        # old.com's last IP dies first among the OTHER names; the
        # just-observed name is never the victim even with the lowest TTL
        assert names == ["mid.com", "new.com"]
        assert c.stats(now=1)["evictions"] == 1

    def test_stats_pending_expiries(self):
        c = FQDNCache()
        c.observe("a.com", ["1.1.1.1"], ttl=10, now=0)
        c.observe("a.com", ["1.1.1.2"], ttl=500, now=0)
        assert c.stats(now=100)["pending_expiries"] == 1
        c.expire(now=100)
        st = c.stats(now=100)
        assert st["pending_expiries"] == 0 and st["ips"] == 1


# --------------------------------------------------------------------------- #
# selector pattern edges (satellite 3)
# --------------------------------------------------------------------------- #
class TestSelectorEdges:
    def test_case_folding_and_trailing_dot(self):
        s = FQDNSelector(match_pattern="*.SVC.Example.COM.")
        assert s.matches("a.svc.example.com")
        assert s.matches("A.B.svc.EXAMPLE.com.")
        assert not s.matches("svc.example.com")

    def test_star_crosses_labels(self):
        # upstream matchpattern.go: '*' → [-a-zA-Z0-9.]* over the WHOLE
        # name — it crosses label boundaries by design
        s = FQDNSelector(match_pattern="api.*.com")
        assert s.matches("api.x.com")
        assert s.matches("api.x.y.com")
        assert not s.matches("api.x.org")

    def test_star_only_pattern(self):
        s = FQDNSelector(match_pattern="*")
        assert s.matches("anything.example.com")
        assert s.matches("x")

    def test_exact_name_trailing_dot_both_sides(self):
        s = FQDNSelector(match_name="api.example.com.")
        assert s.matches("API.EXAMPLE.COM.")


# --------------------------------------------------------------------------- #
# checkpoint round-trip pruning (satellite 3)
# --------------------------------------------------------------------------- #
class TestCheckpointPrune:
    def test_restore_prunes_entries_expired_at_export(self):
        src = FQDNCache()
        src.clock = lambda: 200
        src.observe("dead.com", ["1.1.1.1"], ttl=50, now=100)   # exp 150
        src.observe("live.com", ["2.2.2.2"], ttl=900, now=100)  # exp 1000
        state = src.export_state()
        assert state["now"] == 200

        dst = FQDNCache()
        dst.restore_state(state)
        assert [n for n, _ in dst.names()] == ["live.com"]
        assert dst.stats(now=0)["ips"] == 1

    def test_restore_without_cutoff_keeps_everything(self):
        # pre-ISSUE-18 checkpoints carry no export clock: keep entries and
        # let materialization/GC filter under the restoring clock
        dst = FQDNCache()
        dst.restore_state({"entries": {"a.com": {"1.1.1.1": 5}}})
        assert len(dst) == 1

    def test_roundtrip_preserves_expiries(self):
        src = FQDNCache()
        src.clock = lambda: 100
        src.observe("a.com", ["1.1.1.1", "1.1.1.2"], ttl=300, now=100)
        dst = FQDNCache()
        dst.restore_state(src.export_state())
        assert dst.names() == src.names()


# --------------------------------------------------------------------------- #
# engine integration: coalescing + delta-path retirement
# --------------------------------------------------------------------------- #
FQDN_POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toFQDNs": [{"matchPattern": "*.svc.example.com"}],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


def _engine():
    from cilium_tpu.runtime.datapath import FakeDatapath
    cfg = DaemonConfig(ct_capacity=4096, auto_regen=False)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    clock = {"t": 100}
    eng.ctx.fqdn_cache.clock = lambda: clock["t"]
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(FQDN_POLICY)
    eng.regenerate()
    return eng, clock


def _classify_dst(eng, dst, now):
    from cilium_tpu.kernels.records import batch_from_records
    from cilium_tpu.utils.ip import parse_addr
    from oracle import PacketRecord
    s16, _ = parse_addr("192.168.1.10")
    d16, _ = parse_addr(dst)
    pkt = PacketRecord(s16, d16, 40000, 443, C.PROTO_TCP, C.TCP_SYN,
                       False, 1, C.DIR_EGRESS)
    return eng.classify(batch_from_records(
        [pkt], eng.active.snapshot.ep_slot_of), now=now)


class TestEngineIntegration:
    def test_refresh_coalescing(self):
        """N observes between regenerations collapse into ONE rule
        refresh; the collapsed wakes are counted."""
        eng, clock = _engine()
        for i in range(5):
            eng.observe_dns(f"n{i}.svc.example.com", [f"20.0.0.{i + 1}"],
                            ttl=600, now=100)
        # first observe set pending; the other four coalesced
        assert eng.repo.fqdn_refresh_coalesced == 4
        rev0 = eng.repo.revision
        eng.regenerate()
        # ONE refresh materialized all five names (one revision bump for
        # the refresh change, not five)
        assert eng.repo.revision == rev0 + 1
        assert eng.repo.fqdn_identities_created == 5
        out = _classify_dst(eng, "20.0.0.3", now=101)
        assert bool(out["allow"][0])
        # flush is idempotent: nothing pending → no-op, no extra revision
        assert not eng.repo.flush_fqdn_refresh()
        assert eng.repo.revision == rev0 + 1

    def test_retirement_rides_delta_path(self):
        """Learn → expire: BOTH directions absorb incrementally; expiry
        tombstones the identity without a full rebuild and new flows to
        the dead IP deny (pinned equivalent via the parity-audited
        classify)."""
        eng, clock = _engine()
        eng.observe_dns("api.svc.example.com", ["20.1.2.3"], ttl=600,
                        now=100)
        eng.regenerate()
        fulls_after_learn = eng.metrics.counters.get("regen_full_total", 0)
        assert bool(_classify_dst(eng, "20.1.2.3", now=101)["allow"][0])

        clock["t"] = 1000
        eng.ctx.fqdn_cache.expire(now=1000)
        eng.regenerate()
        # retirement went through place_patch, not a rebuild
        assert eng.metrics.counters.get("regen_full_total", 0) \
            == fulls_after_learn
        assert eng.metrics.counters.get(
            "fqdn_identities_retired_total", 0) == 1
        out = _classify_dst(eng, "20.1.2.3", now=1001)
        assert not bool(out["allow"][0])
        assert int(out["reason"][0]) == C.DropReason.POLICY

    def test_churn_cycles_stay_incremental(self):
        """Steady learn/expire churn: zero full rebuilds after the seed,
        every cycle equivalent (spot-checked by verdicts each round)."""
        eng, clock = _engine()
        eng.regenerate()
        fulls0 = eng.metrics.counters.get("regen_full_total", 0)
        for r in range(4):
            ip_new = f"20.3.{r}.1"
            eng.observe_dns(f"c{r}.svc.example.com", [ip_new], ttl=200,
                            now=clock["t"])
            eng.regenerate()
            assert bool(_classify_dst(eng, ip_new,
                                      now=clock["t"])["allow"][0])
            clock["t"] += 500                    # past every live TTL
            eng.ctx.fqdn_cache.expire(now=clock["t"])
            eng.regenerate()
            assert not bool(_classify_dst(eng, ip_new,
                                          now=clock["t"])["allow"][0])
        assert eng.metrics.counters.get("regen_full_total", 0) == fulls0
        assert eng.metrics.counters.get(
            "fqdn_identities_retired_total", 0) == 4

    def test_status_and_resources_surface(self):
        from cilium_tpu.runtime.api import status_doc
        eng, clock = _engine()
        eng.observe_dns("api.svc.example.com", ["20.1.2.3"], ttl=600,
                        now=100)
        eng.regenerate()
        doc = status_doc(eng)
        assert doc["fqdn"]["cache"]["ips"] == 1
        assert doc["fqdn"]["identities_created"] == 1
        # the ledger row exists when the cache is bounded
        eng2 = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False,
                                   fqdn_max_names=16))
        assert "fqdn_cache" in eng2._res_fqdn()
        eng2.stop()
        eng.stop()

    def test_metrics_fold(self):
        eng, clock = _engine()
        for i in range(3):
            eng.observe_dns(f"m{i}.svc.example.com", [f"20.5.0.{i + 1}"],
                            ttl=600, now=100)
        eng.regenerate()
        text = eng.render_metrics()
        assert "fqdn_identities_created_total 3" in text
        assert "fqdn_refresh_coalesced_total 2" in text
        eng.stop()


# --------------------------------------------------------------------------- #
# wire path: the feeder tap through a DNS-capable shim stand-in
# --------------------------------------------------------------------------- #
from cilium_tpu.shim.bindings import LIB_PATH, FlowShim, build_frame  # noqa: E402

needs_shim = pytest.mark.skipif(
    not os.path.exists(LIB_PATH),
    reason="libflowshim.so not built (make -C cilium_tpu/shim)")


class DNSShim(FlowShim):
    """Payload-capturing harvest stand-in: fills the poll buffer's DNS
    columns for UDP/53 rows (harvest order == feed order)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._dns_fifo = deque()

    def feed_dns(self, frame: bytes, response_payload: bytes) -> None:
        self._dns_fifo.append(response_payload)
        self.feed_frame(frame)

    def poll_batch(self, now_us=0, force=False, out=None):
        b = super().poll_batch(now_us=now_us, force=force, out=out)
        if b is None or not isinstance(b, dict) or "_dns_payload" not in b:
            return b
        sel = (np.asarray(b["valid"])
               & (np.asarray(b["proto"]) == C.PROTO_UDP)
               & ((np.asarray(b["sport"]) == 53)
                  | (np.asarray(b["dport"]) == 53)))
        for i in np.nonzero(sel)[0]:
            if not self._dns_fifo:
                break
            pl = self._dns_fifo.popleft()
            w = b["_dns_payload"].shape[1]
            n = min(len(pl), w)
            b["_dns_payload"][i, :n] = np.frombuffer(pl[:n], np.uint8)
            b["_dns_len"][i] = n
        return b


WIRE_POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [
        # the DNS L7 redirect class: queries to the resolver redirect
        # (allow-all L7 set — replies must always flow; the tap LEARNS)
        {"toCIDR": ["8.8.8.8/32"],
         "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}],
                      "rules": {"http": [{}]}}]},
        {"toFQDNs": [{"matchName": "api.example.com"}],
         "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]},
    ],
}]


def _wire_engine():
    from cilium_tpu.runtime.datapath import FakeDatapath
    cfg = DaemonConfig(ct_capacity=4096, auto_regen=False, batch_size=64,
                       pipeline_flush_ms=1.0, fqdn_proxy_enabled=True)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(WIRE_POLICY)
    eng.regenerate()
    return eng


def _dns_query_frame(sport=41000):
    return build_frame("192.168.1.10", "8.8.8.8", sport, 53,
                       proto=C.PROTO_UDP, payload=b"\x00" * 16)


def _wait(pred, timeout_s=20.0, what="condition"):
    end = time.time() + timeout_s
    while time.time() < end:
        if pred():
            return
        time.sleep(0.01)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


@needs_shim
class TestWirePath:
    def test_feeder_tap_learns_from_traffic(self):
        eng = _wire_engine()
        shim = DNSShim(batch_size=16, timeout_us=100)
        shim.register_endpoint("192.168.1.10", 1)
        try:
            eng.start_feeder(shim)
            assert eng._dns_proxy is not None
            resp = encode_response("api.example.com", ["20.1.2.3"],
                                   ttl=600)
            for i in range(3):
                shim.feed_dns(_dns_query_frame(41000 + i), resp)
            _wait(lambda: eng._dns_proxy.stats()["observed"] > 0,
                  what="proxy learning")
            sel = FQDNSelector(match_name="api.example.com")
            assert eng.ctx.fqdn_cache.lookup_selector(sel) == ["20.1.2.3"]
            # the DNS flows themselves were SERVED (allow, not dropped)
            _wait(lambda: shim.stats()["verdict_passes"] >= 3,
                  what="dns verdicts")
            # learned IP materializes into allow on the policy port
            eng.regenerate()
            out = _classify_dst(eng, "20.1.2.3",
                                now=int(eng.ctx.fqdn_cache.clock()))
            assert bool(out["allow"][0])
        finally:
            eng.stop()
            shim.close()

    def test_feeder_tap_fail_open_under_fault(self):
        """fqdn.parse armed on the WIRE path: the replies still get their
        verdicts (zero divergence), only learning is lost — and counted."""
        eng = _wire_engine()
        shim = DNSShim(batch_size=16, timeout_us=100)
        shim.register_endpoint("192.168.1.10", 1)
        try:
            eng.start_feeder(shim)
            FAULTS.arm("fqdn.parse", mode="fail", times=100)
            resp = encode_response("api.example.com", ["20.1.2.3"],
                                   ttl=600)
            for i in range(3):
                shim.feed_dns(_dns_query_frame(42000 + i), resp)
            # verdicts flow while the parser is broken
            _wait(lambda: shim.stats()["verdict_passes"] >= 3,
                  what="dns verdicts under fault")
            _wait(lambda: eng._dns_proxy.stats()["parse_errors"] > 0,
                  what="parse-error accounting")
            assert eng._dns_proxy.stats()["observed"] == 0
            assert len(eng.ctx.fqdn_cache) == 0
        finally:
            FAULTS.reset()
            eng.stop()
            shim.close()


# --------------------------------------------------------------------------- #
# slow: the churn soak with the parser fault armed the whole run
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestChurnSoakFaulted:
    def test_soak_learn_expire_with_parse_faults(self):
        """30 learn/expire rounds with ``fqdn.parse`` armed at 50%:
        serving never wavers (the verdict each round is exactly what the
        cache's learned state predicts), faulted rounds lose LEARNING
        only (counted, name stays denied), unfaulted rounds learn and
        their expiries retire through the delta path with zero full
        rebuilds across the whole soak."""
        eng, clock = _engine()
        eng.regenerate()
        proxy = DNSProxy(eng.ctx.fqdn_cache, metrics=eng.metrics)
        fulls0 = eng.metrics.counters.get("regen_full_total", 0)
        FAULTS.arm("fqdn.parse", mode="prob", prob=0.5, seed=7)
        learned_rounds = faulted_rounds = 0
        for r in range(30):
            ip = f"20.9.{r}.1"
            frame = encode_response(f"s{r}.svc.example.com", [ip],
                                    ttl=200)
            buf, out = _tap_batch([frame])
            errs0 = proxy.parse_errors_total
            proxy.observe_batch(buf, out)
            eng.regenerate()
            hit = proxy.parse_errors_total > errs0
            allowed = bool(_classify_dst(eng, ip,
                                         now=clock["t"])["allow"][0])
            if hit:
                faulted_rounds += 1
                assert not allowed      # learning lost, fail-open counted
            else:
                learned_rounds += 1
                assert allowed          # learned → identity → allow
            clock["t"] += 500           # past the 200s TTL
            eng.ctx.fqdn_cache.expire(now=clock["t"])
            eng.regenerate()
            assert not bool(_classify_dst(eng, ip,
                                          now=clock["t"])["allow"][0])
        FAULTS.disarm("fqdn.parse")
        assert faulted_rounds > 0 and learned_rounds > 0
        assert proxy.parse_errors_total == faulted_rounds
        # every learn AND every expiry absorbed incrementally
        assert eng.metrics.counters.get("regen_full_total", 0) == fulls0
        assert eng.metrics.counters.get(
            "fqdn_identities_retired_total", 0) == learned_rounds
        eng.stop()
