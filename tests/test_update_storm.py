"""Live-state fast paths (ROADMAP item 3): sparse delta patching of the
device-resident policy image, the StalePlacement donation fence, the
overlapped device-side CT GC, conntrack survival across restart, and the
bounded classify-fn memo.

The contract under test: a live rule add/remove updates the placed verdict
image in place (donated scatter-apply) behind a revision fence — no batch
ever classifies under a torn update — and stays bit-identical to both a
fresh full compile and the semantics oracle at every revision; the chunked
epoch GC is semantics-free (probes already ignore expired slots) and never
stalls classify.
"""

import os

import numpy as np
import pytest

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.incremental import IncrementalCompiler
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime import checkpoint as ckpt
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import (CT_FORMAT_VERSION, FakeDatapath,
                                         JITDatapath, StalePlacement)
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord


# --------------------------------------------------------------------------- #
# world helpers
# --------------------------------------------------------------------------- #
N_PEERS = 6

OUT_KEYS = ("allow", "reason", "status", "remote_identity", "redirect")


def peer_rule_docs(i, port=80, deny=False, label=None):
    """One labeled per-peer rule document (labels make replace_policy
    toggles work — the storm's add/remove primitive)."""
    key = "ingressDeny" if deny else "ingress"
    block = {"fromEndpoints": [{"matchLabels": {"peer": f"p{i}"}}]}
    if not deny:
        block["toPorts"] = [{"ports": [{"port": str(port),
                                        "protocol": "TCP"}]}]
    return [{"endpointSelector": {"matchLabels": {"app": "web"}},
             "labels": [label or f"k8s:storm=r{i}-{port}-{int(deny)}"],
             key: [block]}]


def make_engine(datapath, n_peers=N_PEERS, **cfg_kw):
    cfg = DaemonConfig(ct_capacity=2048, auto_regen=False, **cfg_kw)
    eng = Engine(cfg, datapath=datapath)
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    for i in range(n_peers):
        eng.add_endpoint([f"k8s:peer=p{i}", f"k8s:group=g{i % 2}"],
                         ips=(f"172.16.{i}.5",), ep_id=10 + i)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"group": "g0"}}],
                     "toPorts": [{"ports": [
                         {"port": "80", "protocol": "TCP"}]}]}]}])
    eng.regenerate()
    return eng


def jit_engine(**kw):
    cfg = DaemonConfig(ct_capacity=2048, auto_regen=False, **kw)
    return make_engine(JITDatapath(cfg), **kw)


def fake_engine(**kw):
    cfg = DaemonConfig(ct_capacity=2048, auto_regen=False, **kw)
    return make_engine(FakeDatapath(cfg), **kw)


def traffic(slots, n_peers=N_PEERS, flags=C.TCP_SYN, sport0=30000):
    pkts = []
    for i in range(n_peers):
        for dp in (80, 443, 8080):
            s16, _ = parse_addr(f"172.16.{i}.5")
            d16, _ = parse_addr("192.168.1.10")
            pkts.append(PacketRecord(s16, d16, sport0 + i, dp, C.PROTO_TCP,
                                     flags, False, 1, C.DIR_INGRESS))
    return batch_from_records(pkts, slots)


def warm_geometry(*engines, ports=(443, 8080)):
    """Split every peer's identity class and every port boundary once, so
    subsequent churn rides the pure delta path (the long-lived-daemon
    steady state)."""
    for i in range(N_PEERS):
        for p in ports:
            for e in engines:
                e.replace_policy([f"k8s:warm=w{i}-{p}"],
                                 peer_rule_docs(i, p,
                                                label=f"k8s:warm=w{i}-{p}"))
                e.regenerate()


def assert_same_verdicts(a, b, msg=""):
    for k in OUT_KEYS:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      f"{msg}:{k}")


# --------------------------------------------------------------------------- #
# the delta-patch fast path
# --------------------------------------------------------------------------- #
class TestDeltaPatch:
    def test_warm_churn_rides_the_delta_path_bit_identical(self):
        """Steady-state rule toggles on warm geometry must (a) actually
        take the scatter-apply path and (b) stay bit-identical to the
        oracle-backed fake at every revision."""
        eng, ref = jit_engine(), fake_engine()
        warm_geometry(eng, ref)
        base = dict(eng.datapath.patch_stats)
        now = 1000
        for step in range(10):
            i, p = step % N_PEERS, (443, 8080)[step % 2]
            label = f"k8s:warm=w{i}-{p}"
            body = None if step % 3 == 2 else peer_rule_docs(i, p,
                                                             label=label)
            for e in (eng, ref):
                e.replace_policy([label], body)
                e.regenerate()
            b = traffic(eng.active.snapshot.ep_slot_of)
            assert_same_verdicts(eng.classify(dict(b), now=now),
                                 ref.classify(dict(b), now=now),
                                 f"step{step}")
            now += 10
        ps = eng.datapath.patch_stats
        assert ps["patch_delta"] - base["patch_delta"] >= 5, ps
        # patches carried their sparse payloads, not whole-plane uploads
        assert ps["patch_rows"] > base["patch_rows"]

    def test_delta_patched_image_equals_full_place(self):
        """After a run of in-place scatter patches the device-resident
        verdict must equal what a from-scratch placement of the same
        snapshot would hold (no drift, ever)."""
        eng = jit_engine()
        warm_geometry(eng)
        for step in range(6):
            label = f"k8s:warm=w{step % N_PEERS}-443"
            eng.replace_policy(
                [label],
                None if step % 2 else peer_rule_docs(step % N_PEERS, 443,
                                                     label=label))
            eng.regenerate()
        assert eng.datapath.patch_stats["patch_delta"] >= 3
        snap = eng.active.snapshot
        fresh = eng.datapath.place(snap)
        np.testing.assert_array_equal(
            np.asarray(eng.active.tensors["verdict"]),
            np.asarray(fresh["verdict"]))

    def test_stale_placement_fence_and_engine_retry(self):
        """A handle captured before a delta patch and enqueued after must
        raise StalePlacement (never read a donated buffer); the engine's
        retry classifies against the patched snapshot."""
        eng = jit_engine()
        warm_geometry(eng)
        # ensure the toggled rule exists so the next replace is a delta
        eng.replace_policy(["k8s:warm=w0-443"],
                           peer_rule_docs(0, 443, label="k8s:warm=w0-443"))
        eng.regenerate()
        old = eng.active
        before = eng.datapath.patch_stats["patch_delta"]
        eng.replace_policy(["k8s:warm=w0-443"], None)
        eng.regenerate()
        assert eng.datapath.patch_stats["patch_delta"] == before + 1
        b = traffic(old.snapshot.ep_slot_of)
        with pytest.raises(StalePlacement):
            eng.datapath.classify(old.tensors, old.snapshot, dict(b), 500)
        assert eng.datapath.patch_stats["patch_stale_fences"] >= 1
        # the engine-level path retries transparently
        out = eng.classify(traffic(eng.active.snapshot.ep_slot_of), now=600)
        assert out["allow"].shape[0] > 0

    def test_delta_budget_gate_falls_back_to_full_upload(self):
        """A patch past the delta budget ships as a whole-plane upload
        (full_tensors), not a sparse payload."""
        ctx_eng = jit_engine(patch_delta_rows=1)
        warm_geometry(ctx_eng)
        inc = ctx_eng._inc
        assert inc is not None and inc.delta_budget_rows == 1
        # a group rule touches every member's class → > 1 row
        ctx_eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "labels": ["k8s:storm=wide"],
            "ingressDeny": [{"fromEndpoints": [
                {"matchLabels": {"group": "g1"}}]}]}])
        before = dict(ctx_eng.datapath.patch_stats)
        ctx_eng.regenerate()
        ps = ctx_eng.datapath.patch_stats
        assert ps["patch_delta"] == before["patch_delta"]
        assert ps["patch_full"] == before["patch_full"] + 1

    def test_scatter_failure_self_heals_with_full_upload(self):
        """A scatter that fails AFTER the donation must not pin a dead
        handle on the engine's serve-last-good path: place_patch recovers
        with a full verdict upload of the new snapshot."""
        eng = jit_engine()
        warm_geometry(eng)
        eng.replace_policy(["k8s:warm=w2-443"],
                           peer_rule_docs(2, 443, label="k8s:warm=w2-443"))
        eng.regenerate()
        dp = eng.datapath

        calls = {"n": 0}
        real = dp._scatter_rows

        def flaky(verdict, rows, vals):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected scatter failure")
            return real(verdict, rows, vals)

        dp._scatter_rows = flaky
        try:
            before = dp.patch_stats["patch_scatter_errors"]
            eng.replace_policy(["k8s:warm=w2-443"], None)
            eng.regenerate()              # must NOT raise
            assert dp.patch_stats["patch_scatter_errors"] == before + 1
            # the healed image serves and equals a fresh placement
            snap = eng.active.snapshot
            np.testing.assert_array_equal(
                np.asarray(eng.active.tensors["verdict"]),
                np.asarray(dp.place(snap)["verdict"]))
            out = eng.classify(traffic(eng.active.snapshot.ep_slot_of),
                               now=900)
            assert out["allow"].shape[0] > 0
        finally:
            dp._scatter_rows = real

    def test_compiler_emits_sparse_payload(self):
        """Unit: the incremental compiler's patch carries rows+values
        matching the emitted snapshot's own cells."""
        eng = fake_engine()
        warm_geometry(eng)
        inc = eng._inc
        eng.replace_policy(["k8s:warm=w1-443"], None)
        eps = sorted(eng.endpoints.values(), key=lambda e: e.ep_id)
        res = inc.try_update(CTConfig(capacity=2048), endpoints=eps)
        assert res is not None
        snap, patch, stats = res
        assert patch.is_delta and stats.delta_rows == patch.delta_rows.shape[0]
        dense = snap.image.verdict        # lazy materialization
        r = patch.delta_rows
        np.testing.assert_array_equal(
            dense[r[:, 0], r[:, 1], r[:, 2]], patch.delta_vals)

    def test_sharded_delta_patch_parity(self):
        """Scatter-apply onto the meshed (flows×rules) verdict: delta
        churn through a 2x2 backend matches the fake."""
        cfg = DaemonConfig(ct_capacity=2048, auto_regen=False,
                           n_shards=2, rule_shards=2)
        eng = make_engine(JITDatapath(cfg))
        ref = fake_engine()
        warm_geometry(eng, ref)
        base = eng.datapath.patch_stats["patch_delta"]
        now = 700
        for step in range(6):
            label = f"k8s:warm=w{step % N_PEERS}-8080"
            body = None if step % 2 else peer_rule_docs(
                step % N_PEERS, 8080, label=label)
            for e in (eng, ref):
                e.replace_policy([label], body)
                e.regenerate()
            b = traffic(eng.active.snapshot.ep_slot_of)
            assert_same_verdicts(eng.classify(dict(b), now=now),
                                 ref.classify(dict(b), now=now),
                                 f"sharded-step{step}")
            now += 10
        assert eng.datapath.patch_stats["patch_delta"] > base


# --------------------------------------------------------------------------- #
# overlay emission invariants
# --------------------------------------------------------------------------- #
class TestOverlayEmission:
    def _world(self):
        from cilium_tpu.model.identity import IdentityAllocator
        from cilium_tpu.model.ipcache import IPCache
        from cilium_tpu.model.labels import Labels
        from cilium_tpu.model.endpoint import Endpoint
        from cilium_tpu.policy import PolicyContext, Repository
        from cilium_tpu.policy.selectorcache import SelectorCache
        alloc = IdentityAllocator()
        ctx = PolicyContext(allocator=alloc,
                            selector_cache=SelectorCache(alloc),
                            ipcache=IPCache())
        repo = Repository(ctx)
        lbls = Labels.parse(["k8s:app=web0"])
        ident = alloc.allocate(lbls)
        ctx.ipcache.upsert("192.168.0.10/32", ident.id)
        eps = [Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)]
        for i in range(4):
            pid = alloc.allocate(Labels.parse([f"k8s:peer=q{i}"]))
            ctx.ipcache.upsert(f"172.17.{i}.0/24", pid.id)
        return ctx, repo, eps

    def _rule(self, i, port, tag):
        from cilium_tpu.model.rules import parse_rule
        return parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web0"}},
            "labels": [f"k8s:t={tag}"],
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"peer": f"q{i}"}}],
                "toPorts": [{"ports": [{"port": str(port),
                                        "protocol": "TCP"}]}]}]})

    def test_tiny_rebase_budget_keeps_equivalence_and_frozen_snapshots(self):
        """With rebase_rows=1 every emission rebases; with a large budget
        the overlay accumulates — both must stay semantically identical to
        a fresh build and previously emitted snapshots must stay frozen."""
        for rebase in (1, 10_000):
            ctx, repo, eps = self._world()
            repo.add([self._rule(0, 80, "seed")])
            snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
            inc = IncrementalCompiler(repo, ctx, eps, snap,
                                      rebase_rows=rebase)
            emitted = []
            for step in range(8):
                i = step % 4
                if step % 3 == 2:
                    repo.delete_by_labels(
                        __import__("cilium_tpu.model.labels",
                                   fromlist=["Labels"]).Labels.parse(
                            [f"k8s:t=s{step - 2}"]))
                else:
                    repo.add([self._rule(i, 80, f"s{step}")])
                res = inc.try_update(CTConfig(capacity=1024))
                assert res is not None, inc.last_fallback
                s, patch, _ = res
                emitted.append((s, s.image.verdict.copy()))
                fresh = build_snapshot(repo, ctx, eps,
                                       CTConfig(capacity=1024))
                # dense lookups agree cell-for-cell where geometry matches
                for ident in [i.id for i in ctx.allocator.all()]:
                    idx_s = s.id_classes.index_of.get(ident)
                    idx_f = fresh.id_classes.index_of.get(ident)
                    if idx_s is None or idx_f is None:
                        continue
                    cs = s.id_classes.class_of[idx_s]
                    cf = fresh.id_classes.class_of[idx_f]
                    for port in (79, 80, 81, 443):
                        ps = s.port_classes.table[0, port]
                        pf = fresh.port_classes.table[0, port]
                        assert (int(s.image.verdict[0, 1, cs, ps])
                                & C.VERDICT_DECISION_MASK) == \
                               (int(fresh.image.verdict[0, 1, cf, pf])
                                & C.VERDICT_DECISION_MASK), \
                            (rebase, step, ident, port)
            # revision fencing: every emitted image unchanged
            for s, frozen in emitted:
                np.testing.assert_array_equal(s.image.verdict, frozen)

    def test_overlay_image_nbytes_without_materialization(self):
        ctx, repo, eps = self._world()
        repo.add([self._rule(0, 80, "seed")])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        repo.add([self._rule(0, 80, "x")])
        res = inc.try_update(CTConfig(capacity=1024))
        assert res is not None
        s, patch, _ = res
        from cilium_tpu.compile.policy_image import OverlayImage
        if isinstance(s.image, OverlayImage):
            assert s.image._dense is None
            assert s.nbytes > 0                 # no materialization
            assert s.image._dense is None
            _ = s.image.verdict                 # now materialize
            assert s.image._dense is not None


# --------------------------------------------------------------------------- #
# randomized storm: rule add/remove + endpoint churn, engine-level
# --------------------------------------------------------------------------- #
class TestRandomStorm:
    @pytest.mark.parametrize("seed", [3, 11])
    def test_engine_storm_matches_oracle(self, seed):
        """Property storm: random rule toggles (delta path) interleaved
        with endpoint adds/removes (full-build gate) — the JIT engine must
        stay bit-identical to the oracle-backed fake at every revision."""
        import random
        rng = random.Random(seed)
        eng, ref = jit_engine(), fake_engine()
        warm_geometry(eng, ref)
        next_ep = [100]
        added_eps = []
        now = 2000
        for step in range(14):
            op = rng.random()
            if op < 0.7:
                i, p = rng.randrange(N_PEERS), rng.choice((443, 8080))
                label = f"k8s:warm=w{i}-{p}"
                body = None if rng.random() < 0.4 else peer_rule_docs(
                    i, p, deny=rng.random() < 0.3, label=label)
                for e in (eng, ref):
                    e.replace_policy([label], body)
            elif op < 0.85 or not added_eps:
                ep_id = next_ep[0]
                next_ep[0] += 1
                added_eps.append(ep_id)
                for e in (eng, ref):
                    e.add_endpoint([f"k8s:peer=px{ep_id}"],
                                   ips=(f"172.18.{ep_id % 250}.9",),
                                   ep_id=ep_id)
            else:
                ep_id = added_eps.pop(rng.randrange(len(added_eps)))
                for e in (eng, ref):
                    e.remove_endpoint(ep_id)
            for e in (eng, ref):
                e.regenerate()
            assert eng.active.revision == ref.active.revision
            b = traffic(eng.active.snapshot.ep_slot_of)
            assert_same_verdicts(eng.classify(dict(b), now=now),
                                 ref.classify(dict(b), now=now),
                                 f"storm{seed}-{step}")
            now += 7
        assert eng.datapath.patch_stats["patch_delta"] >= 1


# --------------------------------------------------------------------------- #
# overlapped device-side CT GC
# --------------------------------------------------------------------------- #
class TestOverlappedCTGC:
    def _ct_with_expiries(self, cap=1024):
        import jax.numpy as jnp
        ct = make_ct_arrays(CTConfig(capacity=cap, probe_depth=4))
        rng = np.random.default_rng(5)
        n = cap // 2
        slots = rng.choice(cap, size=n, replace=False)
        ct["expiry"][slots] = rng.integers(1, 200, n).astype(np.uint32)
        ct["keys"][slots, 0] = np.arange(n, dtype=np.uint32) + 1
        return {k: jnp.asarray(v) for k, v in ct.items()}

    def test_chunked_epoch_equals_whole_table_sweep(self):
        """One full epoch of chunk sweeps == one whole-table sweep: same
        final table, same total reclaimed."""
        import jax.numpy as jnp
        from cilium_tpu.kernels.conntrack import ct_sweep, ct_sweep_chunk
        cap, chunk = 1024, 128
        ct_a = self._ct_with_expiries(cap)
        ct_b = {k: v + 0 for k, v in ct_a.items()}   # independent copy
        now = jnp.uint32(100)
        swept, n_full = ct_sweep(ct_a, now)
        total = 0
        for start in range(0, cap, chunk):
            ct_b, n, live = ct_sweep_chunk(ct_b, now, jnp.uint32(start),
                                           chunk)
            total += int(n)
        assert total == int(n_full)
        for k in swept:
            np.testing.assert_array_equal(np.asarray(swept[k]),
                                          np.asarray(ct_b[k]), k)

    def test_chunk_window_wraps(self):
        import jax.numpy as jnp
        from cilium_tpu.kernels.conntrack import ct_sweep_chunk
        cap, chunk = 256, 128
        ct = self._ct_with_expiries(cap)
        # start near the end: window covers [192, 256) ∪ [0, 64)
        new_ct, n, _ = ct_sweep_chunk(ct, jnp.uint32(100),
                                      jnp.uint32(192), chunk)
        exp_old = np.asarray(ct["expiry"])
        exp_new = np.asarray(new_ct["expiry"])
        in_win = np.r_[np.arange(192, 256), np.arange(0, 64)]
        out_win = np.arange(64, 192)
        dead = (exp_old[in_win] > 0) & (exp_old[in_win] <= 100)
        assert (exp_new[in_win][dead] == 0).all()
        np.testing.assert_array_equal(exp_new[out_win], exp_old[out_win])
        assert int(n) == int(dead.sum())

    def test_sweep_step_overlap_and_metrics(self):
        """Engine.sweep_step drives the double-buffered sweep: reclaimed
        counts harvest one tick late, the counter/gauge families export,
        and live flows survive while expired ones are reclaimed."""
        eng = jit_engine(ct_gc_chunk_rows=256)
        slots = eng.active.snapshot.ep_slot_of
        # establish allowed flows at t=1000 (peers in g0 on port 80)
        eng.classify(traffic(slots), now=1000)
        live0 = eng.datapath.ct_stats(1001)["live"]
        assert live0 > 0
        # run one full epoch well past expiry: every entry reclaims
        ticks = (2048 // 256) + 2
        total = 0
        st = None
        for _ in range(ticks + 1):      # +1: the last tick's harvest
            st = eng.sweep_step(now=1_000_000)   # far past every expiry
            total += st["reclaimed"]
        assert total >= live0, (total, live0)
        assert st["epoch"] >= 1
        rendered = eng.render_metrics()
        assert "ct_gc_reclaimed_total" in rendered
        assert "ct_occupancy" in rendered

    def test_gc_is_semantics_free_under_traffic(self):
        """Interleaving chunk sweeps with classify must not change any
        verdict: a live flow stays ESTABLISHED, an expired one re-learns
        as NEW — identical to an engine that never sweeps."""
        eng_gc, eng_ref = jit_engine(), jit_engine()
        slots = eng_gc.active.snapshot.ep_slot_of
        for e in (eng_gc, eng_ref):
            e.classify(traffic(slots), now=1000)      # SYN: establish
        out = []
        for step in range(6):
            now = 1005 + step
            eng_gc.sweep_step(now=now)
            a = eng_gc.classify(traffic(slots, flags=0x10), now=now)
            b = eng_ref.classify(traffic(slots, flags=0x10), now=now)
            assert_same_verdicts(a, b, f"gc-step{step}")
            out.append(a)
        est = np.asarray(out[-1]["status"])
        assert (est == int(C.CTStatus.ESTABLISHED)).any()

    def test_ct_gc_fault_point(self):
        eng = jit_engine()
        FAULTS.arm("ct.gc", mode="fail", times=1)
        try:
            with pytest.raises(FaultInjected):
                eng.sweep_step()
        finally:
            FAULTS.disarm("ct.gc")
        # next tick proceeds normally
        st = eng.sweep_step()
        assert st["chunk_rows"] == eng.config.ct_gc_chunk_rows

    def test_controller_selection(self):
        """Overlap-capable backend at ct_gc_interval_s; the fake keeps the
        host sweep. Neither start crashes; both register ct-gc."""
        for eng in (jit_engine(), fake_engine()):
            try:
                eng.start_background()
                assert "ct-gc" in getattr(eng.controllers, "_controllers",
                                          {"ct-gc": None})
            finally:
                eng.stop()

    def test_host_sweep_exports_counters_too(self):
        eng = fake_engine()
        slots = eng.active.snapshot.ep_slot_of
        eng.classify(traffic(slots), now=1000)
        reclaimed = eng.sweep(now=10_000_000)
        rendered = eng.render_metrics()
        assert "ct_occupancy" in rendered
        if reclaimed:
            assert "ct_gc_reclaimed_total" in rendered


# --------------------------------------------------------------------------- #
# bounded classify-fn memo
# --------------------------------------------------------------------------- #
class TestClassifyFnCacheLRU:
    def test_lru_cap_and_eviction_counter(self, monkeypatch):
        from cilium_tpu.kernels import classify as ck
        monkeypatch.setattr(ck, "FN_CACHE_CAP", 4)
        ck._FN_CACHE.clear()
        ev0 = ck._FN_EVICTIONS[0]
        fns = [ck.make_classify_fn(lb_probe_depth=8 + i) for i in range(6)]
        st = ck.fn_cache_stats()
        assert st["size"] <= 4
        assert ck._FN_EVICTIONS[0] == ev0 + 2
        # the most-recent entries survive; hits touch LRU order
        assert ck.make_classify_fn(lb_probe_depth=13) is fns[5]
        # an evicted key rebuilds without growing past the cap
        ck.make_classify_fn(lb_probe_depth=8)
        assert ck.fn_cache_stats()["size"] <= 4

    def test_memo_hit_returns_same_fn(self):
        from cilium_tpu.kernels import classify as ck
        a = ck.make_classify_fn(probe_depth=8, packed=True)
        b = ck.make_classify_fn(probe_depth=8, packed=True)
        assert a is b


# --------------------------------------------------------------------------- #
# conntrack survival across restart (ROADMAP 3b)
# --------------------------------------------------------------------------- #
def _flow_pkt(flags):
    s16, _ = parse_addr("172.16.0.5")
    d16, _ = parse_addr("192.168.1.10")
    return PacketRecord(s16, d16, 33333, 80, C.PROTO_TCP, flags, False, 1,
                        C.DIR_INGRESS)


class TestCTRestart:
    @pytest.mark.parametrize("backend", ["fake", "jit"])
    def test_established_flows_survive_restart(self, tmp_path, backend):
        def dp():
            cfg = DaemonConfig(ct_capacity=2048, auto_regen=False)
            return (JITDatapath(cfg) if backend == "jit"
                    else FakeDatapath(cfg))
        eng = make_engine(dp())
        slots = eng.active.snapshot.ep_slot_of
        b = batch_from_records([_flow_pkt(C.TCP_SYN)], slots)
        out = eng.classify(b, now=1000)
        assert bool(out["allow"][0])
        path = str(tmp_path / "ckpt")
        ckpt.save(eng, path)
        eng.stop()

        # restart: restored CT → the non-SYN packet is ESTABLISHED
        eng2 = Engine(DaemonConfig(ct_capacity=2048, auto_regen=False),
                      datapath=dp())
        assert ckpt.restore(eng2, path) is True
        b2 = batch_from_records(
            [_flow_pkt(0x10)], eng2.active.snapshot.ep_slot_of)
        out2 = eng2.classify(b2, now=1005)
        assert bool(out2["allow"][0])
        assert int(out2["status"][0]) == int(C.CTStatus.ESTABLISHED)
        eng2.stop()

        # control: a cold engine sees the same packet as NEW
        eng3 = make_engine(dp())
        out3 = eng3.classify(
            batch_from_records([_flow_pkt(0x10)],
                               eng3.active.snapshot.ep_slot_of), now=1005)
        assert int(out3["status"][0]) == int(C.CTStatus.NEW)

    def test_ct_archive_is_versioned(self, tmp_path):
        eng = fake_engine()
        eng.classify(batch_from_records(
            [_flow_pkt(C.TCP_SYN)], eng.active.snapshot.ep_slot_of),
            now=1000)
        path = str(tmp_path / "ckpt")
        ckpt.save(eng, path)
        with np.load(os.path.join(path, "ct.npz")) as npz:
            assert "__ct_format__" in npz.files
            assert int(npz["__ct_format__"]) == CT_FORMAT_VERSION
        state = ckpt._read_state(path)
        assert state["ct_format"] == CT_FORMAT_VERSION
        # a FUTURE-format archive is dropped (flows re-learn), control
        # plane restores fine
        arrays = ckpt._read_ct(path)
        np.savez(os.path.join(path, "ct.npz"),
                 __ct_format__=np.int32(CT_FORMAT_VERSION + 1), **arrays)
        # the sha no longer matches either way; _read_ct's version check
        # fires first when loaded directly
        assert ckpt._read_ct(path) is None

    @pytest.mark.slow
    def test_restart_mid_soak_keeps_verdicts(self, tmp_path):
        """The chaos-adjacent soak: pipelined traffic, daemon restarts
        mid-soak (save → stop → fresh engine → restore), established flows
        keep their verdicts through the reloaded CT."""
        eng = jit_engine()
        slots = eng.active.snapshot.ep_slot_of
        n_flows = 48
        # all flows from p0 (group g0 — the allowed ingress peer): a
        # denied flow never establishes, so it cannot test CT survival
        syn = [PacketRecord(parse_addr("172.16.0.5")[0],
                            parse_addr("192.168.1.10")[0],
                            40000 + i, 80, C.PROTO_TCP, C.TCP_SYN, False,
                            1, C.DIR_INGRESS) for i in range(n_flows)]
        ack = [PacketRecord(p.src_addr, p.dst_addr, p.src_port, p.dst_port,
                            p.proto, 0x10, False, p.ep_id, p.direction)
               for p in syn]
        for chunk in range(0, n_flows, 16):
            t = eng.submit(batch_from_records(syn[chunk:chunk + 16], slots),
                           now=3000 + chunk)
            t.result(timeout=30)
        # upgrade past the SYN lifetime (SEEN_NON_SYN → full TCP lifetime)
        for chunk in range(0, n_flows, 16):
            eng.submit(batch_from_records(ack[chunk:chunk + 16], slots),
                       now=3050).result(timeout=30)
        assert eng.drain(timeout=30)
        path = str(tmp_path / "soak-ckpt")
        ckpt.save(eng, path)
        eng.stop()

        eng2 = Engine(DaemonConfig(ct_capacity=2048, auto_regen=False),
                      datapath=JITDatapath(
                          DaemonConfig(ct_capacity=2048, auto_regen=False)))
        assert ckpt.restore(eng2, path) is True
        slots2 = eng2.active.snapshot.ep_slot_of
        est = 0
        for chunk in range(0, n_flows, 16):
            out = eng2.submit(
                batch_from_records(ack[chunk:chunk + 16], slots2),
                now=3100 + chunk).result(timeout=30)
            est += int((np.asarray(out["status"])
                        == int(C.CTStatus.ESTABLISHED)).sum())
        eng2.stop()
        assert est == n_flows, f"only {est}/{n_flows} flows survived"


# --------------------------------------------------------------------------- #
# the storm soak with the parity auditor at sampling 1.0
# --------------------------------------------------------------------------- #
class TestStormAudit:
    @pytest.mark.slow
    def test_policy_storm_audited_at_full_sampling(self):
        """Pipelined traffic under continuous rule churn with the shadow
        auditor at sampling 1.0: zero parity mismatches, and the churn
        actually exercised the delta-patch path (no batch classified under
        a torn revision — the auditor replays each batch against the exact
        revision it classified under)."""
        eng = jit_engine(audit_enabled=True, audit_sample_rate=1.0,
                         audit_pool_batches=64, audit_max_rows=512)
        eng.auditor.configure(sample_rate=1.0)
        warm_geometry(eng)
        slots = eng.active.snapshot.ep_slot_of
        now = 5000
        tickets = []
        for step in range(60):
            if step % 3 == 0:
                i, p = step % N_PEERS, (443, 8080)[step % 2]
                label = f"k8s:warm=w{i}-{p}"
                body = None if step % 6 else peer_rule_docs(i, p,
                                                            label=label)
                eng.replace_policy([label], body)
                eng.regenerate()
            tickets.append(eng.submit(traffic(slots), now=now))
            now += 1
        assert eng.drain(timeout=120)
        for t in tickets:
            t.result(timeout=10)
        # drain the audit pool completely
        for _ in range(200):
            step = eng.audit_step(budget=64)
            if not step or (not step.get("replayed")
                            and not step.get("pending")):
                break
        st = eng.auditor.stats()
        assert st["checked_rows"] > 0, st
        assert st["mismatched_rows"] == 0, st
        assert eng.datapath.patch_stats["patch_delta"] >= 1
        eng.stop()
