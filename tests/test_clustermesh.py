"""Multi-host control-plane sync (SURVEY.md §5 distributed backend; upstream
pkg/clustermesh): two engines share a store directory; each publishes its
endpoints' (prefix, labels) and ingests the other's, allocating LOCAL
identities for remote label sets — so ordinary label policy selects remote
pods, verdicts included."""

import json
import os
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.clustermesh import ClusterMesh
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def write_peer(store, node, gen, entries, published_at=None,
               claimed_node=None):
    """Write a peer file the way publish() would (atomic rename)."""
    os.makedirs(store, exist_ok=True)
    doc = {"format_version": 1, "node": claimed_node or node,
           "generation": gen,
           "published_at": time.time() if published_at is None
           else published_at,
           "entries": entries}
    path = os.path.join(store, f"{node}.json")
    with open(path + ".tmp", "w") as f:
        json.dump(doc, f)
    os.replace(path + ".tmp", path)


def _node(tmp_path, name, node=True):
    cfg = DaemonConfig(ct_capacity=1024, auto_regen=False,
                       cluster_store=str(tmp_path / "store") if node else "",
                       node_name=name if node else "")
    return Engine(cfg, datapath=FakeDatapath(DaemonConfig(ct_capacity=1024)))


def _pkt(src, dst, sp, dp, ep_id, d=C.DIR_INGRESS):
    s16, _ = parse_addr(src)
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, C.PROTO_TCP, C.TCP_SYN, False,
                        ep_id, d)


class TestClusterMesh:
    def test_cross_node_policy_by_labels(self, tmp_path):
        """Node B's policy 'allow from role=backup' matches node A's pod via
        the mesh: A publishes (ip, labels); B allocates a local identity for
        those labels; B's selector picks it up; classify allows."""
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        b.add_endpoint(["k8s:app=db"], ips=("10.2.0.9",), ep_id=1)
        b.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"role": "backup"}}],
                "toPorts": [{"ports": [
                    {"port": "5432", "protocol": "TCP"}]}]}]}])

        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b")
        mesh_a.step()
        mesh_b.step()
        b.regenerate()

        slots = b.active.snapshot.ep_slot_of
        batch = batch_from_records(
            [_pkt("10.1.0.5", "10.2.0.9", 40000, 5432, 1),   # remote backup
             _pkt("10.9.9.9", "10.2.0.9", 40001, 5432, 1)],  # unknown world
            slots)
        out = b.classify(dict(batch), now=100)
        assert bool(out["allow"][0]), "remote pod not selected by policy"
        assert not bool(out["allow"][1])
        # the remote identity resolved is a real local allocation with the
        # peer's labels
        rid = int(out["remote_identity"][0])
        ident = b.ctx.allocator.get(rid)
        assert ident is not None
        assert "k8s:role=backup" in ident.labels.to_strings()

    def test_withdrawal_and_stale_peer(self, tmp_path, monkeypatch):
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b",
                             stale_after_s=60)
        mesh_a.step()
        mesh_b.sync()
        assert "10.1.0.5/32" in b.ctx.ipcache.snapshot()

        # endpoint removed on A → withdrawn on B at the next round trip
        a.remove_endpoint(1)
        mesh_a.publish()
        mesh_b.sync()
        assert "10.1.0.5/32" not in b.ctx.ipcache.snapshot()

        # stale peer (lease expiry): state withdrawn even with no explicit
        # removal. Staleness is judged from B's OWN lease clock, renewed
        # only on generation progress (never from the peer-written
        # published_at, which a skewed peer clock would poison) — so the
        # stall is simulated by freezing A's generation and advancing B's
        # clock past the lease.
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.6",), ep_id=2)
        mesh_a.publish()
        mesh_b.sync()
        assert "10.1.0.6/32" in b.ctx.ipcache.snapshot()
        import cilium_tpu.runtime.clustermesh as cm
        real_time = time.time
        monkeypatch.setattr(cm.time, "time", lambda: real_time() + 3600)
        mesh_b.sync()
        assert "10.1.0.6/32" not in b.ctx.ipcache.snapshot()

    def test_label_change_reallocates(self, tmp_path):
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b")
        mesh_a.step()
        mesh_b.sync()
        id1 = b.ctx.ipcache.snapshot()["10.1.0.5/32"]
        # relabel the pod on A → B must re-ingest under a new identity
        a.remove_endpoint(1)
        a.add_endpoint(["k8s:role=primary"], ips=("10.1.0.5",), ep_id=2)
        mesh_a.publish()
        mesh_b.sync()
        id2 = b.ctx.ipcache.snapshot()["10.1.0.5/32"]
        assert id1 != id2
        ident = b.ctx.allocator.get(id2)
        assert "k8s:role=primary" in ident.labels.to_strings()

    def test_handoff_rides_delta_patch_path(self, tmp_path):
        """ISSUE 12 datapath consequence: remote entries arriving AFTER the
        incremental compiler is seeded ride the PR 9 delta path (identity
        growth + LPM rebuild), not a full rebuild — and the verdict matches
        what a fresh compile of the merged world produces."""
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        b.add_endpoint(["k8s:app=db"], ips=("10.2.0.9",), ep_id=1)
        b.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"role": "backup"}}],
                "toPorts": [{"ports": [
                    {"port": "5432", "protocol": "TCP"}]}]}]}])
        b.regenerate()                 # seed BEFORE remote entries arrive
        full_before = b.metrics.counters.get("regen_full_total", 0)

        ClusterMesh(a, str(tmp_path / "store"), "node-a").step()
        ClusterMesh(b, str(tmp_path / "store"), "node-b").step()
        b.regenerate()
        assert b.metrics.counters.get("regen_incremental_total", 0) >= 1
        assert b.metrics.counters.get("regen_full_total", 0) == full_before

        batch = batch_from_records(
            [_pkt("10.1.0.5", "10.2.0.9", 40000, 5432, 1)],
            b.active.snapshot.ep_slot_of)
        out = b.classify(dict(batch), now=100)
        assert bool(out["allow"][0])

    def test_engine_lifecycle_integration(self, tmp_path):
        """start_background wires the controller; stop withdraws the node
        file; corrupt peer files are skipped without failing the sync."""
        a = _node(tmp_path, "node-a")
        a.add_endpoint(["k8s:x=1"], ips=("10.1.0.7",), ep_id=1)
        a.config.cluster_sync_interval_s = 0.05
        a.start_background()
        store = tmp_path / "store"
        deadline = time.time() + 5
        while not (store / "node-a.json").exists():
            assert time.time() < deadline, "publish never happened"
            time.sleep(0.02)
        # garbage peer file must not break the loop
        (store / "node-bad.json").write_text("{not json")
        time.sleep(0.1)
        assert (store / "node-a.json").exists()
        a.stop()
        assert not (store / "node-a.json").exists()


class _Clock:
    """Mutable test clock handed to ClusterMesh(clock=...)."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _mesh(engine, tmp_path, name, clock, stale_after_s=60.0,
          staleness_budget_s=15.0):
    m = ClusterMesh(engine, str(tmp_path / "store"), name,
                    stale_after_s=stale_after_s,
                    staleness_budget_s=staleness_budget_s, clock=clock)
    engine._mesh = m               # health() folds the mesh detail in
    return m


class TestPartitionContract:
    """ISSUE 12 (a): store partition — last-good serving, MESH_STALE past
    the budget, never fail closed on established remote flows."""

    def test_partition_serves_last_good_then_mesh_stale(self, tmp_path):
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk, staleness_budget_s=5.0)
        write_peer(str(tmp_path / "store"), "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None

        FAULTS.arm("clustermesh.store_list", mode="fail")
        clk.t += 2.0
        mesh.sync()
        # inside the budget: stale not yet declared, state held
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None
        assert not mesh.is_stale()
        assert mesh.status()["state"] == C.HEALTH_OK
        assert not mesh.status()["store_ok"]

        clk.t += 10.0                 # budget spent
        mesh.sync()
        st = mesh.status()
        assert mesh.is_stale()
        assert st["state"] == C.MESH_STALE
        # last-good remote state still serves: partition is a control-plane
        # outage, never a data-plane one
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None
        health = b.health()
        assert health["state"] == C.HEALTH_DEGRADED
        assert health["mesh"]["state"] == C.MESH_STALE

        FAULTS.disarm("clustermesh.store_list")
        mesh.sync()                   # heal: next good pass clears it
        assert not mesh.is_stale()
        assert mesh.status()["state"] == C.HEALTH_OK
        assert b.health()["state"] == C.HEALTH_OK
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None

    def test_lease_never_expires_during_partition(self, tmp_path):
        """A peer lease must only age out under a HEALTHY listing: during
        a partition no heartbeat is observable at all, and expiring then
        would turn the control-plane outage into a data-plane one. After
        heal, a peer whose generation did not progress expires on the
        first good pass."""
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk, stale_after_s=30.0)
        write_peer(str(tmp_path / "store"), "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None

        FAULTS.arm("clustermesh.store_list", mode="fail")
        clk.t += 300.0                # way past the lease, store dark
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None, \
            "lease expired during a partition"

        FAULTS.disarm("clustermesh.store_list")
        mesh.sync()                   # heal: gen 1 never progressed
        assert b.ctx.ipcache.get("10.1.0.5/32") is None

    def test_unreadable_peer_file_holds_last_good(self, tmp_path):
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk)
        store = str(tmp_path / "store")
        write_peer(store, "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()
        (tmp_path / "store" / "node-a.json").write_text("{torn")
        clk.t += 5.0
        mesh.sync()                   # single-file flake: state held
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None
        # explicit deletion from a HEALTHY store is a clean withdraw
        os.unlink(os.path.join(store, "node-a.json"))
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is None

    def test_dead_peers_file_cannot_resurrect_it(self, tmp_path):
        """A crashed peer's file lingers in the store. After its lease
        expires the generation is tombstoned: only real progress (the node
        restarting and publishing anew) revives the peer."""
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk, stale_after_s=30.0)
        store = str(tmp_path / "store")
        write_peer(store, "node-a", 7,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()
        clk.t += 31.0
        mesh.sync()                   # lease expired, file still present
        assert b.ctx.ipcache.get("10.1.0.5/32") is None
        for _ in range(3):            # the lingering file must stay dead
            clk.t += 1.0
            mesh.sync()
            assert b.ctx.ipcache.get("10.1.0.5/32") is None
        write_peer(store, "node-a", 8,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()                   # generation progressed: resurrected
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None


class TestConflictContract:
    """ISSUE 12 (b): conflicting prefix claims resolve deterministically —
    highest generation, then lexicographically-first node name — and
    identically regardless of the order claims were observed."""

    PREFIX = "10.77.0.7/32"

    def _claims(self, store, order):
        docs = {
            "node-a": (4, {self.PREFIX: {"labels": ["k8s:app=a"]}}),
            "node-b": (9, {self.PREFIX: {"labels": ["k8s:app=b"]}}),
        }
        for node in order:
            gen, entries = docs[node]
            write_peer(store, node, gen, entries)

    def _winner_labels(self, engine):
        ident = engine.ctx.allocator.get(
            engine.ctx.ipcache.get(self.PREFIX))
        return tuple(sorted(ident.labels.to_strings()))

    @pytest.mark.parametrize("order", [("node-a", "node-b"),
                                       ("node-b", "node-a")])
    def test_winner_identical_for_both_ingest_orders(self, tmp_path, order):
        """Acceptance: run BOTH ingest orders — the first claim lands and
        is ingested alone, then the second arrives; the final owner is the
        same either way (node-b: generation 9 beats 4), the loser's claim
        withdrawn rather than split-brained."""
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        mesh = _mesh(c, tmp_path, "node-c", clk)
        store = str(tmp_path / "store")
        first, second = order
        self._claims(store, [first])
        mesh.sync()                   # first claim alone: ingested as-is
        assert self._winner_labels(c) == (f"k8s:app={first[-1]}",)
        self._claims(store, [second])
        clk.t += 1.0
        mesh.sync()                   # conflict: deterministic resolution
        assert self._winner_labels(c) == ("k8s:app=b",)
        st = mesh.status()
        assert st["conflicts"][self.PREFIX]["winner"] == "node-b"
        assert st["conflicts"][self.PREFIX]["losers"] == ["node-a"]
        assert c.metrics.counters.get(
            'clustermesh_conflicts_total{prefix_winner="node-b"}', 0) >= 1
        view = mesh.remote_view()
        assert view[self.PREFIX]["peer"] == "node-b"

    def test_generation_tie_breaks_on_node_name(self, tmp_path):
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        mesh = _mesh(c, tmp_path, "node-c", clk)
        store = str(tmp_path / "store")
        write_peer(store, "node-b", 5,
                   {self.PREFIX: {"labels": ["k8s:app=b"]}})
        write_peer(store, "node-a", 5,
                   {self.PREFIX: {"labels": ["k8s:app=a"]}})
        mesh.sync()
        assert mesh.status()["conflicts"][self.PREFIX]["winner"] == "node-a"
        assert self._winner_labels(c) == ("k8s:app=a",)

    def test_local_prefix_beats_any_remote_claim(self, tmp_path):
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        c.add_endpoint(["k8s:app=local"], ips=("10.77.0.7",), ep_id=1)
        local_id = c.ctx.ipcache.get(self.PREFIX)
        mesh = _mesh(c, tmp_path, "node-c", clk)
        write_peer(str(tmp_path / "store"), "node-b", 999,
                   {self.PREFIX: {"labels": ["k8s:app=b"]}})
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) == local_id
        st = mesh.status()
        assert st["conflicts"][self.PREFIX]["winner"] == "node-c"


class TestStoreHygiene:
    """Satellites: spoofed peer files, tmp litter, loud withdraw."""

    def test_spoofed_peer_file_ignored(self, tmp_path):
        """A peer file whose doc claims another node must not be ingested
        under the filename's ledger — and must not displace the real
        peer's last-good state (spoofed withdrawal on the next sync)."""
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk)
        store = str(tmp_path / "store")
        write_peer(store, "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:role=backup"]}})
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None
        # node-a's file now impersonates node-z (carrying no entries —
        # the spoofed-withdrawal shape)
        write_peer(store, "node-a", 2, {}, claimed_node="node-z")
        clk.t += 1.0
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None, \
            "spoofed file displaced the real peer's state"
        assert b.metrics.counters.get(
            "clustermesh_spoofed_peer_files_total", 0) >= 1
        assert "node-z" not in mesh.status()["peers"]

    def test_publish_failure_leaves_no_tmp_litter(self, tmp_path,
                                                  monkeypatch):
        a = _node(tmp_path, "node-a")
        mesh = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        import cilium_tpu.runtime.clustermesh as cm

        def boom(*args, **kw):
            raise OSError("disk full")
        monkeypatch.setattr(cm.json, "dump", boom)
        with pytest.raises(OSError):
            mesh.publish()
        litter = [n for n in os.listdir(str(tmp_path / "store"))
                  if n.startswith(".")]
        assert litter == []

    def test_startup_sweeps_tmp_litter(self, tmp_path):
        store = tmp_path / "store"
        store.mkdir()
        own = store / ".node-a-deadbeef"
        own.write_text("{}")          # our own crash litter: always swept
        old = store / ".node-b-cafe"
        old.write_text("{}")          # another writer's, long-dead
        os.utime(old, (time.time() - 3600, time.time() - 3600))
        fresh = store / ".node-c-beef"
        fresh.write_text("{}")        # another writer mid-rename: kept
        a = _node(tmp_path, "node-a")
        ClusterMesh(a, str(store), "node-a")
        assert not own.exists()
        assert not old.exists()
        assert fresh.exists()
        assert a.metrics.counters.get("clustermesh_tmp_swept_total") == 2

    def test_withdraw_failure_is_counted(self, tmp_path, monkeypatch):
        """Satellite: a node that cannot cleanly withdraw looks identical
        to one that did, for the whole lease — so the failure is loud."""
        a = _node(tmp_path, "node-a")
        mesh = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh.publish()
        import cilium_tpu.runtime.clustermesh as cm

        def boom(path):
            raise PermissionError(13, "read-only store")
        monkeypatch.setattr(cm.os, "unlink", boom)
        mesh.withdraw()               # must not raise
        assert a.metrics.counters.get(
            "clustermesh_withdraw_errors_total") == 1
        # FileNotFoundError stays silent: never published is not an error
        monkeypatch.setattr(
            cm.os, "unlink",
            lambda p: (_ for _ in ()).throw(FileNotFoundError(p)))
        mesh.withdraw()
        assert a.metrics.counters.get(
            "clustermesh_withdraw_errors_total") == 1


class TestHandoffRace:
    """Satellite: prefix hand-off racing lease expiry — the pod moves
    peers while the departing peer's file is unreadable. The re-upsert
    path and the lease-withdrawal path must compose without a permanent
    ipcache hole."""

    PREFIX = "10.1.0.5/32"
    LABELS = ["k8s:role=backup"]

    def test_handoff_while_departing_file_unreadable(self, tmp_path):
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        mesh = _mesh(c, tmp_path, "node-c", clk, stale_after_s=30.0)
        store = str(tmp_path / "store")
        write_peer(store, "node-a", 10, {self.PREFIX:
                                         {"labels": self.LABELS}})
        mesh.sync()
        id_before = c.ctx.ipcache.get(self.PREFIX)
        assert id_before is not None

        # the pod moves a → b (same labels, b publishes a higher claim);
        # a's file turns to garbage at the same moment (crashed writer)
        (tmp_path / "store" / "node-a.json").write_text("{torn")
        write_peer(store, "node-b", 11, {self.PREFIX:
                                         {"labels": self.LABELS}})
        for _ in range(3):            # race window: every sync must serve
            clk.t += 1.0
            mesh.sync()
            assert c.ctx.ipcache.get(self.PREFIX) is not None, \
                "ipcache hole during hand-off"
        # same labels ⇒ the hand-off re-referenced the same identity
        # (deferred release), not a new number
        assert c.ctx.ipcache.get(self.PREFIX) == id_before
        assert mesh.remote_view()[self.PREFIX]["peer"] == "node-b"

        # now a's lease expires while its file is STILL unreadable: the
        # withdrawal pass must not punch a hole under b's live claim
        # (b is alive, so its generation keeps progressing)
        clk.t += 31.0
        write_peer(store, "node-b", 12, {self.PREFIX:
                                         {"labels": self.LABELS}})
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) is not None
        assert "node-a" not in mesh.status()["peers"]
        assert mesh.remote_view()[self.PREFIX]["peer"] == "node-b"

    def test_remote_to_local_handoff_keeps_local_entry(self, tmp_path):
        """The pod moves from a remote peer TO THIS node: the old remote
        mapping's withdrawal must not delete the live local endpoint's
        ipcache entry (local prefixes are claims too, even though
        _resolve_claims strips them from every peer's effective map)."""
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        mesh = _mesh(c, tmp_path, "node-c", clk)
        store = str(tmp_path / "store")
        write_peer(store, "node-b", 1, {self.PREFIX:
                                        {"labels": self.LABELS}})
        mesh.sync()
        assert mesh.remote_view()[self.PREFIX]["peer"] == "node-b"

        # the pod lands locally; b withdraws its claim
        c.add_endpoint(self.LABELS, ips=("10.1.0.5",), ep_id=1)
        local_id = c.ctx.ipcache.get(self.PREFIX)
        write_peer(store, "node-b", 2, {})
        clk.t += 1.0
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) == local_id, \
            "remote withdrawal deleted the local endpoint's entry"
        assert self.PREFIX not in mesh.remote_view()
        # same outcome when b never withdraws (local always wins): the
        # conflict path must not punch the hole either
        write_peer(store, "node-b", 3, {self.PREFIX:
                                        {"labels": self.LABELS}})
        clk.t += 1.0
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) == local_id

    def test_reupsert_heals_external_deletion(self, tmp_path):
        """The re-upsert branch directly: an ipcache entry deleted out
        from under a still-live claim (the departing-peer/hand-off
        composition) is restored on the next sync instead of
        short-circuiting into a permanent hole."""
        clk = _Clock()
        c = _node(tmp_path, "node-c")
        mesh = _mesh(c, tmp_path, "node-c", clk)
        write_peer(str(tmp_path / "store"), "node-b", 1,
                   {self.PREFIX: {"labels": self.LABELS}})
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) is not None
        c.ctx.ipcache.delete(self.PREFIX)
        clk.t += 1.0
        mesh.sync()
        assert c.ctx.ipcache.get(self.PREFIX) is not None


class TestLagMetrics:
    """ISSUE 12 (c): per-peer lag gauges + replication-lag p99, clamped
    at zero under publisher clock skew."""

    def test_replication_lag_sampled_and_clamped(self, tmp_path):
        clk = _Clock(1000.0)
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk)
        store = str(tmp_path / "store")
        # gen 1 published 2s ago on our clock: a real 2s lag sample
        write_peer(store, "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:x=1"]}},
                   published_at=998.0)
        mesh.sync()
        # gen 2 published "in the future" (peer clock 1h ahead): clamped
        write_peer(store, "node-a", 2,
                   {"10.1.0.5/32": {"labels": ["k8s:x=1"]}},
                   published_at=clk.t + 3600.0)
        clk.t += 1.0
        mesh.sync()
        assert b.ctx.ipcache.get("10.1.0.5/32") is not None, \
            "live publisher dropped for running a fast clock"
        assert list(mesh._repl_lag) == [2.0, 0.0]
        assert mesh.replication_lag_p99() <= 2.0
        assert mesh.replication_lag_p99() >= 0.0
        st = mesh.status()
        assert st["replication_lag_p99_s"] >= 0.0
        assert st["peers"]["node-a"]["lag_s"] >= 0.0

    def test_peer_lag_gauge_tracks_generation_stall(self, tmp_path):
        clk = _Clock()
        b = _node(tmp_path, "node-b")
        mesh = _mesh(b, tmp_path, "node-b", clk, stale_after_s=1000.0)
        write_peer(str(tmp_path / "store"), "node-a", 1,
                   {"10.1.0.5/32": {"labels": ["k8s:x=1"]}})
        mesh.sync()
        assert mesh.status()["peers"]["node-a"]["lag_s"] == 0.0
        clk.t += 12.0                 # generation frozen: lag accrues
        mesh.sync()
        assert mesh.status()["peers"]["node-a"]["lag_s"] == 12.0
        assert b.metrics.gauges.get(
            'clustermesh_peer_lag_seconds{peer="node-a"}') == 12.0
        # a departed peer's gauge goes with it — a frozen last value
        # would read as a small, healthy lag for a dead peer forever
        os.unlink(str(tmp_path / "store" / "node-a.json"))
        mesh.sync()
        assert 'clustermesh_peer_lag_seconds{peer="node-a"}' \
            not in b.metrics.gauges


@pytest.mark.slow
class TestClusterSoak:
    """Satellite (CI wiring): the 2-proc partition/heal soak `make
    cluster-smoke` runs — real spawned engine processes over one store,
    with `clustermesh.peer_read` and `clustermesh.store_list` faults
    armed through partition phases, gating on convergence-after-heal and
    zero parity mismatches."""

    def test_two_proc_partition_heal_soak(self, tmp_path):
        from cilium_tpu.runtime.cluster import ClusterSupervisor

        store = str(tmp_path / "store")
        names = ["node-0", "node-1"]
        overrides = {n: {"cluster_stale_after_s": 30.0,
                         "cluster_staleness_budget_s": 5.0}
                     for n in names}
        sup = ClusterSupervisor(store, names, overrides=overrides,
                                datapath="fake")
        try:
            for i, name in enumerate(names):
                sup.add_endpoint(name,
                                 ["k8s:cluster=mesh", f"k8s:app=svc{i}"],
                                 [f"10.{i + 1}.0.10"], ep_id=1)
                sup.nodes[name].call("policy", docs=[{
                    "endpointSelector": {"matchLabels":
                                         {"app": f"svc{i}"}},
                    "ingress": [{"fromEndpoints": [
                        {"matchLabels": {"cluster": "mesh"}}],
                        "toPorts": [{"ports": [
                            {"port": "8080", "protocol": "TCP"}]}]}]}])
                sup.nodes[name].call("regen")
            sup.converge()

            flows = [{"src": "10.2.0.10", "dst": "10.1.0.10",
                      "sport": 41000, "dport": 8080, "ep_id": 1}]
            rev_flows = [{"src": "10.1.0.10", "dst": "10.2.0.10",
                          "sport": 41001, "dport": 8080, "ep_id": 1}]
            out = sup.nodes["node-0"].call("classify", flows=flows,
                                           now=100)
            assert out["allow"] == [True]
            out = sup.nodes["node-1"].call("classify", flows=rev_flows,
                                           now=100)
            assert out["allow"] == [True]

            # soak: alternate store partitions and single-file flakes on
            # node-0; the cross-boundary flow must keep serving from
            # last-good state the whole time
            for rnd in range(6):
                point = ("clustermesh.store_list" if rnd % 2 == 0
                         else "clustermesh.peer_read")
                sup.nodes["node-0"].call("arm", point=point,
                                         spec={"mode": "fail"})
                for step in range(3):
                    sup.broadcast("step")
                    now = 200 + rnd * 10 + step
                    out = sup.nodes["node-0"].call(
                        "classify", flows=flows, now=now)
                    assert out["allow"] == [True], \
                        f"failed closed during {point} round {rnd}"
                    out = sup.nodes["node-1"].call(
                        "classify", flows=rev_flows, now=now)
                    assert out["allow"] == [True], \
                        f"healthy peer failed closed during {point} " \
                        f"round {rnd}"
                sup.nodes["node-0"].call("disarm", point=point)
            rounds = sup.converge()
            assert rounds >= 1

            # post-heal: both nodes OK, zero parity mismatches at 1.0
            for name in names:
                st = sup.nodes[name].call("status")
                assert st["mesh"]["state"] == "OK"
                audit = sup.nodes[name].call("audit")
                assert audit["mismatched_rows"] == 0
                assert audit["checked_rows"] > 0
        finally:
            sup.stop_all()
