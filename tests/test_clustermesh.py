"""Multi-host control-plane sync (SURVEY.md §5 distributed backend; upstream
pkg/clustermesh): two engines share a store directory; each publishes its
endpoints' (prefix, labels) and ingests the other's, allocating LOCAL
identities for remote label sets — so ordinary label policy selects remote
pods, verdicts included."""

import json
import os
import time

import numpy as np

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.clustermesh import ClusterMesh
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord


def _node(tmp_path, name, node=True):
    cfg = DaemonConfig(ct_capacity=1024, auto_regen=False,
                       cluster_store=str(tmp_path / "store") if node else "",
                       node_name=name if node else "")
    return Engine(cfg, datapath=FakeDatapath(DaemonConfig(ct_capacity=1024)))


def _pkt(src, dst, sp, dp, ep_id, d=C.DIR_INGRESS):
    s16, _ = parse_addr(src)
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, C.PROTO_TCP, C.TCP_SYN, False,
                        ep_id, d)


class TestClusterMesh:
    def test_cross_node_policy_by_labels(self, tmp_path):
        """Node B's policy 'allow from role=backup' matches node A's pod via
        the mesh: A publishes (ip, labels); B allocates a local identity for
        those labels; B's selector picks it up; classify allows."""
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        b.add_endpoint(["k8s:app=db"], ips=("10.2.0.9",), ep_id=1)
        b.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"role": "backup"}}],
                "toPorts": [{"ports": [
                    {"port": "5432", "protocol": "TCP"}]}]}]}])

        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b")
        mesh_a.step()
        mesh_b.step()
        b.regenerate()

        slots = b.active.snapshot.ep_slot_of
        batch = batch_from_records(
            [_pkt("10.1.0.5", "10.2.0.9", 40000, 5432, 1),   # remote backup
             _pkt("10.9.9.9", "10.2.0.9", 40001, 5432, 1)],  # unknown world
            slots)
        out = b.classify(dict(batch), now=100)
        assert bool(out["allow"][0]), "remote pod not selected by policy"
        assert not bool(out["allow"][1])
        # the remote identity resolved is a real local allocation with the
        # peer's labels
        rid = int(out["remote_identity"][0])
        ident = b.ctx.allocator.get(rid)
        assert ident is not None
        assert "k8s:role=backup" in ident.labels.to_strings()

    def test_withdrawal_and_stale_peer(self, tmp_path, monkeypatch):
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b",
                             stale_after_s=60)
        mesh_a.step()
        mesh_b.sync()
        assert "10.1.0.5/32" in b.ctx.ipcache.snapshot()

        # endpoint removed on A → withdrawn on B at the next round trip
        a.remove_endpoint(1)
        mesh_a.publish()
        mesh_b.sync()
        assert "10.1.0.5/32" not in b.ctx.ipcache.snapshot()

        # stale peer (lease expiry): state withdrawn even with no explicit
        # removal. Staleness is judged from B's OWN lease clock, renewed
        # only on generation progress (never from the peer-written
        # published_at, which a skewed peer clock would poison) — so the
        # stall is simulated by freezing A's generation and advancing B's
        # clock past the lease.
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.6",), ep_id=2)
        mesh_a.publish()
        mesh_b.sync()
        assert "10.1.0.6/32" in b.ctx.ipcache.snapshot()
        import cilium_tpu.runtime.clustermesh as cm
        real_time = time.time
        monkeypatch.setattr(cm.time, "time", lambda: real_time() + 3600)
        mesh_b.sync()
        assert "10.1.0.6/32" not in b.ctx.ipcache.snapshot()

    def test_label_change_reallocates(self, tmp_path):
        a = _node(tmp_path, "node-a")
        b = _node(tmp_path, "node-b")
        a.add_endpoint(["k8s:role=backup"], ips=("10.1.0.5",), ep_id=1)
        mesh_a = ClusterMesh(a, str(tmp_path / "store"), "node-a")
        mesh_b = ClusterMesh(b, str(tmp_path / "store"), "node-b")
        mesh_a.step()
        mesh_b.sync()
        id1 = b.ctx.ipcache.snapshot()["10.1.0.5/32"]
        # relabel the pod on A → B must re-ingest under a new identity
        a.remove_endpoint(1)
        a.add_endpoint(["k8s:role=primary"], ips=("10.1.0.5",), ep_id=2)
        mesh_a.publish()
        mesh_b.sync()
        id2 = b.ctx.ipcache.snapshot()["10.1.0.5/32"]
        assert id1 != id2
        ident = b.ctx.allocator.get(id2)
        assert "k8s:role=primary" in ident.labels.to_strings()

    def test_engine_lifecycle_integration(self, tmp_path):
        """start_background wires the controller; stop withdraws the node
        file; corrupt peer files are skipped without failing the sync."""
        a = _node(tmp_path, "node-a")
        a.add_endpoint(["k8s:x=1"], ips=("10.1.0.7",), ep_id=1)
        a.config.cluster_sync_interval_s = 0.05
        a.start_background()
        store = tmp_path / "store"
        deadline = time.time() + 5
        while not (store / "node-a.json").exists():
            assert time.time() < deadline, "publish never happened"
            time.sleep(0.02)
        # garbage peer file must not break the loop
        (store / "node-bad.json").write_text("{not json")
        time.sleep(0.1)
        assert (store / "node-a.json").exists()
        a.stop()
        assert not (store / "node-a.json").exists()
