"""Oracle semantics tests: CT state machine, policy interaction, L7-lite,
sequential vs snapshot batch modes."""

import pytest

from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rule
from cilium_tpu.policy.repository import PolicyContext, Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import ConntrackTable, Oracle, PacketRecord


def build_world(rules, ep_labels=("k8s:app=web",), extra_ipcache=None):
    alloc = IdentityAllocator()
    ipcache = IPCache()
    ctx = PolicyContext(allocator=alloc, selector_cache=SelectorCache(alloc),
                        ipcache=ipcache)
    repo = Repository(ctx)
    lbls = Labels.parse(ep_labels)
    ident = alloc.allocate(lbls)
    ep = Endpoint(ep_id=1, labels=lbls, identity_id=ident.id,
                  ips=("192.168.1.10",))
    ipcache.upsert("192.168.1.10/32", ident.id)
    repo.add([parse_rule(r) for r in rules])
    pol = repo.resolve(ep)
    entries = ipcache.snapshot()
    if extra_ipcache:
        entries.update(extra_ipcache)
    return Oracle({1: pol}, entries), ctx


def pkt(dst="10.1.2.3", sport=40000, dport=443, proto=C.PROTO_TCP,
        flags=C.TCP_SYN, src="192.168.1.10", direction=C.DIR_EGRESS,
        ep_id=1, method=C.HTTP_METHOD_ANY, path=b""):
    s, s6 = parse_addr(src)
    d, d6 = parse_addr(dst)
    return PacketRecord(src_addr=s, dst_addr=d, src_port=sport, dst_port=dport,
                        proto=proto, tcp_flags=flags, is_ipv6=s6 or d6,
                        ep_id=ep_id, direction=direction,
                        http_method=method, http_path=path)


EGRESS_CIDR_RULE = {
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
}


class TestPipeline:
    def test_allowed_flow_creates_ct(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        v = o.classify(pkt(), now=100)
        assert v.allow and v.ct_status == C.CTStatus.NEW
        assert v.remote_identity & C.LOCAL_IDENTITY_SCOPE
        assert len(o.ct) == 1

    def test_denied_flow_no_ct(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        v = o.classify(pkt(dport=80), now=100)
        assert not v.allow and v.drop_reason == C.DropReason.POLICY
        assert len(o.ct) == 0

    def test_established_skips_policy(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        assert o.classify(pkt(), now=100).allow
        # second packet same tuple → ESTABLISHED even though policy would deny
        # nothing here; change policy by attacking another port: the CT hit is
        # on the exact tuple, so just verify status.
        v = o.classify(pkt(flags=C.TCP_ACK), now=101)
        assert v.allow and v.ct_status == C.CTStatus.ESTABLISHED

    def test_reply_direction(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        assert o.classify(pkt(), now=100).allow
        # reply: src/dst swapped, ingress direction — no ingress policy exists
        # (unenforced), but the point is it's recognized as REPLY
        reply = pkt(src="10.1.2.3", dst="192.168.1.10", sport=443, dport=40000,
                    flags=C.TCP_ACK, direction=C.DIR_INGRESS)
        v = o.classify(reply, now=101)
        assert v.allow and v.ct_status == C.CTStatus.REPLY

    def test_reply_of_denied_ingress_flow_passes_via_ct(self):
        """An egress-opened flow's replies pass even under a default-deny
        ingress policy — the CT REPLY path skips the ladder."""
        rules = [EGRESS_CIDR_RULE,
                 {"endpointSelector": {"matchLabels": {"app": "web"}},
                  "ingress": []}]  # enforce ingress, allow nothing
        o, _ = build_world(rules)
        assert o.classify(pkt(), now=100).allow
        reply = pkt(src="10.1.2.3", dst="192.168.1.10", sport=443, dport=40000,
                    flags=C.TCP_ACK, direction=C.DIR_INGRESS)
        v = o.classify(reply, now=101)
        assert v.allow and v.ct_status == C.CTStatus.REPLY
        # but a NEW ingress flow is dropped
        fresh = pkt(src="10.9.9.9", dst="192.168.1.10", sport=555, dport=8080,
                    direction=C.DIR_INGRESS)
        v2 = o.classify(fresh, now=101)
        assert not v2.allow and v2.drop_reason == C.DropReason.POLICY

    def test_world_miss(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        v = o.classify(pkt(dst="8.8.8.8"), now=100)
        assert v.remote_identity == C.IDENTITY_WORLD and not v.allow


class TestCTStateMachine:
    def test_syn_timeout_vs_established(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(flags=C.TCP_SYN), now=100)
        e = next(iter(o.ct.entries.values()))
        assert e.expiry == 100 + C.CT_LIFETIME_SYN
        o.classify(pkt(flags=C.TCP_ACK), now=110)
        assert e.expiry == 110 + C.CT_LIFETIME_TCP
        assert e.flags & C.CT_FLAG_SEEN_NON_SYN

    def test_fin_moves_to_close_timeout(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(flags=C.TCP_SYN), now=100)
        o.classify(pkt(flags=C.TCP_ACK), now=101)
        o.classify(pkt(flags=C.TCP_FIN | C.TCP_ACK), now=102)
        e = next(iter(o.ct.entries.values()))
        assert e.flags & C.CT_FLAG_TX_CLOSING
        assert e.expiry == 102 + C.CT_LIFETIME_CLOSE

    def test_rst_closes_both(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(flags=C.TCP_SYN), now=100)
        o.classify(pkt(flags=C.TCP_RST), now=101)
        e = next(iter(o.ct.entries.values()))
        assert e.flags & C.CT_FLAG_TX_CLOSING and e.flags & C.CT_FLAG_RX_CLOSING

    def test_expired_entry_is_new_again(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(flags=C.TCP_SYN), now=100)
        v = o.classify(pkt(flags=C.TCP_SYN), now=100 + C.CT_LIFETIME_SYN + 1)
        assert v.ct_status == C.CTStatus.NEW

    def test_udp_lifetime(self):
        o, _ = build_world([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"]}]}])
        o.classify(pkt(proto=C.PROTO_UDP, dport=53, flags=0), now=100)
        e = next(iter(o.ct.entries.values()))
        assert e.expiry == 100 + C.CT_LIFETIME_NONTCP

    def test_sweep(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(), now=100)
        assert o.ct.sweep(now=100 + C.CT_LIFETIME_SYN + 1) == 1
        assert len(o.ct) == 0


class TestL7Lite:
    RULES = [{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{"toPorts": [{
            "ports": [{"port": "80", "protocol": "TCP"}],
            "rules": {"http": [{"method": "GET", "path": "/api"}]},
        }]}],
    }]

    def in_pkt(self, **kw):
        kw.setdefault("src", "10.9.9.9")
        kw.setdefault("dst", "192.168.1.10")
        kw.setdefault("sport", 5555)
        kw.setdefault("dport", 80)
        kw.setdefault("direction", C.DIR_INGRESS)
        return pkt(**kw)

    def test_handshake_passes_without_tokens(self):
        o, _ = build_world(self.RULES)
        v = o.classify(self.in_pkt(flags=C.TCP_SYN), now=100)
        assert v.allow and v.redirect

    def test_request_token_match(self):
        o, _ = build_world(self.RULES)
        o.classify(self.in_pkt(flags=C.TCP_SYN), now=100)
        good = self.in_pkt(flags=C.TCP_ACK, method=C.HTTP_METHOD_IDS["GET"],
                           path=b"/api/users")
        assert o.classify(good, now=101).allow
        bad_path = self.in_pkt(flags=C.TCP_ACK, method=C.HTTP_METHOD_IDS["GET"],
                               path=b"/admin")
        v = o.classify(bad_path, now=102)
        assert not v.allow and v.drop_reason == C.DropReason.POLICY_L7
        bad_method = self.in_pkt(flags=C.TCP_ACK,
                                 method=C.HTTP_METHOD_IDS["POST"], path=b"/api")
        assert not o.classify(bad_method, now=103).allow

    def test_l7_on_new_flow_with_tokens(self):
        o, _ = build_world(self.RULES)
        v = o.classify(self.in_pkt(flags=C.TCP_ACK,
                                   method=C.HTTP_METHOD_IDS["GET"],
                                   path=b"/api"), now=100)
        assert v.allow and v.redirect


class TestBatchModes:
    def test_batch_size_one_equivalence(self):
        """snapshot mode with batch size 1 must equal sequential mode."""
        import copy
        o1, _ = build_world([EGRESS_CIDR_RULE])
        o2, _ = build_world([EGRESS_CIDR_RULE])
        packets = [
            pkt(flags=C.TCP_SYN),
            pkt(flags=C.TCP_ACK),
            pkt(dst="10.5.5.5", dport=443, flags=C.TCP_SYN),
            pkt(dport=80),  # denied
            pkt(flags=C.TCP_FIN),
        ]
        seq = o1.classify_batch_sequential(packets, now=100)
        snap = []
        for p in packets:
            snap.extend(o2.classify_batch_snapshot([p], now=100))
        assert [(v.allow, v.drop_reason, v.ct_status) for v in seq] == \
               [(v.allow, v.drop_reason, v.ct_status) for v in snap]
        assert o1.ct.entries.keys() == o2.ct.entries.keys()
        for k in o1.ct.entries:
            e1, e2 = o1.ct.entries[k], o2.ct.entries[k]
            assert (e1.flags, e1.expiry, e1.pkts_fwd, e1.pkts_rev) == \
                   (e2.flags, e2.expiry, e2.pkts_fwd, e2.pkts_rev)

    def test_snapshot_intra_batch_new_flow(self):
        """Two packets of the same new flow in one batch: both NEW under
        snapshot semantics, one CT entry, counters aggregated."""
        o, _ = build_world([EGRESS_CIDR_RULE])
        batch = [pkt(flags=C.TCP_SYN), pkt(flags=C.TCP_ACK)]
        vs = o.classify_batch_snapshot(batch, now=100)
        assert [v.ct_status for v in vs] == [C.CTStatus.NEW, C.CTStatus.NEW]
        assert len(o.ct) == 1
        e = next(iter(o.ct.entries.values()))
        assert e.pkts_fwd == 2
        assert e.flags & C.CT_FLAG_SEEN_NON_SYN
        assert e.expiry == 100 + C.CT_LIFETIME_TCP

    def test_snapshot_established_flow_updates(self):
        o, _ = build_world([EGRESS_CIDR_RULE])
        o.classify(pkt(flags=C.TCP_SYN), now=100)
        vs = o.classify_batch_snapshot(
            [pkt(flags=C.TCP_ACK), pkt(flags=C.TCP_ACK)], now=105)
        assert all(v.ct_status == C.CTStatus.ESTABLISHED for v in vs)
        e = next(iter(o.ct.entries.values()))
        assert e.pkts_fwd == 3 and e.expiry == 105 + C.CT_LIFETIME_TCP
