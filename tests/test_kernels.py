"""Kernel unit tests on the CPU backend: hash np/jnp agreement, LPM walk vs
host reference, L7 match vs host reference, CT probe/insert mechanics."""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.l7 import L7SetInterner, build_l7_tensors, l7_match_host
from cilium_tpu.compile.lpm import build_lpm, lpm_lookup_host
from cilium_tpu.kernels import conntrack as ctk
from cilium_tpu.kernels.hashing import hash_words_jnp, hash_words_np
from cilium_tpu.kernels.l7 import l7_match_batch
from cilium_tpu.kernels.lpm import lpm_lookup_batch
from cilium_tpu.kernels.records import (PACK4_L7_WORDS, PACK4_WORDS,
                                        PACK_L7DICT_WORDS, PACK_WORDS,
                                        ct_key_words, empty_batch,
                                        pack_batch, pack_batch_l7dict,
                                        pack_batch_v4)
from cilium_tpu.model.rules import HTTPRule
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr


class TestHash:
    def test_np_jnp_agree(self):
        rng = np.random.default_rng(0)
        words = rng.integers(0, 2**32, size=(64, 10), dtype=np.uint32)
        h_np = hash_words_np(words)
        h_jnp = np.asarray(hash_words_jnp(jnp.asarray(words)))
        np.testing.assert_array_equal(h_np, h_jnp)

    def test_avalanche(self):
        words = np.zeros((2, 10), dtype=np.uint32)
        words[1, 9] = 1
        h = hash_words_np(words)
        assert h[0] != h[1]


class TestLPMKernel:
    def test_matches_host_walk(self):
        entries = {"10.0.0.0/8": 100, "10.1.0.0/16": 200, "10.1.2.3/32": 300,
                   "2001:db8::/32": 400, "::/0": 500, "0.0.0.0/0": 600}
        ids = sorted(set(entries.values()) | {C.IDENTITY_WORLD})
        index = {v: i for i, v in enumerate(ids)}
        tables = build_lpm(entries, index, default_index=index[C.IDENTITY_WORLD])
        probes = ["10.1.2.3", "10.1.9.9", "10.2.3.4", "9.9.9.9",
                  "2001:db8::1", "fe80::1"]
        addr_words = np.zeros((len(probes), 4), dtype=np.uint32)
        is_v6 = np.zeros(len(probes), dtype=bool)
        want = []
        for i, a in enumerate(probes):
            a16, v6 = parse_addr(a)
            addr_words[i] = np.frombuffer(a16, dtype=">u4")
            is_v6[i] = v6
            want.append(lpm_lookup_host(tables, a16, v6))
        got = np.asarray(lpm_lookup_batch(
            jnp.asarray(tables.v4_nodes), jnp.asarray(tables.v6_nodes),
            jnp.asarray(addr_words), jnp.asarray(is_v6),
            default_index=index[C.IDENTITY_WORLD]))
        np.testing.assert_array_equal(got, np.asarray(want))


class TestL7Kernel:
    def test_matches_host(self):
        interner = L7SetInterner()
        s1 = interner.intern(frozenset({HTTPRule("GET", "/api"),
                                        HTTPRule("", "/pub")}))
        s2 = interner.intern(frozenset({HTTPRule("POST", "/x")}))
        t = build_l7_tensors(interner)
        cases = [(s1, 0, b"/api/v1"), (s1, 1, b"/api"), (s1, 1, b"/pub/z"),
                 (s2, 1, b"/x"), (s2, 0, b"/x"), (0, 0, b"/whatever"),
                 (s1, 0, b""), (s2, 1, b"")]
        n = len(cases)
        set_id = jnp.asarray([c[0] for c in cases], dtype=jnp.int32)
        method = jnp.asarray([c[1] for c in cases], dtype=jnp.int32)
        path = np.zeros((n, C.L7_PATH_MAXLEN), dtype=np.uint8)
        for i, (_, _, p) in enumerate(cases):
            path[i, :len(p)] = np.frombuffer(p, dtype=np.uint8)
        tensors = {"l7_methods": jnp.asarray(t.methods),
                   "l7_valid": jnp.asarray(t.valid),
                   "l7_path_len": jnp.asarray(t.path_len),
                   "l7_path": jnp.asarray(t.path)}
        got = np.asarray(l7_match_batch(tensors, set_id, method,
                                        jnp.asarray(path)))
        want = [l7_match_host(t, sid, m, p) if sid > 0 else True
                for sid, m, p in cases]
        np.testing.assert_array_equal(got, np.asarray(want))


def _mk_batch(n, tuples):
    """tuples: list of (src, dst, sport, dport, proto, dir)."""
    b = empty_batch(n)
    for i, (src, dst, sp, dp, proto, d) in enumerate(tuples):
        s16, sv6 = parse_addr(src)
        d16, dv6 = parse_addr(dst)
        b["src"][i] = np.frombuffer(s16, dtype=">u4")
        b["dst"][i] = np.frombuffer(d16, dtype=">u4")
        b["sport"][i], b["dport"][i] = sp, dp
        b["proto"][i] = proto
        b["direction"][i] = d
        b["is_v6"][i] = sv6
        b["valid"][i] = True
    return b


class TestCTKernel:
    def _jnp_ct(self, cap=1024):
        return {k: jnp.asarray(v) for k, v in
                make_ct_arrays(CTConfig(capacity=cap)).items()}

    def test_probe_miss_on_empty(self):
        ct = self._jnp_ct()
        b = _mk_batch(4, [("10.0.0.1", "10.0.0.2", 1, 2, 6, 0)] * 4)
        keys = ctk.ct_key_words_jnp({k: jnp.asarray(v) for k, v in b.items()})
        slot = ctk.ct_probe(ct, keys, jnp.uint32(100))
        assert (np.asarray(slot) == -1).all()

    def test_insert_then_probe_hits(self):
        ct = self._jnp_ct()
        b = {k: jnp.asarray(v) for k, v in _mk_batch(
            4, [("10.0.0.1", "10.0.0.2", 1000 + i, 80, 6, 0)
                for i in range(4)]).items()}
        keys = ctk.ct_key_words_jnp(b)
        want = jnp.asarray([True] * 4)
        nk, ncr, zm, slot, fail, _ev = ctk.ct_insert_new(
            ct, keys, want, jnp.uint32(100))
        assert (np.asarray(slot) >= 0).all() and not np.asarray(fail).any()
        ct2 = ctk.ct_apply(ct, b, slot, jnp.zeros(4, bool), want,
                           jnp.uint32(100), new_keys=nk,
                           new_created=ncr, zero_mask=zm)
        slot2 = ctk.ct_probe(ct2, keys, jnp.uint32(101))
        np.testing.assert_array_equal(np.asarray(slot2), np.asarray(slot))

    def test_duplicate_keys_one_slot(self):
        ct = self._jnp_ct()
        b = {k: jnp.asarray(v) for k, v in _mk_batch(
            4, [("10.0.0.1", "10.0.0.2", 7, 80, 6, 0)] * 4).items()}
        keys = ctk.ct_key_words_jnp(b)
        nk, ncr, zm, slot, fail, _ev = ctk.ct_insert_new(
            ct, keys, jnp.asarray([True] * 4), jnp.uint32(100))
        s = np.asarray(slot)
        assert (s == s[0]).all() and (s >= 0).all()
        assert int(np.asarray(zm).sum()) == 1  # exactly one slot claimed

    def test_insert_fail_when_window_full(self):
        # capacity 8 with probe depth 8: 9 distinct keys that all hash into a
        # full table → at least one fail
        ct = self._jnp_ct(cap=8)
        tuples = [("10.0.0.1", "10.0.0.2", 100 + i, 80, 6, 0) for i in range(12)]
        b = {k: jnp.asarray(v) for k, v in _mk_batch(12, tuples).items()}
        keys = ctk.ct_key_words_jnp(b)
        nk, ncr, zm, slot, fail, _ev = ctk.ct_insert_new(
            ct, keys, jnp.asarray([True] * 12), jnp.uint32(100))
        assert int(np.asarray(fail).sum()) >= 4  # 8 slots, 12 flows
        assert int(np.asarray(zm).sum()) == 8

    def test_sweep_reclaims(self):
        ct = self._jnp_ct()
        raw = _mk_batch(1, [("10.0.0.1", "10.0.0.2", 7, 80, 6, 0)])
        raw["tcp_flags"][0] = C.TCP_SYN  # SYN-only → 60s lifetime
        b = {k: jnp.asarray(v) for k, v in raw.items()}
        keys = ctk.ct_key_words_jnp(b)
        one = jnp.asarray([True])
        nk, ncr, zm, slot, fail, _ev = ctk.ct_insert_new(
            ct, keys, one, jnp.uint32(100))
        ct2 = ctk.ct_apply(ct, b, slot, jnp.zeros(1, bool), one,
                           jnp.uint32(100), new_keys=nk,
                           new_created=ncr, zero_mask=zm)
        ct3, n = ctk.ct_sweep(ct2, jnp.uint32(100 + C.CT_LIFETIME_SYN + 1))
        assert int(n) == 1
        assert ctk.ct_probe(ct3, keys, jnp.uint32(200))[0] == -1

    def test_key_words_np_jnp_agree(self):
        b = _mk_batch(3, [("10.0.0.1", "10.0.0.2", 5, 6, 17, 1),
                          ("2001:db8::1", "2001:db8::2", 9, 10, 6, 0),
                          ("1.1.1.1", "2.2.2.2", 0, 0, 1, 0)])
        for rev in (False, True):
            np_words = ct_key_words(b, reverse=rev)
            jnp_words = np.asarray(ctk.ct_key_words_jnp(
                {k: jnp.asarray(v) for k, v in b.items()}, reverse=rev))
            np.testing.assert_array_equal(np_words, jnp_words)


class TestPackOutVariants:
    """out= pack kernels must produce byte-identical wires to the
    allocating versions across every format, including partially-filled
    (valid-masked) buckets — the staging ring's correctness contract."""

    @staticmethod
    def _batch(n, n_valid=None, v6=False, l7=False, seed=0):
        rng = np.random.default_rng(seed)
        b = empty_batch(n)
        b["src"][:, 2] = 0xFFFF
        b["dst"][:, 2] = 0xFFFF
        b["src"][:, 3] = rng.integers(0, 2**32, n, dtype=np.uint32)
        b["dst"][:, 3] = rng.integers(0, 2**32, n, dtype=np.uint32)
        b["sport"][:] = rng.integers(0, 65536, n)
        b["dport"][:] = rng.integers(0, 65536, n)
        b["proto"][:] = rng.choice([6, 17, 1], n)
        b["tcp_flags"][:] = rng.integers(0, 256, n)
        b["ep_slot"][:] = rng.integers(0, 8, n)
        b["direction"][:] = rng.integers(0, 2, n)
        b["valid"][: n if n_valid is None else n_valid] = True
        if v6:
            b["is_v6"][::3] = True
            b["src"][::3, 0] = 0x20010DB8
        if l7:
            paths = [b"/api/v1", b"/submit", b"/", b"/static/app.js"]
            for i in range(0, n, 2):
                p = paths[i % len(paths)]
                b["http_method"][i] = i % 3
                b["http_path"][i, : len(p)] = np.frombuffer(p, np.uint8)
        return b

    def test_v4_out_bit_identical(self):
        b = self._batch(32, n_valid=20)
        want = pack_batch_v4(b)
        out = np.full((32, PACK4_WORDS), 0xDEADBEEF, dtype=np.uint32)
        got = pack_batch_v4(b, out=out)
        np.testing.assert_array_equal(got, want)
        assert got.base is out or got is out       # wrote in place

    def test_v4_out_oversized_prefix(self):
        """A max_bucket-rows ring buffer serves smaller buckets through
        its [:n] prefix."""
        b = self._batch(16)
        out = np.zeros((64, PACK4_WORDS), dtype=np.uint32)
        got = pack_batch_v4(b, out=out)
        assert got.shape == (16, PACK4_WORDS)
        np.testing.assert_array_equal(got, pack_batch_v4(b))
        np.testing.assert_array_equal(out[:16], got)

    def test_full_out_bit_identical(self):
        for v6 in (False, True):
            b = self._batch(24, n_valid=17, v6=v6, seed=3)
            want = pack_batch(b)
            got = pack_batch(b, out=np.empty((24, want.shape[1]),
                                             np.uint32))
            np.testing.assert_array_equal(got, want)

    def test_full_out_l7_path_block(self):
        b = self._batch(16, n_valid=9, l7=True, seed=4)
        want = pack_batch(b)                       # auto-detects l7
        assert want.shape[1] > PACK_WORDS
        got = pack_batch(b, out=np.empty_like(want))
        np.testing.assert_array_equal(got, want)

    def test_l7dict_out_both_variants(self):
        # compact 5-word variant
        b = self._batch(16, n_valid=11, l7=True, seed=5)
        w0, d0 = pack_batch_l7dict(b)
        assert w0.shape[1] == PACK4_L7_WORDS
        w1, d1 = pack_batch_l7dict(
            b, out=np.empty((16, PACK4_L7_WORDS), np.uint32))
        np.testing.assert_array_equal(w0, w1)
        np.testing.assert_array_equal(d0, d1)
        # full 12-word variant (force_full, as the wide sticky path does)
        w0, d0 = pack_batch_l7dict(b, force_full=True)
        assert w0.shape[1] == PACK_L7DICT_WORDS
        w1, d1 = pack_batch_l7dict(
            b, force_full=True,
            out=np.empty((16, PACK_L7DICT_WORDS), np.uint32))
        np.testing.assert_array_equal(w0, w1)
        np.testing.assert_array_equal(d0, d1)

    def test_out_mismatch_rejected(self):
        b = self._batch(8)
        with pytest.raises(ValueError):
            pack_batch_v4(b, out=np.zeros((4, PACK4_WORDS), np.uint32))
        with pytest.raises(ValueError):
            pack_batch_v4(b, out=np.zeros((8, PACK_WORDS), np.uint32))
        with pytest.raises(ValueError):
            pack_batch_v4(b, out=np.zeros((8, PACK4_WORDS), np.int32))
