"""Fuzzing of the two attacker-facing input paths (SURVEY.md §4 "keep: fuzz
rule parser + header parser"; upstream fuzzes pkg/policy/api parsing and the
datapath header parsers through oss-fuzz):

- the CNP rule parser (model/rules.py): arbitrary JSON-shaped documents must
  either parse into a well-formed Rule or raise RuleParseError — never any
  other exception, never a Rule that then crashes resolution/compilation;
- the C++ shim frame parser: arbitrary bytes and mutated valid frames must
  never crash the process, and every accepted frame must carry sane field
  ranges. Runs through ctypes against libflowshim.so, so a memory fault
  would kill the test process — that IS the assertion.
"""

import os
import random
import struct
import subprocess

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property fuzzing needs the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import RuleParseError, parse_rule
from cilium_tpu.utils import constants as C

SHIM_DIR = os.path.join(os.path.dirname(__file__), "..", "cilium_tpu", "shim")


@pytest.fixture(scope="module", autouse=True)
def built_shim():
    subprocess.run(["make", "-C", SHIM_DIR, "-s"], check=True)


# --------------------------------------------------------------------------- #
# rule parser: grammar-guided JSON documents
# --------------------------------------------------------------------------- #
_label_key = st.text(
    alphabet=st.sampled_from("abcdefghij-._/"), min_size=0, max_size=12)
_label_val = st.text(
    alphabet=st.sampled_from("abcXYZ019-._"), min_size=0, max_size=12)
_match_labels = st.dictionaries(_label_key, _label_val, max_size=3)
_selector = st.fixed_dictionaries({}, optional={
    "matchLabels": _match_labels,
    "matchExpressions": st.lists(st.fixed_dictionaries({}, optional={
        "key": _label_key,
        "operator": st.sampled_from(
            ["In", "NotIn", "Exists", "DoesNotExist", "Bogus"]),
        "values": st.lists(_label_val, max_size=2),
    }), max_size=2),
})
_port = st.one_of(
    st.integers(min_value=-5, max_value=70000).map(str),
    st.sampled_from(["", "http", "0", "65535", "65536", "1-2", "  80"]))
_port_rule = st.fixed_dictionaries({}, optional={
    "ports": st.lists(st.fixed_dictionaries({}, optional={
        "port": _port,
        "endPort": st.integers(min_value=-2, max_value=70000),
        "protocol": st.sampled_from(
            ["TCP", "UDP", "SCTP", "ANY", "tcp", "ICMP", "QUIC", ""]),
    }), max_size=2),
    "rules": st.fixed_dictionaries({}, optional={
        "http": st.lists(st.fixed_dictionaries({}, optional={
            "method": st.sampled_from(
                ["GET", "POST", "get", "FETCH", ""]),
            "path": st.text(alphabet=st.sampled_from("/abc%. *"),
                            max_size=16),
        }), max_size=2),
    }),
})
_cidr = st.one_of(
    st.sampled_from([
        "10.0.0.0/8", "0.0.0.0/0", "::/0", "2001:db8::/32", "300.1.2.3/8",
        "10.0.0.1/33", "10.0.0.1", "not-a-cidr", "", "10.0.0.0/-1",
        "1.2.3.4/31", "fe80::1/128", "1.2.3.4/8",
    ]),
    st.tuples(st.integers(0, 255), st.integers(0, 255),
              st.integers(0, 40)).map(lambda t: f"{t[0]}.{t[1]}.0.0/{t[2]}"))
_block = st.fixed_dictionaries({}, optional={
    "fromEndpoints": st.lists(_selector, max_size=2),
    "toEndpoints": st.lists(_selector, max_size=2),
    "fromEntities": st.lists(st.sampled_from(
        ["all", "world", "host", "cluster", "remote-node", "nonsense"]),
        max_size=2),
    "toEntities": st.lists(st.sampled_from(["world", "host", "bad"]),
                           max_size=2),
    "toCIDR": st.lists(_cidr, max_size=2),
    "toCIDRSet": st.lists(st.fixed_dictionaries({}, optional={
        "cidr": _cidr, "except": st.lists(_cidr, max_size=2)}), max_size=2),
    "toPorts": st.lists(_port_rule, max_size=2),
    "icmps": st.lists(st.fixed_dictionaries({}, optional={
        "fields": st.lists(st.fixed_dictionaries({}, optional={
            "type": st.integers(-1, 300),
            "family": st.sampled_from(["IPv4", "IPv6", "IPvX"]),
        }), max_size=2)}), max_size=1),
    "toServices": st.lists(st.fixed_dictionaries({}, optional={
        "k8sService": st.fixed_dictionaries({}, optional={
            "serviceName": _label_val, "namespace": _label_val})}),
        max_size=1),
    "toFQDNs": st.lists(st.fixed_dictionaries({}, optional={
        "matchName": st.sampled_from(
            ["example.com", "*.example.com", "", "..", "*"]),
        "matchPattern": st.sampled_from(["*.svc.local", "**", ""]),
    }), max_size=1),
})
_rule_doc = st.fixed_dictionaries(
    {"endpointSelector": _selector},
    optional={
        "ingress": st.lists(_block, max_size=2),
        "egress": st.lists(_block, max_size=2),
        "ingressDeny": st.lists(_block, max_size=1),
        "egressDeny": st.lists(_block, max_size=1),
        "labels": st.lists(st.fixed_dictionaries({}, optional={
            "key": _label_key, "value": _label_val,
            "source": st.sampled_from(["k8s", "unspec"])}), max_size=2),
        "description": st.text(max_size=20),
        "unknownField": st.integers(),
    })


class TestRuleParserFuzz:
    @settings(max_examples=400, deadline=None)
    @given(doc=_rule_doc)
    def test_parse_rule_total(self, doc):
        """parse_rule is total over JSON documents: a Rule or RuleParseError,
        nothing else; accepted rules survive selection + contribution
        expansion against a live repository (the path a hostile CNP would
        take to the compiler)."""
        try:
            rule = parse_rule(doc)
        except RuleParseError:
            return
        # accepted → must be usable end to end
        from cilium_tpu.model.endpoint import Endpoint
        from cilium_tpu.model.identity import IdentityAllocator
        from cilium_tpu.model.ipcache import IPCache
        from cilium_tpu.policy import PolicyContext, Repository
        from cilium_tpu.policy.selectorcache import SelectorCache
        alloc = IdentityAllocator()
        ctx = PolicyContext(allocator=alloc,
                            selector_cache=SelectorCache(alloc),
                            ipcache=IPCache())
        repo = Repository(ctx)
        repo.add([rule])
        lbls = Labels.parse(["k8s:a=b"])
        ident = alloc.allocate(lbls)
        ep = Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)
        pol = repo.resolve(ep)
        # every compiled key is range-sane
        for dirpol in (pol.ingress, pol.egress):
            for key, entry in dirpol.mapstate.items():
                assert 0 <= key.port_lo <= key.port_hi <= 65535
                assert 0 <= key.proto <= 255
        repo.clear()

    @settings(max_examples=100, deadline=None)
    @given(data=st.recursive(
        st.one_of(st.none(), st.booleans(), st.integers(), st.text(max_size=8)),
        lambda children: st.one_of(
            st.lists(children, max_size=3),
            st.dictionaries(st.text(max_size=8), children, max_size=3)),
        max_leaves=12))
    def test_parse_rule_arbitrary_json(self, data):
        """Entirely unstructured JSON values must raise RuleParseError (or
        parse, for the rare shape-coincident doc) — never TypeError/KeyError."""
        try:
            parse_rule(data)
        except RuleParseError:
            pass


# --------------------------------------------------------------------------- #
# shim frame parser: garbage + mutation corpus through the C ABI
# --------------------------------------------------------------------------- #
def _mutate(frame: bytes, rng: random.Random) -> bytes:
    b = bytearray(frame)
    op = rng.randrange(4)
    if op == 0 and len(b) > 1:           # truncate
        del b[rng.randrange(1, len(b)):]
    elif op == 1:                        # flip random bytes
        for _ in range(rng.randrange(1, 8)):
            b[rng.randrange(len(b))] = rng.randrange(256)
    elif op == 2:                        # extend with junk
        b += bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64)))
    else:                                # corrupt length/header fields
        for off in (14 + 2, 14 + 3, 14 + 0, 12, 13):
            if off < len(b):
                b[off] = rng.randrange(256)
    return bytes(b)


class TestShimFrameFuzz:
    def test_garbage_and_mutated_frames(self):
        from cilium_tpu.shim.bindings import (
            FlowShim, build_frame, build_http_frame)
        rng = random.Random(0xC0FFEE)
        s = FlowShim(batch_size=64, timeout_us=0)
        s.register_endpoint("192.168.1.10", 1)
        seeds = [
            build_frame("192.168.1.10", "10.0.0.1", 40000, 443),
            build_frame("192.168.1.10", "10.0.0.1", 1, 1,
                        proto=C.PROTO_UDP),
            build_frame("2001:db8::10", "2001:db8::1", 2, 2),
            build_frame("192.168.1.10", "10.0.0.1", 3, 8,
                        proto=C.PROTO_ICMP),
            build_frame("192.168.1.10", "10.0.0.1", 4, 443, vlan=7),
            build_http_frame("9.9.9.9", "192.168.1.10", 5, 80,
                             "GET", "/" + "a" * 100),
        ]
        n_fed = 0
        for trial in range(3000):
            if trial % 5 == 0:
                frame = bytes(rng.randrange(256)
                              for _ in range(rng.randrange(0, 160)))
            else:
                frame = _mutate(rng.choice(seeds), rng)
            s.feed_frame(frame)        # must not crash, any return ok
            n_fed += 1
            if n_fed % 64 == 0:
                b = s.poll_batch(force=True)
                if b is None:
                    continue
                # accepted records carry sane ranges
                valid = b["_ep_raw"] != 0
                assert (b["sport"][:64] >= 0).all()
                assert (b["sport"][:64] <= 65535).all()
                assert (b["dport"][:64] >= 0).all()
                assert (b["dport"][:64] <= 65535).all()
                assert (b["proto"][:64] >= 0).all()
                assert (b["proto"][:64] <= 255).all()
        st_ = s.stats()
        assert st_["frames_seen"] == 3000
        assert st_["frames_parsed"] + st_["parse_errors"] == 3000
        s.close()

    def test_http_tokenizer_hostile_payloads(self):
        from cilium_tpu.shim.bindings import FlowShim, build_frame
        s = FlowShim(batch_size=16, timeout_us=0)
        s.register_endpoint("192.168.1.10", 1)
        hostile = [
            b"GET ",                      # method, no path
            b"GET  HTTP/1.1\r\n",         # empty path
            b"GET /" + b"x" * 500,        # path far over 64B
            b"G",                         # truncated method
            b"GET\t/p HTTP/1.1",          # tab separator (not a space)
            b"POST " + b"\xff" * 70,      # binary path
            b"OPTIONS * HTTP/1.1\r\n",
            b"\r\n\r\nGET /late HTTP/1.1",
        ]
        for i, payload in enumerate(hostile):
            s.feed_frame(build_frame("9.9.9.9", "192.168.1.10", 100 + i, 80,
                                     tcp_flags=C.TCP_ACK, payload=payload))
        b = s.poll_batch(force=True)
        assert b is not None
        # tokenized paths are always NUL-padded 64B, length-capped
        assert b["http_path"].shape[1] == C.L7_PATH_MAXLEN
        s.close()
