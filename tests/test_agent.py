"""The long-lived agent process (upstream cilium-agent analog): start,
serve the API, checkpoint on shutdown, restore on restart — connection
survival across restarts is the headline upstream feature this mirrors."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from cilium_tpu.runtime.api import UnixAPIClient


def _spawn_agent(tmp_path, extra=()):
    sock = str(tmp_path / "agent.sock")
    state = str(tmp_path / "state")
    cfg = {"ct_capacity": 1024, "api_socket": sock, "state_dir": state,
           "flowlog_mode": "all"}
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cilium_tpu.cli.main", "agent", "run",
         "--config", str(cfg_path), "--fake-datapath", *extra],
        cwd="/root/repo", env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    deadline = time.time() + 60
    while not os.path.exists(sock):
        if proc.poll() is not None:
            raise AssertionError(f"agent died: {proc.stderr.read()}")
        assert time.time() < deadline, "agent never came up"
        time.sleep(0.05)
    # the socket file may exist before serve_forever runs; poll healthz
    client = UnixAPIClient(sock, timeout=5)
    while True:
        try:
            code, _ = client.get("/v1/healthz")
            if code == 200:
                break
        except OSError:
            pass
        assert time.time() < deadline, "api never answered"
        time.sleep(0.05)
    return proc, sock, state


class TestAgentProcess:
    def test_serve_policy_shutdown_restore(self, tmp_path):
        proc, sock, state = _spawn_agent(tmp_path)
        try:
            client = UnixAPIClient(sock, timeout=10)
            code, _ = client.post("/v1/policy", [{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "ingress": [{"toPorts": [{"ports": [
                    {"port": "80", "protocol": "TCP"}]}]}]}])
            assert code == 200
            code, st = client.get("/v1/status")
            assert st["rules"] == 1
        finally:
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=60) == 0, proc.stderr.read()
        # clean shutdown: socket removed, checkpoint written
        assert not os.path.exists(sock)
        assert os.path.exists(os.path.join(state, "state.json"))

        # restart restores the applied policy (upgrade-survival analog)
        proc2, sock2, _ = _spawn_agent(tmp_path)
        try:
            code, st = UnixAPIClient(sock2, timeout=10).get("/v1/status")
            assert code == 200 and st["rules"] == 1, st
        finally:
            proc2.send_signal(signal.SIGTERM)
            assert proc2.wait(timeout=60) == 0

    def test_oneshot(self, tmp_path):
        sock = str(tmp_path / "a.sock")
        state = str(tmp_path / "st")
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, "-m", "cilium_tpu.cli.main", "agent", "run",
             "--api-socket", sock, "--state-dir", state,
             "--fake-datapath", "--oneshot"],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=120)
        assert out.returncode == 0, out.stderr
        assert os.path.exists(os.path.join(state, "state.json"))
        assert not os.path.exists(sock)
