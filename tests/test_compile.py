"""Tensor compiler tests: trie vs reference LPM, port/identity classes, and
the central property — dense verdict cells == sparse MapState ladder."""

import random

import numpy as np
import pytest

from cilium_tpu.compile.idclass import build_identity_classes
from cilium_tpu.compile.l7 import L7SetInterner, build_l7_tensors, l7_match_host
from cilium_tpu.compile.lpm import build_lpm, lpm_lookup_host
from cilium_tpu.compile.policy_image import build_policy_image
from cilium_tpu.compile.portclass import build_port_classes
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache, lpm_lookup
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import HTTPRule, parse_rule
from cilium_tpu.policy import PolicyContext, Repository
from cilium_tpu.policy.mapstate import MapState, MapStateEntry, MapStateKey
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle.datapath import l7_match


class TestLPM:
    def _roundtrip(self, entries, probes):
        ident_ids = sorted(set(entries.values()) | {C.IDENTITY_WORLD})
        index = {i: n for n, i in enumerate(ident_ids)}
        tables = build_lpm(entries, index, default_index=index[C.IDENTITY_WORLD])
        for addr in probes:
            want = lpm_lookup(entries, addr)
            addr16, is_v6 = parse_addr(addr)
            got_idx = lpm_lookup_host(tables, addr16, is_v6)
            assert ident_ids[got_idx] == want, f"{addr}: {ident_ids[got_idx]} != {want}"

    def test_basic_v4(self):
        entries = {"10.0.0.0/8": 100, "10.1.0.0/16": 200, "10.1.2.3/32": 300,
                   "0.0.0.0/0": 400}
        self._roundtrip(entries, ["10.1.2.3", "10.1.9.9", "10.2.0.1",
                                  "8.8.8.8", "10.1.2.4"])

    def test_miss_is_world(self):
        tables = build_lpm({"10.0.0.0/8": 100}, {100: 1, C.IDENTITY_WORLD: 0},
                           default_index=0)
        addr16, v6 = parse_addr("8.8.8.8")
        assert lpm_lookup_host(tables, addr16, v6) == 0

    def test_non_octet_prefixes(self):
        entries = {"10.0.0.0/12": 1, "10.16.0.0/12": 2, "10.0.0.0/9": 3,
                   "192.168.0.0/22": 4}
        self._roundtrip(entries, ["10.0.0.1", "10.15.255.255", "10.16.0.1",
                                  "10.31.9.9", "10.127.0.1", "10.128.0.1",
                                  "192.168.3.255", "192.168.4.0"])

    def test_v6(self):
        entries = {"2001:db8::/32": 1, "2001:db8:1::/48": 2, "::/0": 3,
                   "2001:db8:1:2::5/128": 4}
        self._roundtrip(entries, ["2001:db8::1", "2001:db8:1::9",
                                  "2001:db8:1:2::5", "fe80::1"])

    def test_family_separation(self):
        entries = {"::/0": 1, "0.0.0.0/0": 2}
        self._roundtrip(entries, ["1.2.3.4", "2001:db8::1"])

    def test_random_property(self):
        rng = random.Random(42)
        entries = {}
        for _ in range(300):
            plen = rng.choice([8, 12, 16, 20, 24, 28, 32])
            addr = f"{rng.randrange(1,224)}.{rng.randrange(256)}.{rng.randrange(256)}.{rng.randrange(256)}"
            import ipaddress
            net = str(ipaddress.ip_network(f"{addr}/{plen}", strict=False))
            entries[net] = rng.randrange(1000, 5000)
        probes = [f"{rng.randrange(1,224)}.{rng.randrange(256)}.{rng.randrange(256)}.{rng.randrange(256)}"
                  for _ in range(200)]
        self._roundtrip(entries, probes)


class TestPortClasses:
    def test_partition(self):
        t = build_port_classes({C.PROTO_FAMILY_TCP: [(80, 80), (8080, 8090)]})
        tcp = t.table[C.PROTO_FAMILY_TCP]
        assert tcp[80] != tcp[79] and tcp[80] != tcp[81]
        assert tcp[8080] == tcp[8085] == tcp[8090]
        assert tcp[8079] != tcp[8080] and tcp[8091] != tcp[8090]
        # contiguous runs between boundaries share a class
        assert tcp[0] == tcp[79] and tcp[81] == tcp[8079] and tcp[8091] == tcp[65535]
        assert tcp[79] != tcp[81]  # split at the 80 boundary

    def test_families_disjoint(self):
        t = build_port_classes({C.PROTO_FAMILY_TCP: [(80, 80)],
                                C.PROTO_FAMILY_UDP: [(53, 53)]})
        assert set(np.unique(t.table[C.PROTO_FAMILY_TCP])).isdisjoint(
            set(np.unique(t.table[C.PROTO_FAMILY_UDP])))

    def test_classes_for_range(self):
        t = build_port_classes({C.PROTO_FAMILY_TCP: [(10, 20), (15, 30)]})
        # [15,20] is exactly the overlap segment → exactly one class
        classes = t.classes_for_range(C.PROTO_FAMILY_TCP, 15, 20)
        assert len(classes) == 1
        # [10,30] spans three segments
        assert len(t.classes_for_range(C.PROTO_FAMILY_TCP, 10, 30)) == 3


class TestIdentityClasses:
    def test_same_entries_same_class(self):
        ms = MapState()
        for ident in (100, 200):
            ms.add(MapStateKey(ident, C.PROTO_TCP, 80, 80), MapStateEntry())
        ms.add(MapStateKey(300, C.PROTO_TCP, 443, 443), MapStateEntry())
        ic = build_identity_classes([2, 100, 200, 300, 400],
                                    [(0, C.DIR_INGRESS, ms)])
        cls = {i: ic.class_of[ic.index_of[i]] for i in (2, 100, 200, 300, 400)}
        assert cls[100] == cls[200]
        assert cls[300] != cls[100]
        assert cls[2] == cls[400] == 0  # untouched identities share class 0

    def test_deny_distinguishes(self):
        ms = MapState()
        ms.add(MapStateKey(100, C.PROTO_TCP, 80, 80), MapStateEntry())
        ms.add(MapStateKey(200, C.PROTO_TCP, 80, 80), MapStateEntry(deny=True))
        ic = build_identity_classes([100, 200], [(0, 0, ms)])
        assert ic.class_of[ic.index_of[100]] != ic.class_of[ic.index_of[200]]


class TestL7Tensors:
    def test_match_parity_with_oracle(self):
        interner = L7SetInterner()
        rules = frozenset({HTTPRule(method="GET", path="/api"),
                           HTTPRule(method="", path="/public")})
        sid = interner.intern(rules)
        t = build_l7_tensors(interner)
        cases = [
            (C.HTTP_METHOD_IDS["GET"], b"/api/users"),
            (C.HTTP_METHOD_IDS["POST"], b"/api"),
            (C.HTTP_METHOD_IDS["POST"], b"/public/x"),
            (C.HTTP_METHOD_IDS["GET"], b"/admin"),
            (C.HTTP_METHOD_IDS["GET"], b"/ap"),
            (C.HTTP_METHOD_IDS["GET"], b""),
        ]
        for method, path in cases:
            assert l7_match_host(t, sid, method, path) == \
                l7_match(rules, method, path), (method, path)


def _random_mapstate(rng, identities):
    ms = MapState()
    for _ in range(rng.randrange(1, 40)):
        ident = rng.choice([C.IDENTITY_ANY] + identities)
        kind = rng.random()
        if kind < 0.2:
            key = MapStateKey(ident, C.PROTO_ANY, 0, 65535)
        else:
            proto = rng.choice([C.PROTO_TCP, C.PROTO_UDP, C.PROTO_ICMP])
            if proto == C.PROTO_ICMP:
                t = rng.randrange(0, 40)
                key = MapStateKey(ident, proto, t, t)
            elif kind < 0.5:
                key = MapStateKey(ident, proto, 0, 65535)
            else:
                lo = rng.randrange(1, 65000)
                hi = min(65535, lo + rng.choice([0, 0, 0, 10, 1000]))
                key = MapStateKey(ident, proto, lo, hi)
        deny = rng.random() < 0.25
        l7 = None
        if not deny and rng.random() < 0.15:
            l7 = frozenset({HTTPRule(method="GET", path=f"/p{rng.randrange(5)}")})
        ms.add(key, MapStateEntry(deny=deny, l7_rules=l7))
    return ms


class TestDenseLadderEquivalence:
    """THE compiler property: dense verdict cell == sparse ladder, for every
    (identity, proto, port) probe."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_equivalence(self, seed):
        rng = random.Random(seed)
        identities = [100, 200, 300, 0x1000000, 0x1000001]
        ms = _random_mapstate(rng, identities)
        all_ids = identities + [C.IDENTITY_WORLD]
        ic = build_identity_classes(all_ids, [(0, C.DIR_EGRESS, ms)])
        ranges = {}
        for key, _ in ms.items():
            if key.proto == C.PROTO_ANY:
                continue
            ranges.setdefault(C.proto_family(key.proto), []).append(
                (key.port_lo, key.port_hi))
        pc = build_port_classes(ranges)
        l7 = L7SetInterner()
        from cilium_tpu.compile.policy_image import _build_plane
        plane = _build_plane(ms, ic, pc, l7, ic.n_classes, pc.n_classes)

        # probe every identity × proto × interesting ports
        probe_ports = set()
        for key, _ in ms.items():
            for p in (key.port_lo - 1, key.port_lo, key.port_hi, key.port_hi + 1):
                if 0 <= p <= 65535:
                    probe_ports.add(p)
        probe_ports |= {0, 1, 80, 443, 65535}
        for ident in all_ids:
            row = ic.class_of[ic.index_of[ident]]
            for proto in (C.PROTO_TCP, C.PROTO_UDP, C.PROTO_ICMP, C.PROTO_SCTP, 47):
                fam = C.proto_family(proto)
                for port in probe_ports:
                    col = pc.table[fam, port]
                    cell = int(plane[row, col])
                    got = cell & C.VERDICT_DECISION_MASK
                    want = ms.lookup(ident, proto, port).decision
                    assert got == want, (
                        f"seed={seed} id={ident} proto={proto} port={port}: "
                        f"dense={got} ladder={want}")


class TestSnapshot:
    def test_end_to_end_build(self):
        alloc = IdentityAllocator()
        ipc = IPCache()
        ctx = PolicyContext(allocator=alloc, selector_cache=SelectorCache(alloc),
                            ipcache=ipc)
        repo = Repository(ctx)
        lbls = Labels.parse(["k8s:app=web"])
        ident = alloc.allocate(lbls)
        ep = Endpoint(ep_id=7, labels=lbls, identity_id=ident.id)
        ipc.upsert("192.168.1.10/32", ident.id)
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
            "ingress": [{"toPorts": [{
                "ports": [{"port": "80", "protocol": "TCP"}],
                "rules": {"http": [{"method": "GET", "path": "/api"}]}}]}],
        })])
        snap = build_snapshot(repo, ctx, [ep])
        assert snap.ep_slot_of[7] == 0
        assert snap.l7.n_sets == 1
        t = snap.tensors()
        assert t["verdict"].shape[0] == 1 and t["verdict"].shape[1] == 2
        # verdict sanity through the tensors: egress 443 to the CIDR identity
        cidr_id = ipc.lookup("10.5.5.5")
        row = snap.id_classes.class_of[snap.id_classes.index_of[cidr_id]]
        col = snap.port_classes.table[C.PROTO_FAMILY_TCP, 443]
        cell = int(t["verdict"][0, C.DIR_EGRESS, row, col])
        assert cell & C.VERDICT_DECISION_MASK == C.VERDICT_ALLOW
        # ingress 80 redirect cell carries an l7 id
        row_w = snap.id_classes.class_of[snap.id_classes.index_of[C.IDENTITY_WORLD]]
        col80 = snap.port_classes.table[C.PROTO_FAMILY_TCP, 80]
        cell80 = int(t["verdict"][0, C.DIR_INGRESS, row_w, col80])
        assert cell80 & C.VERDICT_DECISION_MASK == C.VERDICT_REDIRECT
        assert cell80 >> C.VERDICT_L7_SHIFT == 1
