"""Fused Pallas classify megakernel tests (kernels/fused.py, ISSUE 8).

The contract: with ``fused_kernels=on`` the Pallas interior (LPM stride
walk, fused CT probe pair, policy+L7+verdict kernel) must be bit-identical
to the jnp reference AND to the semantics oracle — outputs, CT state and
counters — in interpret mode on CPU (the tier-1 configuration; compiled
Pallas on a real TPU runs the same kernel bodies). Coverage:

- per-kernel unit parity (fused vs jnp vs the host reference walk),
  including the property-fuzz LPM suite over random v4/v6 prefix sets
  (ROADMAP item 4c seed: the 16-level v6 walk, 100k prefixes slow-marked)
  and the ROW_BLOCK grid path;
- ``ct_key_words_pair`` word-derivation identity (the shared-hashing
  satellite — it feeds the jnp fallback path too);
- the full end-to-end parity suite (tests/test_parity.run_parity) rerun
  with the fused interior, plus fused-vs-jnp bit-identity on outputs, CT
  and counters with per-stage fallback forced through the fuse_plan
  budget;
- ``make_classify_fn`` memoization (repeated snapshot placements must not
  re-trace identical static configs);
- serving integration: engine classify, pipelined submissions, a 1-shard
  vs 4-shard mesh, and the shadow-oracle auditor (PR 7) — all with
  ``fused_kernels=on`` — plus the ``datapath.compute`` span's ``fused``
  executor tag.
"""

import random
import time

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.lpm import build_lpm, lpm_lookup_host
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels import conntrack as ctk
from cilium_tpu.kernels import fused as fk
from cilium_tpu.kernels.classify import (classify_interior_core,
                                         classify_step, make_classify_fn)
from cilium_tpu.kernels.lpm import lpm_lookup_batch
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import (FakeDatapath, JITDatapath,
                                         resolve_fused)
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr

from tests.test_parity import build_world, random_packet, run_parity

FUSED_KW = {"fused": True, "fused_interpret": True}


def _assert_tree_equal(a, b, ctx=""):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"{ctx}:{k}")


# --------------------------------------------------------------------------- #
# LPM: property-fuzz parity over random prefix sets (jnp + fused vs the
# host reference walk — which model.ipcache pins to oracle semantics)
# --------------------------------------------------------------------------- #
def _random_prefix_set(rng, n_v4, n_v6, max_ident=50):
    entries = {}
    for _ in range(n_v4):
        plen = int(rng.choice([8, 12, 16, 20, 24, 28, 32]))
        addr = rng.integers(0, 1 << 32) & ((0xFFFFFFFF << (32 - plen))
                                           & 0xFFFFFFFF)
        prefix = (f"{(addr >> 24) & 0xFF}.{(addr >> 16) & 0xFF}."
                  f"{(addr >> 8) & 0xFF}.{addr & 0xFF}/{plen}")
        entries[prefix] = int(rng.integers(1, max_ident))
    for _ in range(n_v6):
        plen = int(rng.choice([16, 32, 48, 56, 64, 96, 128]))
        words = [int(rng.integers(0, 1 << 16)) for _ in range(8)]
        addr = ":".join(f"{w:x}" for w in words)
        entries[f"{addr}/{plen}"] = int(rng.integers(1, max_ident))
    return entries


def _fuzz_addresses(rng, entries, n):
    """Half the probe addresses land inside random prefixes from the set
    (bit-match pressure on every level), half are uniform random."""
    probes = []
    keys = list(entries)
    for i in range(n):
        if keys and i % 2 == 0:
            prefix = keys[int(rng.integers(0, len(keys)))]
            addr_s, plen_s = prefix.rsplit("/", 1)
            a16, is_v6 = parse_addr(addr_s)
            raw = bytearray(a16)
            plen = int(plen_s) + (0 if is_v6 else 96)
            for bit in range(plen, 128):      # randomize the host bits
                if rng.integers(0, 2):
                    raw[bit // 8] |= 1 << (7 - bit % 8)
                else:
                    raw[bit // 8] &= ~(1 << (7 - bit % 8))
            if not is_v6:                     # keep the v4-mapped prelude
                raw[:12] = a16[:12]
            probes.append((bytes(raw), is_v6))
        else:
            is_v6 = bool(rng.integers(0, 2))
            if is_v6:
                probes.append((rng.integers(0, 256, 16, dtype=np.uint8)
                               .tobytes(), True))
            else:
                probes.append((b"\x00" * 10 + b"\xff\xff"
                               + rng.integers(0, 256, 4, dtype=np.uint8)
                               .tobytes(), False))
    return probes


def _lpm_parity(entries, probes, default_index=0):
    from cilium_tpu.compile.lpm import lpm_lookup_host_prov
    from cilium_tpu.kernels.lpm import lpm_lookup_prov_batch
    idents = sorted(set(entries.values()))
    identity_index = {i: n for n, i in enumerate(idents)}
    tables = build_lpm(entries, identity_index, default_index)
    want = np.asarray([lpm_lookup_host(tables, a, v6) for a, v6 in probes],
                      dtype=np.int32)
    want_meta = np.asarray(
        [lpm_lookup_host_prov(tables, a, v6)[1] for a, v6 in probes],
        dtype=np.int32)
    addr = np.stack([np.frombuffer(a, dtype=">u4").astype(np.uint32)
                     for a, _ in probes])
    is_v6 = np.asarray([v6 for _, v6 in probes])
    v4n, v6n = jnp.asarray(tables.v4_nodes), jnp.asarray(tables.v6_nodes)
    got_jnp, got_jnp_meta = lpm_lookup_prov_batch(
        v4n, v6n, jnp.asarray(addr), jnp.asarray(is_v6), default_index)
    got_fused, got_fused_meta = fk.lpm_lookup_fused(
        v4n, v6n, jnp.asarray(addr), jnp.asarray(is_v6), default_index,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(got_jnp), want,
                                  "jnp walk != host walk")
    np.testing.assert_array_equal(np.asarray(got_fused), want,
                                  "fused walk != host walk")
    # match provenance ((slot<<8)|plen) rides the same walk: all three
    # executors must name the same winning prefix
    np.testing.assert_array_equal(np.asarray(got_jnp_meta), want_meta,
                                  "jnp provenance != host provenance")
    np.testing.assert_array_equal(np.asarray(got_fused_meta), want_meta,
                                  "fused provenance != host provenance")
    if not is_v6.any():
        got4, got4_meta = fk.lpm_lookup_fused(
            v4n, v6n, jnp.asarray(addr), jnp.asarray(is_v6), default_index,
            v4_only=True, interpret=True)
        np.testing.assert_array_equal(np.asarray(got4), want,
                                      "fused v4_only != host")
        np.testing.assert_array_equal(np.asarray(got4_meta), want_meta,
                                      "fused v4_only provenance != host")


class TestLPMFuzzParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_mixed_family_sets(self, seed):
        rng = np.random.default_rng(seed)
        entries = _random_prefix_set(rng, n_v4=120, n_v6=80)
        probes = _fuzz_addresses(rng, entries, 256)
        _lpm_parity(entries, probes, default_index=int(rng.integers(0, 5)))

    def test_v4_only_sets(self):
        rng = np.random.default_rng(9)
        entries = _random_prefix_set(rng, n_v4=200, n_v6=0)
        probes = _fuzz_addresses(
            rng, entries, 128)
        probes = [p for p in probes if not p[1]]
        _lpm_parity(entries, probes)

    def test_empty_table_resolves_default(self):
        _lpm_parity({}, _fuzz_addresses(np.random.default_rng(1), {}, 32),
                    default_index=7)

    def test_grid_block_path(self):
        """2048 probes → the ROW_BLOCK grid (2 blocks) must equal the
        single-block jnp result."""
        rng = np.random.default_rng(5)
        entries = _random_prefix_set(rng, n_v4=60, n_v6=40)
        probes = _fuzz_addresses(rng, entries, 2048)
        _lpm_parity(entries, probes)

    @pytest.mark.slow
    def test_v6_walk_at_100k_prefixes(self):
        """ROADMAP item 4c seed: the 16-level stride walk over a
        BGP-table-scale v6 set (100k distinct prefixes under a shared /32,
        bounding trie width like a real table's aggregation does)."""
        rng = np.random.default_rng(42)
        entries = {}
        while len(entries) < 100_000:
            b4, b5, b6 = (int(rng.integers(0, 256)),
                          int(rng.integers(0, 256)),
                          int(rng.integers(0, 256)))
            entries[f"2001:db8:{b4:02x}{b5:02x}:{b6:02x}00::/56"] = \
                int(rng.integers(1, 64))
        probes = _fuzz_addresses(rng, entries, 1024)
        probes = [p for p in probes if p[1]]
        _lpm_parity(entries, probes)


# --------------------------------------------------------------------------- #
# CT probe pair + key-pair derivation
# --------------------------------------------------------------------------- #
def _random_batch(rng, n, v6_frac=0.25):
    recs = []
    for i in range(n):
        v6 = rng.random() < v6_frac
        if v6:
            src, _ = parse_addr(f"2001:db8::{rng.randrange(1, 9999):x}")
            dst, _ = parse_addr(f"2001:db9::{rng.randrange(1, 9999):x}")
        else:
            src, _ = parse_addr(f"10.0.{rng.randrange(256)}.{rng.randrange(1, 255)}")
            dst, _ = parse_addr(f"10.1.{rng.randrange(256)}.{rng.randrange(1, 255)}")
        from oracle import PacketRecord
        recs.append(PacketRecord(
            src, dst, rng.randrange(1024, 65535), rng.randrange(1, 65535),
            rng.choice([C.PROTO_TCP, C.PROTO_UDP]), C.TCP_SYN, v6, 1,
            rng.choice([C.DIR_EGRESS, C.DIR_INGRESS])))
    return batch_from_records(recs, {1: 0})


class TestCtKeyPair:
    def test_pair_matches_two_sided_normalization(self):
        rng = random.Random(3)
        for trial in range(3):
            b = {k: jnp.asarray(v)
                 for k, v in _random_batch(rng, 64).items()}
            fwd, rev = ctk.ct_key_words_pair(b)
            np.testing.assert_array_equal(
                np.asarray(fwd),
                np.asarray(ctk.ct_key_words_jnp(b, reverse=False)))
            np.testing.assert_array_equal(
                np.asarray(rev),
                np.asarray(ctk.ct_key_words_jnp(b, reverse=True)))


class TestCtProbePairFused:
    def _populated_ct(self, rng, cap=1024, n_flows=300):
        ct = {k: jnp.asarray(v)
              for k, v in make_ct_arrays(CTConfig(capacity=cap)).items()}
        b = {k: jnp.asarray(v)
             for k, v in _random_batch(rng, n_flows).items()}
        keys = ctk.ct_key_words_jnp(b)
        want = jnp.ones((n_flows,), dtype=bool)
        new_keys, new_created, zero_mask, slot, _fail, _ev = ctk.ct_insert_new(
            ct, keys, want, jnp.uint32(100))
        ct = ctk.ct_apply(ct, b, slot, jnp.zeros((n_flows,), bool),
                          slot >= 0, jnp.uint32(100), new_keys=new_keys,
                          new_created=new_created, zero_mask=zero_mask)
        return ct, b

    def test_fused_pair_matches_two_probes(self):
        rng = random.Random(7)
        ct, seeded = self._populated_ct(rng)
        for trial, now in ((0, 110), (1, 10_000)):   # live + all-expired
            probe = {k: jnp.asarray(v)
                     for k, v in _random_batch(rng, 128).items()}
            # half the probe rows revisit seeded flows (hits both ways)
            mix = {k: jnp.concatenate([v[:64], seeded[k][:64]])
                   for k, v in probe.items()}
            fwd, rev = ctk.ct_key_words_pair(mix)
            want_f = ctk.ct_probe(ct, fwd, jnp.uint32(now))
            want_r = ctk.ct_probe(ct, rev, jnp.uint32(now))
            got_f, got_r = fk.ct_probe_pair_fused(
                ct, fwd, rev, jnp.uint32(now), probe_depth=8,
                interpret=True)
            np.testing.assert_array_equal(np.asarray(got_f),
                                          np.asarray(want_f), (trial, "fwd"))
            np.testing.assert_array_equal(np.asarray(got_r),
                                          np.asarray(want_r), (trial, "rev"))


# --------------------------------------------------------------------------- #
# policy + L7 + verdict kernel
# --------------------------------------------------------------------------- #
class TestPolicyVerdictFused:
    def test_kernel_matches_interior_core(self):
        rng = random.Random(11)
        ctx, repo, eps = build_world()
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        prior = []
        for trial in range(3):
            packets = [random_packet(rng, prior) for _ in range(96)]
            b = {k: jnp.asarray(v) for k, v in
                 batch_from_records(packets, snap.ep_slot_of).items()}
            nrng = np.random.default_rng(trial)
            est = jnp.asarray(nrng.random(96) < 0.3)
            reply = jnp.asarray(~np.asarray(est)
                                & (nrng.random(96) < 0.2))
            id_idx = lpm_lookup_batch(
                tensors["lpm_v4"], tensors["lpm_v6"],
                jnp.where((b["direction"] == C.DIR_EGRESS)[:, None],
                          b["dst"], b["src"]),
                b["is_v6"], default_index=snap.world_index)
            args = (tensors, b["ep_slot"], b["direction"], id_idx,
                    b["proto"], b["dport"], b["http_method"],
                    b["http_path"], est, reply, b["valid"])
            want = classify_interior_core(*args)
            got = fk.policy_verdict_fused(*args, interpret=True)
            for name, w, g in zip(("allow", "reason", "status", "redirect",
                                   "matched_rule"),
                                  want, got):
                np.testing.assert_array_equal(np.asarray(w), np.asarray(g),
                                              (trial, name))
            prior.extend(packets)
            prior = prior[-80:]


# --------------------------------------------------------------------------- #
# full classify step: fused vs jnp vs oracle
# --------------------------------------------------------------------------- #
class TestFusedClassifyParity:
    @pytest.mark.parametrize("seed", range(2))
    def test_fused_oracle_parity(self, seed):
        """The end-to-end parity suite with the Pallas interior — verdicts,
        reasons, CT state all bit-identical to the semantics oracle."""
        run_parity(seed, n_batches=4, batch=80, classify_kwargs=FUSED_KW)

    def test_fused_vs_jnp_bit_identity(self):
        """Outputs, CT arrays AND counters bit-identical across a stateful
        multi-batch stream (v6 + L7 + CT revisits)."""
        rng = random.Random(5)
        ctx, repo, eps = build_world()
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        mk = lambda: {k: jnp.asarray(v) for k, v in  # noqa: E731
                      make_ct_arrays(CTConfig(capacity=4096)).items()}
        ct_a, ct_b = mk(), mk()
        prior, now = [], 500
        for bi in range(4):
            packets = [random_packet(rng, prior) for _ in range(96)]
            b = {k: jnp.asarray(v) for k, v in
                 batch_from_records(packets, snap.ep_slot_of).items()}
            out_a, ct_a, cnt_a = classify_step(
                tensors, ct_a, b, jnp.uint32(now),
                world_index=snap.world_index)
            out_b, ct_b, cnt_b = classify_step(
                tensors, ct_b, b, jnp.uint32(now),
                world_index=snap.world_index, **FUSED_KW)
            _assert_tree_equal(out_a, out_b, f"out[{bi}]")
            _assert_tree_equal(ct_a, ct_b, f"ct[{bi}]")
            _assert_tree_equal(cnt_a, cnt_b, f"counters[{bi}]")
            prior.extend(packets)
            prior = prior[-100:]
            now += 40

    def test_fuse_plan_budget_gates_per_stage(self):
        """A geometry over the table budget falls back to the jnp
        reference PER STAGE (still bit-identical); the plan is a
        trace-time constant of the shapes."""
        ctx, repo, eps = build_world()
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=1024)).items()}
        plan = fk.fuse_plan(tensors, ct)
        assert plan.lpm and plan.ct and plan.policy and plan.any
        tiny = fk.fuse_plan(tensors, ct, budget=1)
        assert not (tiny.lpm or tiny.ct or tiny.policy or tiny.any)
        # rule sharding pins the policy stage on the reference
        assert not fk.fuse_plan(tensors, ct, rule_axis="rules").policy
        # forced fallback still bit-identical through classify_step
        rng = random.Random(2)
        packets = [random_packet(rng, []) for _ in range(64)]
        b = {k: jnp.asarray(v) for k, v in
             batch_from_records(packets, snap.ep_slot_of).items()}
        old = fk.FUSED_TABLE_BYTES
        try:
            fk.FUSED_TABLE_BYTES = 1
            out_a, _, _ = classify_step(tensors, dict(ct), b,
                                        jnp.uint32(100),
                                        world_index=snap.world_index,
                                        **FUSED_KW)
        finally:
            fk.FUSED_TABLE_BYTES = old
        out_b, _, _ = classify_step(tensors, dict(ct), b, jnp.uint32(100),
                                    world_index=snap.world_index)
        _assert_tree_equal(out_a, out_b, "budget-fallback")


class TestMakeClassifyFnMemo:
    def test_same_static_config_shares_one_callable(self):
        a = make_classify_fn(8, False, donate_ct=False)
        assert a is make_classify_fn(8, False, donate_ct=False)
        assert a is not make_classify_fn(8, True, donate_ct=False)
        assert a is not make_classify_fn(8, False, donate_ct=False,
                                         packed=True)
        assert a is not make_classify_fn(8, False, donate_ct=False,
                                         fused=True, fused_interpret=True)
        assert a is not make_classify_fn(8, False, donate_ct=False,
                                         lb_probe_depth=4)


# --------------------------------------------------------------------------- #
# serving integration: selector, engine, pipeline, mesh, audit
# --------------------------------------------------------------------------- #
def _world(eng):
    from tests.test_datapath import FIXTURE_RULES
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.add_endpoint(["k8s:role=fe"], ips=("192.168.1.30",), ep_id=3)
    eng.apply_policy(FIXTURE_RULES)
    eng.regenerate()


def jit_engine(fused="on", **kw):
    kw.setdefault("ct_capacity", 2048)
    kw.setdefault("auto_regen", False)
    kw.setdefault("flowlog_mode", "none")
    kw.setdefault("batch_size", 128)
    kw.setdefault("pipeline_flush_ms", 1.0)
    cfg = DaemonConfig(fused_kernels=fused, **kw)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    _world(eng)
    return eng


def _chunks(eng, n_chunks=4, size=40, seed=3):
    from tests.test_sharded_pipeline import _mk_phase
    return _mk_phase(eng.active.snapshot.ep_slot_of, n_chunks,
                     (size, size + 9), seed)


class TestFusedSelector:
    def test_resolve_modes_on_cpu(self):
        assert resolve_fused(DaemonConfig(fused_kernels="off")) \
            == (False, False)
        assert resolve_fused(DaemonConfig(fused_kernels="auto")) \
            == (False, False)      # auto keeps the jnp reference off-TPU
        assert resolve_fused(DaemonConfig(fused_kernels="on")) \
            == (True, True)        # forced → interpret mode on CPU

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            DaemonConfig(fused_kernels="yes")

    def test_backend_surfaces_state_and_status(self):
        eng = jit_engine("on")
        try:
            assert eng.datapath.fused_state == {
                "mode": "on", "active": True, "interpret": True}
            from cilium_tpu.runtime.api import status_doc
            assert status_doc(eng)["fused_kernels"]["active"] is True
        finally:
            eng.stop()
        cfg = DaemonConfig()
        fake = Engine(cfg, datapath=FakeDatapath(cfg))
        try:
            from cilium_tpu.runtime.api import status_doc
            assert status_doc(fake)["fused_kernels"] is None
        finally:
            fake.stop()

    def test_compute_span_carries_executor_tag(self):
        eng = jit_engine("on", trace_sample_rate=1.0)
        try:
            ch = _chunks(eng, 1)[0]
            eng.classify(dict(ch), now=100)
            spans = [s for s in eng.tracer.spans(name="datapath.compute")
                     if s.get("attrs")]
            assert spans and spans[-1]["attrs"]["fused"] == 1
        finally:
            eng.stop()


class TestFusedServing:
    OUT_KEYS = ("allow", "reason", "status", "remote_identity", "redirect",
                "svc", "nat_dst", "nat_dport", "rnat", "rnat_src",
                "rnat_sport")

    def test_engine_classify_matches_reference(self):
        ref, fus = jit_engine("off"), jit_engine("on")
        try:
            for i, ch in enumerate(_chunks(ref, 5)):
                oa = ref.classify(dict(ch), now=100 + i)
                ob = fus.classify(dict(ch), now=100 + i)
                for k in self.OUT_KEYS:
                    np.testing.assert_array_equal(oa[k], ob[k], k)
        finally:
            ref.stop()
            fus.stop()

    def test_pipelined_fused_matches_pipelined_reference(self):
        """FIFO pipeline verdicts through the fused interior == the same
        submissions through the jnp-reference pipeline, bit-identical on
        every out column (zero-copy pack path included)."""
        ref, fus = jit_engine("off"), jit_engine("on")
        try:
            chunks = _chunks(ref, 6, size=30, seed=8)
            t_ref = [ref.submit(dict(ch), now=200 + i)
                     for i, ch in enumerate(chunks)]
            t_fus = [fus.submit(dict(ch), now=200 + i)
                     for i, ch in enumerate(chunks)]
            assert ref.drain(timeout=60) and fus.drain(timeout=60)
            for i, (ta, tb) in enumerate(zip(t_ref, t_fus)):
                want, got = ta.result(timeout=10), tb.result(timeout=10)
                for k in got:
                    np.testing.assert_array_equal(
                        got[k], want[k], err_msg=f"chunk {i}:{k}")
        finally:
            ref.stop()
            fus.stop()

    def test_sharded_mesh_fused_parity(self):
        """1-shard fused vs 4-shard fused pipelines bit-identical, and both
        equal to the oracle-backed serial path on the comparable keys —
        the sharded parity suite with the Pallas interior."""
        from tests.test_sharded_pipeline import (ORACLE_KEYS,
                                                 fake_serial_engine)
        serial = fake_serial_engine()
        one = jit_engine("on", n_shards=1)
        eight = jit_engine("on", n_shards=4)
        try:
            chunks = _chunks(one, 5, size=28, seed=13)
            want = [serial.classify(dict(ch), now=300 + i)
                    for i, ch in enumerate(chunks)]
            got = {}
            for eng in (one, eight):
                ts = [eng.submit(dict(ch), now=300 + i)
                      for i, ch in enumerate(chunks)]
                assert eng.drain(timeout=60)
                got[id(eng)] = [t.result(timeout=10) for t in ts]
                for i, g in enumerate(got[id(eng)]):
                    for k in ORACLE_KEYS:
                        np.testing.assert_array_equal(
                            g[k], want[i][k],
                            err_msg=f"chunk {i}:{k} vs oracle")
            for i, (a, b) in enumerate(zip(got[id(one)], got[id(eight)])):
                for k in self.OUT_KEYS:
                    np.testing.assert_array_equal(
                        a[k], b[k], err_msg=f"chunk {i}:{k} 1 vs 4 shard")
        finally:
            serial.stop()
            one.stop()
            eight.stop()

    def test_audit_clean_with_fused_interior(self):
        """The shadow-oracle auditor (PR 7) at sampling 1.0 over the fused
        path: every captured batch replays bit-identical against the
        oracle — checked > 0, zero mismatches, health stays OK."""
        eng = jit_engine("on", audit_enabled=True, audit_sample_rate=1.0)
        try:
            for i, ch in enumerate(_chunks(eng, 4, size=24, seed=21)):
                eng.classify(dict(ch), now=400 + i)
            eng.audit_step()
            st = eng.auditor.stats()
            assert st["checked_batches"] >= 4
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0
            assert eng.auditor.healthy
            assert eng.health()["state"] == C.HEALTH_OK
        finally:
            eng.stop()


@pytest.mark.slow
class TestFusedSoak:
    def test_long_horizon_fused_oracle_parity(self):
        """Expiry + slot reuse + large time steps through the fused
        interior (the test_parity long-horizon case)."""
        run_parity(seed=99, n_batches=8, batch=64, time_step=90,
                   classify_kwargs=FUSED_KW)

    def test_pipelined_fused_soak(self):
        """A few hundred pipelined submissions through the fused engine
        with audit armed at 1.0: zero mismatches, no restarts."""
        eng = jit_engine("on", audit_enabled=True, audit_sample_rate=1.0,
                         audit_pool_batches=64)
        try:
            chunks = _chunks(eng, 40, size=30, seed=31)
            tickets = [eng.submit(dict(ch), now=500 + i)
                       for i, ch in enumerate(chunks)]
            assert eng.drain(timeout=120)
            for t in tickets:
                t.result(timeout=10)
            deadline = time.time() + 30
            while time.time() < deadline:
                eng.audit_step()
                if eng.auditor.stats()["checked_batches"] >= 10:
                    break
            st = eng.auditor.stats()
            assert st["checked_batches"] >= 10
            assert st["mismatched_rows"] == 0
            assert eng.health()["pipeline"]["restarts"] == 0 \
                if eng.health().get("pipeline") else True
        finally:
            eng.stop()
