"""Incremental tensor updates (SURVEY.md §3.2 hot spot, §7 step 3): after
any sequence of rule add/remove/refresh, the patched snapshot must be
semantically identical to a fresh build_snapshot — same decision and same L7
rule set for every (endpoint, direction, identity, proto, port), same
enforced flags, same mapstate lookups. Class partitions may differ (splits
are never re-merged); that is representation, not semantics, so equivalence
is asserted through the lookup surface, not array equality."""

import random

import numpy as np
import pytest

from cilium_tpu.compile.ct_layout import CTConfig
from cilium_tpu.compile.incremental import IncrementalCompiler
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rule
from cilium_tpu.policy import PolicyContext, Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath, JITDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from oracle import PacketRecord
from cilium_tpu.utils.ip import parse_addr


# --------------------------------------------------------------------------- #
# world + equivalence helpers
# --------------------------------------------------------------------------- #
N_PEERS = 12


def make_world(n_eps=2, n_peers=N_PEERS):
    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache())
    repo = Repository(ctx)
    eps = []
    for e in range(n_eps):
        lbls = Labels.parse([f"k8s:app=web{e}"])
        ident = alloc.allocate(lbls)
        ctx.ipcache.upsert(f"192.168.{e}.10/32", ident.id)
        eps.append(Endpoint(ep_id=e + 1, labels=lbls, identity_id=ident.id))
    for i in range(n_peers):
        ident = alloc.allocate(Labels.parse(
            [f"k8s:peer=p{i}", f"k8s:group=g{i % 3}"]))
        ctx.ipcache.upsert(f"172.16.{i}.0/24", ident.id)
    return ctx, repo, eps


def _cell_lookup(snap, slot, d, ident_id, proto, dport):
    """Resolve one probe through a snapshot's dense tensors (host-side
    mirror of kernels/policy.policy_lookup_batch)."""
    if not snap.image.enforced[slot, d]:
        return ("unenforced",)
    idx = snap.id_classes.index_of[ident_id]
    cls = snap.id_classes.class_of[idx]
    fam = C.proto_family(proto)
    pcls = snap.port_classes.table[fam, dport]
    cell = int(snap.image.verdict[slot, d, cls, pcls])
    decision = cell & C.VERDICT_DECISION_MASK
    if decision == C.VERDICT_REDIRECT:
        l7 = snap.l7_interner.sets[(cell >> C.VERDICT_L7_SHIFT) - 1]
        return (decision, frozenset(l7))
    return (decision,)


def assert_equivalent(inc_snap, fresh_snap, probes):
    assert inc_snap.revision == fresh_snap.revision
    np.testing.assert_array_equal(inc_snap.image.enforced,
                                  fresh_snap.image.enforced)
    for slot, d, ident, proto, dport in probes:
        got = _cell_lookup(inc_snap, slot, d, ident, proto, dport)
        want = _cell_lookup(fresh_snap, slot, d, ident, proto, dport)
        assert got == want, (slot, d, ident, proto, dport, got, want)
        # the sparse (oracle-facing) mapstates must agree too
        gi = inc_snap.policies[slot].direction(d)
        fi = fresh_snap.policies[slot].direction(d)
        assert gi.enforced == fi.enforced
        ri = gi.lookup(ident, proto, dport)
        rf = fi.lookup(ident, proto, dport)
        assert ri.decision == rf.decision, (slot, d, ident, proto, dport)
        if ri.entry is not None and rf.entry is not None:
            assert (ri.entry.deny, ri.entry.l7_rules) \
                == (rf.entry.deny, rf.entry.l7_rules)


def make_probes(ctx, n_eps):
    idents = [i.id for i in ctx.allocator.all()]
    ports = [0, 1, 53, 79, 80, 81, 443, 999, 1000, 1001, 5000, 8079,
             8080, 8081, 32768, 65535]
    probes = []
    for slot in range(n_eps):
        for d in (C.DIR_EGRESS, C.DIR_INGRESS):
            for ident in idents:
                for proto in (C.PROTO_TCP, C.PROTO_UDP):
                    for p in ports:
                        probes.append((slot, d, ident, proto, p))
    return probes


def l4_rule(ep_sel, group, port, proto="TCP", deny=False, l7=None,
            direction="ingress"):
    block = {"fromEndpoints" if direction.startswith("in") else "toEndpoints":
             [{"matchLabels": {"group": f"g{group}"}}]}
    if port is not None:
        pr = {"ports": [{"port": str(port), "protocol": proto}]}
        if l7:
            pr["rules"] = {"http": l7}
        block["toPorts"] = [pr]
    key = direction if not deny else direction + "Deny"
    return parse_rule({
        "endpointSelector": {"matchLabels": {"app": ep_sel}},
        key: [block]})


# --------------------------------------------------------------------------- #
# randomized sequence parity (the round-4 "done" criterion)
# --------------------------------------------------------------------------- #
class TestRandomizedParity:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_add_remove_refresh_sequences(self, seed):
        rng = random.Random(seed)
        ctx, repo, eps = make_world()
        # a starting rule set so the first build has real geometry
        repo.add([l4_rule("web0", 0, 80),
                  l4_rule("web0", 1, 443, deny=True),
                  l4_rule("web1", 2, None)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        probes = make_probes(ctx, len(eps))

        label_pool = [f"batch={b}" for b in range(6)]
        for step in range(14):
            op = rng.random()
            tag = rng.choice(label_pool)
            if op < 0.55 or len(repo) < 2:
                kind = rng.random()
                port = rng.choice([80, 81, 443, 1000, 8080, None])
                group = rng.randrange(3)
                ep_sel = rng.choice(["web0", "web1"])
                if kind < 0.25:
                    rule = l4_rule(ep_sel, group, port, deny=True)
                elif kind < 0.45 and port is not None:
                    rule = l4_rule(ep_sel, group, port,
                                   l7=[{"method": "GET",
                                        "path": f"/v{step}"}])
                elif kind < 0.6:
                    rule = l4_rule(ep_sel, group, port, proto="UDP")
                else:
                    rule = l4_rule(ep_sel, group, port)
                # tag rules so removal batches have labels to match
                object.__setattr__(rule, "labels",
                                   Labels.parse([f"k8s:{tag}"]))
                repo.add([rule])
            else:
                repo.delete_by_labels(Labels.parse([f"k8s:{tag}"]))

            result = inc.try_update(CTConfig(capacity=1024))
            assert result is not None, \
                f"unexpected fallback at step {step}: {inc.last_fallback}"
            inc_snap, patch, stats = result
            fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
            assert_equivalent(inc_snap, fresh, probes)

    def test_emitted_snapshots_stay_frozen(self):
        """Revision fencing: updating must not mutate previously emitted
        snapshots (COW discipline)."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])
        snap0 = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap0)
        v0 = snap0.image.verdict.copy()
        ms_len0 = len(snap0.policies[0].ingress.mapstate)

        repo.add([l4_rule("web0", 1, 443, deny=True)])
        snap1, _, _ = inc.try_update(CTConfig(capacity=1024))
        v1 = snap1.image.verdict.copy()
        ms_len1 = len(snap1.policies[0].ingress.mapstate)

        repo.add([l4_rule("web0", 2, 8080)])
        inc.try_update(CTConfig(capacity=1024))

        np.testing.assert_array_equal(snap0.image.verdict, v0)
        np.testing.assert_array_equal(snap1.image.verdict, v1)
        assert len(snap0.policies[0].ingress.mapstate) == ms_len0
        assert len(snap1.policies[0].ingress.mapstate) == ms_len1


class TestGeometryPaths:
    def test_port_class_split(self):
        """A new port that bisects an existing class appends columns, not a
        rebuild."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        cols0 = snap.image.verdict.shape[3]
        repo.add([l4_rule("web0", 1, 5000)])   # new boundary pair
        inc_snap, patch, stats = inc.try_update(CTConfig(capacity=1024))
        assert stats.port_class_splits >= 1
        assert inc_snap.image.verdict.shape[3] > cols0
        assert "port_class" in patch.full_tensors
        fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap, fresh, make_probes(ctx, len(eps)))

    def test_identity_class_split(self):
        """A rule targeting one member of a shared class splits it (row
        append + copy), keeping every other member's verdicts intact."""
        ctx, repo, eps = make_world()
        # one rule covering the whole g0 group → its members share a class
        repo.add([l4_rule("web0", 0, 80)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        # now target ONE pod of g0 specifically
        rule = parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web0"}},
            "ingressDeny": [{"fromEndpoints": [
                {"matchLabels": {"peer": "p0"}}]}]})
        repo.add([rule])
        inc_snap, patch, stats = inc.try_update(CTConfig(capacity=1024))
        assert stats.id_class_splits >= 1
        fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap, fresh, make_probes(ctx, len(eps)))

    def test_enforced_flip(self):
        """First rule for a direction flips enforced; removing the last rule
        flips it back — both as patches."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])     # ingress enforced for web0
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        rule = l4_rule("web0", 1, 443, direction="egress")
        object.__setattr__(rule, "labels", Labels.parse(["k8s:eg=1"]))
        repo.add([rule])                       # egress now enforced
        inc_snap, patch, _ = inc.try_update(CTConfig(capacity=1024))
        assert "enforced" in patch.full_tensors
        fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap, fresh, make_probes(ctx, len(eps)))
        repo.delete_by_labels(Labels.parse(["k8s:eg=1"]))
        inc_snap, patch, _ = inc.try_update(CTConfig(capacity=1024))
        fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap, fresh, make_probes(ctx, len(eps)))

    def test_identity_growth_absorbed_removal_gates(self):
        """ISSUE 12: a CIDR rule allocating NEW identities (+ ipcache
        entries) is absorbed incrementally — appended singleton classes +
        an LPM rebuild in the patch, equivalent to a fresh build. Since
        ISSUE 18, identity REMOVAL (the rule deleted, identities
        released) is ALSO absorbed: retirement tombstones the dead
        class's rows and excises the prefix in the same patch."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        cidr = parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web0"}},
            "egress": [{"toCIDR": ["10.5.0.0/16"]}]})
        repo.add([cidr])
        res = inc.try_update(CTConfig(capacity=1024))
        assert res is not None, inc.last_fallback
        inc_snap, patch, stats = res
        assert stats.new_identities == 1
        assert stats.lpm_rebuilt
        assert {"verdict", "id_class_of", "identity_ids",
                "lpm_v4", "lpm_v6"} <= patch.full_tensors
        fresh = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap, fresh, make_probes(ctx, len(eps)))
        # the new CIDR identity resolves through the patched LPM exactly
        # like the fresh build's
        from cilium_tpu.compile.lpm import lpm_lookup_host
        a16, _ = __import__("cilium_tpu.utils.ip", fromlist=["parse_addr"]
                            ).parse_addr("10.5.1.2")
        assert lpm_lookup_host(inc_snap.lpm, a16, False) \
            == lpm_lookup_host(fresh.lpm, a16, False)
        # removal (ISSUE 18): the rule's release retires the identity on
        # the delta path — tombstoned verdict rows + an LPM rebuild in the
        # patch, still equivalent to a fresh build from the shrunk world
        repo.clear()
        res = inc.try_update(CTConfig(capacity=1024))
        assert res is not None, inc.last_fallback
        inc_snap2, _patch2, stats2 = res
        assert stats2.retired_identities == 1
        fresh2 = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        assert_equivalent(inc_snap2, fresh2, make_probes(ctx, len(eps)))


# --------------------------------------------------------------------------- #
# engine integration: the production loop actually uses the patch path
# --------------------------------------------------------------------------- #
def _mk_pkt(src, dst, sp, dp, ep_id, direction, proto=C.PROTO_TCP,
            flags=C.TCP_SYN):
    s16, _ = parse_addr(src)
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, proto, flags, False, ep_id,
                        direction)


class TestEngineIncremental:
    def _world_engine(self, datapath, incremental=True):
        eng = Engine(DaemonConfig(ct_capacity=2048, auto_regen=False,
                                  incremental=incremental),
                     datapath=datapath)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        for i in range(6):
            eng.add_endpoint([f"k8s:peer=p{i}", f"k8s:group=g{i % 2}"],
                             ips=(f"172.16.{i}.5",), ep_id=10 + i)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [{"matchLabels": {"group": "g0"}}],
                         "toPorts": [{"ports": [
                             {"port": "80", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        return eng

    def _traffic(self, slots):
        pkts = []
        for i in range(6):
            for dp in (80, 443, 8080):
                pkts.append(_mk_pkt(f"172.16.{i}.5", "192.168.1.10",
                                    30000 + i, dp, 1, C.DIR_INGRESS))
        return batch_from_records(pkts, slots)

    @pytest.mark.parametrize("backend", ["jit", "fake"])
    def test_incremental_engine_matches_full_engine(self, backend):
        def dp(inc):
            if backend == "jit":
                return JITDatapath(DaemonConfig(ct_capacity=2048,
                                                auto_regen=False))
            return FakeDatapath(DaemonConfig(ct_capacity=2048))
        eng_inc = self._world_engine(dp(True), incremental=True)
        eng_full = self._world_engine(dp(False), incremental=False)
        updates = [
            [{"endpointSelector": {"matchLabels": {"app": "web"}},
              "ingress": [{"fromEndpoints": [
                  {"matchLabels": {"group": "g1"}}],
                  "toPorts": [{"ports": [
                      {"port": "443", "protocol": "TCP"}]}]}]}],
            [{"endpointSelector": {"matchLabels": {"app": "web"}},
              "ingressDeny": [{"fromEndpoints": [
                  {"matchLabels": {"peer": "p0"}}]}]}],
            [{"endpointSelector": {"matchLabels": {"app": "web"}},
              "ingress": [{"toPorts": [{
                  "ports": [{"port": "8080", "protocol": "TCP"}],
                  "rules": {"http": [{"method": "GET",
                                      "path": "/api"}]}}]}]}],
        ]
        now = 1000
        for docs in updates:
            eng_inc.apply_policy(docs)
            eng_full.apply_policy(docs)
            eng_inc.regenerate()
            eng_full.regenerate()
            slots = eng_inc.active.snapshot.ep_slot_of
            assert slots == eng_full.active.snapshot.ep_slot_of
            batch = self._traffic(slots)
            out_i = eng_inc.classify(dict(batch), now=now)
            out_f = eng_full.classify(dict(batch), now=now)
            for k in ("allow", "reason", "status", "remote_identity",
                      "redirect"):
                np.testing.assert_array_equal(
                    np.asarray(out_f[k]), np.asarray(out_i[k]), k)
            now += 50
        # the incremental path must actually have been taken
        rendered = eng_inc.metrics.render_prometheus()
        assert "regen_incremental_total" in rendered

    def test_incremental_sharded_backend(self):
        """place_patch through the meshed backend: device-side row updates
        on a sharded verdict tensor."""
        eng_inc = self._world_engine(
            JITDatapath(DaemonConfig(ct_capacity=2048, auto_regen=False,
                                     n_shards=2, rule_shards=2)),
            incremental=True)
        eng_full = self._world_engine(
            FakeDatapath(DaemonConfig(ct_capacity=2048)), incremental=False)
        eng_inc.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingressDeny": [{"fromEndpoints": [
                {"matchLabels": {"peer": "p2"}}]}]}])
        eng_full.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingressDeny": [{"fromEndpoints": [
                {"matchLabels": {"peer": "p2"}}]}]}])
        eng_inc.regenerate()
        eng_full.regenerate()
        slots = eng_inc.active.snapshot.ep_slot_of
        batch = self._traffic(slots)
        out_i = eng_inc.classify(dict(batch), now=500)
        out_f = eng_full.classify(dict(batch), now=500)
        for k in ("allow", "reason", "status", "remote_identity"):
            np.testing.assert_array_equal(
                np.asarray(out_f[k]), np.asarray(out_i[k]), k)


class TestEndpointGate:
    def test_add_endpoint_falls_back_to_full_build(self):
        """Regression (round-5 review): a new endpoint reusing an existing
        identity (no ipcache change) must still invalidate the incremental
        path — the snapshot's ep_slot space changed."""
        eng = Engine(DaemonConfig(ct_capacity=1024, auto_regen=False,
                                  incremental=True),
                     datapath=FakeDatapath(DaemonConfig(ct_capacity=1024)))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [
                {"port": "80", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        # same labels → identity refcount reuse; no IP → no ipcache bump
        eng.add_endpoint(["k8s:app=web"], ep_id=2)
        snap = eng.regenerate().snapshot
        assert 2 in snap.ep_slot_of, "new endpoint missing from snapshot"
        eng.remove_endpoint(2)
        snap = eng.regenerate().snapshot
        assert 2 not in snap.ep_slot_of


class TestMoreGates:
    def test_enforcement_mode_change_gates(self):
        """Runtime enforcement-mode change (PATCH /v1/config path) must not
        be absorbed by the incremental compiler — it rewrites every plane."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        ctx.enforcement_mode = C.ENFORCEMENT_NEVER
        assert inc.try_update(CTConfig(capacity=1024)) is None
        assert inc.last_fallback == "enforcement-mode-changed"

    def test_endpoint_gate_via_param(self):
        """The endpoints kwarg drives the endpoint-set gate."""
        ctx, repo, eps = make_world()
        repo.add([l4_rule("web0", 0, 80)])
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=1024))
        inc = IncrementalCompiler(repo, ctx, eps, snap)
        grown = list(eps) + [Endpoint(ep_id=99, labels=eps[0].labels,
                                      identity_id=eps[0].identity_id)]
        assert inc.try_update(CTConfig(capacity=1024),
                              endpoints=grown) is None
        assert inc.last_fallback == "endpoint-set-changed"
        # unchanged set still patches
        repo.add([l4_rule("web0", 1, 443)])
        assert inc.try_update(CTConfig(capacity=1024),
                              endpoints=eps) is not None
