"""CLI tests: every cilium-dbg-analog command against a real checkpoint
state dir, plus the jax-free-import guarantee for the inspection path."""

import json
import subprocess
import sys

import pytest

from cilium_tpu.cli.main import main as cli_main
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.model.services import Backend, Frontend, Service
from cilium_tpu.runtime.checkpoint import save
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
    "egressDeny": [{"toCIDR": ["10.66.0.0/16"]}],
    "ingress": [{"toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                              "rules": {"http": [
                                  {"method": "GET", "path": "/api"}]}}]}],
}]


@pytest.fixture(scope="module")
def state_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("state")
    eng = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(POLICY)
    eng.upsert_service(Service(
        name="api", namespace="prod",
        frontends=(Frontend("172.30.0.1", 443, C.PROTO_TCP),),
        lb_backends=(Backend("10.7.0.1", 443),)))
    s16, _ = parse_addr("192.168.1.10")
    d16, _ = parse_addr("10.1.2.3")
    eng.classify(batch_from_records(
        [PacketRecord(s16, d16, 40000, 443, C.PROTO_TCP, C.TCP_SYN, False,
                      1, C.DIR_EGRESS)],
        eng.active.snapshot.ep_slot_of), now=100)
    save(eng, str(d))
    return str(d)


def run_cli(capsys, *argv):
    rc = cli_main(list(argv))
    out = capsys.readouterr().out
    return rc, out


def run_json(capsys, *argv):
    rc, out = run_cli(capsys, *argv, "-o", "json")
    assert rc == 0, out
    return json.loads(out)


class TestCLI:
    def test_version(self, capsys):
        rc, out = run_cli(capsys, "version")
        assert rc == 0 and "version" in out

    def test_status(self, state_dir, capsys):
        doc = run_json(capsys, "status", "--state-dir", state_dir)
        assert doc["endpoints"] == 1
        assert doc["services"] == 1
        assert doc["conntrack"]["live"] == 1

    def test_endpoint_list_get(self, state_dir, capsys):
        doc = run_json(capsys, "endpoint", "list", "--state-dir", state_dir)
        assert doc[0]["ep_id"] == 1 and "192.168.1.10" in doc[0]["ips"]
        doc = run_json(capsys, "endpoint", "get", "--state-dir", state_dir,
                       "1")
        assert doc["egress"]["enforced"] is True
        assert doc["egress"]["entries"] >= 2

    def test_identity_list(self, state_dir, capsys):
        doc = run_json(capsys, "identity", "list", "--state-dir", state_dir)
        ids = {e["id"] for e in doc}
        assert C.IDENTITY_WORLD in ids
        assert any(e["id"] >= C.CLUSTER_IDENTITY_BASE for e in doc)

    def test_policy_get(self, state_dir, capsys):
        doc = run_json(capsys, "policy", "get", "--state-dir", state_dir)
        assert doc == POLICY

    def test_policy_trace_allow(self, state_dir, capsys):
        doc = run_json(capsys, "policy", "trace", "--state-dir", state_dir,
                       "--ep", "1", "--remote", "10.1.2.3",
                       "--dport", "443", "--proto", "TCP")
        assert doc["verdict"] == "ALLOWED"
        assert doc["matched_key"] is not None
        assert doc["derived_from"]

    def test_policy_trace_deny_precedence(self, state_dir, capsys):
        doc = run_json(capsys, "policy", "trace", "--state-dir", state_dir,
                       "--ep", "1", "--remote", "10.66.1.1",
                       "--dport", "443")
        assert doc["verdict"] == "DENIED"
        assert doc["reason"] == "explicit deny rule"

    def test_policy_trace_default_deny(self, state_dir, capsys):
        doc = run_json(capsys, "policy", "trace", "--state-dir", state_dir,
                       "--ep", "1", "--remote", "8.8.8.8", "--dport", "22")
        assert doc["verdict"] == "DENIED"
        assert doc["matched_key"] is None

    def test_policy_trace_l7(self, state_dir, capsys):
        doc = run_json(capsys, "policy", "trace", "--state-dir", state_dir,
                       "--ep", "1", "--direction", "ingress",
                       "--remote", "8.8.8.8", "--dport", "80")
        assert doc["verdict"] == "ALLOWED"
        assert "L7" in doc["reason"]
        assert doc["l7_rules"]

    def test_service_list(self, state_dir, capsys):
        doc = run_json(capsys, "service", "list", "--state-dir", state_dir)
        assert doc[0]["name"] == "prod/api"
        assert any("172.30.0.1:443" in f for f in doc[0]["frontends"])

    def test_ct_list(self, state_dir, capsys):
        doc = run_json(capsys, "ct", "list", "--state-dir", state_dir,
                       "--now", "100")
        assert doc["live"] == 1
        e = doc["entries"][0]
        assert e["src"] == "192.168.1.10" and e["dst"] == "10.1.2.3"
        assert e["dport"] == 443 and e["proto"] == "TCP"

    def test_map_get(self, state_dir, capsys):
        doc = run_json(capsys, "map", "get", "--state-dir", state_dir,
                       "--ep", "1")
        actions = {e["action"] for e in doc}
        assert {"ALLOW", "DENY", "REDIRECT"} <= actions

    def test_text_output(self, state_dir, capsys):
        rc, out = run_cli(capsys, "policy", "trace", "--state-dir", state_dir,
                          "--ep", "1", "--remote", "10.66.1.1",
                          "--dport", "443")
        assert rc == 0 and "Final verdict: DENIED" in out

    def test_unknown_endpoint(self, state_dir, capsys):
        rc = cli_main(["endpoint", "get", "--state-dir", state_dir, "99"])
        assert rc == 1


class TestObservability:
    @pytest.fixture()
    def obs_engine(self, tmp_path):
        eng = Engine(DaemonConfig(
            ct_capacity=4096, auto_regen=False, flowlog_mode="all",
            flowlog_path=str(tmp_path / "flows.jsonl"),
            metrics_path=str(tmp_path / "metrics.prom")))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        s16, _ = parse_addr("192.168.1.10")
        pkts = [
            PacketRecord(s16, parse_addr("10.1.2.3")[0], 40000, 443,
                         C.PROTO_TCP, C.TCP_SYN, False, 1, C.DIR_EGRESS),
            PacketRecord(s16, parse_addr("10.1.2.4")[0], 40001, 80,
                         C.PROTO_TCP, C.TCP_SYN, False, 1, C.DIR_EGRESS),
        ]
        eng.classify(batch_from_records(pkts, eng.active.snapshot.ep_slot_of),
                     now=100)
        eng.flush_observability()
        return eng, tmp_path

    def test_flowlog_sink_and_monitor(self, obs_engine, capsys):
        eng, tmp_path = obs_engine
        path = str(tmp_path / "flows.jsonl")
        assert sum(1 for _ in open(path)) == 2
        rc = cli_main(["monitor", "--flowlog-path", path, "-o", "json"])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 2
        recs = [json.loads(x) for x in out]
        assert {r["verdict"] for r in recs} == {"FORWARDED", "DROPPED"}
        # filters
        rc = cli_main(["monitor", "--flowlog-path", path,
                       "--verdict", "DROPPED", "-o", "json"])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1 and json.loads(out[0])["dst_port"] == 80
        rc = cli_main(["monitor", "--flowlog-path", path,
                       "--ip", "10.1.2.3"])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1 and "FORWARDED" in out[0]

    def test_flowlog_ring_filters(self, obs_engine):
        eng, _ = obs_engine
        assert len(eng.flowlog.tail(verdict="DROPPED")) == 1
        assert len(eng.flowlog.tail(verdict="FORWARDED")) == 1

    def test_metrics_file_and_cli(self, obs_engine, capsys):
        eng, tmp_path = obs_engine
        path = str(tmp_path / "metrics.prom")
        rc = cli_main(["metrics", "--metrics-path", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ciliumtpu_packets_total 2" in out
        assert 'reason="POLICY"' in out

    def test_missing_files_error(self, capsys):
        assert cli_main(["monitor", "--flowlog-path", "/nope.jsonl"]) == 1
        assert cli_main(["metrics", "--metrics-path", "/nope.prom"]) == 1


class TestEnforcementModePersistence:
    def test_trace_uses_checkpointed_enforcement(self, tmp_path, capsys):
        """'always' mode must survive into the CLI: an unselected endpoint is
        default-denied by the datapath, and trace must agree (the parity
        tool may not contradict the datapath)."""
        eng = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False,
                                  enforcement_mode="always"))
        eng.add_endpoint(["k8s:app=lonely"], ips=("192.168.3.1",), ep_id=1)
        eng.active
        save(eng, str(tmp_path / "s"))
        doc = run_json(capsys, "policy", "trace", "--state-dir",
                       str(tmp_path / "s"), "--ep", "1",
                       "--remote", "8.8.8.8", "--dport", "443")
        assert doc["enforced"] is True
        assert doc["verdict"] == "DENIED"
        doc = run_json(capsys, "status", "--state-dir", str(tmp_path / "s"))
        assert doc["enforcement_mode"] == "always"


class TestJaxFree:
    def test_inspection_never_imports_jax(self, state_dir):
        """The CLI inspection path must not import jax (no device claim):
        run in a subprocess with jax poisoned."""
        code = (
            "import sys; sys.modules['jax'] = None\n"
            "from cilium_tpu.cli.main import main\n"
            f"rc = main(['status', '--state-dir', {state_dir!r}])\n"
            "assert rc == 0\n"
            f"rc = main(['policy', 'trace', '--state-dir', {state_dir!r},"
            "'--ep', '1', '--remote', '10.1.2.3', '--dport', '443'])\n"
            "assert rc == 0\n"
            "print('JAXFREE-OK')\n"
        )
        import pathlib
        repo_root = str(pathlib.Path(__file__).resolve().parents[1])
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           cwd=repo_root)
        assert "JAXFREE-OK" in r.stdout, r.stdout + r.stderr
