"""Sharded serving tests: the multi-chip zero-copy path (PR 6).

Unit tests drive a raw sharded Pipeline against an echo dispatch to pin the
steered staging ring mechanics: rows land grouped in per-shard segments,
per-ticket verdicts un-steer back to FIFO submission order, a skewed
submission sheds with ``reason="steer_overflow"`` instead of crashing the
worker, pre-binned ``_shard`` columns skip the hash, and reused segment
tails cannot leak stale rows.

Integration tests run the same submissions through 1-shard and 8-shard
JITDatapath pipelines (CPU host-platform mesh, conftest provisions the 8
fake devices) and the oracle-backed FakeDatapath serial path, asserting
bit-identical verdicts in FIFO order — including partial buckets, a
deadline-shed submission, CT continuity across drained phases (the
direction-normalized steer must keep both directions of a flow on one
shard) and a mid-soak ``place_patch``. A tracemalloc check pins the steered
staging path allocation-free in steady state, and the slow soak
(``make multichip-smoke``) pushes 10k frames through the mock-ring feeder
into an 8-shard mesh with ``shim.rx_ring`` faults armed, asserting the
steered path never fell back to an allocating pack
(``datapath_pack_fallback_total{reason="steered"} == 0``).
"""

import gc
import os
import random
import time
import tracemalloc

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records, empty_batch
from cilium_tpu.pipeline import Pipeline, PipelineDeadlineExceeded, \
    PipelineDrop
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath, JITDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from tests.test_datapath import FIXTURE_RULES, pkt
from tests.test_pipeline import EchoDispatch, sub_batch

#: full out geometry — comparable between two JIT backends (1-shard vs
#: 8-shard must be bit-identical in every column)
OUT_KEYS = ("allow", "reason", "status", "remote_identity", "redirect",
            "svc", "nat_dst", "nat_dport", "rnat", "rnat_src", "rnat_sport")
#: keys comparable between the JIT kernel and the oracle-backed fake (the
#: kernel reports the post-LB tuple in nat_* for non-service flows where
#: the oracle reports zeros — same convention as test_parallel's
#: TestShardedEngine)
ORACLE_KEYS = ("allow", "reason", "status", "remote_identity", "redirect",
               "svc", "rnat")


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


class ViewEchoDispatch(EchoDispatch):
    """EchoDispatch with the sharded dispatch signature (the pipeline
    passes the bucket's steer revision) that also snapshots each
    dispatched batch view (the staging buffer is recycled, so layout
    assertions need a copy)."""

    def __init__(self):
        super().__init__()
        self.views = []
        self.steer_revs = []

    def __call__(self, batch, now, steer_rev=None):
        fin = super().__call__(batch, now)
        self.views.append({k: np.asarray(v).copy()
                           for k, v in batch.items()})
        self.steer_revs.append(steer_rev)
        return fin


def shard_mod(n_shards):
    """Deterministic unit-test steering: shard by sport (the row tag the
    echo dispatch echoes back), so tests can predict each row's segment."""
    def fn(batch):
        return np.asarray(batch["sport"]) % n_shards
    return fn


def sharded_pipeline(d, n_shards=4, **kw):
    kw.setdefault("max_bucket", 16)
    kw.setdefault("min_bucket", 1)
    kw.setdefault("flush_ms", 5.0)
    kw.setdefault("shard_fn", shard_mod(n_shards))
    kw.setdefault("shard_headroom", 4)
    return Pipeline(d, n_shards=n_shards, **kw)


# --------------------------------------------------------------------------- #
# Unit: the steered staging ring
# --------------------------------------------------------------------------- #
class TestSteeredStaging:
    def test_rows_grouped_by_shard_and_fifo_unsteer(self):
        """Dispatched buckets carry rows grouped into per-shard segments;
        each ticket's verdicts come back un-steered, in submission row
        order (the slice dst_rows gather)."""
        d = ViewEchoDispatch()
        pl = sharded_pipeline(d, n_shards=4)
        try:
            seg = pl.stats()["shard_capacity"]
            t1 = pl.submit(sub_batch(6, start=100))   # sports 100..105
            t2 = pl.submit(sub_batch(5, start=200))   # sports 200..204
            assert pl.drain(timeout=10)
            # FIFO per ticket, original row order restored
            assert t1.result(timeout=5)["reason"].tolist() == \
                list(range(100, 106))
            assert t2.result(timeout=5)["reason"].tolist() == \
                list(range(200, 205))
            # one coalesced steered bucket; rows grouped by sport % 4
            assert len(d.batches) == 1
            view_sports = d.views[0]["sport"]
            view_valid = d.views[0]["valid"]
            assert view_valid.shape[0] == 4 * seg    # the full steered shape
            for row in np.nonzero(view_valid)[0]:
                assert view_sports[row] % 4 == row // seg
            # arrival order preserved inside each shard segment
            for s in range(4):
                seg_sports = view_sports[s * seg:(s + 1) * seg][
                    view_valid[s * seg:(s + 1) * seg]]
                in_100s = [x for x in seg_sports if x < 200]
                in_200s = [x for x in seg_sports if x >= 200]
                assert in_100s == sorted(in_100s)
                assert in_200s == sorted(in_200s)
                assert seg_sports.tolist() == in_100s + in_200s
        finally:
            pl.close(timeout=5)

    def test_steer_batch_out_reuse_equivalent(self):
        """steer_batch(out=) into a reused buffer is byte-identical to the
        allocating steer, including after a larger previous use (stale
        rows restored to empty-batch defaults)."""
        from cilium_tpu.kernels.records import empty_batch as eb
        from cilium_tpu.parallel.mesh import steer_batch
        big = sub_batch(16, start=100)
        small = sub_batch(4, start=200)
        buf = eb(4 * 8)
        steer_batch(big, 4, per_shard=8, out=buf)
        for b in (small, big):
            want, ws, _ = steer_batch(b, 4, per_shard=8)
            got, gs, _ = steer_batch(b, 4, per_shard=8, out=buf)
            assert got is buf
            np.testing.assert_array_equal(ws, gs)
            for k in want:
                np.testing.assert_array_equal(want[k], got[k], k)
        with pytest.raises(ValueError):
            steer_batch(big, 4, per_shard=8, out=eb(8))   # too few rows

    def test_no_direct_bypass_when_sharded(self):
        """A bucket-shaped submission still stages (its arbitrary row
        order carries no shard placement) — the 'direct' flush reason can
        never fire on a sharded pipeline."""
        d = ViewEchoDispatch()
        pl = sharded_pipeline(d, n_shards=4, max_bucket=16, min_bucket=16)
        try:
            t = pl.submit(sub_batch(16, start=300))
            assert pl.drain(timeout=10)
            assert t.result(timeout=5)["reason"].tolist() == \
                list(range(300, 316))
            assert pl.stats()["flush_reasons"]["direct"] == 0
        finally:
            pl.close(timeout=5)

    def test_steer_overflow_sheds_with_reason(self):
        """A submission more skewed than the per-shard segment capacity is
        shed with reason="steer_overflow" (PipelineDrop, retryable) — the
        old steer_batch per_shard ValueError would have crashed the worker
        into a watchdog restart. The worker survives and keeps serving."""
        d = ViewEchoDispatch()
        pl = sharded_pipeline(d, n_shards=4, max_bucket=16,
                              shard_headroom=1)
        try:
            seg = pl.stats()["shard_capacity"]
            skewed = sub_batch(16, start=400)
            skewed["sport"][:] = 400            # every row → shard 0
            assert seg < 16
            t = pl.submit(skewed)
            with pytest.raises(PipelineDrop):
                t.result(timeout=5)
            s = pl.stats()
            assert s["shed_reasons"] == {"steer_overflow": 1}
            assert pl.metrics.counters[
                'pipeline_shed_total{reason="steer_overflow"}'] == 1
            assert s["restarts"] == 0           # no watchdog involvement
            ok = pl.submit(sub_batch(4, start=500))
            assert pl.drain(timeout=10)
            assert ok.result(timeout=5)["reason"].tolist() == \
                list(range(500, 504))
        finally:
            pl.close(timeout=5)

    def test_skewed_flood_sheds_one_shard_others_keep_serving(self):
        """Adversarial skew (ISSUE 10 satellite): a flood whose flow hash
        lands predominantly in ONE shard segment sheds with
        reason="steer_overflow" FIFO-safely, while interleaved balanced
        traffic keeps serving through the other shards with verdict
        parity (the echo contract) for every surviving row."""
        d = ViewEchoDispatch()
        pl = sharded_pipeline(d, n_shards=4, max_bucket=16,
                              shard_headroom=1)
        try:
            seg = pl.stats()["shard_capacity"]
            assert seg < 16
            outcomes = []                     # (ticket, kind) in FIFO order
            for i in range(6):
                if i % 2 == 0:
                    flood = sub_batch(16, start=1000 + 100 * i)
                    flood["sport"][:] = 1000 + 100 * i   # all → one shard
                    outcomes.append((pl.submit(flood), "flood"))
                else:
                    legit = sub_batch(4, start=2000 + 100 * i)
                    outcomes.append((pl.submit(legit), "legit"))
            assert pl.drain(timeout=10)
            for t, kind in outcomes:
                if kind == "flood":
                    with pytest.raises(PipelineDrop):
                        t.result(timeout=5)
                else:
                    out = t.result(timeout=5)
                    # echo parity for survivors: each row's own sport back
                    start = int(out["reason"][0])
                    assert out["reason"].tolist() == \
                        list(range(start, start + 4))
            s = pl.stats()
            assert s["shed_reasons"] == {"steer_overflow": 3}
            assert pl.metrics.counters[
                'pipeline_shed_total{reason="steer_overflow"}'] == 3
            assert s["restarts"] == 0         # the worker never died
            # the surviving (balanced) rows actually spread across shards
            rows_total = s["shard_rows_total"]
            assert sum(rows_total) == 12 and max(rows_total) < 12
        finally:
            pl.close(timeout=5)

    def test_prebinned_shard_column_skips_hash(self):
        """A producer that pre-binned (the feeder's harvest hash) rides
        the ``_shard`` column (shard+1); shard_fn is never called."""
        d = ViewEchoDispatch()
        calls = []

        def counting_fn(batch):
            calls.append(1)
            return np.asarray(batch["sport"]) % 4

        pl = sharded_pipeline(d, n_shards=4, shard_fn=counting_fn)
        try:
            seg = pl.stats()["shard_capacity"]
            b = sub_batch(8, start=600)
            b["_shard"] = (np.arange(600, 608, dtype=np.int32) % 4) + 1
            t = pl.submit(b)
            assert pl.drain(timeout=10)
            assert t.result(timeout=5)["reason"].tolist() == \
                list(range(600, 608))
            assert not calls                    # pre-binned: no re-hash
            view = d.views[0]
            for row in np.nonzero(view["valid"])[0]:
                assert view["sport"][row] % 4 == row // seg
            # a bogus pre-bin (out-of-range shard) falls back to shard_fn
            b2 = sub_batch(4, start=700)
            b2["_shard"] = np.full(4, 99, dtype=np.int32)
            t2 = pl.submit(b2)
            assert pl.drain(timeout=10)
            assert t2.result(timeout=5)["reason"].tolist() == \
                list(range(700, 704))
            assert calls
        finally:
            pl.close(timeout=5)

    def test_prebinned_shard_revision_gate(self):
        """A pre-bin is only trusted while its binning revision is still
        active: a regen between harvest and stage-write can change the LB
        tables (and with them the post-DNAT steer hash), so a stale bin
        re-hashes through shard_fn instead of mis-steering."""
        from cilium_tpu.pipeline.scheduler import shard_bin_encode
        d = ViewEchoDispatch()
        calls = []
        rev = [7]

        def counting_fn(batch):
            calls.append(1)
            return np.asarray(batch["sport"]) % 4

        pl = sharded_pipeline(d, n_shards=4, shard_fn=counting_fn,
                              shard_rev_fn=lambda: rev[0])
        try:
            b = sub_batch(8, start=600)
            b["_shard"] = shard_bin_encode(
                np.arange(600, 608, dtype=np.int64) % 4, 7)
            t = pl.submit(b)
            assert pl.drain(timeout=10)
            t.result(timeout=5)
            assert not calls               # fresh bin: trusted
            rev[0] = 8                     # "regen" supersedes the bin
            b2 = sub_batch(4, start=700)
            b2["_shard"] = shard_bin_encode(
                np.arange(700, 704, dtype=np.int64) % 4, 7)
            t2 = pl.submit(b2)
            assert pl.drain(timeout=10)
            assert t2.result(timeout=5)["reason"].tolist() == \
                list(range(700, 704))
            assert calls                   # stale bin: re-hashed
        finally:
            pl.close(timeout=5)

    def test_steer_revision_rides_into_dispatch(self):
        """The bucket's steer revision reaches dispatch_fn: a
        single-revision bucket carries that revision, a bucket whose
        riders were steered under different revisions (a regen landed
        mid-coalesce) carries the 'mixed' sentinel — the engine re-steers
        those through the datapath instead of trusting a stale layout."""
        d = ViewEchoDispatch()
        rev = [7]
        pl = sharded_pipeline(d, n_shards=4, flush_ms=60_000.0,
                              shard_rev_fn=lambda: rev[0])
        try:
            pl.submit(sub_batch(3, start=100))
            assert pl.drain(timeout=10)
            assert d.steer_revs == [7]
            pl.submit(sub_batch(3, start=200))
            end = time.time() + 5           # rider 200 staged under rev 7
            while pl.stats()["staged_rows"] < 3 and time.time() < end:
                time.sleep(0.005)
            rev[0] = 8                      # regen between riders
            pl.submit(sub_batch(3, start=300))
            assert pl.drain(timeout=10)
            assert d.steer_revs == [7, -2]  # mixed bucket flagged
        finally:
            pl.close(timeout=5)

    def test_flush_shed_masks_steered_rows(self):
        """A staged rider whose deadline expires before the bucket
        dispatches is valid-masked out of its scattered rows; co-staged
        riders still serve in FIFO order."""
        d = ViewEchoDispatch()
        pl = sharded_pipeline(d, n_shards=4, flush_ms=60_000.0)
        try:
            doomed = pl.submit(sub_batch(3, start=10), deadline_ms=30)
            keeper = pl.submit(sub_batch(3, start=20))
            time.sleep(0.08)
            assert pl.drain(timeout=5)
            with pytest.raises(PipelineDeadlineExceeded):
                doomed.result(timeout=1)
            assert keeper.result(timeout=1)["reason"].tolist() == \
                [20, 21, 22]
            assert sorted(d.batches[0]) == [20, 21, 22]
            assert pl.stats()["shed_reasons"] == {"flush": 1}
        finally:
            pl.close(timeout=5)

    def test_segment_tails_reset_between_reuses(self):
        """A segment written full by one flush must not leak stale rows
        into a later, smaller flush from the same staging slot — the
        per-segment dirty watermark restores empty-batch defaults."""
        d = ViewEchoDispatch()
        # inflight=1 → 2 staging buffers; two drained rounds reuse slot 0
        pl = sharded_pipeline(d, n_shards=2, max_bucket=8, inflight=1)
        try:
            seg = pl.stats()["shard_capacity"]
            for start in (800, 900):            # fills both shards
                t = pl.submit(sub_batch(8, start=start))
                assert pl.drain(timeout=10)
                t.result(timeout=5)
            small = pl.submit(sub_batch(2, start=1000))
            assert pl.drain(timeout=10)
            small.result(timeout=5)
            # find the dispatch view of the small flush: exactly 2 valid
            view = d.views[-1]
            assert int(view["valid"].sum()) == 2
            # every invalid row is back at empty-batch defaults
            inv = ~view["valid"]
            assert not view["sport"][inv].any()
            assert (view["http_method"][inv] == C.HTTP_METHOD_ANY).all()
            assert view["valid"].shape[0] == 2 * seg
        finally:
            pl.close(timeout=5)


# --------------------------------------------------------------------------- #
# Integration: 1-shard vs 8-shard JIT pipelines vs the oracle-backed serial
# path — the sharded parity suite
# --------------------------------------------------------------------------- #
def jit_pipeline_engine(n_shards, **kw):
    kw.setdefault("ct_capacity", 2048)
    kw.setdefault("auto_regen", False)
    kw.setdefault("batch_size", 128)
    kw.setdefault("pipeline_flush_ms", 1.0)
    kw.setdefault("flowlog_mode", "none")
    cfg = DaemonConfig(n_shards=n_shards, **kw)
    eng = Engine(cfg, datapath=JITDatapath(cfg))
    _world(eng)
    return eng


def fake_serial_engine(**kw):
    kw.setdefault("ct_capacity", 2048)
    kw.setdefault("auto_regen", False)
    kw.setdefault("flowlog_mode", "none")
    cfg = DaemonConfig(**kw)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    _world(eng)
    return eng


def _world(eng):
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.add_endpoint(["k8s:role=fe"], ips=("192.168.1.30",), ep_id=3)
    eng.apply_policy(FIXTURE_RULES)
    eng.regenerate()


def _mk_phase(slot_of, n_chunks, sizes, seed, revisit=None):
    """Sub-full chunks of fresh flows (unique per row — the coalescing-
    legal regime), padded with invalid tails (partial buckets). With
    ``revisit`` (list of (sport, dport, dst, flags)) the first chunk
    re-touches flows established in an earlier, drained phase — CT
    continuity across the steered path."""
    rng = np.random.default_rng(seed)
    chunks = []
    for c in range(n_chunks):
        recs = []
        if revisit and c == 0:
            recs.extend(pkt("192.168.1.10", dst, sp, dp, flags=flags)
                        for sp, dp, dst, flags in revisit)
        n = sizes[c % len(sizes)]
        for r in range(n):
            dp = int(rng.choice([443, 443, 80, 22]))
            dst = f"10.{rng.integers(0, 2)}.2.{rng.integers(1, 250)}"
            sp = 42000 + seed * 1000 + c * 64 + r
            recs.append(pkt("192.168.1.10", dst, sp, dp))
        chunks.append(batch_from_records(recs, slot_of,
                                         pad_to=len(recs) + (c % 3)))
    return chunks


def _run_phase(serial, pipes, chunks, now0):
    """Classify ``chunks`` serially (the oracle-backed truth) and submit
    them to every pipelined engine: each pipeline must match the oracle on
    ORACLE_KEYS, and the pipelines must match EACH OTHER bit-identically
    on the full out geometry (1-shard vs 8-shard). Returns the serial
    outs."""
    outs = [serial.classify(dict(ch), now=now0 + i)
            for i, ch in enumerate(chunks)]
    tickets = {id(p): [p.submit(dict(ch), now=now0 + i)
                       for i, ch in enumerate(chunks)] for p in pipes}
    got = {}
    for p in pipes:
        assert p.drain(timeout=60)
        got[id(p)] = [t.result(timeout=10) for t in tickets[id(p)]]
        for i, (g, want) in enumerate(zip(got[id(p)], outs)):
            for k in ORACLE_KEYS:
                np.testing.assert_array_equal(
                    g[k], want[k],
                    err_msg=f"chunk {i} field {k} diverged from oracle "
                            f"(shards={p.datapath.pipeline_shards})")
    ref = pipes[0]
    for p in pipes[1:]:
        for i, (g, r) in enumerate(zip(got[id(p)], got[id(ref)])):
            for k in OUT_KEYS:
                np.testing.assert_array_equal(
                    g[k], r[k],
                    err_msg=f"chunk {i} field {k}: "
                            f"{p.datapath.pipeline_shards}-shard != "
                            f"{ref.datapath.pipeline_shards}-shard")
    return outs


class TestShardedParity:
    def test_8shard_pipeline_bit_identical_to_serial(self):
        """The acceptance pin: the same submission stream through the
        1-shard and the 8-shard pipelines produces verdicts bit-identical
        to the serial single-chip path — partial buckets, a deadline-shed
        submission, CT continuity across drained phases (direction-stable
        steering), and a mid-soak place_patch included."""
        serial = fake_serial_engine()
        eng1 = jit_pipeline_engine(1)
        eng8 = jit_pipeline_engine(8)
        pipes = [eng1, eng8]
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            # phase 1: fresh flows, odd sizes + invalid padding
            ch1 = _mk_phase(slot_of, 6, (1, 5, 17, 32, 9, 23), seed=1)
            _run_phase(serial, pipes, ch1, now0=1000)

            # a deadline-shed submission: both pipelines shed it, the
            # serial path simply never sees it — parity must survive
            stale = batch_from_records(
                [pkt("192.168.1.10", "10.0.2.9", 47999, 443)], slot_of)
            for p in pipes:
                t = p.submit(dict(stale), now=1100, deadline_ms=0.001)
                with pytest.raises(PipelineDeadlineExceeded):
                    t.result(timeout=10)

            # phase 2: revisit established flows in BOTH directions — the
            # direction-normalized steer must land forward and reply
            # packets on the SAME shard or the CT hit (and therefore the
            # verdict) diverges from the serial single-chip path
            est = [pkt("192.168.1.10", "10.0.2.7", 48100 + i, 443)
                   for i in range(4)]
            pre = batch_from_records(est, slot_of)
            outs = _run_phase(serial, pipes, [pre], now0=1200)
            assert outs[0]["allow"].all()
            reply = [pkt("10.0.2.7", "192.168.1.10", 443, 48100 + i,
                         flags=C.TCP_ACK, direction=C.DIR_INGRESS)
                     for i in range(4)]
            fwd_ack = [(48100 + i, 443, "10.0.2.7", C.TCP_ACK)
                       for i in range(2)]
            ch2 = [batch_from_records(reply, slot_of, pad_to=len(reply) + 2)]
            ch2 += _mk_phase(slot_of, 3, (7, 13, 2), seed=2,
                             revisit=fwd_ack)
            outs2 = _run_phase(serial, pipes, ch2, now0=1210)
            # the revisits really exercised CT: replies hit as REPLY,
            # forward ACKs as ESTABLISHED (not silently all-NEW)
            assert (np.asarray(outs2[0]["status"])[:len(reply)]
                    == int(C.CTStatus.REPLY)).all()
            assert (np.asarray(outs2[1]["status"])[:2]
                    == int(C.CTStatus.ESTABLISHED)).all()

            # mid-soak policy update through the incremental patch path
            patch_rule = [{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egressDeny": [{"toCIDR": ["10.1.128.0/17"]}],
            }]
            for e in (serial, eng1, eng8):
                e.apply_policy(patch_rule)
                e.regenerate()

            ch3 = _mk_phase(slot_of, 4, (11, 3, 29, 6), seed=3)
            _run_phase(serial, pipes, ch3, now0=1400)

            # CT occupancy identical across all three backends
            live = serial.ct_stats(now=1500)["live"]
            assert eng1.ct_stats(now=1500)["live"] == live
            assert eng8.ct_stats(now=1500)["live"] == live

            # the steered serving path packed in place — zero allocating
            # fallbacks attributable to the sharded layout
            ps = eng8.datapath.pack_stats
            assert ps["pack_fallback_steered"] == 0
            assert ps["pack_fallback_disabled"] == 0
            assert ps["pack_inplace"] > 0
            assert eng8.pipeline_stats()["n_shards"] == 8
        finally:
            for e in (serial, eng1, eng8):
                e.stop()

    def test_sharded_engine_health_carries_shards(self):
        eng = jit_pipeline_engine(2)
        try:
            eng.submit(batch_from_records(
                [pkt("192.168.1.10", "10.0.2.3", 40000, 443)],
                eng.active.snapshot.ep_slot_of), now=100)
            assert eng.drain(timeout=30)
            h = eng.health()
            assert h["pipeline"]["shards"] == 2
            text = eng.render_metrics()
            assert "ciliumtpu_pipeline_mesh_shards 2" in text
            assert 'ciliumtpu_datapath_pack_fallback_total' \
                   '{reason="steered"}' not in text      # none happened
            assert "ciliumtpu_datapath_pack_inplace_total" in text
        finally:
            eng.stop()

    def test_zero_copy_disabled_still_bit_identical(self):
        """zero_copy_ingest=False falls back to the legacy dict dispatch —
        counted under reason="disabled" — with identical verdicts."""
        serial = fake_serial_engine()
        eng = jit_pipeline_engine(4, zero_copy_ingest=False)
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            ch = _mk_phase(slot_of, 3, (5, 12, 3), seed=4)
            _run_phase(serial, [eng], ch, now0=2000)
            ps = eng.datapath.pack_stats
            assert ps["pack_fallback_disabled"] > 0
            assert ps["pack_inplace"] == 0
        finally:
            serial.stop()
            eng.stop()


class TestSteeredStagingAllocFree:
    def test_steered_staging_steady_state_alloc_free(self):
        """PR 5's tracemalloc contract extended to the steered path: after
        warmup, a 512-batch pipelined run through the 4-shard mesh adds no
        per-batch buffer allocations in the pack/stage/steer files (net
        growth under 64KB — temporaries are freed; what must not appear is
        a surviving allocation per batch)."""
        eng = jit_pipeline_engine(4, pipeline_flush_ms=0.5)
        slot_of = eng.active.snapshot.ep_slot_of
        chunks = _mk_phase(slot_of, 8, (9, 17, 5, 30), seed=5)
        now = [3000]

        def run(n):
            for i in range(n):
                now[0] += 1
                eng.submit(dict(chunks[i % len(chunks)]), now=now[0])
                if i % 16 == 15:
                    assert eng.drain(timeout=60)
            assert eng.drain(timeout=60)

        try:
            run(128)                    # warmup: traces, views, pools
            gc.collect()
            tracemalloc.start()
            # one full measured window FIRST, then the baseline snapshot:
            # the steered path keeps a bounded turnover footprint (the
            # most recent flush's per-ticket out dicts, the pooled wire
            # buffer) that is re-allocated rather than grown — comparing
            # two equal windows cancels it, so the assertion catches
            # exactly per-batch growth
            run(256)
            gc.collect()
            flt = [tracemalloc.Filter(True, f"*{os.sep}{name}") for name in
                   ("records.py", "scheduler.py", "datapath.py", "mesh.py")]
            snap1 = tracemalloc.take_snapshot()
            # a genuine per-batch leak grows EVERY window; a transient
            # (GC timing, another thread's allocation landing in the
            # filtered files mid-snapshot) does not — so a window over
            # budget gets exactly one fresh window before failing
            for attempt in range(2):
                run(512)
                gc.collect()
                snap2 = tracemalloc.take_snapshot()
                diff = snap2.filter_traces(flt).compare_to(
                    snap1.filter_traces(flt), "lineno")
                growth = sum(d.size_diff for d in diff)
                if growth < 64 * 1024:
                    break
                snap1 = snap2
            tracemalloc.stop()
            ps = eng.datapath.pack_stats
            assert ps["pack_inplace"] > 0
            assert ps["pack_fallback_steered"] == 0
            assert growth < 64 * 1024, \
                f"steered stage/pack path grew {growth}B:\n" + \
                "\n".join(str(d) for d in diff[:10])
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# Slow soak (`make multichip-smoke`): the feeder → 8-shard mesh path under
# rx-ring faults
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestShardedSoak:
    def test_soak_10k_sharded_with_rx_faults(self):
        """10k submissions through the 8-shard mesh behind one admission
        queue. With the C shim built the stream rides the mock rings +
        async feeder (harvest pre-binning) with ``shim.rx_ring`` faults
        armed; otherwise direct submissions with dispatch faults. Either
        way: every frame/submission resolves, the steered path never falls
        back to an allocating pack, and the guard never restarts."""
        from cilium_tpu.shim.bindings import LIB_PATH
        n = 10_000
        eng = jit_pipeline_engine(
            8, batch_size=256, pipeline_queue_batches=256,
            ingest_pool_batches=8, pipeline_flush_ms=0.5)
        try:
            if os.path.exists(LIB_PATH):
                from cilium_tpu.shim.bindings import FlowShim, build_frame
                shim = FlowShim(batch_size=64, timeout_us=100)
                shim.register_endpoint("192.168.1.10", 1)
                shim.mock_rings_init(ring_size=64, frame_size=2048,
                                     n_frames=64)
                feeder = eng.start_feeder(shim)
                FAULTS.arm("shim.rx_ring", mode="prob", prob=0.05, seed=31)
                end = time.time() + 300
                for i in range(n):
                    f = build_frame(
                        "192.168.1.10",
                        f"10.{i % 2}.2.{1 + i % 250}",
                        40000 + (i % 20000), 443 if i % 4 else 80)
                    while shim.mock_rx_inject(f) != 0:
                        shim.mock_tx_drain(64)
                        if time.time() > end:
                            raise TimeoutError("rx ring wedged")
                        time.sleep(0.0002)
                while time.time() < end:
                    shim.mock_tx_drain(64)
                    st = shim.stats()
                    if st["verdict_passes"] + st["verdict_drops"] \
                            + st["tx_full_drops"] >= n:
                        break
                    time.sleep(0.002)
                FAULTS.reset()
                st = shim.stats()
                fstats = feeder.stats()
                assert st["verdict_passes"] + st["verdict_drops"] \
                    + st["tx_full_drops"] >= n
                assert fstats["harvested_records"] == n
                eng.stop()
                shim.close()
            else:
                FAULTS.arm("pipeline.dispatch", mode="prob", prob=0.02,
                           seed=7)
                slot_of = eng.active.snapshot.ep_slot_of
                rng = np.random.default_rng(9)
                tickets = []
                for i in range(n):
                    m = 1 + (i % 3)
                    recs = [pkt("192.168.1.10",
                                f"10.{int(rng.integers(0, 2))}.2."
                                f"{int(rng.integers(1, 250))}",
                                40000 + (i % 20000) + r, 443)
                            for r in range(m)]
                    tickets.append(eng.submit(
                        batch_from_records(recs, slot_of), now=100 + i))
                assert eng.drain(timeout=300)
                FAULTS.reset()
                resolved = sum(1 for t in tickets if t.done())
                assert resolved == n
                eng.stop()
            ps = eng.datapath.pack_stats
            # the sharded-soak acceptance: zero steered fallbacks — the
            # serving path packed in place into pooled per-shard segments
            assert ps["pack_fallback_steered"] == 0
            assert ps["pack_inplace"] > 0
        finally:
            FAULTS.reset()
            eng.stop()


# --------------------------------------------------------------------------- #
# Degraded survivor geometry (ISSUE 19): the n-1 mesh is a first-class
# serving shape, not an error state — bit-identity to the oracle and a
# clean parity audit must hold on it from a cold CT
# --------------------------------------------------------------------------- #
class TestDegradedMeshParity:
    @pytest.mark.parametrize("n_shards,victim", [
        (4, 1),
        pytest.param(8, 5, marks=pytest.mark.slow),
    ])
    def test_n_minus_1_bit_identical_to_serial(self, n_shards, victim):
        """Shrink the mesh BEFORE any traffic (a device latched dead, one
        remesh tick onto the survivors), then run the sharded parity
        phases on the degraded geometry: fresh flows with partial
        buckets, CT continuity in BOTH directions, and the shadow
        auditor at sampling 1.0 staying clean — proving degraded serving
        is the same verdict machine, just narrower."""
        FAULTS.reset()
        serial = fake_serial_engine()
        eng = jit_pipeline_engine(n_shards, audit_enabled=True,
                                  audit_sample_rate=1.0,
                                  audit_pool_batches=64)
        eng.auditor.configure(sample_rate=1.0)
        slot_of = serial.active.snapshot.ep_slot_of
        try:
            eng.datapath.note_device_loss(victim, reason="drill")
            doc = eng.remesh_step()
            assert doc["remesh"]["from"] == n_shards
            assert doc["remesh"]["to"] == n_shards - 1
            assert victim not in \
                eng.datapath.mesh_health()["live_ordinals"]

            ch1 = _mk_phase(slot_of, 5, (1, 5, 17, 9, 23),
                            seed=60 + n_shards)
            _run_phase(serial, [eng], ch1, now0=1000)

            est = [pkt("192.168.1.10", "10.0.2.7", 49300 + i, 443)
                   for i in range(4)]
            outs = _run_phase(serial, [eng],
                              [batch_from_records(est, slot_of)],
                              now0=1200)
            assert outs[0]["allow"].all()
            reply = [pkt("10.0.2.7", "192.168.1.10", 443, 49300 + i,
                         flags=C.TCP_ACK, direction=C.DIR_INGRESS)
                     for i in range(4)]
            outs2 = _run_phase(
                serial, [eng],
                [batch_from_records(reply, slot_of, pad_to=6)],
                now0=1210)
            # the degraded steer kept both directions on one survivor
            # shard: replies really hit CT
            assert (np.asarray(outs2[0]["status"])[:4]
                    == int(C.CTStatus.REPLY)).all()

            assert eng.pipeline_stats()["n_shards"] == n_shards - 1
            for _ in range(100):
                step = eng.audit_step(budget=128)
                if not step or (not step.get("replayed")
                                and not step.get("pending")):
                    break
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0
        finally:
            serial.stop()
            eng.stop()
