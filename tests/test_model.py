"""Unit tests for the model layer (labels/selectors/rules/identity/ipcache)."""

import pytest

from cilium_tpu.model.labels import Label, Labels, parse_label
from cilium_tpu.model.selectors import EndpointSelector
from cilium_tpu.model.rules import (
    CIDRSelector, PortProtocol, RuleParseError, parse_rule, parse_rules,
)
from cilium_tpu.model.identity import IdentityAllocator, cidr_identity_labels
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import addr_to_words, parse_addr, parse_prefix, addr_to_str


class TestLabels:
    def test_parse(self):
        lbl = parse_label("k8s:app=web")
        assert lbl == Label("k8s", "app", "web")
        assert parse_label("reserved:world") == Label("reserved", "world", "")
        assert parse_label("app=web") == Label("unspec", "app", "web")

    def test_sorted_canonical_and_hashable(self):
        a = Labels.parse(["k8s:app=web", "k8s:tier=fe"])
        b = Labels.parse(["k8s:tier=fe", "k8s:app=web"])
        assert a == b and hash(a) == hash(b)
        assert a.to_strings() == ("k8s:app=web", "k8s:tier=fe")

    def test_any_source_lookup(self):
        lbls = Labels.parse(["k8s:app=web"])
        assert lbls.get("any", "app").value == "web"
        assert lbls.get("k8s", "app").value == "web"
        assert lbls.get("reserved", "app") is None


class TestSelectors:
    def test_match_labels(self):
        sel = EndpointSelector.from_json({"matchLabels": {"app": "web"}})
        assert sel.matches(Labels.parse(["k8s:app=web"]))
        assert not sel.matches(Labels.parse(["k8s:app=db"]))

    def test_source_prefixed_key(self):
        sel = EndpointSelector.from_json({"matchLabels": {"reserved:world": ""}})
        assert sel.matches(Labels.reserved("world"))
        assert not sel.matches(Labels.parse(["k8s:world="]))

    def test_match_expressions(self):
        sel = EndpointSelector.from_json({"matchExpressions": [
            {"key": "app", "operator": "In", "values": ["web", "api"]},
            {"key": "banned", "operator": "DoesNotExist"},
        ]})
        assert sel.matches(Labels.parse(["k8s:app=api"]))
        assert not sel.matches(Labels.parse(["k8s:app=api", "k8s:banned=1"]))
        assert not sel.matches(Labels.parse(["k8s:app=db"]))

    def test_wildcard(self):
        sel = EndpointSelector.from_json({})
        assert sel.is_wildcard
        assert sel.matches(Labels())

    def test_any_source_spans_duplicate_keys(self):
        # same key under two sources: 'any' must consider all of them
        lbls = Labels.parse(["cidr:app=x", "k8s:app=web"])
        assert EndpointSelector.from_json(
            {"matchLabels": {"app": "web"}}).matches(lbls)
        assert EndpointSelector.from_json({"matchExpressions": [
            {"key": "app", "operator": "In", "values": ["web"]}]}).matches(lbls)
        assert not EndpointSelector.from_json({"matchExpressions": [
            {"key": "app", "operator": "NotIn", "values": ["web"]}]}).matches(lbls)

    def test_port_zero_with_endport_rejected(self):
        with pytest.raises(RuleParseError):
            PortProtocol(port=0, end_port=90, protocol="TCP")


class TestRules:
    def test_parse_basic_cnp(self):
        rule = parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"role": "fe"}}],
                "toPorts": [{"ports": [
                    {"port": "80", "protocol": "TCP"},
                    {"port": "8080", "endPort": 8090, "protocol": "TCP"},
                ]}],
            }],
        })
        assert rule.enforces_ingress and not rule.enforces_egress
        pr = rule.ingress[0].to_ports[0]
        assert pr.ports[0].port_range == (80, 80)
        assert pr.ports[1].port_range == (8080, 8090)

    def test_empty_section_flips_enforcement(self):
        rule = parse_rule({"endpointSelector": {}, "ingress": []})
        assert rule.enforces_ingress

    def test_cidrset_with_except(self):
        rule = parse_rule({
            "endpointSelector": {},
            "egress": [{"toCIDRSet": [
                {"cidr": "10.0.0.0/8", "except": ["10.1.0.0/16"]}]}],
        })
        cs = rule.egress[0].peer.cidrs[0]
        assert cs.cidr == "10.0.0.0/8" and cs.excepts == ("10.1.0.0/16",)

    def test_proto_any_expands(self):
        assert PortProtocol(port=53, protocol="ANY").protocols() == C.PORT_PROTOS

    def test_l7_http(self):
        rule = parse_rule({
            "endpointSelector": {},
            "ingress": [{"toPorts": [{
                "ports": [{"port": "80", "protocol": "TCP"}],
                "rules": {"http": [{"method": "GET", "path": "/api"}]},
            }]}],
        })
        assert rule.ingress[0].to_ports[0].http[0].method == "GET"

    def test_rejects_out_of_scope(self):
        with pytest.raises(RuleParseError):
            parse_rule({"endpointSelector": {},
                        "egress": [{"toRequires": [{}]}]})
        with pytest.raises(RuleParseError):
            parse_rule({"endpointSelector": {},
                        "ingressDeny": [{"toPorts": [{
                            "ports": [{"port": "80", "protocol": "TCP"}],
                            "rules": {"http": [{"path": "/"}]}}]}]})

    def test_entities(self):
        rule = parse_rule({"endpointSelector": {},
                           "egress": [{"toEntities": ["world", "cluster"]}]})
        assert rule.egress[0].peer.entities == ("world", "cluster")
        with pytest.raises(RuleParseError):
            parse_rule({"endpointSelector": {},
                        "egress": [{"toEntities": ["galaxy"]}]})


class TestIdentity:
    def test_reserved_preallocated(self):
        alloc = IdentityAllocator()
        assert alloc.get(C.IDENTITY_WORLD).labels == Labels.reserved("world")

    def test_idempotent_cluster_alloc(self):
        alloc = IdentityAllocator()
        a = alloc.allocate(Labels.parse(["k8s:app=web"]))
        b = alloc.allocate(Labels.parse(["k8s:app=web"]))
        assert a.id == b.id >= C.CLUSTER_IDENTITY_BASE

    def test_cidr_identity_is_local_scope(self):
        alloc = IdentityAllocator()
        ident = alloc.allocate_cidr("10.0.0.0/8")
        assert ident.id & C.LOCAL_IDENTITY_SCOPE
        assert ident.is_cidr
        # CIDR identities carry reserved:world (world-scoped)
        assert ident.labels.has("reserved", "world")

    def test_release_refcounted(self):
        alloc = IdentityAllocator()
        a = alloc.allocate(Labels.parse(["k8s:app=web"]))
        alloc.allocate(Labels.parse(["k8s:app=web"]))
        assert not alloc.release(a)
        assert alloc.release(a)
        assert alloc.get(a.id) is None

    def test_observer_notified(self):
        alloc = IdentityAllocator()
        events = []
        alloc.add_observer(lambda add, rem: events.append((len(add), len(rem))),
                           replay=False)
        ident = alloc.allocate(Labels.parse(["k8s:app=web"]))
        alloc.release(ident)
        assert events == [(1, 0), (0, 1)]

    def test_export_restore_stable(self):
        alloc = IdentityAllocator()
        a = alloc.allocate(Labels.parse(["k8s:app=web"]))
        state = alloc.export_state()
        alloc2 = IdentityAllocator()
        alloc2.restore_state(state)
        assert alloc2.lookup_by_labels(Labels.parse(["k8s:app=web"])).id == a.id
        b = alloc2.allocate(Labels.parse(["k8s:app=db"]))
        assert b.id == a.id + 1


class TestIPCache:
    def test_lpm_most_specific_wins(self):
        cache = IPCache()
        cache.upsert("10.0.0.0/8", 100)
        cache.upsert("10.1.0.0/16", 200)
        cache.upsert("10.1.2.3/32", 300)
        assert cache.lookup("10.2.0.1") == 100
        assert cache.lookup("10.1.9.9") == 200
        assert cache.lookup("10.1.2.3") == 300

    def test_miss_is_world(self):
        cache = IPCache()
        assert cache.lookup("8.8.8.8") == C.IDENTITY_WORLD

    def test_family_separation(self):
        cache = IPCache()
        cache.upsert("::/0", 500)
        cache.upsert("0.0.0.0/0", 600)
        assert cache.lookup("1.2.3.4") == 600
        assert cache.lookup("2001:db8::1") == 500

    def test_revision_bumps(self):
        cache = IPCache()
        r0 = cache.revision
        cache.upsert("10.0.0.0/8", 1)
        assert cache.revision == r0 + 1


class TestIPUtils:
    def test_v4_mapped(self):
        addr, is_v6 = parse_addr("1.2.3.4")
        assert not is_v6
        assert addr_to_str(addr) == "1.2.3.4"
        assert addr_to_words(addr) == (0, 0, 0xFFFF, 0x01020304)

    def test_prefix_normalization(self):
        net, plen, is_v6 = parse_prefix("10.1.2.3/16")
        assert plen == 96 + 16 and not is_v6
        assert addr_to_str(net) == "10.1.0.0"
