"""Observability subsystem tests (cilium_tpu/observe/).

Unit tests cover the tracer's deterministic counter sampling + span ring,
the vectorized flow-metrics windows, and the autotuner's hysteresis /
convergence / no-oscillation contract against a stub pipeline. Integration
tests run tracing through the real Pipeline + Engine (spans appear per
stage; verdicts stay bit-identical to the serial path with sampling at
1.0 — the acceptance gate), exercise the REST routes, and pin the
``Engine._dirty`` Event semantics (a mark set mid-compile survives the
regeneration). The ``slow``-marked soak (``make observe-smoke``) asserts
the 1/64-sampled pipeline costs <2% over tracing disabled.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.observe.autotune import Autotuner
from cilium_tpu.observe.flowmetrics import FlowMetrics
from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.metrics import Metrics, quantile_from
from tests.test_pipeline import (EchoDispatch, POLICY, _assert_parity,
                                 fake_engine, mk_chunks, pkt, sub_batch)
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.pipeline import Pipeline


@pytest.fixture(autouse=True)
def _quiet_tracer():
    """Engines configure the process-wide TRACER from their DaemonConfig;
    leave it disabled and empty for the next test."""
    yield
    TRACER.configure(sample_rate=0.0)
    TRACER.reset()


class TestTracer:
    def test_disabled_costs_nothing_and_records_nothing(self):
        t = Tracer(sample_rate=0.0, capacity=8)
        assert not t.enabled
        assert t.maybe_sample() is None and t.force_sample() is None
        with t.span(None, "x"):
            pass
        t.record(None, "x", 0.0, 1.0)
        assert t.spans() == [] and t.summary() == {}
        assert t.event("decision") is None

    def test_counter_sampling_is_deterministic(self):
        t = Tracer(sample_rate=0.25, capacity=64)
        decisions = [t.maybe_sample() is not None for _ in range(12)]
        assert decisions == [True, False, False, False] * 3
        assert t.sampled_total == 3

    def test_rate_one_samples_everything(self):
        t = Tracer(sample_rate=1.0, capacity=64)
        assert all(t.maybe_sample() is not None for _ in range(10))

    def test_ring_keeps_newest(self):
        t = Tracer(sample_rate=1.0, capacity=4)
        for i in range(10):
            t.record(i + 1, f"s{i}", 0.0, 0.001 * i)
        names = [s["name"] for s in t.spans(limit=100)]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_span_context_manager_and_summary(self):
        t = Tracer(sample_rate=1.0, capacity=64)
        tid = t.maybe_sample()
        for _ in range(5):
            with t.span(tid, "stage.a"):
                pass
        t.record(tid, "stage.b", 0.0, 0.010)
        s = t.summary()
        assert s["stage.a"]["count"] == 5
        assert s["stage.b"]["p50_ms"] == pytest.approx(10.0, rel=0.01)
        assert s["stage.a"]["p99_ms"] >= s["stage.a"]["p50_ms"]

    def test_trace_context_is_thread_local(self):
        t = Tracer(sample_rate=1.0, capacity=16)
        seen = {}
        with t.context(42):
            assert t.current() == 42

            def peek():
                seen["other"] = t.current()
            th = threading.Thread(target=peek)
            th.start()
            th.join()
            with t.context(7):
                assert t.current() == 7
            assert t.current() == 42
        assert t.current() is None and seen["other"] is None

    def test_context_propagates_across_tracer_instances(self):
        """The cross-layer seam: the datapath attaches spans via active(),
        so a Pipeline constructed with an injected tracer still gets its
        pack/transfer/compute spans recorded on THAT tracer."""
        from cilium_tpu.observe.trace import TRACER as global_tracer, active
        t1 = Tracer(sample_rate=1.0, capacity=8)
        t2 = Tracer(sample_rate=1.0, capacity=8)
        with t1.context(5):
            tr, tid = active()
            assert tr is t1 and tid == 5
            assert t2.current() == 5     # any instance reads the context
        tr, tid = active()
        assert tr is global_tracer and tid is None

    def test_event_records_with_attrs(self):
        t = Tracer(sample_rate=1 / 64, capacity=16)
        t.event("autotune.decision", knob="flush_ms", old=2.0, new=1.0)
        spans = t.spans(name="autotune.decision")
        assert len(spans) == 1
        assert spans[0]["attrs"]["knob"] == "flush_ms"

    def test_stats_shape(self):
        t = Tracer(sample_rate=0.5, capacity=8)
        tid = t.maybe_sample()
        t.record(tid, "x", 0.0, 0.001)
        st = t.stats()
        assert st["enabled"] and st["capacity"] == 8
        assert st["spans_in_ring"] == 1 and st["sample_rate"] == 0.5

    def test_forced_events_do_not_skew_sampled_total(self):
        """Coverage math (sampled_total x 1/rate ~= submissions) must not
        be inflated by always-traced regen/autotune events."""
        t = Tracer(sample_rate=0.25, capacity=16)
        for _ in range(8):
            t.maybe_sample()
        t.force_sample()
        t.event("autotune.decision", knob="flush_ms")
        st = t.stats()
        assert st["sampled_total"] == 2      # 8 events at 1/4
        assert st["forced_total"] == 2       # forced + event, separately

    def test_reconfigure_same_capacity_preserves_ring(self):
        """Constructing a second Engine (which re-states the tracer config)
        must not wipe spans another engine already recorded."""
        t = Tracer(sample_rate=1.0, capacity=8)
        t.record(t.maybe_sample(), "x", 0.0, 0.001)
        t.configure(sample_rate=1.0, capacity=8)
        assert len(t.spans()) == 1           # same capacity: ring kept
        t.configure(capacity=4)
        assert t.spans() == []               # real change: reallocated

    def test_engine_with_tracing_off_leaves_global_tracer_alone(self):
        TRACER.configure(sample_rate=1.0, capacity=32)
        tid = TRACER.maybe_sample()
        TRACER.record(tid, "pre.existing", 0.0, 0.001)
        eng = fake_engine()                  # trace_sample_rate default 0
        assert TRACER.enabled               # not silently disabled
        assert any(s["name"] == "pre.existing" for s in TRACER.spans())
        eng.stop()


class TestPipelineTracing:
    def test_stage_spans_recorded_at_rate_one(self):
        d = EchoDispatch()
        tr = Tracer(sample_rate=1.0, capacity=256)
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1.0,
                      tracer=tr)
        try:
            t = pl.submit(sub_batch(16, start=100))    # direct path
            t.result(timeout=5)        # resolve before any rows stage
            for i in range(6):
                pl.submit(sub_batch(3, start=i * 4))   # coalesced path
            assert pl.drain(timeout=10)
            s = tr.summary()
            assert s["pipeline.admission"]["count"] == 7
            assert s["pipeline.microbatch"]["count"] == 6   # direct skips it
            assert s["pipeline.dispatch"]["count"] >= 2
            assert s["pipeline.finalize"]["count"] \
                == s["pipeline.dispatch"]["count"]
        finally:
            pl.close(timeout=5)

    def test_unsampled_pipeline_records_nothing(self):
        d = EchoDispatch()
        tr = Tracer(sample_rate=0.0, capacity=64)
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=1.0,
                      tracer=tr)
        try:
            for i in range(5):
                pl.submit(sub_batch(4, start=i * 4))
            assert pl.drain(timeout=10)
            assert tr.spans() == []
        finally:
            pl.close(timeout=5)

    def test_runtime_knob_setters_validate(self):
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=2.0)
        try:
            pl.set_flush_ms(7.5)
            assert pl.flush_ms == pytest.approx(7.5)
            pl.set_min_bucket(8)
            assert pl.min_bucket == 8
            assert pl.stats()["min_bucket"] == 8
            assert pl.stats()["flush_ms"] == pytest.approx(7.5)
            with pytest.raises(ValueError):
                pl.set_min_bucket(12)          # not a power of two
            with pytest.raises(ValueError):
                pl.set_min_bucket(32)          # > max_bucket
            with pytest.raises(ValueError):
                pl.set_flush_ms(0)
            # changed floor takes effect: an 8-row submission now rides the
            # zero-copy direct path
            t = pl.submit(sub_batch(8, start=0))
            t.result(timeout=5)
            assert pl.flush_reasons["direct"] >= 1
        finally:
            pl.close(timeout=5)

    def test_engine_parity_bit_identical_with_tracing_at_one(self):
        """The acceptance gate: full-rate tracing must not perturb a single
        verdict, counter, or CT entry vs the serial path."""
        engines = []
        for _ in range(2):
            eng = fake_engine(trace_sample_rate=1.0,
                              pipeline_min_bucket=16,
                              pipeline_flush_ms=1.0)
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",),
                             ep_id=1)
            eng.apply_policy(POLICY)
            engines.append(eng)
        ser, pipe = engines
        # unique flows per row: the regime where coalescing is a legal
        # scheduling choice (same contract test_pipeline pins untraced)
        chunks = mk_chunks(ser.active.snapshot.ep_slot_of, n_chunks=18,
                           rows_per_chunk=5)
        _assert_parity(ser, pipe, chunks)
        # and the pipeline stages actually traced
        names = set(TRACER.summary())
        assert {"pipeline.admission", "pipeline.dispatch",
                "pipeline.finalize", "engine.classify"} <= names
        pipe.stop()
        ser.stop()


class TestFlowMetrics:
    @staticmethod
    def _batch_out():
        n = 8
        batch = {
            "valid": np.array([1, 1, 1, 1, 1, 1, 0, 0], bool),
            "proto": np.array([6, 6, 17, 6, 6, 1, 6, 6], np.int32),
            "dport": np.array([443, 443, 53, 80, 443, 0, 9, 9], np.int32),
        }
        out = {
            "allow": np.array([1, 1, 1, 0, 0, 1, 1, 1], bool),
            "reason": np.zeros(n, np.int32),
            "remote_identity": np.array([5, 5, 7, 5, 9, 7, 1, 1], np.int32),
        }
        out["reason"][3] = 133       # POLICY_DENIED-ish bin
        out["reason"][4] = 133
        return batch, out

    def test_vectorized_counts(self):
        fm = FlowMetrics(window_s=10, n_windows=4, top_k=3)
        batch, out = self._batch_out()
        fm.add_batch(batch, out, now=105)
        [w] = fm.series()
        assert w["window_start"] == 100
        assert w["forwarded"] == 4 and w["dropped"] == 2
        assert sum(w["drop_reasons"].values()) == 2
        assert w["protos"] == {"TCP": 4, "UDP": 1, "ICMP": 1}
        assert w["top_ports"][0] == {"port": 443, "count": 3}
        assert {d["identity"]: d["count"] for d in w["top_identities"]} \
            == {5: 3, 7: 2, 9: 1}
        # invalid rows (ports 9, identity 1) never counted
        assert all(p["port"] != 9 for p in w["top_ports"])

    def test_windows_advance_and_cap(self):
        fm = FlowMetrics(window_s=10, n_windows=3, top_k=3)
        batch, out = self._batch_out()
        for now in (5, 15, 25, 35, 45):
            fm.add_batch(batch, out, now=now)
        starts = [w["window_start"] for w in fm.series()]
        assert starts == [20, 30, 40]       # oldest windows aged out
        t = fm.totals()
        assert t["forwarded"] == 4 * 5 and t["batches"] == 5

    def test_same_window_accumulates(self):
        fm = FlowMetrics(window_s=10, n_windows=3)
        batch, out = self._batch_out()
        fm.add_batch(batch, out, now=100)
        fm.add_batch(batch, out, now=109)
        [w] = fm.series()
        assert w["forwarded"] == 8 and w["dropped"] == 4

    def test_axis_cardinality_bounded(self):
        from cilium_tpu.observe import flowmetrics as fmod
        fm = FlowMetrics(window_s=10, n_windows=2, top_k=5)
        n = fmod.AXIS_CAP + 50
        batch = {
            "valid": np.ones(n, bool),
            "proto": np.full(n, 6, np.int32),
            "dport": np.arange(n, dtype=np.int32),     # a port scan
        }
        out = {
            "allow": np.ones(n, bool),
            "reason": np.zeros(n, np.int32),
            "remote_identity": np.zeros(n, np.int32),
        }
        fm.add_batch(batch, out, now=10)
        with fm._lock:
            assert len(fm._totals.ports) <= fmod.AXIS_CAP
            total_port_counts = (sum(fm._totals.ports.values())
                                 + fm._totals.ports_other)
        assert total_port_counts == n       # nothing lost, only collapsed
        # the collapsed remainder exports as the monotone "other" series
        assert 'ciliumtpu_flow_port_total{port="other"}' \
            in fm.render_prometheus()

    def test_totals_series_stay_monotone_under_churn(self):
        """The Prometheus counter contract: once a port/identity series is
        exported from totals it never decreases and never vanishes, no
        matter how the traffic mix churns past AXIS_CAP distinct keys."""
        from cilium_tpu.observe import flowmetrics as fmod

        def parse(text):
            return {line.rpartition(" ")[0]: int(line.rpartition(" ")[2])
                    for line in text.splitlines()
                    if line.startswith("ciliumtpu_flow_port_total")}

        fm = FlowMetrics(window_s=10, n_windows=2, top_k=5)
        rng = np.random.default_rng(3)
        prev = {}
        for round_i in range(6):
            n = fmod.AXIS_CAP
            batch = {
                "valid": np.ones(n, bool),
                "proto": np.full(n, 6, np.int32),
                # shifting port population: later rounds bring new keys
                "dport": (rng.integers(0, 2 * fmod.AXIS_CAP, n)
                          + round_i * 37).astype(np.int32),
            }
            out = {"allow": np.ones(n, bool),
                   "reason": np.zeros(n, np.int32),
                   "remote_identity": np.zeros(n, np.int32)}
            fm.add_batch(batch, out, now=round_i * 10)
            cur = parse(fm.render_prometheus())
            for series, value in prev.items():
                assert series in cur, f"series vanished: {series}"
                assert cur[series] >= value, f"decreased: {series}"
            prev = cur

    def test_prometheus_render(self):
        fm = FlowMetrics(window_s=10, n_windows=2, top_k=2)
        batch, out = self._batch_out()
        fm.add_batch(batch, out, now=7)
        text = fm.render_prometheus()
        assert 'ciliumtpu_flow_verdicts_total{verdict="FORWARDED"} 4' in text
        assert 'ciliumtpu_flow_verdicts_total{verdict="DROPPED"} 2' in text
        assert 'ciliumtpu_flow_proto_total{proto="TCP"} 4' in text
        assert 'ciliumtpu_flow_port_total{port="443"} 3' in text
        # every retained entry exports (the axes are capped, not top-k'd,
        # so the series stay monotone between scrapes); nothing was pruned
        # here → no "other" series
        for ident, n in ((5, 3), (7, 2), (9, 1)):
            assert (f'ciliumtpu_flow_identity_total{{identity="{ident}"}} '
                    f"{n}") in text
        assert 'identity="other"' not in text


class _StubPipeline:
    """Duck-typed pipeline for autotuner unit tests: the test scripts the
    interval deltas (dispatches, fill, flush reasons) and the queue-wait
    observations go straight into the shared metrics histogram."""

    def __init__(self, metrics, flush_ms=2.0, min_bucket=256,
                 max_bucket=8192):
        self.metrics = metrics
        self._flush_ms = flush_ms
        self._min_bucket = min_bucket
        self._max_bucket = max_bucket
        self.dispatched = 0
        self.fill_rows = 0
        self.bucket_rows = 0
        self.reasons = {"direct": 0, "full": 0, "deadline": 0, "drain": 0}

    # the Autotuner consumer surface
    flush_ms = property(lambda self: self._flush_ms)
    min_bucket = property(lambda self: self._min_bucket)
    max_bucket = property(lambda self: self._max_bucket)

    def set_flush_ms(self, v):
        self._flush_ms = v

    def set_min_bucket(self, v):
        self._min_bucket = v

    def stats(self):
        return {"dispatched_batches": self.dispatched,
                "fill_rows": self.fill_rows,
                "bucket_rows": self.bucket_rows,
                "flush_reasons": dict(self.reasons)}

    def interval(self, batches=10, fill=0.9, wait_ms=1.0,
                 reason="full"):
        """Simulate one interval of pipeline activity."""
        h = self.metrics.histogram("pipeline_queue_wait_seconds")
        for _ in range(batches):
            h.observe(wait_ms / 1e3)
        self.dispatched += batches
        self.bucket_rows += batches * 1024
        self.fill_rows += int(batches * 1024 * fill)
        self.reasons[reason] += batches


def mk_autotuner(pl, m, **kw):
    kw.setdefault("flush_ms_min", 0.5)
    kw.setdefault("flush_ms_max", 16.0)
    kw.setdefault("min_bucket_floor", 64)
    kw.setdefault("queue_wait_p99_budget_ms", 5.0)
    kw.setdefault("hysteresis", 3)
    kw.setdefault("step_factor", 2.0)
    return Autotuner(pl, m, tracer=Tracer(sample_rate=1.0, capacity=64),
                     **kw)


class TestAutotuner:
    def test_needs_hysteresis_before_acting(self):
        m = Metrics()
        pl = _StubPipeline(m)
        at = mk_autotuner(pl, m)
        pl.interval(wait_ms=50.0)           # way over budget
        assert at.step() is None            # baseline interval
        for _ in range(2):                  # 2 more: still under hysteresis=3
            pl.interval(wait_ms=50.0)
            at.step()
        assert pl.flush_ms == 2.0
        pl.interval(wait_ms=50.0)           # 3rd consecutive over-budget
        obs = at.step()
        assert pl.flush_ms == 1.0           # one capped step down
        assert obs["adjusted"][0]["knob"] == "flush_ms"

    def test_converges_down_under_sustained_burst_and_respects_floor(self):
        m = Metrics()
        pl = _StubPipeline(m, flush_ms=8.0)
        at = mk_autotuner(pl, m)
        history = []
        for _ in range(30):
            pl.interval(wait_ms=40.0, fill=0.9)
            at.step()
            history.append(pl.flush_ms)
        assert pl.flush_ms == 0.5           # clamped at flush_ms_min
        # monotone non-increasing path down — no overshoot/oscillation
        assert all(b <= a for a, b in zip(history, history[1:]))

    def test_raises_flush_when_underfilled_and_fast(self):
        m = Metrics()
        pl = _StubPipeline(m, flush_ms=1.0)
        at = mk_autotuner(pl, m)
        for _ in range(8):
            pl.interval(wait_ms=0.5, fill=0.2, reason="deadline")
            at.step()
        assert pl.flush_ms > 1.0

    def test_dead_band_is_stable(self):
        """In-budget wait + on-target fill → zero adjustments, ever."""
        m = Metrics()
        pl = _StubPipeline(m)
        at = mk_autotuner(pl, m)
        for _ in range(12):
            pl.interval(wait_ms=1.0, fill=0.8)
            at.step()
        assert pl.flush_ms == 2.0 and not at.adjustments

    def test_alternating_load_never_oscillates(self):
        """The hysteresis contract: direction flips every interval, so the
        streak never reaches 3 and no knob ever moves."""
        m = Metrics()
        pl = _StubPipeline(m)
        at = mk_autotuner(pl, m)
        for i in range(20):
            if i % 2:
                pl.interval(wait_ms=50.0, fill=0.9)       # wants down
            else:
                pl.interval(wait_ms=0.5, fill=0.2)        # wants up
            at.step()
        assert not at.adjustments and pl.flush_ms == 2.0

    def test_bucket_floor_down_on_deadline_dominated_low_fill(self):
        m = Metrics()
        pl = _StubPipeline(m, min_bucket=1024)
        at = mk_autotuner(pl, m)
        for _ in range(8):
            pl.interval(wait_ms=1.0, fill=0.3, reason="deadline")
            at.step()
        assert pl.min_bucket < 1024
        assert pl.min_bucket >= 64          # the configured floor holds

    def test_bucket_floor_shrink_clamps_lane_bucket(self):
        """The "lane_bucket never exceeds min_bucket" invariant is
        enforced the moment the bulk arm shrinks min_bucket — the lane
        arm's own (hysteresis-gated) shrink path may take many intervals
        to fire, or never, and the lane would dispatch above the bulk
        floor meanwhile."""
        m = Metrics()
        pl = _StubPipeline(m, min_bucket=1024)
        pl.lane_bucket = 1024                # at the ceiling
        pl.set_lane_bucket = lambda v: setattr(pl, "lane_bucket", v)
        at = mk_autotuner(pl, m)
        for _ in range(8):
            pl.interval(wait_ms=1.0, fill=0.3, reason="deadline")
            at.step()
        assert pl.min_bucket < 1024
        assert pl.lane_bucket <= pl.min_bucket
        assert any(a["knob"] == "lane_bucket" for a in at.adjustments)
        m = Metrics()
        pl = _StubPipeline(m, min_bucket=256)
        at = mk_autotuner(pl, m)
        for _ in range(8):
            pl.interval(wait_ms=1.0, fill=0.97, reason="full")
            at.step()
        assert pl.min_bucket > 256

    def test_idle_interval_is_skipped(self):
        m = Metrics()
        pl = _StubPipeline(m)
        at = mk_autotuner(pl, m)
        pl.interval(wait_ms=50.0)
        at.step()                            # baseline
        assert at.step() is None             # no new dispatches → no signal
        assert pl.flush_ms == 2.0

    def test_decisions_are_traced_and_counted(self):
        m = Metrics()
        pl = _StubPipeline(m)
        at = mk_autotuner(pl, m, hysteresis=1)
        pl.interval(wait_ms=50.0)
        at.step()
        pl.interval(wait_ms=50.0)
        at.step()
        assert m.counters["autotune_adjustments_total"] >= 1
        ev = at.tracer.spans(name="autotune.decision")
        assert ev and ev[0]["attrs"]["knob"] == "flush_ms"
        st = at.status()
        assert st["adjustments_total"] == len(at.adjustments)

    def test_config_rejects_nonsense_autotune_knobs(self):
        from cilium_tpu.runtime.config import DaemonConfig
        for kw in ({"autotune_target_fill": 0.0},
                   {"autotune_target_fill": 1.5},
                   {"autotune_queue_wait_p99_ms": -1.0},
                   {"autotune_interval_s": 0.0},
                   {"trace_sample_rate": 1.5},
                   {"trace_capacity": 0},
                   {"flowmetrics_window_s": 0},
                   {"autotune_flush_ms_min": 0.0},
                   {"autotune_step_factor": 1.0}):
            with pytest.raises(ValueError):
                DaemonConfig(**kw)

    def test_quantile_from_deltas(self):
        m = Metrics()
        h = m.histogram("pipeline_queue_wait_seconds")
        for v in (0.001,) * 90 + (0.2,) * 10:
            h.observe(v)
        buckets, counts, _t, _n = h.snapshot()
        assert quantile_from(buckets, counts, 0.5) < 0.01
        assert quantile_from(buckets, counts, 0.99) > 0.05
        # the empty-window sentinel (PR 7): a delta histogram with zero
        # counts between scrapes reads NaN, not a fabricated 0.0 — the
        # autotuner and the SLO burn math both skip such intervals
        from cilium_tpu.runtime.metrics import quantile_is_empty
        assert quantile_is_empty(
            quantile_from(buckets, [0] * len(counts), 0.99))


class TestEngineIntegration:
    def test_autotune_controller_steps_through_engine(self):
        eng = fake_engine(autotune_enabled=True, pipeline_flush_ms=2.0,
                          pipeline_min_bucket=16)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        assert eng._autotune_step() is None       # no pipeline yet
        assert eng.autotune_status() is None
        slot_of = eng.active.snapshot.ep_slot_of
        for i in range(8):
            eng.submit(batch_from_records(
                [pkt("192.168.1.10", "10.1.2.3", 40000 + i, 443)],
                slot_of), now=100 + i)
        assert eng.drain(timeout=10)
        eng._autotune_step()                      # baseline interval
        st = eng.autotune_status()
        assert st is not None
        lo, hi = st["bounds"]["flush_ms"]
        assert lo <= eng._pipeline.flush_ms <= hi
        eng.stop()

    def test_dirty_mark_during_compile_survives_regeneration(self):
        """The VERDICT weak-#6 race, pinned: an observer marking the engine
        dirty while a regeneration is compiling must not have its mark
        erased by that regeneration's completion."""
        eng = fake_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.regenerate()
        assert not eng._dirty
        orig_place = eng.datapath.place

        def place_and_mark(snap):
            eng._mark_dirty()        # e.g. an ipcache upsert mid-compile
            return orig_place(snap)

        eng.datapath.place = place_and_mark
        eng.regenerate(force=True)
        assert eng._dirty            # the mid-compile mark survived
        eng.datapath.place = orig_place
        eng.regenerate()
        assert not eng._dirty
        eng.stop()

    def test_failed_regen_leaves_engine_dirty(self):
        from cilium_tpu.runtime.faults import FAULTS
        eng = fake_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.regenerate()
        try:
            FAULTS.arm("regen.compile", mode="fail", times=1)
            eng._mark_dirty()
            eng.regenerate()         # supervised: serves last-good
            assert eng._dirty        # retry still owed
        finally:
            FAULTS.reset()
            eng.stop()

    def test_api_routes(self, tmp_path):
        from cilium_tpu.runtime.api import APIServer, UnixAPIClient
        eng = fake_engine(trace_sample_rate=1.0, flowlog_mode="all")
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443),
             pkt("192.168.1.10", "10.1.2.3", 40001, 80)], slot_of),
            now=1000)
        sock = str(tmp_path / "api.sock")
        srv = APIServer(eng, sock)
        srv.start()
        try:
            client = UnixAPIClient(sock)
            code, doc = client.get("/v1/flows/metrics")
            assert code == 200
            assert doc["totals"]["forwarded"] == 1
            assert doc["totals"]["dropped"] == 1
            assert doc["windows"][0]["window_start"] == 1000
            code, doc = client.get("/v1/flows/metrics?last=1")
            assert code == 200 and len(doc["windows"]) == 1
            code, tr = client.get("/v1/trace?limit=5")
            assert code == 200 and tr["stats"]["enabled"]
            assert "engine.classify" in tr["summary"]
            code, tr = client.get("/v1/trace?name=engine.classify")
            assert code == 200
            assert all(s["name"] == "engine.classify" for s in tr["spans"])
            code, text = client.get("/v1/metrics")
            assert code == 200
            assert "ciliumtpu_flow_verdicts_total" in text
            code, st = client.get("/v1/status")
            assert code == 200 and st["trace"]["enabled"]
            assert st["autotune"] is None
        finally:
            srv.stop()
            eng.stop()

    def test_metrics_textfile_includes_flowmetrics(self, tmp_path):
        eng = fake_engine(metrics_path=str(tmp_path / "metrics.prom"),
                          flowlog_mode="all")
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)],
            eng.active.snapshot.ep_slot_of), now=50)
        eng.flush_observability()
        text = (tmp_path / "metrics.prom").read_text()
        assert "ciliumtpu_packets_total" in text
        assert 'ciliumtpu_flow_verdicts_total{verdict="FORWARDED"} 1' in text
        eng.stop()


@pytest.mark.slow
class TestTraceOverheadSoak:
    def test_sampled_1_64_overhead_under_2pct(self):
        """The hot-path contract behind the 1/64 default ("an unsampled
        event pays one counter"). Two measurements:

        1. The per-event sampling delta — ``maybe_sample`` at rate 0 (the
           early-out) vs 1/64 (counter + modulo, plus the full span
           recording every 64th event, i.e. the recording cost amortized
           exactly as the pipeline amortizes it) — must stay under 2% of
           the measured per-submission pipeline cost. This is the precise
           form of the claim, and it is deterministic.
        2. An end-to-end pipeline soak (interleaved off/on windows) as a
           gross-regression sanity bound; wall-clock medians on a
           multi-threaded pipeline carry scheduler noise well above 2%,
           so this bound is deliberately loose (15%) — the tight
           assertion is #1.
        """
        import gc
        d = EchoDispatch()
        tr = Tracer(sample_rate=0.0, capacity=4096)
        pl = Pipeline(d, min_bucket=64, max_bucket=256, flush_ms=0.5,
                      queue_batches=512, tracer=tr)
        batch = sub_batch(64, start=0)        # bucket-shaped: direct path

        def one_pass(n=1000):
            t0 = time.perf_counter()
            for _ in range(n):
                pl.submit(batch)
            assert pl.drain(timeout=60)
            return time.perf_counter() - t0

        reps = 100_000

        def micro_pass():
            # ~4 spans ride each sampled submission (admission, microbatch,
            # dispatch, finalize) — charge them to the sampled branch
            t0 = time.perf_counter()
            for _ in range(reps):
                tid = tr.maybe_sample()
                if tid is not None:
                    tr.record(tid, "a", 0.0, 0.0)
                    tr.record(tid, "b", 0.0, 0.0)
                    tr.record(tid, "c", 0.0, 0.0)
                    tr.record(tid, "d", 0.0, 0.0)
            return (time.perf_counter() - t0) / reps

        try:
            for _ in range(3):
                one_pass(300)                  # warmup both code paths
            gc_was = gc.isenabled()
            gc.disable()
            try:
                micro_pass()
                tr.configure(sample_rate=0.0)
                micro_off = min(micro_pass() for _ in range(5))
                tr.configure(sample_rate=1 / 64)
                micro_on = min(micro_pass() for _ in range(5))

                off, on = [], []
                for _i in range(5):            # interleaved A/B windows
                    tr.configure(sample_rate=0.0)
                    off.append(one_pass())
                    tr.configure(sample_rate=1 / 64)
                    on.append(one_pass())
            finally:
                if gc_was:
                    gc.enable()

            per_submit = min(off) / 1000       # best-case submission cost
            delta = micro_on - micro_off       # true hot-path addition
            frac = delta / per_submit
            assert frac < 0.02, \
                f"1/64 sampling adds {delta * 1e9:.0f}ns/event = " \
                f"{frac:.2%} of the {per_submit * 1e6:.1f}us submit path " \
                f"(budget 2%)"
            assert min(on) <= min(off) * 1.15, \
                f"end-to-end regression: off={min(off) * 1e3:.1f}ms " \
                f"on={min(on) * 1e3:.1f}ms"
            assert tr.sampled_total > 0        # the sampler did fire
        finally:
            pl.close(timeout=10)
