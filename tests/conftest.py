"""Test config: force JAX onto CPU with 8 fake devices BEFORE jax import.

This is the standard JAX idiom for testing pmap/shard_map sharding logic
without TPU hardware (SURVEY.md §4: the control-plane-fixture-replay analog).
Must run before anything imports jax, hence conftest at collection time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize.py in some environments registers a TPU PJRT plugin and
# overrides jax_platforms after import, defeating the env vars above. Pin the
# config explicitly — this must happen before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5): no such option — the XLA_FLAGS fallback above
    # already forces 8 host devices
    pass
