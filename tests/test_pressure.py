"""Resource pressure ledger tests (ISSUE 13: observe/pressure.py + the HBM
ledger + the trace-ring drop accounting + departed-subject gauge sweeps).

Tier-1: ledger mechanics (registration, high-water, ETA math, forecast
latching, deregistration gauge sweeps, provider isolation), the engine's
≥12-resource registration floor, CT-row-tracks-gauge exactness, the
RESOURCE_PRESSURE health detail, the overload ladder's fourth latch, the
{resource=} label families surviving concurrent render_metrics scrapes,
ledger register/deregister under engine restart, trace-ring drop
accounting, the pipeline's departed-shard gauge sweep, the verifier budget
doc, and the JIT HBM ledger.

Slow (make pressure-smoke): the cfg6-form storm soak — flood a tiny CT
through the live pipelined engine under the auditor, asserting the ledger's
ct_table row tracks the ct_occupancy gauge bit-for-bit every tick and the
time-to-exhaustion forecast fires before the ladder reaches SHED-NEW —
plus the 8-shard audited scrape-race soak with a mid-soak watchdog restart
(the PR 7/11 house pattern, extended to the resource_* families).
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.observe.pressure import (GAUGE_FAMILIES, LADDER_EXCLUDE,
                                         ResourceLedger)
from cilium_tpu.observe.trace import Tracer
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.utils import constants as C


def _fake_engine(**kw):
    kw.setdefault("auto_regen", False)
    cfg = DaemonConfig(**kw)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",))
    eng.apply_policy([{"endpointSelector": {"matchLabels": {"app": "web"}},
                       "egress": [{"toCIDR": ["10.0.0.0/8"]}]}])
    eng.regenerate()
    return eng


class TestResourceLedger:
    def test_poll_derives_pressure_and_high_water(self):
        m = Metrics()
        led = ResourceLedger(metrics=m)
        occ = {"v": 25.0}
        led.register("p", lambda: {"r": (100, occ["v"])})
        rep = led.poll(now=1.0)
        row = rep["resources"]["r"]
        assert row["capacity"] == 100 and row["occupancy"] == 25
        assert row["pressure"] == 0.25
        assert row["high_water"] == 25
        occ["v"] = 60.0
        led.poll(now=2.0)
        occ["v"] = 10.0
        row = led.poll(now=3.0)["resources"]["r"]
        assert row["occupancy"] == 10 and row["high_water"] == 60
        # the label families exported under the ciliumtpu_resource_* names
        assert m.gauges['resource_high_water{resource="r"}'] == 60
        assert m.gauges['resource_pressure{resource="r"}'] == 0.1

    def test_explicit_pressure_passes_through_verbatim(self):
        led = ResourceLedger()
        led.register("p", lambda: {"ring": (256, 256, 0.0)})
        row = led.poll(now=1.0)["resources"]["ring"]
        # a wrap-by-design ring at full occupancy is NOT pressured
        assert row["occupancy"] == 256 and row["pressure"] == 0.0
        assert led.pressured() == []

    def test_eta_fires_before_exhaustion_then_freezes_on_it(self):
        events = []
        led = ResourceLedger(
            eta_warn_s=50.0, warn=0.5, crit=0.9,
            event_sink=lambda kind, **a: events.append((kind, a)))
        occ = {"v": 0.0}
        led.register("p", lambda: {"ct": (100, occ["v"])})
        # growing 10/s: at occ=60 pressure 0.6 >= warn, eta = 40/10 = 4s
        for t in range(8):
            occ["v"] = 10.0 * t
            led.poll(now=float(t))
        kinds = [k for k, _ in events]
        assert "resource-pressure" in kinds
        fc = dict(events)["resource-pressure"]
        assert fc["resource"] == "ct" and fc["eta_s"] > 0
        assert "resource-exhaustion" not in kinds   # not exhausted yet
        # one event per excursion (latched)
        assert kinds.count("resource-pressure") == 1
        # now actually exhaust: the forecast-then-exhaustion strict freeze
        occ["v"] = 100.0
        led.poll(now=8.0)
        assert [k for k, _ in events].count("resource-exhaustion") == 1
        assert led.report()["exhaustions_total"] == 1

    def test_flat_or_shrinking_resource_has_no_eta(self):
        led = ResourceLedger()
        led.register("p", lambda: {"r": (100, 50.0)})
        for t in range(4):
            led.poll(now=float(t))
        assert led.poll(now=5.0)["resources"]["r"]["eta_s"] is None

    def test_forecast_rearms_after_recovery(self):
        events = []
        led = ResourceLedger(
            eta_warn_s=100.0, warn=0.5, crit=0.99,
            event_sink=lambda kind, **a: events.append(kind))
        occ = {"v": 0.0}
        led.register("p", lambda: {"r": (100, occ["v"])})
        for t in range(7):
            occ["v"] = 10.0 * t
            led.poll(now=float(t))
        assert events.count("resource-pressure") == 1
        # recover: pressure below warn, shrinking → latch re-arms
        for t in range(7, 12):
            occ["v"] = 10.0
            led.poll(now=float(t))
        for t in range(12, 19):
            occ["v"] = 10.0 * (t - 11)
            led.poll(now=float(t))
        assert events.count("resource-pressure") == 2

    def test_deregister_sweeps_every_gauge_family(self):
        m = Metrics()
        led = ResourceLedger(metrics=m)
        led.register("p", lambda: {"a": (10, 9.0), "b": (10, 2.0)})
        led.poll(now=1.0)
        assert 'resource_occupancy{resource="a"}' in m.gauges
        gone = led.deregister("p")
        assert sorted(gone) == ["a", "b"]
        for fam in GAUGE_FAMILIES:
            for r in ("a", "b"):
                assert f'{fam}{{resource="{r}"}}' not in m.gauges
        assert led.report()["resources"] == {}

    def test_silently_departed_resource_is_swept(self):
        # a healthy provider that stops reporting a resource (pipeline
        # closed, incremental compiler discarded) must not leave its
        # frozen pressure pinned in state/gauges — only an ERRORING
        # provider's last readings stand (transient ≠ departed)
        m = Metrics()
        led = ResourceLedger(metrics=m)
        have = {"a": (10, 9.0), "b": (10, 2.0)}
        led.register("p", lambda: dict(have))
        led.poll(now=1.0)
        assert 'resource_pressure{resource="a"}' in m.gauges
        del have["a"]
        rep = led.poll(now=2.0)
        assert "a" not in rep["resources"] and "b" in rep["resources"]
        for fam in GAUGE_FAMILIES:
            assert f'{fam}{{resource="a"}}' not in m.gauges
        # an erroring provider sweeps nothing
        led.register("q", lambda: {"c": (10, 5.0)})
        led.poll(now=3.0)

        def boom():
            raise RuntimeError("transient")
        led.register("q", boom)
        rep = led.poll(now=4.0)
        assert "c" in rep["resources"]       # last good reading stands

    def test_failing_provider_is_isolated_and_counted(self):
        led = ResourceLedger()

        def bad():
            raise RuntimeError("boom")
        led.register("bad", bad)
        led.register("good", lambda: {"r": (10, 5.0)})
        rep = led.poll(now=1.0)
        assert rep["resources"]["r"]["occupancy"] == 5
        assert rep["provider_errors_total"] == 1

    def test_max_pressure_respects_exclusions(self):
        led = ResourceLedger()
        led.register("p", lambda: {"ct_table": (10, 10.0),
                                   "other": (10, 3.0)})
        led.poll(now=1.0)
        assert led.max_pressure() == 1.0
        assert led.max_pressure(exclude=LADDER_EXCLUDE) == 0.3

    def test_validation(self):
        with pytest.raises(ValueError):
            ResourceLedger(warn=0.9, crit=0.5)
        with pytest.raises(ValueError):
            ResourceLedger(window=1)
        with pytest.raises(ValueError):
            ResourceLedger(eta_warn_s=0)


class TestEngineLedger:
    def test_at_least_twelve_resources_register(self):
        # the ISSUE 13 acceptance floor — on the jax-free fake, even
        eng = _fake_engine()
        try:
            eng.start_pipeline()
            rep = eng.resource_step(now=1.0)
            assert len(rep["resources"]) >= 12, sorted(rep["resources"])
            for name in ("ct_table", "admission_queue", "flowlog_ring",
                         "trace_ring", "blackbox_events", "audit_pool",
                         "mapstate_overlay", "patch_budget"):
                assert name in rep["resources"], name
        finally:
            eng.stop()

    def test_ct_row_tracks_occupancy_gauge_exactly(self):
        eng = _fake_engine(ct_capacity=1 << 10)
        try:
            from tests.test_datapath import pkt  # house fixture helpers
            from cilium_tpu.kernels.records import batch_from_records
            recs = [pkt("192.168.0.10", f"10.0.{i >> 8}.{i & 255}",
                        40000 + i, 443, ep_id=1) for i in range(64)]
            eng.classify(batch_from_records(
                recs, eng.active.snapshot.ep_slot_of), now=1000)
            eng.sweep(now=1000)
            gauge = eng.metrics.gauges["ct_occupancy"]
            assert gauge > 0
            row = eng.resource_step(now=5.0)["resources"]["ct_table"]
            assert row["pressure"] == gauge          # bit-for-bit
            assert row["occupancy"] == gauge * (1 << 10)
        finally:
            eng.stop()

    def test_health_resource_pressure_detail_and_degrade(self):
        eng = _fake_engine()
        try:
            assert "resources" not in eng.health()
            eng.ledger.register("drill", lambda: {"drill_pool": (10, 8.0)})
            eng.resource_step(now=1.0)
            h = eng.health()
            assert h["resources"]["detail"] == C.RESOURCE_PRESSURE
            assert "drill_pool" in h["resources"]["pressured"]
            assert h["state"] == C.HEALTH_OK       # warn is attention-only
            eng.ledger.register("drill", lambda: {"drill_pool": (10, 10.0)})
            eng.resource_step(now=2.0)
            h = eng.health()
            assert h["resources"]["critical"]
            assert h["state"] == C.HEALTH_DEGRADED
            # deregistration clears the detail (and the degraded verdict)
            eng.ledger.deregister("drill")
            assert "resources" not in eng.health()
        finally:
            eng.stop()

    def test_overload_ladder_takes_resource_as_fourth_latch(self):
        eng = _fake_engine(overload_up_ticks=1)
        try:
            eng.ledger.register("drill", lambda: {"drill_pool": (10, 10.0)})
            eng.resource_step(now=1.0)
            st = eng.overload_step()
            assert st["inputs"]["resource_pressure"] == 1.0
            assert st["lit"]["resource"]
            st = eng.overload_step()
            # one lit signal holds PRESSURE, exactly like the original three
            from cilium_tpu.pipeline.guard import OVERLOAD_PRESSURE
            assert st["level"] == OVERLOAD_PRESSURE
            # excluded resources never light the latch
            eng.ledger.deregister("drill")
            eng.ledger.register(
                "drill2", lambda: {"audit_pool": (8, 8.0)})
            eng.resource_step(now=2.0)
            st = eng.overload_step()
            assert st["inputs"]["resource_pressure"] == 0.0
        finally:
            eng.stop()

    def test_past_patch_budget_consumption_is_not_standing_pressure(self):
        # a near-budget delta cycle is the LAST cycle's consumption, not a
        # standing occupancy: it must stay visible (occupancy/high-water)
        # without pinning health or the ladder's resource latch forever
        eng = _fake_engine()
        try:
            class _St:
                delta_rows = 1000       # 0.98 of the 1024 budget
                new_identities = 500
            eng._last_update_stats = _St()
            rep = eng.resource_step(now=1.0)
            row = rep["resources"]["patch_budget"]
            assert row["occupancy"] == 1000
            assert row["pressure"] == 0.0        # informational
            assert "resources" not in eng.health()
            st = eng.overload_step()
            assert st["inputs"]["resource_pressure"] == 0.0
        finally:
            eng.stop()

    def test_ladder_caps_at_shed_new_with_all_four_signals_lit(self):
        # severity can reach 4 now; the ladder must hold the top rung,
        # never step past the state table (was a KeyError crashing the
        # overload controller exactly when shedding mattered most)
        from cilium_tpu.pipeline.guard import (OVERLOAD_SHED_NEW,
                                               OverloadLadder)
        ladder = OverloadLadder(up_ticks=1)
        for _ in range(6):
            state, _ = ladder.observe(1.0, 100.0, 1.0,
                                      resource_pressure=1.0)
        assert state == OVERLOAD_SHED_NEW
        assert ladder.status()["inputs"]["severity"] == 4

    def test_wire_out_shed_on_failed_dispatch(self):
        # a fault-tripped dispatch dies between checkout and finalize:
        # the buffer sheds to the GC but the in-flight count must come
        # back down (no phantom wire_pool occupancy)
        from cilium_tpu.runtime.datapath import JITDatapath
        from cilium_tpu.runtime.faults import FAULTS
        from cilium_tpu.kernels.records import empty_batch
        cfg = DaemonConfig(auto_regen=False, ct_capacity=1 << 10)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        try:
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",))
            eng.regenerate()
            b = empty_batch(64)
            FAULTS.reset()
            FAULTS.load_spec("ct.insert=fail:3")
            for _ in range(3):
                with pytest.raises(Exception):
                    eng.classify(dict(b), now=1000)
            FAULTS.reset()
            assert eng.datapath.wire_pool_stats()["in_flight"] == 0
            eng.classify(dict(b), now=1000)   # healthy dispatch balances
            assert eng.datapath.wire_pool_stats()["in_flight"] == 0
        finally:
            FAULTS.reset()
            eng.stop()

    def test_wire_pool_occupancy_counts_checkouts_not_free(self):
        from cilium_tpu.runtime.datapath import JITDatapath
        cfg = DaemonConfig(auto_regen=False, ct_capacity=1 << 10)
        dp = JITDatapath(cfg)
        s = dp.wire_pool_stats()
        assert s["in_flight"] == 0               # idle pool ≠ exhausted
        with dp._pack_lock:
            buf = dp._wire_buf(256, 4)
        assert dp.wire_pool_stats()["in_flight"] == 1
        dp._wire_buf_release((256, 4), buf)
        s = dp.wire_pool_stats()
        assert s["in_flight"] == 0 and s["free"] == 1

    def test_register_deregister_under_engine_restart(self):
        eng = _fake_engine()
        eng.start_pipeline()
        eng.resource_step(now=1.0)
        fams = [g for g in eng.metrics.gauges if g.startswith("resource_")]
        assert fams
        eng.stop()
        # a stopped engine sweeps its whole exported surface
        assert not [g for g in eng.metrics.gauges
                    if g.startswith("resource_")]
        assert eng.ledger.report()["resources"] == {}
        # a fresh engine re-registers from scratch
        eng2 = _fake_engine()
        try:
            rep = eng2.resource_step(now=1.0)
            assert "ct_table" in rep["resources"]
        finally:
            eng2.stop()

    def test_resource_families_survive_concurrent_scrapes(self):
        # the PR 7/11 scrape-race house pattern on the new {resource=}
        # families: render_metrics scrapers race ledger polls AND a
        # register/deregister churn loop — no exceptions, parseable text
        eng = _fake_engine()
        eng.start_pipeline()
        errors = []
        stop = threading.Event()

        def scraper():
            try:
                while not stop.is_set():
                    text = eng.render_metrics()
                    assert "ciliumtpu_" in text
            except Exception as e:   # noqa: BLE001
                errors.append(e)

        def churn():
            try:
                i = 0
                while not stop.is_set():
                    i += 1
                    eng.ledger.register(
                        "churn", lambda: {"churn_pool": (64, 32.0)})
                    eng.resource_step(now=float(i))
                    eng.ledger.deregister("churn")
            except Exception as e:   # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=scraper) for _ in range(2)] \
            + [threading.Thread(target=churn)]
        for t in threads:
            t.start()
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join(5)
        eng.stop()
        assert not errors

    def test_status_doc_carries_resources_and_hbm(self):
        from cilium_tpu.runtime.api import status_doc
        eng = _fake_engine()
        try:
            eng.resource_step(now=1.0)
            doc = status_doc(eng)
            assert "pressured" in doc["resources"]
            assert doc["hbm"]["ledger"] is None    # jax-free fake
            eng.note_verifier_budget({"worst_total_bytes": 123})
            assert status_doc(eng)["hbm"]["verifier"][
                "worst_total_bytes"] == 123
        finally:
            eng.stop()

    def test_resources_api_route(self, tmp_path):
        from cilium_tpu.runtime.api import APIServer, UnixAPIClient
        eng = _fake_engine()
        sock = str(tmp_path / "api.sock")
        srv = APIServer(eng, sock)
        srv.start()
        try:
            eng.resource_step(now=1.0)   # the controller's role
            status, doc = UnixAPIClient(sock).get("/v1/resources")
            assert status == 200
            assert "ct_table" in doc["resources"]
            assert doc["hbm"]["ledger"] is None
            # the route is the READ side: a scrape must not advance the
            # ledger's sampling (no resource.poll side effects)
            polls = doc["polls_total"]
            status, doc2 = UnixAPIClient(sock).get("/v1/resources")
            assert doc2["polls_total"] == polls
        finally:
            srv.stop()
            eng.stop()


class TestTraceRingDropAccounting:
    def test_overwrites_count_and_wraps(self):
        tr = Tracer(sample_rate=1.0, capacity=4)
        for i in range(10):
            tid = tr.maybe_sample()
            tr.record(tid, "s", 0.0, 0.001)
        st = tr.stats()
        assert st["spans_in_ring"] == 4
        assert st["spans_dropped_total"] == 6
        # a wrap is a completed cycle of LOSS (the initial free fill is
        # not one): 10 records = fill 4 + one full drop cycle + 2
        assert st["ring_wraps"] == 1
        tr.reset()
        st = tr.stats()
        assert st["spans_dropped_total"] == 0 and st["ring_wraps"] == 0

    def test_no_drops_while_ring_has_room(self):
        tr = Tracer(sample_rate=1.0, capacity=16)
        for _ in range(10):
            tr.record(tr.maybe_sample(), "s", 0.0, 0.001)
        st = tr.stats()
        assert st["spans_dropped_total"] == 0 and st["ring_wraps"] == 0

    def test_engine_exports_drop_counters(self):
        from cilium_tpu.observe.trace import TRACER
        eng = _fake_engine()
        try:
            TRACER.reset()
            TRACER.configure(sample_rate=1.0, capacity=4)
            for _ in range(9):
                TRACER.record(TRACER.maybe_sample(), "drill", 0.0, 0.001)
            text = eng.render_metrics()
            assert "ciliumtpu_trace_spans_dropped_total 5" in text
            assert "ciliumtpu_trace_ring_wraps_total 1" in text
        finally:
            TRACER.configure(sample_rate=0.0, capacity=4096)
            TRACER.reset()
            eng.stop()


class TestDepartedSubjectSweeps:
    def test_pipeline_close_drops_shard_gauges(self):
        from cilium_tpu.pipeline import Pipeline
        from tests.test_pipeline import EchoDispatch, sub_batch
        m = Metrics()
        echo = EchoDispatch()
        pl = Pipeline(lambda b, now, steer_rev=None: echo(b, now),
                      metrics=m, max_bucket=64,
                      min_bucket=8, n_shards=2,
                      shard_fn=lambda b: np.zeros(
                          b["valid"].shape[0], dtype=np.int64))
        pl.submit(sub_batch(8, 0)).result(timeout=10)
        assert 'pipeline_staged_rows{shard="0"}' in m.gauges
        pl.close(timeout=10)
        assert 'pipeline_staged_rows{shard="0"}' not in m.gauges
        assert 'pipeline_staged_rows{shard="1"}' not in m.gauges

    def test_mesh_withdraw_drops_peer_lag_gauges(self, tmp_path):
        eng = _fake_engine()
        eng2 = None
        try:
            mesh = eng.attach_mesh(store_dir=str(tmp_path), node_name="a")
            cfg2 = DaemonConfig(auto_regen=False)
            eng2 = Engine(cfg2, datapath=FakeDatapath(cfg2))
            eng2.add_endpoint(["k8s:app=db"], ips=("192.168.1.20",))
            eng2.regenerate()
            mesh2 = eng2.attach_mesh(store_dir=str(tmp_path),
                                     node_name="b")
            mesh2.step()
            mesh.step()
            assert 'clustermesh_peer_lag_seconds{peer="b"}' \
                in eng.metrics.gauges
            mesh.withdraw()
            assert 'clustermesh_peer_lag_seconds{peer="b"}' \
                not in eng.metrics.gauges
        finally:
            eng.stop()
            if eng2 is not None:
                eng2.stop()


class TestVerifierBudgetDoc:
    def test_budget_doc_summarizes_worst_combo(self):
        from cilium_tpu.compile.verifier import ComboReport, budget_doc
        reports = [
            ComboReport(name="a", ok=True, argument_bytes=100,
                        temp_bytes=50),
            ComboReport(name="b", ok=True, argument_bytes=400,
                        temp_bytes=100),
            ComboReport(name="c", ok=False, error="reject"),
        ]
        doc = budget_doc(reports, max_hbm_bytes=1 << 20)
        assert doc["combos"] == 3 and doc["accepted"] == 2
        assert doc["rejected"] == ["c"]
        assert doc["worst_combo"] == "b"
        assert doc["worst_total_bytes"] == 500
        assert doc["max_hbm_bytes"] == 1 << 20

    def test_memory_stats_public_name(self):
        from cilium_tpu.compile import verifier

        class FakeCompiled:
            def memory_analysis(self):
                class M:
                    argument_size_in_bytes = 10
                    temp_size_in_bytes = 20
                    output_size_in_bytes = 30
                return M()
        st = verifier.memory_stats(FakeCompiled())
        assert st == {"argument_bytes": 10, "temp_bytes": 20,
                      "output_bytes": 30}


class TestMapstateOverlayStats:
    def test_overlay_copy_updates_module_stats(self):
        from cilium_tpu.policy.mapstate import (MapState, overlay_stats)
        ms = MapState()
        clone = ms.overlay_copy()
        base = overlay_stats()
        assert base["fold_budget"] == MapState.OVERLAY_FOLD_KEYS
        clone2 = clone.overlay_copy()
        assert overlay_stats()["copies"] > base["copies"]
        assert clone2 is not clone


class TestJITHBMLedger:
    def test_place_and_patch_account_groups(self):
        from cilium_tpu.runtime.datapath import JITDatapath
        cfg = DaemonConfig(auto_regen=False, ct_capacity=1 << 10,
                           max_hbm_bytes=1 << 28)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        try:
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",))
            eng.apply_policy([{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [{"toCIDR": ["10.0.0.0/8"]}]}])
            eng.regenerate()
            hl = eng.datapath.hbm_ledger()
            assert hl["places_total"] == 1
            for g in ("verdict", "tries", "policy", "ct"):
                assert hl["groups"][g] > 0, g
            assert hl["device_bytes"] == sum(
                v for k, v in hl["groups"].items() if k != "wire_pool")
            # a live patch re-accounts without a full place
            eng.apply_policy([{
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [{"toCIDR": ["172.16.0.0/12"]}]}])
            eng.regenerate()
            hl2 = eng.datapath.hbm_ledger()
            assert hl2["places_total"] + hl2["patches_total"] >= 2
            # the hbm resource row budgets device bytes
            row = eng.resource_step(now=1.0)["resources"]["hbm"]
            assert row["capacity"] == float(1 << 28)
            assert row["occupancy"] == hl2["device_bytes"]
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# slow: the cfg6-form pressure soak (make pressure-smoke)
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestPressureSoak:
    def test_storm_ct_row_bit_identical_and_eta_before_shed_new(self):
        """The cfg6 acceptance, in-tree: a SYN flood saturates a tiny CT
        through the live pipelined engine (auditor at 1.0); every tick the
        ledger's ct_table row must equal the ct_occupancy gauge EXACTLY,
        and the time-to-exhaustion forecast must fire before the overload
        ladder reaches SHED-NEW."""
        from cilium_tpu.pipeline.guard import OVERLOAD_SHED_NEW
        from cilium_tpu.runtime.datapath import JITDatapath
        rng = np.random.default_rng(7)
        cap = 1 << 10
        cfg = DaemonConfig(
            ct_capacity=cap, auto_regen=False, batch_size=256,
            pipeline_flush_ms=0.5, pipeline_queue_batches=8,
            pipeline_block_timeout_s=0.05,
            audit_enabled=True, audit_sample_rate=1.0,
            audit_pool_batches=64, flowlog_mode="none",
            ct_gc_chunk_rows=1 << 8,
            ct_pressure_high=0.8, ct_pressure_low=0.5,
            overload_up_ticks=1, overload_down_ticks=4,
            overload_shed_rate_high=15.0, overload_shed_rate_low=2.0,
            resource_eta_warn_s=1000.0)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.auditor.configure(sample_rate=1.0)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromCIDR": ["10.0.0.0/8"],
                         "toPorts": [{"ports": [
                             {"port": "80", "protocol": "TCP"}]}]}]}])
        eng.regenerate()

        def flood_batch(n=256):
            from cilium_tpu.kernels.records import empty_batch
            b = empty_batch(n)
            b["valid"][:] = True
            b["src"][:, 3] = (0x0A000000
                              + rng.integers(1, 1 << 24, n)).astype(
                                  np.uint32)
            b["dst"][:, 3] = 0xC0A8000A
            b["dst"][:, 2] = 0xFFFF
            b["src"][:, 2] = 0xFFFF
            b["sport"][:] = rng.integers(1024, 65535, n)
            b["dport"][:] = 80
            b["proto"][:] = C.PROTO_TCP
            b["tcp_flags"][:] = 0x02
            b["direction"][:] = C.DIR_INGRESS
            b["ep_slot"][:] = 0
            b["_prio"] = np.ones((n,), np.int8)
            return b

        L = 50_000
        forecast_tick = shed_new_tick = None
        mismatches = []
        try:
            for tick in range(60):
                L += 1
                for _ in range(6):
                    try:
                        eng.submit(flood_batch(), now=L, deadline_ms=200)
                    except Exception:   # noqa: BLE001 — sheds are the point
                        pass
                eng.drain(timeout=60)
                st = eng.overload_step()
                eng.sweep_step(now=L)
                eng.audit_step(budget=32)
                rep = eng.resource_step(now=float(L))
                row = rep["resources"]["ct_table"]
                gauge = float(eng.metrics.gauges.get("ct_occupancy", 0.0))
                if row["pressure"] != gauge:
                    mismatches.append((tick, row["pressure"], gauge))
                if forecast_tick is None and row["forecast"]:
                    forecast_tick = tick
                if shed_new_tick is None \
                        and st["level"] >= OVERLOAD_SHED_NEW:
                    shed_new_tick = tick
                if shed_new_tick is not None and forecast_tick is not None:
                    break
            assert not mismatches, mismatches[:4]
            assert forecast_tick is not None, \
                "time-to-exhaustion never fired for ct_table"
            if shed_new_tick is not None:
                assert forecast_tick < shed_new_tick, (forecast_tick,
                                                       shed_new_tick)
            aud = eng.auditor.stats()
            assert aud["mismatched_rows"] == 0
        finally:
            eng.stop()

    def test_8shard_audited_soak_scrape_race_with_restart(self):
        """The PR 7/11 house pattern extended to the {resource=} families:
        an 8-shard audited pipeline soak with concurrent render_metrics
        scrapers and a mid-soak watchdog restart (hang-forced), asserting
        the resource families stay scrapeable and consistent throughout
        and after the restart the per-shard staged gauges are live again."""
        from cilium_tpu.runtime.datapath import JITDatapath
        from cilium_tpu.runtime.faults import FAULTS
        from tests.test_datapath import pkt
        from cilium_tpu.kernels.records import batch_from_records
        cfg = DaemonConfig(
            n_shards=8, auto_regen=False, batch_size=512,
            ct_capacity=1 << 12, pipeline_flush_ms=0.5,
            audit_enabled=True, audit_sample_rate=1.0,
            pipeline_stall_timeout_s=1.0, pipeline_max_restarts=3,
            pipeline_restart_backoff_s=0.05)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.auditor.configure(sample_rate=1.0)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [
                            {"port": "443", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        errors = []
        stop = threading.Event()

        def scraper():
            try:
                while not stop.is_set():
                    text = eng.render_metrics()
                    lines = [ln for ln in text.splitlines()
                             if ln.startswith("ciliumtpu_resource_")]
                    for ln in lines:       # every exported row parses
                        float(ln.rsplit(" ", 1)[1])
            except Exception as e:   # noqa: BLE001
                errors.append(e)
        threads = [threading.Thread(target=scraper) for _ in range(2)]
        for t in threads:
            t.start()

        def batch(i):
            recs = [pkt("192.168.0.10", f"10.0.{(i + j) % 250}.1",
                        40000 + j, 443, ep_id=1) for j in range(64)]
            return batch_from_records(recs,
                                      eng.active.snapshot.ep_slot_of)
        try:
            FAULTS.reset()
            for i in range(20):
                eng.submit(batch(i), now=1000 + i)
            assert eng.drain(timeout=120)
            eng.resource_step(now=1.0)
            # mid-soak watchdog restart: hang one dispatch past the stall
            # budget; the watchdog fences the worker and restarts
            FAULTS.load_spec("datapath.transfer=hang:4:1")
            try:
                eng.submit(batch(99), now=2000)
            except Exception:   # noqa: BLE001 — the wedged window rejects
                pass
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                ps = eng.pipeline_stats()
                if ps and ps["restarts"] >= 1 and ps["state"] == "ok":
                    break
                time.sleep(0.1)
            FAULTS.reset()
            ps = eng.pipeline_stats()
            assert ps["restarts"] >= 1
            # post-restart: serving resumes and the families still export
            for i in range(10):
                eng.submit(batch(200 + i), now=3000 + i)
            assert eng.drain(timeout=120)
            for _ in range(50):
                step = eng.audit_step(budget=128)
                if not step or (not step.get("replayed")
                                and not step.get("pending")):
                    break
            rep = eng.resource_step(now=10.0)
            assert "staging_segment_peak" in rep["resources"]
            assert len(rep["resources"]) >= 12
            assert eng.auditor.stats()["mismatched_rows"] == 0
        finally:
            stop.set()
            for t in threads:
                t.join(5)
            FAULTS.reset()
            eng.stop()
        assert not errors
