"""Verdict-provenance tests: shadow-oracle parity audit (observe/audit.py),
the flight recorder (observe/blackbox.py), and the end-to-end latency SLO
plumbing.

Unit tests drive the auditor directly — deterministic counter sampling,
bounded capture pool with ``skipped`` accounting, the ``audit.corrupt``
fault drill (detection + health degradation + frozen bundle with the
offending rows and revision), and fault tolerance (a wedged/crashing
auditor never stalls serving). Integration tests run it against engines on
both backends, including a sharded 8-shard pipeline; the ``slow``-marked
soak (``make audit-smoke``) pushes 10k submissions with the auditor armed
at sampling 1.0 and asserts zero mismatches, then arms ``audit.corrupt``
and asserts the corruption is detected within the sampling window.

The satellite coverage also lives here: ``quantile_from`` empty-window
sentinel, feeder-stats Prometheus families + labeled-histogram TYPE
dedupe, a concurrent ``render_metrics`` scrape racing a sharded soak, and
trace-ring wraparound with audit capture armed.
"""

import json
import math
import threading
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.observe.audit import ShadowAuditor
from cilium_tpu.observe.blackbox import FlightRecorder
from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.runtime.metrics import (EMPTY_QUANTILE, Histogram, Metrics,
                                        quantile_from, quantile_is_empty)
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

from tests.test_pipeline import POLICY, fake_engine, mk_chunks, pkt


@pytest.fixture(autouse=True)
def _clean():
    FAULTS.reset()
    yield
    FAULTS.reset()
    TRACER.configure(sample_rate=0.0)
    TRACER.reset()


def audited_engine(**kw):
    kw.setdefault("audit_enabled", True)
    kw.setdefault("audit_sample_rate", 1.0)
    return fake_engine(**kw)


class ShardedFake(FakeDatapath):
    """Oracle-backed fake serving an 8-way flow mesh: the class attribute
    shadows the base property, so the engine builds the 8-segment steered
    staging ring (per-shard scatter, unsteer-on-finalize) on top of the
    oracle — the audit path then sees real steered-geometry buckets."""

    pipeline_shards = 8


def sharded_audited_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    kw.setdefault("batch_size", 64)
    kw.setdefault("audit_enabled", True)
    kw.setdefault("audit_sample_rate", 1.0)
    cfg = DaemonConfig(**kw)
    return Engine(cfg, datapath=ShardedFake(cfg))


def web_batch(eng, dports=(443, 80, 22)):
    slot_of = eng.active.snapshot.ep_slot_of
    recs = [pkt("192.168.1.10", "10.1.2.3", 40000 + dp, dp)
            for dp in dports]
    return batch_from_records(recs, slot_of)


def setup_web(eng):
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(POLICY)
    return eng


# --------------------------------------------------------------------------- #
# shadow auditor
# --------------------------------------------------------------------------- #
class TestAuditorUnit:
    def test_counter_sampling_is_deterministic(self):
        eng = setup_web(audited_engine(audit_sample_rate=0.25))
        b = web_batch(eng)
        for i in range(8):
            eng.classify(dict(b), now=100 + i)
        eng.audit_step()
        st = eng.auditor.stats()
        # every 4th finalized batch captured: batches 0 and 4
        assert st["captured_batches"] == 2
        assert st["checked_batches"] == 2
        eng.stop()

    def test_clean_engine_audits_clean(self):
        eng = setup_web(audited_engine())
        b = web_batch(eng)
        for i in range(5):
            eng.classify(dict(b), now=100 + i)   # CT revisits included
        eng.audit_step()
        st = eng.auditor.stats()
        assert st["checked_rows"] == 15 and st["mismatched_rows"] == 0
        assert eng.auditor.healthy
        assert eng.health()["state"] == C.HEALTH_OK
        # the labeled mismatch family must not exist on a clean engine
        assert not any("parity_audit_mismatched" in k
                       for k in eng.metrics.counters)
        assert eng.metrics.counters["parity_audit_checked_total"] == 15
        eng.stop()

    def test_disabled_auditor_captures_nothing(self):
        eng = setup_web(fake_engine())       # audit_enabled defaults False
        eng.classify(web_batch(eng), now=100)
        assert eng.auditor.sample_rate == 0.0
        assert eng.auditor.stats()["captured_batches"] == 0
        eng.stop()

    def test_bounded_pool_sheds_with_skipped_accounting(self):
        eng = setup_web(audited_engine(audit_pool_batches=2))
        b = web_batch(eng)
        for i in range(6):                   # no replay between captures
            eng.classify(dict(b), now=100 + i)
        st = eng.auditor.stats()
        assert st["captured_batches"] == 2 and st["skipped_batches"] == 4
        assert eng.metrics.counters["parity_audit_skipped_total"] == 4
        # the backlog replays clean once the controller catches up
        eng.audit_step()
        st = eng.auditor.stats()
        assert st["checked_batches"] == 2 and st["mismatched_rows"] == 0
        eng.stop()

    def test_corruption_drill_detects_degrades_and_freezes(self):
        """The acceptance contract: with audit.corrupt armed the auditor
        detects within the sampling window, health goes DEGRADED, and a
        flight-recorder bundle with the offending rows + revision comes
        out of the debug-bundle surface."""
        eng = setup_web(audited_engine())
        b = web_batch(eng)
        eng.classify(dict(b), now=100)
        eng.audit_step()
        assert eng.auditor.healthy
        rev = eng.active.revision
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(dict(b), now=101)
        eng.classify(dict(b), now=102)       # later batches are clean again
        eng.audit_step()
        st = eng.auditor.stats()
        assert st["mismatched_batches"] == 1
        assert st["mismatched_rows"] == 3    # every flipped row caught
        assert st["last_mismatch_revision"] == rev
        h = eng.health()
        assert h["state"] == C.HEALTH_DEGRADED
        assert h["audit"]["mismatched_rows"] == 3
        key = f'parity_audit_mismatched_total{{revision="{rev}"}}'
        assert eng.metrics.counters[key] == 3
        bundle = eng.debug_bundle()
        assert bundle["frozen"] and bundle["reason"] == "parity-mismatch"
        assert bundle["detail"]["revision"] == rev
        assert bundle["detail"]["rows"], "offending rows must ride the bundle"
        assert bundle["detail"]["rows"][0]["diffs"]["allow"]
        assert bundle["engine"]["audit"]["mismatched_rows"] == 3
        json.dumps(bundle, default=str)      # exportable as-is
        eng.stop()

    def test_clear_rearms_health_and_next_mismatch_freezes_again(self):
        """The operator workflow the runbook promises: pull the bundle
        with clear=True → health returns to OK and the recorder unfreezes;
        a LATER mismatch degrades and freezes afresh."""
        eng = setup_web(audited_engine())
        b = web_batch(eng)
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(dict(b), now=100)
        eng.audit_step()
        assert eng.health()["state"] == C.HEALTH_DEGRADED
        eng.debug_bundle(clear=True)         # investigated: re-arm
        assert eng.health()["state"] == C.HEALTH_OK
        assert not eng.blackbox.stats()["frozen"]
        assert eng.auditor.healthy
        # per-revision mismatch counters are history and survive re-arm
        assert any("parity_audit_mismatched" in k
                   for k in eng.metrics.counters)
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(dict(b), now=200)
        eng.audit_step()
        assert eng.health()["state"] == C.HEALTH_DEGRADED
        assert eng.debug_bundle()["frozen"]
        eng.stop()

    def test_mismatch_diff_names_the_field_and_flow(self):
        eng = setup_web(audited_engine())
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(web_batch(eng, dports=(443,)), now=100)
        eng.audit_step()
        (m,) = list(eng.auditor.mismatches)
        row = m["rows"][0]
        assert row["diffs"]["allow"] == {"want": True, "got": False}
        # a flipped allow on a NEW flow also tears the implied CT delta
        assert row["diffs"]["ct_delta"] == {"want": "create", "got": "none"}
        assert row["flow"]["dport"] == 443 and row["flow"]["ep_id"] == 1
        assert m["corrupt_injected"] is True
        eng.stop()

    def test_capture_crash_never_reaches_serving(self, monkeypatch):
        eng = setup_web(audited_engine())
        monkeypatch.setattr(eng.auditor, "_capture",
                            lambda *a, **k: 1 / 0)
        out = eng.classify(web_batch(eng), now=100)   # must not raise
        assert out["allow"][0]
        assert eng.auditor.stats()["capture_errors"] == 1
        assert eng.metrics.counters[
            "parity_audit_capture_errors_total"] == 1
        eng.stop()

    def test_replay_crash_is_counted_not_fatal(self, monkeypatch):
        eng = setup_web(audited_engine())
        eng.classify(web_batch(eng), now=100)
        monkeypatch.setattr(eng.auditor, "_oracle_for",
                            lambda snap: 1 / 0)
        res = eng.audit_step()               # must not raise
        assert res["replayed"] == 1
        assert eng.auditor.stats()["replay_errors"] == 1
        eng.stop()

    def test_wedged_auditor_never_stalls_serving(self):
        """A deliberately wedged replay thread: serving keeps answering
        at full function while captures overflow into `skipped` — the
        bounded-pool degradation contract."""
        eng = setup_web(audited_engine(audit_pool_batches=2))
        b = web_batch(eng)
        release = threading.Event()

        def wedged_step():
            release.wait(30)                 # the wedge
            return eng.audit_step()

        t = threading.Thread(target=wedged_step, daemon=True)
        t.start()
        outs = [eng.classify(dict(b), now=100 + i) for i in range(10)]
        assert all(bool(o["allow"][0]) for o in outs)
        st = eng.auditor.stats()
        assert st["skipped_batches"] >= 8    # pool=2, 10 batches at rate 1.0
        release.set()
        t.join(10)
        assert eng.auditor.stats()["mismatched_rows"] == 0
        eng.stop()

    def test_audit_controller_runs_in_background(self):
        eng = setup_web(audited_engine(audit_interval_s=0.05))
        eng.start_background()
        try:
            eng.classify(web_batch(eng), now=100)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if eng.auditor.stats()["checked_batches"] >= 1:
                    break
                time.sleep(0.02)
            st = eng.auditor.stats()
            assert st["checked_batches"] >= 1 and st["mismatched_rows"] == 0
        finally:
            eng.stop()

    def test_replay_against_superseded_revision(self):
        """A capture replays against the snapshot it classified under,
        even after a policy change regenerated a newer world — the
        revision fence of the audit path."""
        eng = setup_web(audited_engine())
        b = web_batch(eng)
        eng.classify(dict(b), now=100)
        old_rev = eng.active.revision
        # flip the policy so the same flow now gets the opposite verdict
        eng.replace_policy(["k8s:app=web"], [{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egressDeny": [{"toCIDR": ["10.0.0.0/8"]}]}])
        eng.regenerate(force=True)
        assert eng.active.revision > old_rev
        eng.classify(dict(b), now=101)
        eng.audit_step()
        st = eng.auditor.stats()
        assert st["checked_batches"] == 2 and st["mismatched_rows"] == 0
        eng.stop()


class TestAuditorPipelined:
    def test_pipelined_batches_audit_clean(self):
        eng = setup_web(audited_engine(pipeline_min_bucket=16))
        chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=12,
                           rows_per_chunk=8, repeats=True)
        tickets = [eng.submit(dict(ch), now=100 + i)
                   for i, ch in enumerate(chunks)]
        assert eng.drain(timeout=30)
        for t in tickets:
            t.result(timeout=5)
        while eng.audit_step()["replayed"]:
            pass
        st = eng.auditor.stats()
        assert st["checked_rows"] > 0 and st["mismatched_rows"] == 0
        eng.stop()

    def test_pipelined_corruption_detected(self):
        eng = setup_web(audited_engine(pipeline_min_bucket=16))
        chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=6,
                           rows_per_chunk=8)
        FAULTS.arm("audit.corrupt", mode="fail", times=1)
        for i, ch in enumerate(chunks):
            eng.submit(dict(ch), now=100 + i)
        assert eng.drain(timeout=30)
        FAULTS.disarm("audit.corrupt")
        while eng.audit_step()["replayed"]:
            pass
        assert eng.auditor.stats()["mismatched_rows"] > 0
        assert eng.health()["state"] == C.HEALTH_DEGRADED
        assert eng.debug_bundle()["frozen"]
        eng.stop()


# --------------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------------- #
class TestFlightRecorder:
    def test_event_ring_is_bounded(self):
        fr = FlightRecorder(capacity=4, metrics=Metrics())
        for i in range(10):
            fr.record_event("regen", revision=i)
        st = fr.stats()
        assert st["events_in_ring"] == 4 and st["events_total"] == 10
        assert not st["frozen"]

    def test_first_anomaly_wins(self):
        fr = FlightRecorder(metrics=Metrics())
        fr.record_event("regen", revision=1)
        fr.record_event("watchdog", action="restart", reason="stall")
        fr.record_event("breaker", old="closed", new="open")
        st = fr.stats()
        assert st["frozen"] and st["freezes_total"] == 2
        assert st["frozen_reason"].startswith("watchdog")
        b = fr.bundle()
        kinds = [e["kind"] for e in b["events"]]
        assert kinds[0] == "regen"           # lead-up context preserved
        fr.clear()
        assert not fr.stats()["frozen"]

    def test_breaker_close_does_not_freeze(self):
        fr = FlightRecorder(metrics=Metrics())
        fr.record_event("breaker", old="open", new="half-open")
        fr.record_event("breaker", old="half-open", new="closed")
        assert not fr.stats()["frozen"]

    def test_shed_spike_freezes_single_shed_does_not(self):
        fr = FlightRecorder(shed_spike=5, shed_window_s=10.0,
                            metrics=Metrics())
        fr.record_event("shed", reason="flush")
        assert not fr.stats()["frozen"]
        for _ in range(5):
            fr.record_event("shed", reason="flush")
        st = fr.stats()
        assert st["frozen"] and st["frozen_reason"].startswith("shed-spike")

    def test_verdict_summaries_and_span_tail_ride_the_bundle(self):
        tr = Tracer(sample_rate=1.0, capacity=32)
        tid = tr.maybe_sample()
        tr.record(tid, "pipeline.dispatch", 0.0, 0.002)
        fr = FlightRecorder(metrics=Metrics(), tracer=tr)
        out = {"allow": np.array([True, False, False]),
               "reason": np.array([0, int(C.DropReason.POLICY),
                                   int(C.DropReason.POLICY)], np.int32)}
        fr.record_verdicts(out, n_valid=3, now=100)
        b = fr.freeze("parity-mismatch", detail={"revision": 7})
        (vs,) = b["verdict_summaries"]
        assert vs["allowed"] == 1 and vs["dropped"] == 2
        assert vs["top_reasons"] == {"POLICY": 2}
        assert b["spans"][0]["name"] == "pipeline.dispatch"
        assert b["detail"]["revision"] == 7

    def test_pipeline_guard_events_reach_the_recorder(self):
        """The scheduler's event_sink: a real watchdog restart (hang-wedged
        dispatch) must land in the engine's flight recorder and freeze."""
        eng = setup_web(fake_engine(pipeline_stall_timeout_s=30.0,
                                    pipeline_restart_backoff_s=0.05))
        pl = eng.start_pipeline()
        pl.set_stall_timeout_s(0.5)
        FAULTS.arm("pipeline.dispatch", mode="hang", delay_s=5.0, times=1)
        for i in range(3):
            eng.submit(web_batch(eng), now=100 + i)
        eng.drain(timeout=20)
        FAULTS.disarm("pipeline.dispatch")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and not eng.blackbox.stats()["frozen"]:
            time.sleep(0.05)
        st = eng.blackbox.stats()
        assert st["frozen"] and st["frozen_reason"].startswith("watchdog")
        bundle = eng.debug_bundle()
        assert any(e["kind"] == "watchdog" for e in bundle["events"])
        eng.stop()


# --------------------------------------------------------------------------- #
# end-to-end latency SLO plumbing
# --------------------------------------------------------------------------- #
class TestE2ELatency:
    def test_ingest_mono_rides_the_ticket(self):
        eng = setup_web(fake_engine())
        stamp = time.monotonic() - 0.25      # harvested 250ms ago
        t = eng.submit(web_batch(eng), now=100, ingest_mono=stamp)
        t.result(timeout=10)
        assert t.ingest_mono == stamp
        t2 = eng.submit(web_batch(eng), now=101)
        t2.result(timeout=10)
        assert t2.ingest_mono is None
        eng.stop()

    def test_feeder_observes_e2e_and_burns_slo(self):
        """Drive _apply_one directly with a back-dated harvest stamp: the
        e2e histogram and the SLO burn counter must both move."""
        from cilium_tpu.shim.feeder import ShimFeeder

        class _StubShim:
            batch_size = 8

            def make_poll_buffer(self):
                from cilium_tpu.kernels.records import empty_batch
                b = empty_batch(8)
                b["_ep_raw"] = np.zeros(8, np.int64)
                return b

            def apply_verdicts(self, allow):
                pass

        class _StubTicket:
            def done(self):
                return True

            def result(self, timeout=None):
                return {"allow": np.ones(8, bool)}

        m = Metrics()
        fd = ShimFeeder(_StubShim(), engine=None, pool_batches=1,
                        slo_ms=50.0, metrics=m)
        buf = fd._free[0]
        fd._apply_one(_StubTicket(), buf,
                      ingest_mono=time.monotonic() - 0.2)
        fd._apply_one(_StubTicket(), buf,
                      ingest_mono=time.monotonic() - 0.001)
        h = m.histograms["ingest_e2e_latency_seconds"]
        assert h.count == 2
        assert m.counters["ingest_e2e_slo_burn_total"] == 1
        st = fd.stats()
        assert st["slo_burns"] == 1 and st["e2e_p99_ms"] > 50

    def test_per_shard_e2e_families_and_type_dedupe(self):
        """Sharded feeder: per-shard labeled e2e histogram families render
        with ONE TYPE line for the base metric (the satellite's labeled-
        histogram contract)."""
        from cilium_tpu.pipeline.scheduler import shard_bin_encode
        from cilium_tpu.shim.feeder import ShimFeeder

        class _StubShim:
            batch_size = 8

            def make_poll_buffer(self):
                from cilium_tpu.kernels.records import empty_batch
                b = empty_batch(8)
                b["_ep_raw"] = np.zeros(8, np.int64)
                return b

            def apply_verdicts(self, allow):
                pass

        class _StubTicket:
            def done(self):
                return True

            def result(self, timeout=None):
                return {"allow": np.ones(8, bool)}

        m = Metrics()
        fd = ShimFeeder(_StubShim(), engine=None, pool_batches=1,
                        n_shards=4, slo_ms=10.0, metrics=m)
        buf = fd._free[0]
        buf["_shard"][:] = shard_bin_encode(
            np.array([0, 0, 1, 1, 3, 3, 3, 3]), revision=1)
        # only the valid rows' bins attribute — the padding tail's
        # zeroed-row hash must not credit an idle shard
        buf["valid"][:6] = True              # shards 0, 1, 3 (3 via rows 4-5)
        fd._apply_one(_StubTicket(), buf,
                      ingest_mono=time.monotonic() - 0.1)
        assert 'ingest_e2e_latency_seconds{shard="0"}' in m.histograms
        assert 'ingest_e2e_latency_seconds{shard="3"}' in m.histograms
        assert 'ingest_e2e_latency_seconds{shard="2"}' not in m.histograms
        assert m.counters['ingest_e2e_slo_burn_total{shard="0"}'] == 1
        text = m.render_prometheus()
        type_lines = [ln for ln in text.splitlines()
                      if ln.startswith("# TYPE ciliumtpu_ingest_e2e"
                                       "_latency_seconds ")]
        assert len(type_lines) == 1          # one TYPE per base family
        assert ('ciliumtpu_ingest_e2e_latency_seconds_bucket'
                '{shard="3",le="+Inf"} 1') in text
        assert 'ciliumtpu_ingest_e2e_latency_seconds_sum{shard="3"}' in text
        # no malformed TYPE with labels anywhere
        assert not any("{" in ln for ln in text.splitlines()
                       if ln.startswith("# TYPE"))


# --------------------------------------------------------------------------- #
# satellites: metrics sentinel, feeder families, scrape races, trace ring
# --------------------------------------------------------------------------- #
class TestQuantileSentinel:
    def test_empty_window_returns_sentinel(self):
        h = Histogram()
        buckets, counts, _t, _c = h.snapshot()
        v = quantile_from(buckets, counts, 0.99)
        assert quantile_is_empty(v) and math.isnan(v)
        assert math.isnan(EMPTY_QUANTILE)

    def test_display_quantile_still_reads_zero_when_empty(self):
        assert Histogram().quantile(0.5) == 0.0

    def test_delta_window_with_counts_is_unchanged(self):
        h = Histogram()
        for v in (0.001, 0.002, 0.004):
            h.observe(v)
        b, c, _t, _n = h.snapshot()
        assert quantile_from(b, c, 0.5) > 0.0
        assert not quantile_is_empty(quantile_from(b, c, 0.5))

    def test_autotuner_skips_empty_window(self):
        """Dispatched batches but an empty queue-wait delta (histogram
        reset race): the autotuner must observe-and-skip, never compare
        against the NaN sentinel."""
        from cilium_tpu.observe.autotune import Autotuner

        class _StubPipeline:
            flush_ms = 2.0
            min_bucket = 256
            max_bucket = 8192

            def __init__(self):
                self.d = 0

            def stats(self):
                self.d += 10
                return {"fill_rows": 0, "bucket_rows": 0,
                        "dispatched_batches": self.d, "flush_reasons": {}}

            def set_flush_ms(self, v):
                raise AssertionError("must not adjust on empty window")

            def set_min_bucket(self, v):
                raise AssertionError("must not adjust on empty window")

        m = Metrics()
        m.histogram("pipeline_queue_wait_seconds")   # exists, stays empty
        at = Autotuner(_StubPipeline(), m)
        assert at.step() is None             # baseline
        # fill/bucket deltas present, queue-wait delta empty
        at.pipeline.stats = lambda: {"fill_rows": 100, "bucket_rows": 200,
                                     "dispatched_batches": 100,
                                     "flush_reasons": {}}
        at._last_fill = (0, 0)
        assert at.step() is None             # skipped, no crash, no adjust


class TestFeederMetricFamilies:
    def test_feeder_stats_exported_as_families(self):
        """render_metrics() must surface the stats-only feeder fields as
        first-class gauges (a scrape-only consumer sees liveness and pool
        occupancy without the status API)."""
        eng = setup_web(fake_engine())

        class _FakeFeeder:
            def stats(self):
                return {"alive": True, "pool_free": 3, "pending": 1,
                        "harvested_batches": 5}

        eng._feeder = _FakeFeeder()
        text = eng.render_metrics()
        assert "# TYPE ciliumtpu_feeder_alive gauge" in text
        assert "ciliumtpu_feeder_alive 1" in text
        assert "ciliumtpu_feeder_pool_free 3.0" in text \
            or "ciliumtpu_feeder_pool_free 3" in text
        assert "ciliumtpu_feeder_pending 1" in text
        eng._feeder = None
        eng.stop()


class TestScrapeRaces:
    def test_concurrent_scrape_races_sharded_soak(self):
        """A scraper hammering render_metrics() while an 8-shard pipeline
        soaks (including a mid-soak watchdog restart, whose wedged-sweep
        resets the shard gauges a fenced worker may still try to publish):
        no exceptions, every exposition parses, one TYPE line per base."""
        eng = sharded_audited_engine(pipeline_restart_backoff_s=0.05)
        setup_web(eng)
        chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=16,
                           rows_per_chunk=8)
        errors = []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    text = eng.render_metrics()
                    for ln in text.splitlines():
                        if ln.startswith("# TYPE"):
                            assert "{" not in ln, f"labeled TYPE: {ln}"
                except Exception as e:   # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=scraper, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        try:
            pl = eng.start_pipeline()
            assert pl.stats()["n_shards"] == 8
            for round_ in range(6):
                tickets = [eng.submit(dict(ch), now=100 + i)
                           for i, ch in enumerate(chunks)]
                assert eng.drain(timeout=30)
                for t in tickets:
                    t.result(timeout=5)
                if round_ == 2:
                    # wedge → watchdog restart mid-soak (gauge publish vs
                    # fenced-worker reset is the race under test)
                    pl.set_stall_timeout_s(0.4)
                    FAULTS.arm("pipeline.dispatch", mode="hang",
                               delay_s=4.0, times=1)
                    eng.submit(dict(chunks[0]), now=500)
                    eng.drain(timeout=20)
                    FAULTS.disarm("pipeline.dispatch")
                    deadline = time.monotonic() + 10
                    while time.monotonic() < deadline and \
                            (eng.pipeline_stats() or {}).get("state") != "ok":
                        time.sleep(0.05)
                    pl.set_stall_timeout_s(30.0)
            eng.audit_step(budget=None)
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0 and st["mismatched_rows"] == 0
        finally:
            stop.set()
            for t in threads:
                t.join(5)
            eng.stop()
        assert not errors, errors[:1]


class TestTraceRingWraparound:
    def test_trace_ring_wraps_with_audit_capture_armed(self):
        """Tiny span ring + full-rate tracing + full-rate audit capture:
        the ring wraps many times over while captures are in flight; spans
        stay well-formed, audit replay stays clean, and the bundle's span
        tail is the newest slice."""
        TRACER.configure(sample_rate=1.0, capacity=16)
        TRACER.reset()
        eng = setup_web(audited_engine(trace_sample_rate=1.0,
                                       trace_capacity=16))
        b = web_batch(eng)
        for i in range(40):
            eng.classify(dict(b), now=100 + i)
            if i % 8 == 0:
                eng.audit_step()
        eng.audit_step()
        st = eng.auditor.stats()
        assert st["mismatched_rows"] == 0 and st["checked_rows"] > 0
        tr = TRACER.stats()
        assert tr["spans_in_ring"] == 16     # wrapped, exactly full
        for sp in TRACER.spans(limit=100):
            assert sp["trace_id"] > 0 and sp["duration_ms"] >= 0
        bundle = eng.debug_bundle()
        assert len(bundle["spans"]) <= 16
        eng.stop()


# --------------------------------------------------------------------------- #
# export surfaces: REST route + CLI
# --------------------------------------------------------------------------- #
class TestDebugBundleSurfaces:
    @pytest.fixture
    def live(self, tmp_path):
        from cilium_tpu.runtime.api import APIServer, UnixAPIClient
        sock = str(tmp_path / "cilium-tpu.sock")
        eng = setup_web(audited_engine())
        srv = APIServer(eng, sock)
        srv.start()
        yield eng, sock, UnixAPIClient(sock)
        srv.stop()
        eng.stop()

    def test_rest_bundle_live_then_frozen_then_cleared(self, live):
        eng, _sock, client = live
        code, doc = client.get("/v1/debug/bundle")
        assert code == 200 and doc["frozen"] is False
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(web_batch(eng), now=100)
        eng.audit_step()
        code, doc = client.get("/v1/debug/bundle?clear=1")
        assert code == 200 and doc["frozen"] is True
        assert doc["reason"] == "parity-mismatch"
        assert doc["engine"]["audit"]["mismatched_rows"] > 0
        assert doc["detail"]["rows"]
        code, doc = client.get("/v1/debug/bundle")   # cleared: re-armed
        assert code == 200 and doc["frozen"] is False
        # status carries the provenance counters; ?clear=1 re-armed the
        # auditor (mismatch state reset) but history persists
        code, st = client.get("/v1/status")
        assert code == 200
        assert st["audit"]["mismatched_rows"] == 0   # re-armed
        assert st["audit"]["checked_rows"] > 0
        assert st["blackbox"]["freezes_total"] >= 1

    def test_cli_debug_bundle_writes_file(self, live, tmp_path, capsys):
        eng, sock, _client = live
        with FAULTS.inject("audit.corrupt", mode="fail", times=1):
            eng.classify(web_batch(eng), now=100)
        eng.audit_step()
        out_path = tmp_path / "bundle.json"
        from cilium_tpu.cli.main import main as cli_main
        rc = cli_main(["debug-bundle", "--api", sock,
                       "--out", str(out_path), "--clear"])
        assert rc == 0
        doc = json.loads(out_path.read_text())
        assert doc["frozen"] and doc["reason"] == "parity-mismatch"
        assert "written to" in capsys.readouterr().out


# --------------------------------------------------------------------------- #
# bench artifact provenance + compare gate
# --------------------------------------------------------------------------- #
class TestBenchCompare:
    def test_provenance_fields(self):
        import bench
        p = bench._provenance(argv=["--ingest"])
        assert set(p) >= {"git_rev", "jax_version", "config_hash",
                          "generated_at"}
        assert len(p["config_hash"]) == 12
        # deterministic for identical config surface
        assert p["config_hash"] == bench._provenance(
            argv=["--ingest"])["config_hash"]
        assert p["config_hash"] != bench._provenance(
            argv=["--pipeline"])["config_hash"]

    def test_compare_passes_within_noise(self, tmp_path):
        import bench
        old = {"value": 100000.0, "e2e_p99_ms": 20.0,
               "stage_split": {"datapath.pack": {"p50_ms": 0.1}},
               "provenance": {"git_rev": "abc123"}}
        p = tmp_path / "old.json"
        p.write_text(json.dumps(old))
        new = {"value": 90000.0, "e2e_p99_ms": 25.0,
               "stage_split": {"datapath.pack": {"p50_ms": 0.12}}}
        cmp_ = bench._compare_artifacts(new, str(p), factor=1.75)
        assert not cmp_["failed"]
        assert cmp_["baseline_rev"] == "abc123"
        assert cmp_["checked"]["value"]["ratio"] == 0.9

    def test_compare_fails_on_regression(self, tmp_path):
        import bench
        old = {"value": 100000.0, "e2e_p99_ms": 20.0}
        p = tmp_path / "old.json"
        p.write_text(json.dumps(old))
        slow = {"value": 40000.0, "e2e_p99_ms": 21.0}
        cmp_ = bench._compare_artifacts(slow, str(p), factor=1.75)
        assert cmp_["failed"] and "value" in cmp_["regressions"][0]
        lat = {"value": 99000.0, "e2e_p99_ms": 60.0}
        cmp_ = bench._compare_artifacts(lat, str(p), factor=1.75)
        assert cmp_["failed"] and "e2e_p99_ms" in cmp_["regressions"][0]

    def test_compare_env_override(self, tmp_path, monkeypatch):
        import bench
        old = {"value": 100000.0}
        p = tmp_path / "old.json"
        p.write_text(json.dumps(old))
        assert bench._compare_artifacts(
            {"value": 40000.0}, str(p), factor=3.0)["failed"] is False


# --------------------------------------------------------------------------- #
# slow: the audit-smoke soak (make audit-smoke)
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestAuditSoak:
    N_SUBMISSIONS = 10_000

    def test_soak_clean_then_corruption_detected(self):
        """10k pipelined submissions with the auditor armed at sampling
        1.0: zero mismatches and checked > 0 (the acceptance gate), then a
        corruption-injection phase via audit.corrupt that must be detected
        within the sampling window, degrade health, and freeze a bundle
        carrying the offending rows + revision."""
        eng = setup_web(audited_engine(
            pipeline_min_bucket=16, audit_pool_batches=64,
            audit_interval_s=0.05))
        eng.start_background()               # the real background controller
        try:
            chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=32,
                               rows_per_chunk=8, repeats=True)
            n = 0
            while n < self.N_SUBMISSIONS:
                tickets = [eng.submit(dict(ch), now=100 + n + i)
                           for i, ch in enumerate(chunks)]
                n += len(tickets)
                assert eng.drain(timeout=60)
                for t in tickets:
                    t.result(timeout=5)
            # let the controller drain the capture backlog
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline \
                    and eng.auditor.stats()["pending"] > 0:
                time.sleep(0.05)
            eng.audit_step()                 # sweep any tail
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0, "auditor never checked anything"
            assert st["mismatched_rows"] == 0, list(eng.auditor.mismatches)
            assert eng.health()["state"] == C.HEALTH_OK

            # corruption-injection phase: every capture in this window is
            # corrupted; the very next sampled batch must trip
            FAULTS.arm("audit.corrupt", mode="fail", times=4)
            tickets = [eng.submit(dict(ch), now=50_000 + i)
                       for i, ch in enumerate(chunks)]
            assert eng.drain(timeout=60)
            for t in tickets:
                t.result(timeout=5)
            FAULTS.disarm("audit.corrupt")
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline \
                    and eng.auditor.stats()["mismatched_rows"] == 0:
                eng.audit_step()
                time.sleep(0.02)
            st = eng.auditor.stats()
            assert st["mismatched_rows"] > 0, \
                "corruption not detected within the sampling window"
            assert eng.health()["state"] == C.HEALTH_DEGRADED
            bundle = eng.debug_bundle()
            assert bundle["frozen"] \
                and bundle["reason"] == "parity-mismatch"
            assert bundle["detail"]["rows"]
            assert bundle["detail"]["revision"] == eng.active.revision
        finally:
            eng.stop()

    def test_sharded_soak_audits_clean(self):
        """The acceptance pin for the mesh: a clean 8-shard soak (steered
        staging, per-segment buckets, shard-attributed captures) shows
        parity_audit_mismatched_total == 0 with checked > 0."""
        eng = sharded_audited_engine(audit_pool_batches=64,
                                     audit_interval_s=0.05)
        setup_web(eng)
        eng.start_background()
        try:
            chunks = mk_chunks(eng.active.snapshot.ep_slot_of, n_chunks=32,
                               rows_per_chunk=8, repeats=True)
            n = 0
            while n < 2000:
                tickets = [eng.submit(dict(ch), now=100 + n + i)
                           for i, ch in enumerate(chunks)]
                n += len(tickets)
                assert eng.drain(timeout=60)
                for t in tickets:
                    t.result(timeout=5)
            while eng.audit_step()["replayed"]:
                pass
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0, "sharded soak audited nothing"
            assert st["mismatched_rows"] == 0, list(eng.auditor.mismatches)
            assert not any("parity_audit_mismatched" in k
                           for k in eng.metrics.counters)
            assert eng.metrics.counters["parity_audit_checked_total"] > 0
        finally:
            eng.stop()

    def test_auditor_overhead_under_two_percent(self):
        """The <2% contract in the PR 3 trace-soak form: (1) the precise,
        deterministic measurement — ``maybe_capture`` per-batch cost at
        default 1/64 sampling (one counter draw + the row-copy amortized
        every 64th batch) vs disarmed, bounded under 2% of the measured
        per-submission pipeline cost; (2) an interleaved end-to-end soak
        as a loose gross-regression bound (wall-clock on a multi-threaded
        pipeline carries scheduler noise well above 2%)."""
        import gc
        eng = setup_web(audited_engine(audit_sample_rate=1 / 64,
                                       audit_pool_batches=4096,
                                       pipeline_min_bucket=16))
        snap = eng.active.snapshot
        b = web_batch(eng)
        out = eng.classify(dict(b), now=99)
        aud = eng.auditor
        chunks = mk_chunks(snap.ep_slot_of, n_chunks=16, rows_per_chunk=8)

        def one_pass(n_rounds=4):
            t0 = time.perf_counter()
            n = 0
            for _r in range(n_rounds):
                for i, ch in enumerate(chunks):
                    eng.submit(dict(ch), now=1000 + i)
                    n += 1
                assert eng.drain(timeout=60)
            return (time.perf_counter() - t0) / n

        reps = 20_000

        def micro_pass():
            t0 = time.perf_counter()
            for _ in range(reps):
                aud.maybe_capture(b, out, snap, 100)
            dt = (time.perf_counter() - t0) / reps
            aud.step()                   # drain (replay is background cost)
            return dt

        one_pass(2)                      # warmup both code paths
        gc_was = gc.isenabled()
        gc.disable()
        try:
            micro_pass()
            aud.configure(sample_rate=0.0)
            micro_off = min(micro_pass() for _ in range(5))
            aud.configure(sample_rate=1 / 64)
            micro_on = min(micro_pass() for _ in range(5))

            off, on = [], []
            for _i in range(4):          # interleaved A/B windows
                aud.configure(sample_rate=0.0)
                off.append(one_pass())
                aud.configure(sample_rate=1 / 64)
                on.append(one_pass())
                aud.step()
        finally:
            if gc_was:
                gc.enable()
        per_submit = min(off)            # best-case per-submission cost
        delta = micro_on - micro_off     # true hot-path addition per batch
        frac = delta / per_submit
        assert frac < 0.02, \
            f"1/64 audit capture adds {delta * 1e9:.0f}ns/batch = " \
            f"{frac:.2%} of the {per_submit * 1e6:.1f}us submit path " \
            f"(budget 2%)"
        assert min(on) <= min(off) * 1.15, \
            f"end-to-end regression: off={min(off) * 1e6:.1f}us " \
            f"on={min(on) * 1e6:.1f}us"
        assert aud.stats()["mismatched_rows"] == 0
        eng.stop()
