"""Multi-tenant QoS tests (cilium_tpu/qos + the weighted-fair admission
path through pipeline/scheduler.py and the engine).

Tier-1: tenant spec parsing + the compiled ep→tenant LUT (fail-open),
DRR weight-share dequeue with FIFO-within-tenant and the zero-weight
starvation floor, single-tenant degeneracy to plain FIFO (QoS armed but
order bit-identical), per-tenant cap sheds (:class:`PipelineTenantCap`
with ``{reason=,tenant=}`` counters), tenant-scoped OVERLOAD fail-fast
(over-share tenant rejected, within-budget tenant displaces), the
latency lane's immediate flush at the lane bucket, the ``qos.enqueue``
fail-closed path, and engine parity with the auditor at sampling 1.0
with QoS armed.

Slow (make qos-smoke): the 8-shard audited soak with two concurrent
``render_metrics`` scrapers and a mid-soak watchdog restart (the PR
7/11/13 house race pattern, extended to the ``{tenant=}`` label
families and the ``qos_tenant_queue_*`` resource rows).
"""

import threading
import time
from collections import Counter

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records, empty_batch
from cilium_tpu.pipeline import (Pipeline, PipelineDrop, PipelineTenantCap)
from cilium_tpu.pipeline.guard import (OVERLOAD_OVERLOAD, OVERLOAD_PRESSURE,
                                       PRIO_ESTABLISHED, PRIO_NEW)
from cilium_tpu.qos import (TENANT_DEFAULT, TenantQueues, TenantSpecError,
                            TenantTable, parse_assign_spec,
                            parse_tenant_spec)
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord
from cilium_tpu.utils import constants as C

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]

SPEC = "gold=4:lane,silver=2,bulk=1"


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# --------------------------------------------------------------------------- #
# tenant table / spec parsing
# --------------------------------------------------------------------------- #
class TestTenantTable:
    def test_spec_parse(self):
        got = list(parse_tenant_spec("gold=4:lane, silver=2, bulk=1:cap=8"))
        assert got == [("gold", 4.0, True, 0),
                       ("silver", 2.0, False, 0),
                       ("bulk", 1.0, False, 8)]
        assert parse_assign_spec("1=gold, 7=bulk") == {1: "gold", 7: "bulk"}

    @pytest.mark.parametrize("bad", [
        "gold", "gold=x", "gold=-1", "gold=1:warp", "gold=1:cap=x",
        "gold=1:cap=-2", "=3", "b@d=1"])
    def test_spec_rejects(self, bad):
        with pytest.raises(TenantSpecError):
            list(parse_tenant_spec(bad))

    @pytest.mark.parametrize("bad", ["gold", "x=gold", "0=gold", "3="])
    def test_assign_rejects(self, bad):
        with pytest.raises(TenantSpecError):
            parse_assign_spec(bad)

    def test_from_spec_and_lookups(self):
        tbl = TenantTable.from_spec(SPEC, assign="1=gold,2=silver")
        tids = {v: k for k, v in tbl.tenants().items()}
        assert tids["default"] == TENANT_DEFAULT
        assert tbl.weight_of(tids["gold"]) == 4.0
        assert tbl.is_lane(tids["gold"]) and not tbl.is_lane(tids["bulk"])
        assert tbl.tenant_of_ep(1) == tids["gold"]
        assert tbl.tenant_of_ep(99) == TENANT_DEFAULT   # fail-open

    def test_map_tenants_vectorized_fail_open(self):
        tbl = TenantTable.from_spec(SPEC, assign="1=gold,5=bulk")
        tids = {v: k for k, v in tbl.tenants().items()}
        eps = np.array([1, 5, 2, -3, 10_000], dtype=np.int32)
        got = tbl.map_tenants(eps)
        assert got.tolist() == [tids["gold"], tids["bulk"], 0, 0, 0]
        # LUT is cached on the revision counter: same object until a change
        assert tbl.lut() is tbl.lut()
        tbl.assign(2, "silver")
        assert tbl.map_tenants(eps)[2] == tids["silver"]

    def test_remove_retires_tenant(self):
        tbl = TenantTable.from_spec(SPEC, assign="1=gold")
        tids = {v: k for k, v in tbl.tenants().items()}
        tbl.remove("gold")
        # endpoints fall back to default; the retired id keeps a safe name
        assert tbl.tenant_of_ep(1) == TENANT_DEFAULT
        assert tbl.name_of(tids["gold"]) == "default"
        with pytest.raises(ValueError):
            tbl.remove("default")


# --------------------------------------------------------------------------- #
# DRR queue mechanics
# --------------------------------------------------------------------------- #
class _FakeTicket:
    def __init__(self, n_valid):
        self.n_valid = n_valid


class _FakeSub:
    def __init__(self, tenant, n_valid=1, prio=PRIO_NEW, tag=None):
        self.tenant = tenant
        self.prio = prio
        self.tag = tag
        self.ticket = _FakeTicket(n_valid)


class TestTenantQueues:
    def _mk(self, spec=SPEC, quantum_rows=1):
        tbl = TenantTable.from_spec(spec)
        tids = {v: k for k, v in tbl.tenants().items()}
        return TenantQueues(tbl, quantum_rows=quantum_rows), tids

    def test_drr_weight_share(self):
        """Under contention the dequeue order converges to the 4:2:1
        weight ratio — the first full round serves exactly one quantum
        per tenant."""
        qs, tids = self._mk()
        for i in range(12):
            for name in ("bulk", "silver", "gold"):   # bulk enqueues FIRST
                qs.append(_FakeSub(tids[name], tag=f"{name}{i}"))
        first_round = [qs.popleft().tenant for _ in range(7)]
        assert Counter(first_round) == {tids["gold"]: 4, tids["silver"]: 2,
                                        tids["bulk"]: 1}
        # and it keeps that ratio over many rounds
        more = Counter(qs.popleft().tenant for _ in range(14))
        assert more == {tids["gold"]: 8, tids["silver"]: 4, tids["bulk"]: 2}

    def test_fifo_within_tenant_and_single_tenant_fifo(self):
        qs, tids = self._mk()
        for i in range(10):
            qs.append(_FakeSub(tids["gold"], tag=i))
        assert [qs.popleft().tag for _ in range(10)] == list(range(10))
        assert len(qs) == 0 and not qs

    def test_remove_clear_iter(self):
        qs, tids = self._mk()
        subs = [_FakeSub(tids["gold"], tag=0), _FakeSub(tids["bulk"], tag=1)]
        for s in subs:
            qs.append(s)
        assert set(s.tag for s in qs) == {0, 1}
        qs.remove(subs[0])
        assert len(qs) == 1
        with pytest.raises(ValueError):
            qs.remove(subs[0])
        qs.clear()
        assert len(qs) == 0

    def test_zero_weight_starvation_floor(self):
        """A zero-weight tenant still gets served: every full DRR round
        banks WEIGHT_FLOOR_ROWS of credit, so its head batch is reachable
        in a bounded number of pops."""
        qs, tids = self._mk()
        zero = qs.table.register("zero", weight=0.0)
        for i in range(64):
            qs.append(_FakeSub(tids["gold"], n_valid=1, tag=f"g{i}"))
        qs.append(_FakeSub(zero, n_valid=1, tag="starved"))
        served = [qs.popleft().tag for _ in range(len(qs))]
        assert "starved" in served

    def test_over_cap_over_share(self):
        tbl = TenantTable.from_spec("gold=4,bulk=1:cap=2")
        tids = {v: k for k, v in tbl.tenants().items()}
        qs = TenantQueues(tbl, quantum_rows=1)
        assert not qs.over_cap(tids["bulk"])
        qs.append(_FakeSub(tids["bulk"]))
        qs.append(_FakeSub(tids["bulk"]))
        assert qs.over_cap(tids["bulk"])
        assert not qs.over_cap(tids["gold"])        # cap 0 = uncapped
        # bulk holds 100% of the queue >> its 1/5 weight share vs gold
        assert qs.over_share(tids["bulk"])
        assert not qs.over_share(tids["gold"])
        # single-tenant world: over_share is always True (old behavior)
        qs2 = TenantQueues(TenantTable(), quantum_rows=1)
        qs2.append(_FakeSub(TENANT_DEFAULT))
        assert qs2.over_share(TENANT_DEFAULT)

    def test_priority_victim_tenant_scoped(self):
        qs, tids = self._mk()
        est = _FakeSub(tids["gold"], prio=PRIO_ESTABLISHED, tag="g-est")
        new = _FakeSub(tids["gold"], prio=PRIO_NEW, tag="g-new")
        flood = _FakeSub(tids["bulk"], prio=PRIO_NEW, tag="b-new")
        for s in (est, new, flood):
            qs.append(s)
        qs.append(_FakeSub(tids["bulk"], prio=PRIO_NEW, tag="b-new2"))
        # within gold: only a strictly worse class is displaced
        v = qs.priority_victim(PRIO_ESTABLISHED, tids["gold"])
        assert v is not None and v.tag in ("b-new2", "b-new", "g-new")
        # bulk is the worst-pressure tenant (2 queued over weight 1):
        # a same-class submission from silver displaces from bulk, never
        # from gold (gold's pressure 2/4 < bulk's 2/1)
        v = qs.priority_victim(PRIO_NEW, tids["silver"])
        assert v is not None and v.tag == "b-new2"   # newest of worst class
        # an established sub within bulk itself displaces its own NEW first
        v = qs.priority_victim(PRIO_ESTABLISHED, tids["bulk"])
        assert v is not None and v.tag == "b-new2"

    def test_stats_and_occupancy_by_name(self):
        qs, tids = self._mk()
        qs.append(_FakeSub(tids["gold"], n_valid=64))
        st = qs.stats()
        assert st["gold"]["depth"] == 1
        # admitted_* count service (DRR pops), not arrivals — the share
        # gate must see dequeue order, not whatever was accepted
        assert st["gold"]["admitted_rows"] == 0
        assert st["gold"]["lane"] is True
        assert st["bulk"]["depth"] == 0
        occ = qs.occupancy_by_name()
        assert occ == {"gold": (0, 1)}               # active tenants only
        qs.popleft()
        assert qs.stats()["gold"]["admitted_rows"] == 64

    def test_lane_bypass_priority_and_debt_bound(self):
        """A lane tenant's lane-sized head jumps the DRR ring, but only
        until it owes a full quantum — sustained lane traffic falls back
        to its ring turn (the starvation bound), and ring grants pay the
        debt before banking deficit."""
        tbl = TenantTable.from_spec(SPEC)
        tids = {v: k for k, v in tbl.tenants().items()}
        qs = TenantQueues(tbl, quantum_rows=4, lane_rows=8)
        # bulk and silver enqueue FIRST; gold's small sub still pops first
        qs.append(_FakeSub(tids["bulk"], n_valid=4, tag="b0"))
        qs.append(_FakeSub(tids["silver"], n_valid=4, tag="s0"))
        qs.append(_FakeSub(tids["gold"], n_valid=4, tag="g0"))
        assert qs.popleft().tag == "g0"
        # an over-lane-size gold sub does NOT bypass (bulk-shaped work
        # from a lane tenant waits its ring turn like everyone else)
        qs.append(_FakeSub(tids["gold"], n_valid=9, tag="gbig"))
        assert qs.popleft().tag == "b0"              # ring head, not gold
        # debt bound: gold's quantum is 4*4=16 rows; after 4 bypassed
        # 4-row subs the debt is at the quantum and the 5th waits for
        # the ring (which still owes silver its turn first)
        qs, tids = TenantQueues(tbl, quantum_rows=4, lane_rows=8), tids
        qs.append(_FakeSub(tids["silver"], n_valid=4, tag="s0"))
        for i in range(5):
            qs.append(_FakeSub(tids["gold"], n_valid=4, tag=f"g{i}"))
        got = [qs.popleft().tag for _ in range(4)]
        assert got == ["g0", "g1", "g2", "g3"]       # bypass while affordable
        assert qs.popleft().tag == "s0"              # debt cap: ring resumes

    def test_lane_debt_survives_queue_drain(self):
        """A lane tenant that keeps exactly ONE batch queued at a time
        (arrival rate ~ service rate) drains its queue — and is retired
        from the ring — on every single pop. Its lane debt must survive
        that retirement: forgiving it with the credit would reset the
        "bypass only while debt < one quantum" starvation bound on every
        popleft and the ring (bulk tenants) would be starved forever."""
        tbl = TenantTable.from_spec(SPEC)
        tids = {v: k for k, v in tbl.tenants().items()}
        qs = TenantQueues(tbl, quantum_rows=4, lane_rows=8)
        for i in range(6):
            qs.append(_FakeSub(tids["bulk"], n_valid=4, tag=f"b{i}"))
        got = []
        for i in range(5):
            qs.append(_FakeSub(tids["gold"], n_valid=4, tag=f"g{i}"))
            got.append(qs.popleft().tag)
        # gold's quantum is 4*4=16 rows: four bypassed 4-row pops bank a
        # full quantum of debt even though gold's queue drained after
        # each one — the 5th pop falls back to the ring and bulk is
        # finally served
        assert got == ["g0", "g1", "g2", "g3", "b0"]
        assert qs.popleft().tag == "b1"   # ring grant pays the debt down
        assert qs.popleft().tag == "g4"   # ...and the bypass re-arms

    def test_lane_debt_forgiven_when_ring_fully_drains(self):
        """Lane debt is owed to the tenants queued behind the bypass —
        when the LAST queue drains there is nobody left to repay, and
        carrying the debt into the next busy period would deny the lane
        bypass to the first probes after an idle gap (a latency spike
        that repays no one). Debt banked by sparse probes on an idle
        ring must NOT outlive a full drain."""
        tbl = TenantTable.from_spec(SPEC)
        tids = {v: k for k, v in tbl.tenants().items()}
        qs = TenantQueues(tbl, quantum_rows=4, lane_rows=8)
        # unloaded phase: sparse gold probes, one at a time, bank a full
        # quantum (4 * 4 rows >= quantum 16) of debt against an idle ring
        for i in range(4):
            qs.append(_FakeSub(tids["gold"], n_valid=4, tag=f"p{i}"))
            assert qs.popleft().tag == f"p{i}"
        assert len(qs) == 0               # ring fully drained -> debt gone
        # busy period starts: bulk floods, then a gold probe arrives —
        # the bypass must be armed (with stale debt it would queue
        # behind both bulk batches)
        qs.append(_FakeSub(tids["bulk"], n_valid=4, tag="b0"))
        qs.append(_FakeSub(tids["bulk"], n_valid=4, tag="b1"))
        qs.append(_FakeSub(tids["gold"], n_valid=4, tag="g0"))
        assert qs.popleft().tag == "g0"

    def test_zero_weight_big_batch_fast_forwards(self):
        """Two zero-weight tenants with max-bucket-sized heads: the floor
        quantum is 1 row, so reaching a 512-row head used to take 512
        full ring rotations under the pipeline lock — the fruitless-
        rotation fast-forward credits those rounds in one O(tenants)
        pass, and service order is unchanged (first-enqueued first)."""
        tbl = TenantTable.from_spec(SPEC)
        za = tbl.register("za", weight=0.0)
        zb = tbl.register("zb", weight=0.0)
        qs = TenantQueues(tbl, quantum_rows=1)
        qs.append(_FakeSub(za, n_valid=512, tag="a"))
        qs.append(_FakeSub(zb, n_valid=512, tag="b"))
        assert [qs.popleft().tag for _ in range(2)] == ["a", "b"]
        assert len(qs) == 0


# --------------------------------------------------------------------------- #
# pipeline-level QoS (raw Pipeline against an echo dispatch)
# --------------------------------------------------------------------------- #
class EchoDispatch:
    """Records the valid-row sports of every dispatched batch and echoes
    them through ``reason``; ``gate.clear()`` stalls the worker."""

    def __init__(self):
        self.batches = []
        self.gate = threading.Event()
        self.gate.set()

    def __call__(self, batch, now):
        self.gate.wait(timeout=10)
        valid = np.asarray(batch["valid"])
        self.batches.append(np.asarray(batch["sport"])[valid].tolist())
        out = {
            "allow": valid.copy(),
            "reason": np.asarray(batch["sport"], np.int32).copy(),
            "status": np.zeros(valid.shape[0], np.int32),
            "remote_identity": np.zeros(valid.shape[0], np.int32),
        }
        return lambda: out


def tagged_batch(n_rows, start, tenant=0):
    b = empty_batch(n_rows)
    b["sport"][:] = np.arange(start, start + n_rows, dtype=np.int32)
    b["valid"][:] = True
    b["_tenant"] = np.full((n_rows,), tenant, dtype=np.int32)
    return b


class TestQosPipeline:
    def _mk(self, spec=SPEC, **kw):
        tbl = TenantTable.from_spec(spec)
        tids = {v: k for k, v in tbl.tenants().items()}
        d = EchoDispatch()
        kw.setdefault("min_bucket", 4)
        kw.setdefault("max_bucket", 4)
        kw.setdefault("flush_ms", 1000.0)
        pl = Pipeline(d, qos=tbl, **kw)
        return pl, d, tids

    def test_drr_dispatch_order_under_contention(self):
        """Back the queue up behind a gated dispatch, release, and check
        the weighted interleave: the first contended round serves
        4 gold : 2 silver : 1 bulk (quantum = max_bucket rows)."""
        pl, d, tids = self._mk(inflight=1, queue_batches=64)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0, tenant=tids["bulk"]))
            time.sleep(0.1)          # the worker pops this one pre-gate
            tickets = []
            for i in range(8):
                for name in ("bulk", "silver", "gold"):
                    tickets.append(pl.submit(tagged_batch(
                        4, start=100 * (tids[name]) + 4 * i,
                        tenant=tids[name])))
            d.gate.set()
            assert pl.drain(timeout=20)
            served = [b[0] // 100 for b in d.batches[1:]]
            first = Counter(served[:7])
            assert first == {tids["gold"]: 4, tids["silver"]: 2,
                             tids["bulk"]: 1}
            for t in tickets:
                t.result(timeout=5)
        finally:
            pl.close(timeout=5)

    def test_single_tenant_degenerates_to_fifo(self):
        """QoS armed but one tenant submitting: dispatch order is exactly
        submission order — bit-identical to the FIFO world."""
        pl, d, _tids = self._mk(inflight=1, queue_batches=64)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0))
            time.sleep(0.1)
            for i in range(1, 12):
                pl.submit(tagged_batch(4, start=4 * i))
            d.gate.set()
            assert pl.drain(timeout=20)
            assert [b[0] for b in d.batches] == [4 * i for i in range(12)]
        finally:
            pl.close(timeout=5)

    def test_tenant_cap_shed(self):
        """A capped tenant sheds against its OWN budget while the shared
        queue still has room: PipelineTenantCap (a PipelineDrop) plus the
        {reason=,tenant=} counter."""
        pl, d, tids = self._mk(spec="gold=4,bulk=1:cap=1",
                               admission="drop", inflight=1,
                               queue_batches=32)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0, tenant=tids["bulk"]))
            time.sleep(0.1)
            pl.submit(tagged_batch(4, start=4, tenant=tids["bulk"]))
            t = pl.submit(tagged_batch(4, start=8, tenant=tids["bulk"]))
            assert t.dropped
            with pytest.raises(PipelineTenantCap):
                t.result(timeout=1)
            # gold rides free: the shared queue has room
            tg = pl.submit(tagged_batch(4, start=12, tenant=tids["gold"]))
            assert not tg.dropped
            key = 'pipeline_shed_total{reason="tenant_cap",tenant="bulk"}'
            assert pl.metrics.counters.get(key) == 1
            # the labeled family rides ALONGSIDE the pre-QoS reason-only
            # family, never instead of it — dashboards watching the bare
            # family must keep counting with QoS armed
            assert pl.metrics.counters.get(
                'pipeline_shed_total{reason="tenant_cap"}') == 1
            assert pl.shed_reasons.get("tenant_cap") == 1
            d.gate.set()
            assert pl.drain(timeout=10)
            tg.result(timeout=5)
        finally:
            pl.close(timeout=5)

    def test_overload_fail_fast_is_tenant_scoped(self):
        """At OVERLOAD with a full queue, the over-share tenant is
        instant-rejected while a within-budget tenant displaces the
        flooder's newest batch and gets served."""
        pl, d, tids = self._mk(inflight=1, queue_batches=2,
                               block_timeout_s=5.0)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0, tenant=tids["bulk"]))
            time.sleep(0.1)
            q1 = pl.submit(tagged_batch(4, start=4, tenant=tids["bulk"]))
            q2 = pl.submit(tagged_batch(4, start=8, tenant=tids["bulk"]))
            pl.set_overload_state(OVERLOAD_OVERLOAD)
            t0 = time.monotonic()
            tb = pl.submit(tagged_batch(4, start=12, tenant=tids["bulk"]))
            assert tb.dropped                     # over-share: fail fast
            assert time.monotonic() - t0 < 1.0    # no blocking wait burned
            tg = pl.submit(tagged_batch(4, start=16, tenant=tids["gold"]))
            assert not tg.dropped                 # displaced q2 (newest bulk)
            assert q2.dropped
            with pytest.raises(PipelineDrop):
                q2.result(timeout=1)
            d.gate.set()
            assert pl.drain(timeout=10)
            assert not q1.dropped
            tg.result(timeout=5)
        finally:
            pl.close(timeout=5)

    def test_pressure_at_cap_never_strands_a_victim(self):
        """A submitter over its OWN cap gains nothing from displacing a
        cross-tenant victim, so under PRESSURE no victim may be removed
        for it: a removed-but-never-settled victim would leave its
        producer blocked forever in result() and wedge drain()/close().
        Setup: bulk (high weight, cap 1) already holds its cap, gold
        (low weight → worst pressure) holds the rest of a full queue;
        bulk submits again with admission=drop under PRESSURE."""
        pl, d, tids = self._mk(spec="bulk=4:cap=1,gold=0.5",
                               admission="drop", inflight=1,
                               queue_batches=2)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0, tenant=tids["bulk"]))
            time.sleep(0.1)          # the worker pops this one pre-gate
            b1 = pl.submit(tagged_batch(4, start=4, tenant=tids["bulk"]))
            g0 = pl.submit(tagged_batch(4, start=8, tenant=tids["gold"]))
            assert not b1.dropped and not g0.dropped   # queue now full
            pl.set_overload_state(OVERLOAD_PRESSURE)
            t = pl.submit(tagged_batch(4, start=12, tenant=tids["bulk"]))
            assert t.dropped         # rejected against its own budget
            # the drop counts in BOTH admission families (aggregate and
            # tenant-labeled) and g0 was NOT displaced for a submission
            # that could never be admitted
            assert pl.metrics.counters.get(
                "pipeline_admission_drops_total") == 1
            assert pl.metrics.counters.get(
                'pipeline_admission_drops_total{tenant="bulk"}') == 1
            assert not g0.dropped
            d.gate.set()
            # the wedge the stranded victim used to cause: drain() hung
            # forever because _outstanding never drained
            assert pl.drain(timeout=10)
            b1.result(timeout=5)
            g0.result(timeout=5)
        finally:
            pl.close(timeout=5)

    def test_lane_bypasses_microbatching(self):
        """A lane tenant's small batch dispatches immediately at the lane
        bucket; an identical bulk batch waits for the coalescing deadline."""
        pl, d, tids = self._mk(min_bucket=64, max_bucket=64, lane_bucket=8,
                               flush_ms=60_000.0, inflight=2,
                               queue_batches=32)
        try:
            tg = pl.submit(tagged_batch(5, start=0, tenant=tids["gold"]))
            out = tg.result(timeout=5)            # flushed at once: lane
            assert out["reason"].tolist() == list(range(5))
            assert pl.flush_reasons["lane"] >= 1
            assert d.batches[0] == list(range(5))
            st = pl.stats()
            assert st["lane_bucket"] == 8
            assert st["lane_fill_rows"] >= 5
            assert st["lane_bucket_rows"] >= 8     # padded to the lane shape
            assert "pipeline_lane_wait_seconds" in pl.metrics.histograms
            # a lane batch at the lane bucket takes the DIRECT zero-copy
            # path — the bypass floor is the lane bucket, not min_bucket
            pl.submit(tagged_batch(8, start=50,
                                   tenant=tids["gold"])).result(timeout=5)
            assert pl.flush_reasons["direct"] >= 1
            # bulk: same shape, stays staged until an explicit drain
            tb = pl.submit(tagged_batch(8, start=100, tenant=tids["bulk"]))
            time.sleep(0.2)
            assert not tb.done()
            assert pl.drain(timeout=10)
            tb.result(timeout=5)
            assert pl.flush_reasons["drain"] >= 1
        finally:
            pl.close(timeout=5)

    def test_set_lane_bucket_bounds(self):
        pl, _d, _tids = self._mk(min_bucket=16, max_bucket=64,
                                 lane_bucket=16)
        try:
            pl.set_lane_bucket(8)
            assert pl.lane_bucket == 8
            with pytest.raises(ValueError):
                pl.set_lane_bucket(6)             # not a power of two
            with pytest.raises(ValueError):
                pl.set_lane_bucket(128)           # > max_bucket
        finally:
            pl.close(timeout=5)

    def test_qos_enqueue_fault_fails_closed(self):
        """Classification faulting at admission lands the ticket on the
        default tenant's FIFO budget — served, never dropped."""
        pl, d, tids = self._mk()
        try:
            FAULTS.arm("qos.enqueue", mode="fail", times=1)
            t = pl.submit(tagged_batch(4, start=0, tenant=tids["gold"]))
            assert t.tenant == "default"
            t.result(timeout=5)
            assert pl.metrics.counters.get(
                "qos_enqueue_failsafe_total") == 1
            t2 = pl.submit(tagged_batch(4, start=4, tenant=tids["gold"]))
            assert t2.tenant == "gold"
            t2.result(timeout=5)
        finally:
            FAULTS.reset()
            pl.close(timeout=5)

    def test_qos_off_surface_unchanged(self):
        """Without qos the stats/metric surfaces are byte-identical to the
        pre-QoS shapes: no tenants key, unlabeled counter names."""
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=4, admission="drop",
                      queue_batches=1, inflight=1, flush_ms=1000.0)
        try:
            d.gate.clear()
            pl.submit(tagged_batch(4, start=0))
            time.sleep(0.1)
            pl.submit(tagged_batch(4, start=4))
            t = pl.submit(tagged_batch(4, start=8))
            assert t.dropped and t.tenant is None
            st = pl.stats()
            assert "tenants" not in st and "lane_bucket" not in st
            assert "pipeline_admission_drops_total" in pl.metrics.counters
            assert not any("tenant=" in k for k in pl.metrics.counters)
            assert pl.lane_bucket == 0
            d.gate.set()
        finally:
            pl.close(timeout=5)


# --------------------------------------------------------------------------- #
# engine integration
# --------------------------------------------------------------------------- #
def _qos_engine(**kw):
    kw.setdefault("auto_regen", False)
    kw.setdefault("qos_enabled", True)
    kw.setdefault("qos_tenants", SPEC)
    kw.setdefault("qos_assign", "1=gold")
    cfg = DaemonConfig(**kw)
    eng = Engine(cfg, datapath=FakeDatapath(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(POLICY)
    eng.regenerate()
    return eng


def _mk_batch(eng, tenant=0, n=3):
    s16, _ = parse_addr("192.168.1.10")
    recs = []
    for j in range(n):
        d16, _ = parse_addr(f"10.1.2.{3 + j}")
        recs.append(PacketRecord(s16, d16, 40000 + j, 443, C.PROTO_TCP,
                                 C.TCP_SYN, False, 1, C.DIR_EGRESS))
    b = batch_from_records(recs, eng.active.snapshot.ep_slot_of)
    b["_tenant"] = np.full(b["valid"].shape, tenant, dtype=np.int32)
    return b


class TestQosEngine:
    def test_parity_with_auditor_qos_armed(self):
        """Pipeline verdicts stay bit-identical to the serial classify
        path with QoS armed, and the parity auditor at sampling 1.0 sees
        zero mismatched rows."""
        eng = _qos_engine(audit_enabled=True, audit_sample_rate=1.0)
        eng.auditor.configure(sample_rate=1.0)
        try:
            tids = {v: k for k, v in eng.qos.tenants().items()}
            base = eng.classify(_mk_batch(eng), now=100)
            baseline = [bool(a) for a in base["allow"]]
            tickets = [eng.submit(_mk_batch(eng, tenant=tids[name]),
                                  now=200 + i)
                       for i, name in enumerate(
                           ["gold", "silver", "bulk", "default"] * 6)]
            assert eng.drain(timeout=60)
            for t in tickets:
                out = t.result(timeout=5)
                assert [bool(a) for a in out["allow"]] == baseline
            for _ in range(50):
                step = eng.audit_step(budget=128)
                if not step or (not step.get("replayed")
                                and not step.get("pending")):
                    break
            assert eng.auditor.stats()["mismatched_rows"] == 0
        finally:
            eng.stop()

    def test_status_doc_and_ledger_rows(self):
        """The status document carries the qos row, per-tenant queue
        resources register in the ledger, and the global overload ladder
        never reads them."""
        from cilium_tpu.runtime.api import status_doc
        eng = _qos_engine(qos_tenant_cap_batches=0)
        try:
            tids = {v: k for k, v in eng.qos.tenants().items()}
            eng.submit(_mk_batch(eng, tenant=tids["gold"]), now=100)
            assert eng.drain(timeout=30)
            doc = status_doc(eng)
            assert doc["qos"] is not None
            assert doc["qos"]["tenants"]["gold"]["weight"] == 4.0
            assert doc["qos"]["tenants"]["gold"]["admitted_batches"] >= 1
            assert doc["qos"]["lane_bucket"] >= 1
            # ledger rows appear while a tenant has queued work; with the
            # queue drained they are swept (departed-subject discipline)
            rep = eng.resource_step(now=1.0)
            assert not any(r.startswith("qos_tenant_queue_")
                           for r in rep["resources"])
            st = eng.overload_step()
            assert st is not None
        finally:
            eng.stop()

    def test_qos_off_engine_unchanged(self):
        eng = Engine(DaemonConfig(auto_regen=False),
                     datapath=FakeDatapath(DaemonConfig(auto_regen=False)))
        try:
            assert eng.qos is None
            assert eng.qos_status() is None
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_8shard_qos_soak_scrape_race_with_restart(self):
        """The PR 7/11/13 house race pattern extended to the {tenant=}
        families: an 8-shard audited QoS soak with concurrent
        render_metrics scrapers and a mid-soak watchdog restart, asserting
        every {tenant=}-labeled row and qos_tenant_queue_* resource row
        stays parseable throughout and parity holds after the restart."""
        from cilium_tpu.runtime.datapath import JITDatapath
        from tests.test_datapath import pkt
        # stall timeout stays wide through warmup: the QoS lane adds a
        # SECOND dispatch shape (the lane bucket) whose cold JIT compile
        # lands after the generation's one cold-dispatch grace window —
        # the drill shrinks the timeout only once the shapes are warm
        # (the chaos-CLI discipline)
        cfg = DaemonConfig(
            n_shards=8, auto_regen=False, batch_size=512,
            ct_capacity=1 << 12, pipeline_flush_ms=0.5,
            audit_enabled=True, audit_sample_rate=1.0,
            pipeline_max_restarts=3,
            pipeline_restart_backoff_s=0.05,
            qos_enabled=True, qos_tenants=SPEC,
            qos_assign="1=gold")
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.auditor.configure(sample_rate=1.0)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.0.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [
                            {"port": "443", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        tids = {v: k for k, v in eng.qos.tenants().items()}
        errors = []
        stop = threading.Event()

        def scraper():
            try:
                while not stop.is_set():
                    text = eng.render_metrics()
                    for ln in text.splitlines():
                        if ln.startswith("#"):
                            continue
                        if 'tenant="' in ln or "qos_tenant_queue_" in ln:
                            float(ln.rsplit(" ", 1)[1])
            except Exception as e:   # noqa: BLE001
                errors.append(e)
        threads = [threading.Thread(target=scraper) for _ in range(2)]
        for t in threads:
            t.start()

        def batch(i, name):
            recs = [pkt("192.168.0.10", f"10.0.{(i + j) % 250}.1",
                        40000 + j, 443, ep_id=1) for j in range(64)]
            b = batch_from_records(recs, eng.active.snapshot.ep_slot_of)
            b["_tenant"] = np.full(b["valid"].shape, tids[name],
                                   dtype=np.int32)
            return b
        names = ["gold", "silver", "bulk"]
        try:
            FAULTS.reset()
            for i in range(20):
                eng.submit(batch(i, names[i % 3]), now=1000 + i)
            assert eng.drain(timeout=120)
            eng.resource_step(now=1.0)
            # shapes are warm: stall fast, then hang one dispatch past it
            eng.start_pipeline().set_stall_timeout_s(1.0)
            FAULTS.load_spec("datapath.transfer=hang:delay_s=4:times=1")
            try:
                eng.submit(batch(99, "bulk"), now=2000)
            except Exception:   # noqa: BLE001 — the wedged window rejects
                pass
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                ps = eng.pipeline_stats()
                if ps and ps["restarts"] >= 1 and ps["state"] == "ok":
                    break
                time.sleep(0.1)
            FAULTS.reset()
            ps = eng.pipeline_stats()
            assert ps["restarts"] >= 1
            for i in range(10):
                eng.submit(batch(200 + i, names[i % 3]), now=3000 + i)
            assert eng.drain(timeout=120)
            for _ in range(50):
                step = eng.audit_step(budget=128)
                if not step or (not step.get("replayed")
                                and not step.get("pending")):
                    break
            assert eng.auditor.stats()["mismatched_rows"] == 0
            st = eng.pipeline_stats()
            assert st["tenants"]["gold"]["admitted_batches"] >= 1
        finally:
            stop.set()
            for t in threads:
                t.join(5)
            FAULTS.reset()
            eng.stop()
        assert not errors
