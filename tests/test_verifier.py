"""The XLA-as-verifier CI step (SURVEY.md §4 test/verifier analog): every
datapath shape combo must compile, and the CLI command + profiler hook
work."""

import os
import subprocess
import sys

import pytest

from cilium_tpu.compile.verifier import apply_budget, verify_configs


@pytest.fixture(scope="module")
def sweep():
    # ONE compile sweep serves every assertion (budget checks are pure
    # post-processing of the memory stats)
    return verify_configs(batch=64, quick=True)


class TestVerifier:
    def test_all_combos_compile(self, sweep):
        assert len(sweep) >= 10
        bad = [(r.name, r.error) for r in sweep if not r.ok]
        assert not bad, bad
        names = {r.name for r in sweep}
        # the key shapes are all present
        assert "v4only+v4" in names
        assert "dual+l7+l7dict" in names
        assert "dual+addr" in names
        assert "rule-padded" in names

    def test_memory_budget_rejects(self, sweep):
        reports = apply_budget(sweep, max_hbm_bytes=1)
        assert any(not r.ok and "memory budget" in r.error for r in reports)
        # the original sweep is budget-free and still all-ok
        assert all(r.ok for r in sweep)

    def test_cli_verify(self):
        out = subprocess.run(
            [sys.executable, "-m", "cilium_tpu.cli.main", "verify",
             "--batch", "64", "--quick"],
            capture_output=True, text=True, timeout=300, cwd="/root/repo",
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        assert "combos verifier-accepted" in out.stdout
        assert "FAIL" not in out.stdout


class TestProfilerHook:
    def test_profile_classify_writes_trace(self, tmp_path):
        from cilium_tpu.kernels.records import batch_from_records
        from cilium_tpu.runtime.config import DaemonConfig
        from cilium_tpu.runtime.datapath import JITDatapath
        from cilium_tpu.runtime.engine import Engine
        from cilium_tpu.utils import constants as C
        from cilium_tpu.utils.ip import parse_addr
        from oracle import PacketRecord

        eng = Engine(DaemonConfig(ct_capacity=1024, auto_regen=False),
                     datapath=JITDatapath(DaemonConfig(ct_capacity=1024,
                                                       auto_regen=False)))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"]}]}])
        eng.regenerate()
        s16, _ = parse_addr("192.168.1.10")
        d16, _ = parse_addr("10.1.2.3")
        batch = batch_from_records(
            [PacketRecord(s16, d16, 40000, 443, C.PROTO_TCP, C.TCP_SYN,
                          False, 1, C.DIR_EGRESS)],
            eng.active.snapshot.ep_slot_of)
        trace_dir = str(tmp_path / "xprof")
        out = eng.profile_classify(batch, trace_dir, now=1000)
        assert bool(out["allow"][0])
        # a plugin trace directory with at least one event file exists
        found = []
        for root, _dirs, files in os.walk(trace_dir):
            found.extend(files)
        assert found, "no trace files written"
