"""Service load balancing: Maglev properties, host/device lookup agreement,
and end-to-end LB parity (DNAT, rev-NAT via CT, no-backend drops, policy on
the translated tuple) vs the oracle — the lbmap / bpf/lib/lb.h analog."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.lb import (
    LBConfig, build_lb, lb_lookup_np, lb_translate_np, maglev_table,
)
from cilium_tpu.compile.snapshot import build_snapshot
from cilium_tpu.kernels.classify import classify_step
from cilium_tpu.kernels.lb import lb_step
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rules
from cilium_tpu.model.services import Backend, Frontend, Service
from cilium_tpu.policy import PolicyContext, Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr, words_to_addr
from oracle import Oracle, PacketRecord
from tests.test_parity import extract_device_ct, oracle_live_ct


# --------------------------------------------------------------------------- #
# Maglev
# --------------------------------------------------------------------------- #
class TestMaglev:
    def test_full_and_balanced(self):
        backends = [Backend(f"10.0.0.{i}", 8080) for i in range(1, 11)]
        t = maglev_table(backends, 251)
        assert (t >= 0).all()
        counts = np.bincount(t, minlength=10)
        # Maglev guarantees near-perfect balance: max/min <= 2 is loose
        assert counts.min() > 0
        assert counts.max() / counts.min() <= 2.0

    def test_empty(self):
        assert (maglev_table([], 251) == -1).all()

    def test_m_must_be_prime(self):
        with pytest.raises(ValueError):
            maglev_table([Backend("10.0.0.1", 80)], 250)

    def test_minimal_disruption(self):
        backends = [Backend(f"10.0.0.{i}", 8080) for i in range(1, 11)]
        t1 = maglev_table(backends, 251)
        t2 = maglev_table(backends[:-1], 251)  # remove one backend
        moved = (t1 != t2) & (t1 != 9)          # slots not owned by removed
        # consistent hashing: only ~1/B of non-removed slots re-steer
        assert moved.sum() / 251 < 0.35

    def test_weighted(self):
        backends = [Backend("10.0.0.1", 80, weight=3),
                    Backend("10.0.0.2", 80, weight=1)]
        t = maglev_table(backends, 251)
        counts = np.bincount(t, minlength=2)
        assert 2.0 < counts[0] / counts[1] < 4.5

    def test_deterministic(self):
        backends = [Backend(f"10.9.0.{i}", 443) for i in range(1, 6)]
        assert (maglev_table(backends, 251) ==
                maglev_table(backends, 251)).all()


# --------------------------------------------------------------------------- #
# World with services
# --------------------------------------------------------------------------- #
SVC_RULES = [
    {   # client may egress to backend pods on 8080, not 9090
        "endpointSelector": {"matchLabels": {"app": "client"}},
        "egress": [
            {"toEndpoints": [{"matchLabels": {"app": "be"}}],
             "toPorts": [{"ports": [{"port": "8080", "protocol": "TCP"}]}]},
        ],
    },
]


def build_svc_world():
    alloc = IdentityAllocator()
    ipc = IPCache()
    ctx = PolicyContext(allocator=alloc, selector_cache=SelectorCache(alloc),
                        ipcache=ipc)
    repo = Repository(ctx)
    eps = []
    cl = Labels.parse(["k8s:app=client"])
    ident = alloc.allocate(cl)
    eps.append(Endpoint(ep_id=1, labels=cl, identity_id=ident.id,
                        ips=("192.168.2.1",)))
    ipc.upsert("192.168.2.1/32", ident.id)
    be_lbls = Labels.parse(["k8s:app=be"])
    be_ident = alloc.allocate(be_lbls)
    for i in range(1, 4):
        ipc.upsert(f"10.50.0.{i}/32", be_ident.id)
    ipc.upsert("10.60.0.1/32", be_ident.id)  # blocked-port backend
    ctx.services.upsert(Service(
        name="api", namespace="prod",
        frontends=(Frontend("172.20.0.10", 80, C.PROTO_TCP),
                   Frontend("192.168.2.100", 30080, C.PROTO_TCP,
                            kind="NodePort")),
        lb_backends=tuple(Backend(f"10.50.0.{i}", 8080)
                          for i in range(1, 4)),
    ))
    ctx.services.upsert(Service(
        name="blocked", namespace="prod",
        frontends=(Frontend("172.20.0.11", 80, C.PROTO_TCP),),
        lb_backends=(Backend("10.60.0.1", 9090),),
    ))
    ctx.services.upsert(Service(
        name="empty", namespace="prod",
        frontends=(Frontend("172.20.0.12", 80, C.PROTO_TCP),),
        lb_backends=(),
    ))
    repo.add(parse_rules(SVC_RULES))
    return ctx, repo, eps


def svc_packet(rng, dst, dport=80, sport=None, flags=C.TCP_SYN,
               direction=C.DIR_EGRESS):
    s16, _ = parse_addr("192.168.2.1")
    d16, _ = parse_addr(dst)
    if sport is None:
        sport = rng.randrange(30000, 60000)
    if direction == C.DIR_INGRESS:
        s16, d16 = d16, s16
        sport, dport = dport, sport
    return PacketRecord(s16, d16, sport, dport, C.PROTO_TCP, flags,
                        False, 1, direction)


@pytest.fixture(scope="module")
def svc_world():
    ctx, repo, eps = build_svc_world()
    snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096),
                          LBConfig(maglev_m=31))
    return ctx, snap


# --------------------------------------------------------------------------- #
# Lookup agreement host vs device
# --------------------------------------------------------------------------- #
class TestLookupAgreement:
    def test_np_jnp_agree(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(1)
        packets = []
        for _ in range(80):
            dst = rng.choice(["172.20.0.10", "172.20.0.11", "172.20.0.12",
                              "10.50.0.1", "8.8.8.8", "192.168.2.100"])
            dport = rng.choice([80, 81, 8080, 30080])
            packets.append(svc_packet(rng, dst, dport))
        batch = batch_from_records(packets, snap.ep_slot_of)
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        nd, ndp, rn, nb = lb_step(tensors, {k: jnp.asarray(v)
                                            for k, v in batch.items()})
        nd2, ndp2, rn2, nb2, _fe = lb_translate_np(snap.lb, batch)
        np.testing.assert_array_equal(np.asarray(nd), nd2)
        np.testing.assert_array_equal(np.asarray(ndp), ndp2)
        np.testing.assert_array_equal(np.asarray(rn), rn2)
        np.testing.assert_array_equal(np.asarray(nb), nb2)

    def test_frontend_lookup(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(2)
        batch = batch_from_records(
            [svc_packet(rng, "172.20.0.10", 80),      # hit fe
             svc_packet(rng, "172.20.0.10", 81),      # wrong port
             svc_packet(rng, "8.8.8.8", 80),          # not a vip
             svc_packet(rng, "192.168.2.100", 30080)],  # nodeport hit
            snap.ep_slot_of)
        fe = lb_lookup_np(snap.lb, batch)
        assert fe[0] >= 0 and fe[3] >= 0
        assert fe[1] < 0 and fe[2] < 0
        assert fe[0] != fe[3]


# --------------------------------------------------------------------------- #
# End-to-end parity incl. NAT columns
# --------------------------------------------------------------------------- #
def _run_device(snap, ct, packets, now):
    batch = batch_from_records(packets, snap.ep_slot_of)
    tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
    out, new_ct, counters = classify_step(
        tensors, ct, {k: jnp.asarray(v) for k, v in batch.items()},
        jnp.uint32(now), jnp.int32(snap.world_index))
    return ({k: np.asarray(v) for k, v in out.items()}, new_ct,
            {k: np.asarray(v) for k, v in counters.items()})


def _check_against_oracle(out, want, packets):
    for i, v in enumerate(want):
        assert bool(out["allow"][i]) == v.allow, i
        assert int(out["reason"][i]) == int(v.drop_reason), i
        assert int(out["status"][i]) == int(v.ct_status), i
        assert bool(out["svc"][i]) == v.svc, i
        if v.svc:
            assert words_to_addr(out["nat_dst"][i]) == v.nat_dst, i
            assert int(out["nat_dport"][i]) == v.nat_dport, i
        assert bool(out["rnat"][i]) == v.rnat, i
        if v.rnat:
            assert words_to_addr(out["rnat_src"][i]) == v.rnat_src, i
            assert int(out["rnat_sport"][i]) == v.rnat_sport, i


class TestLBParity:
    def test_clusterip_flow(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(3)
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot(), lb=snap.lb)
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=4096)).items()}
        now = 1000

        # batch 1: SYNs to the service VIP → translated, allowed, CT created
        syns = [svc_packet(rng, "172.20.0.10", 80, sport=40000 + i)
                for i in range(16)]
        want = oracle.classify_batch_snapshot(syns, now)
        out, ct, counters = _run_device(snap, ct, syns, now)
        _check_against_oracle(out, want, syns)
        assert all(v.allow and v.svc for v in want)
        # backends actually spread (3 backends, 16 flows)
        bports = {v.nat_dport for v in want}
        assert bports == {8080}
        bips = {v.nat_dst for v in want}
        assert len(bips) > 1
        assert extract_device_ct(ct, now) == oracle_live_ct(oracle, now)

        # batch 2: replies from the chosen backends → rev-NAT back to VIP
        now += 10
        replies = []
        for p, v in zip(syns, want):
            replies.append(PacketRecord(
                v.nat_dst, p.src_addr, v.nat_dport, p.src_port, C.PROTO_TCP,
                C.TCP_SYN | C.TCP_ACK, False, 1, C.DIR_INGRESS))
        want2 = oracle.classify_batch_snapshot(replies, now)
        out2, ct, _ = _run_device(snap, ct, replies, now)
        _check_against_oracle(out2, want2, replies)
        vip16, _ = parse_addr("172.20.0.10")
        for v in want2:
            assert v.allow and v.ct_status == C.CTStatus.REPLY
            assert v.rnat and v.rnat_src == vip16 and v.rnat_sport == 80
        assert extract_device_ct(ct, now) == oracle_live_ct(oracle, now)

        # batch 3: established forward packets keep the same backend
        now += 10
        estab = [PacketRecord(p.src_addr, p.dst_addr, p.src_port, p.dst_port,
                              C.PROTO_TCP, C.TCP_ACK, False, 1, C.DIR_EGRESS)
                 for p in syns]
        want3 = oracle.classify_batch_snapshot(estab, now)
        out3, ct, _ = _run_device(snap, ct, estab, now)
        _check_against_oracle(out3, want3, estab)
        for v0, v3 in zip(want, want3):
            assert v3.ct_status == C.CTStatus.ESTABLISHED
            assert v3.nat_dst == v0.nat_dst  # stateless-deterministic pick

    def test_policy_applies_to_backend_port(self, svc_world):
        """Service 'blocked' DNATs to 9090, which policy does not allow →
        the flow is dropped by policy on the translated tuple."""
        ctx, snap = svc_world
        rng = random.Random(4)
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot(), lb=snap.lb)
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=1024)).items()}
        pkts = [svc_packet(rng, "172.20.0.11", 80) for _ in range(4)]
        want = oracle.classify_batch_snapshot(pkts, 500)
        out, ct, _ = _run_device(snap, ct, pkts, 500)
        _check_against_oracle(out, want, pkts)
        for v in want:
            assert not v.allow and v.svc
            assert v.drop_reason == C.DropReason.POLICY
            assert v.nat_dport == 9090

    def test_no_backend_drop(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(5)
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot(), lb=snap.lb)
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=1024)).items()}
        pkts = [svc_packet(rng, "172.20.0.12", 80) for _ in range(3)]
        want = oracle.classify_batch_snapshot(pkts, 500)
        out, ct, counters = _run_device(snap, ct, pkts, 500)
        _check_against_oracle(out, want, pkts)
        for v in want:
            assert not v.allow
            assert v.drop_reason == C.DropReason.NO_SERVICE
        # counted under NO_SERVICE × egress
        by = counters["by_reason_dir"].reshape(256, 2)
        assert by[int(C.DropReason.NO_SERVICE), C.DIR_EGRESS] == 3
        # no CT entries created
        assert extract_device_ct(ct, 500) == {}

    def test_non_service_traffic_untouched(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(6)
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot(), lb=snap.lb)
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=1024)).items()}
        pkts = [svc_packet(rng, "10.50.0.1", 8080) for _ in range(3)]
        want = oracle.classify_batch_snapshot(pkts, 500)
        out, ct, _ = _run_device(snap, ct, pkts, 500)
        _check_against_oracle(out, want, pkts)
        for v in want:
            assert v.allow and not v.svc and not v.rnat
        live = oracle_live_ct(oracle, 500)
        assert all(e[4] == 0 for e in live.values())  # rev_nat == 0

    def test_mesh_sharded_lb(self, svc_world):
        """Sharded classify with service traffic: steering hashes the
        TRANSLATED tuple so a service flow's forward and reply packets land
        on the same CT shard."""
        from cilium_tpu.parallel.mesh import (
            make_mesh, make_sharded_classify_fn, pad_snapshot_tensors,
            steer_batch, unsteer_outputs,
        )
        ctx, snap = svc_world
        rng = random.Random(8)
        n_flow = 4
        oracle = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                        ctx.ipcache.snapshot(), lb=snap.lb)
        mesh = make_mesh(n_flow, 1)
        tensors = {k: jnp.asarray(v)
                   for k, v in pad_snapshot_tensors(snap.tensors(), 1).items()}
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=4096)).items()}
        fn = make_sharded_classify_fn(mesh, donate_ct=False)
        now = 1000

        syns = [svc_packet(rng, "172.20.0.10", 80, sport=42000 + i)
                for i in range(24)]
        replies = None
        for phase in range(2):
            pkts = syns if phase == 0 else replies
            want = oracle.classify_batch_snapshot(pkts, now)
            raw = batch_from_records(pkts, snap.ep_slot_of)
            steered, scatter, per = steer_batch(raw, n_flow, per_shard=32,
                                                lb=snap.lb)
            out, ct, _ = fn(tensors, ct,
                            {k: jnp.asarray(v) for k, v in steered.items()},
                            jnp.uint32(now), jnp.int32(snap.world_index))
            out_np = unsteer_outputs({k: np.asarray(v)
                                      for k, v in out.items()}, scatter)
            _check_against_oracle(out_np, want, pkts)
            assert extract_device_ct(ct, now) == oracle_live_ct(oracle, now)
            if phase == 0:
                replies = [PacketRecord(
                    v.nat_dst, p.src_addr, v.nat_dport, p.src_port,
                    C.PROTO_TCP, C.TCP_SYN | C.TCP_ACK, False, 1,
                    C.DIR_INGRESS) for p, v in zip(syns, want)]
                now += 10

    def test_sequential_snapshot_agree_size1(self, svc_world):
        ctx, snap = svc_world
        rng = random.Random(7)
        o1 = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                    ctx.ipcache.snapshot(), lb=snap.lb)
        o2 = Oracle(dict(zip(snap.ep_ids, snap.policies)),
                    ctx.ipcache.snapshot(), lb=snap.lb)
        now = 100
        for i in range(40):
            dst = rng.choice(["172.20.0.10", "172.20.0.11", "172.20.0.12",
                              "10.50.0.2", "8.8.8.8"])
            p = svc_packet(rng, dst, 80, sport=41000 + i % 8)
            v1 = o1.classify(p, now)
            [v2] = o2.classify_batch_snapshot([p], now)
            assert v1 == v2, (i, v1, v2)
            now += 3


# --------------------------------------------------------------------------- #
# ServiceRegistry validation (frontend uniqueness is enforced at upsert time,
# not deferred to snapshot compile where auto_regen would swallow it)
# --------------------------------------------------------------------------- #
class TestServiceRegistryValidation:
    def test_conflicting_frontend_rejected(self):
        from cilium_tpu.model.services import ServiceRegistry
        reg = ServiceRegistry()
        reg.upsert(Service(name="a", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),), lb_backends=(Backend("10.0.0.1", 8080),)))
        with pytest.raises(ValueError, match="conflicts"):
            reg.upsert(Service(name="b", namespace="ns", frontends=(
                Frontend("172.20.0.10", 80),),
                lb_backends=(Backend("10.0.0.2", 8080),)))
        # different port on the same VIP is fine
        reg.upsert(Service(name="b", namespace="ns", frontends=(
            Frontend("172.20.0.10", 81),), lb_backends=(Backend("10.0.0.2", 8080),)))

    def test_self_update_keeps_frontend(self):
        from cilium_tpu.model.services import ServiceRegistry
        reg = ServiceRegistry()
        svc = Service(name="a", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),), lb_backends=(Backend("10.0.0.1", 8080),))
        reg.upsert(svc)
        reg.upsert(svc)          # idempotent re-upsert of the owner

    def test_duplicate_frontend_within_service_rejected(self):
        from cilium_tpu.model.services import ServiceRegistry
        reg = ServiceRegistry()
        with pytest.raises(ValueError, match="twice"):
            reg.upsert(Service(name="a", namespace="ns", frontends=(
                Frontend("172.20.0.10", 80), Frontend("172.20.0.10", 80)),
                lb_backends=(Backend("10.0.0.1", 8080),)))

    def test_restore_accepts_legacy_conflict(self):
        """Checkpoint restore (validate=False) must accept conflicting
        frontends that an older engine accepted; the conflict surfaces at
        the next regenerate instead of aborting restore half-way."""
        from cilium_tpu.model.services import ServiceRegistry
        reg = ServiceRegistry()
        reg.upsert(Service(name="a", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),),
            lb_backends=(Backend("10.0.0.1", 8080),)), validate=False)
        reg.upsert(Service(name="b", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),),
            lb_backends=(Backend("10.0.0.2", 8080),)), validate=False)
        assert len(reg.match.__self__._services) == 2

    def test_delete_frees_frontend(self):
        from cilium_tpu.model.services import ServiceRegistry
        reg = ServiceRegistry()
        reg.upsert(Service(name="a", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),),
            lb_backends=(Backend("10.0.0.1", 8080),)))
        assert reg.delete("ns", "a")
        # frontend is free again after delete
        reg.upsert(Service(name="b", namespace="ns", frontends=(
            Frontend("172.20.0.10", 80),),
            lb_backends=(Backend("10.0.0.2", 8080),)))
