"""Pipeline guard tests (pipeline/guard.py + the scheduler surgery):
overload protection and self-healing for the serving pipeline.

Unit tests drive a raw Pipeline against the recording EchoDispatch:
per-submission deadline shed at ingest and at flush (counted per reason in
``pipeline_shed_total{reason}``), circuit-breaker open → fail-fast →
half-open probe → close (traced + counted, no per-submission 1000-retry
burn), watchdog-supervised restart on a hang-mode stall and on worker
crash, hard-fail past the restart budget, the close(timeout) sweep, and
the drain-vs-close / blocked-submit-vs-close races.

Integration tests go through Engine on FakeDatapath and pin the acceptance
contracts: a ``hang``-forced watchdog restart mid-stream leaves no ticket
blocked forever and post-restart verdicts bit-identical to the serial
``classify`` path; breaker state folds into ``Engine.health()`` /
``healthz`` / Prometheus; the REST serving route maps shed → 429 and
unavailable/timeout → 503. The ``slow``-marked soak (`make chaos` tail)
pushes 10k submissions through three forced watchdog restarts and asserts
nothing resolved is lost, reordered, or double-dispatched.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.kernels.records import batch_from_records, empty_batch
from cilium_tpu.observe.trace import Tracer
from cilium_tpu.pipeline import (Pipeline, PipelineClosed,
                                 PipelineDeadlineExceeded, PipelineDrop,
                                 PipelineError, PipelineUnavailable)
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def sub_batch(n_rows, start, n_valid=None):
    b = empty_batch(n_rows)
    b["sport"][:] = np.arange(start, start + n_rows, dtype=np.int32)
    b["valid"][: n_rows if n_valid is None else n_valid] = True
    return b


class EchoDispatch:
    """Records dispatched valid-row sports; echoes sport through reason."""

    def __init__(self):
        self.batches = []
        self.gate = threading.Event()
        self.gate.set()
        self.fail_always = None      # exception type raised on every call

    def __call__(self, batch, now):
        self.gate.wait(timeout=30)
        if self.fail_always is not None:
            raise self.fail_always("backend down")
        valid = np.asarray(batch["valid"])
        self.batches.append(np.asarray(batch["sport"])[valid].tolist())
        out = {
            "allow": valid.copy(),
            "reason": np.asarray(batch["sport"], np.int32).copy(),
            "status": np.zeros(valid.shape[0], np.int32),
            "remote_identity": np.zeros(valid.shape[0], np.int32),
        }
        return lambda: out

    @property
    def sports_seen(self):
        return [s for b in self.batches for s in b]


def guarded(d, **kw):
    kw.setdefault("min_bucket", 4)
    kw.setdefault("max_bucket", 16)
    kw.setdefault("flush_ms", 1000.0)
    kw.setdefault("restart_backoff_s", 0.01)
    return Pipeline(d, **kw)


# --------------------------------------------------------------------------- #
class TestDeadlines:
    def test_shed_at_ingest_while_worker_busy(self):
        """A submission whose deadline passes while it queues behind a
        slow dispatch is shed at ingest — the device never sees it."""
        d = EchoDispatch()
        d.gate.clear()
        pl = guarded(d)
        try:
            hog = pl.submit(sub_batch(4, start=0))       # wedges in dispatch
            time.sleep(0.05)                             # hog reaches worker
            stale = pl.submit(sub_batch(4, start=100), deadline_ms=10)
            time.sleep(0.05)                             # deadline passes
            d.gate.set()
            with pytest.raises(PipelineDeadlineExceeded):
                stale.result(timeout=5)
            assert hog.result(timeout=5)["allow"].all()
            assert 100 not in d.sports_seen              # never dispatched
            s = pl.stats()
            assert s["shed_total"] == 1
            assert s["shed_reasons"] == {"ingest": 1}
            assert pl.metrics.counters[
                'pipeline_shed_total{reason="ingest"}'] == 1
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_shed_at_flush_masks_rows(self):
        """A staged rider whose deadline expires before the bucket
        dispatches is masked out of the bucket and rejected; co-staged
        riders still serve."""
        d = EchoDispatch()
        pl = guarded(d, flush_ms=60_000.0)
        try:
            doomed = pl.submit(sub_batch(3, start=10), deadline_ms=30)
            keeper = pl.submit(sub_batch(3, start=20))
            time.sleep(0.08)                             # both staged; 10ms
            assert pl.drain(timeout=5)                   # forces the flush
            with pytest.raises(PipelineDeadlineExceeded):
                doomed.result(timeout=1)
            assert keeper.result(timeout=1)["reason"].tolist() == \
                [20, 21, 22]
            # the doomed rows were valid-masked out of the shared bucket
            assert d.sports_seen == [20, 21, 22]
            assert pl.stats()["shed_reasons"] == {"flush": 1}
        finally:
            pl.close(timeout=5)

    def test_default_deadline_from_ctor(self):
        d = EchoDispatch()
        d.gate.clear()
        pl = guarded(d, deadline_ms=10)
        try:
            pl.submit(sub_batch(4, start=0))
            time.sleep(0.05)
            late = pl.submit(sub_batch(4, start=50))     # inherits 10ms
            time.sleep(0.05)
            d.gate.set()
            with pytest.raises(PipelineDeadlineExceeded):
                late.result(timeout=5)
        finally:
            d.gate.set()
            pl.close(timeout=5)

    def test_shed_counter_renders_one_type_line(self):
        d = EchoDispatch()
        pl = guarded(d)
        try:
            pl.metrics.inc_counter('pipeline_shed_total{reason="ingest"}')
            pl.metrics.inc_counter('pipeline_shed_total{reason="flush"}')
            text = pl.metrics.render_prometheus()
            assert text.count("# TYPE ciliumtpu_pipeline_shed_total "
                              "counter") == 1
            assert 'ciliumtpu_pipeline_shed_total{reason="flush"} 1' in text
            assert 'ciliumtpu_pipeline_shed_total{reason="ingest"} 1' in text
        finally:
            pl.close(timeout=5)


# --------------------------------------------------------------------------- #
class TestCircuitBreaker:
    def test_opens_fast_fails_probes_and_closes(self):
        """The acceptance sequence: fail-always dispatch opens the breaker
        after `threshold` attempts (not MAX_DISPATCH_RETRIES), submissions
        then fail fast, and after the cooldown a half-open probe dispatch
        closes it again — every transition traced and counted."""
        d = EchoDispatch()
        tracer = Tracer(sample_rate=1.0, capacity=512)
        pl = guarded(d, breaker_threshold=5, breaker_cooldown_s=0.15,
                     tracer=tracer)
        try:
            FAULTS.arm("pipeline.dispatch", mode="fail")   # every fire
            first = pl.submit(sub_batch(4, start=0))
            with pytest.raises(PipelineUnavailable):
                first.result(timeout=10)
            assert pl.dispatch_faults <= 6        # no 1000-retry burn
            assert pl.breaker.state == "open"
            assert pl.state() == "breaker-open"
            # open: fail fast at admission, nothing reaches the worker
            for _ in range(3):
                with pytest.raises(PipelineUnavailable):
                    pl.submit(sub_batch(4, start=8))
            assert pl.stats()["unavailable_total"] >= 3
            # cooldown elapses; the armed fault fails the half-open probe
            time.sleep(0.2)
            probe = pl.submit(sub_batch(4, start=16))
            with pytest.raises(PipelineUnavailable):
                probe.result(timeout=5)
            assert pl.breaker.state == "open"     # probe failure re-opened
            # disarm + cooldown: the next probe closes the breaker
            FAULTS.disarm("pipeline.dispatch")
            time.sleep(0.2)
            ok = pl.submit(sub_batch(4, start=24))
            assert ok.result(timeout=5)["reason"].tolist() == \
                [24, 25, 26, 27]
            assert pl.breaker.state == "closed"
            assert pl.state() == "ok"
            # observability: transitions counted + traced + gauged
            m = pl.metrics
            assert m.counters[
                'pipeline_breaker_transitions_total{to="open"}'] == 2
            assert m.counters[
                'pipeline_breaker_transitions_total{to="half-open"}'] == 2
            assert m.counters[
                'pipeline_breaker_transitions_total{to="closed"}'] == 1
            assert m.gauges["pipeline_breaker_state"] == 0
            events = tracer.spans(limit=100, name="pipeline.breaker")
            tos = [e["attrs"]["to"] for e in events]
            assert tos.count("open") == 2 and tos.count("closed") == 1
        finally:
            FAULTS.reset()
            pl.close(timeout=5)

    def test_real_errors_feed_breaker_and_suppress_queued(self):
        """Non-fault dispatch errors open the breaker too, and batches
        already queued behind the failure are rejected fast (dispatch
        suppressed) instead of hammering the sick backend."""
        d = EchoDispatch()
        d.fail_always = ValueError
        pl = guarded(d, breaker_threshold=3, breaker_cooldown_s=30.0)
        try:
            tickets, fast_fails = [], 0
            for i in range(6):
                try:
                    tickets.append(pl.submit(sub_batch(4, start=4 * i)))
                except PipelineUnavailable:
                    # the breaker can open while we are still submitting
                    # (worker outpaces the producer): fail-fast at
                    # admission is the same guarantee, earlier
                    fast_fails += 1
            assert pl.drain(timeout=10)
            for t in tickets:
                with pytest.raises(PipelineError):
                    t.result(timeout=1)
            assert pl.breaker.state == "open"
            # only `threshold` dispatch attempts hit the backend; the rest
            # were suppressed while open or failed fast at admission
            assert pl.dispatch_errors == 3
            assert d.batches == []
            assert len(tickets) + fast_fails == 6
        finally:
            pl.close(timeout=5)

    def test_finalize_faults_feed_breaker(self):
        d = EchoDispatch()
        pl = guarded(d, breaker_threshold=2, breaker_cooldown_s=30.0)
        try:
            FAULTS.arm("pipeline.finalize", mode="fail")
            t1 = pl.submit(sub_batch(4, start=0))
            with pytest.raises(PipelineError):
                t1.result(timeout=5)
            t2 = pl.submit(sub_batch(4, start=4))
            with pytest.raises(PipelineError):
                t2.result(timeout=5)
            assert pl.breaker.state == "open"
        finally:
            FAULTS.reset()
            pl.close(timeout=5)


# --------------------------------------------------------------------------- #
def pkt(src, dst, sp, dp, ep_id=1):
    s16, _ = parse_addr(src)
    d16, _ = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, C.PROTO_TCP, C.TCP_SYN, False,
                        ep_id, C.DIR_EGRESS, C.HTTP_METHOD_ANY, b"")


def fake_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    kw.setdefault("batch_size", 64)
    cfg = DaemonConfig(**kw)
    return Engine(cfg, datapath=FakeDatapath(cfg))


def unique_chunks(slot_of, n_chunks, rows, base=40000):
    """Unique-flow SYN chunks (allowed and denied mix): under the CT
    snapshot-batch semantics batch composition cannot change a unique
    flow's verdict, so a serial engine classifying the same chunks is a
    bit-exact oracle for whichever tickets resolve."""
    chunks = []
    for c in range(n_chunks):
        recs = []
        for r in range(rows):
            sp = base + c * rows + r
            dp = 443 if (c + r) % 3 else 80          # mix allow/deny
            recs.append(pkt("192.168.1.10", f"10.0.{c % 200}.{r + 1}",
                            sp, dp))
        chunks.append(batch_from_records(recs, slot_of))
    return chunks


OUT_KEYS = ("allow", "reason", "status", "remote_identity", "svc",
            "nat_dst", "nat_dport", "rnat", "rnat_src", "rnat_sport")


class TestWatchdogRestart:
    def test_parity_across_forced_restart(self):
        """The acceptance pin: a hang-mode fault wedges the worker
        mid-stream → the watchdog restarts it. Every pre-stall ticket
        resolves or is rejected (none blocks forever), and every verdict
        that resolves — pre-stall survivors and post-restart submissions —
        is bit-identical to the serial classify path on the same
        submissions."""
        ser = fake_engine()
        pipe = fake_engine(pipeline_min_bucket=16,
                           pipeline_flush_ms=1.0,
                           pipeline_stall_timeout_s=0.2,
                           pipeline_restart_backoff_s=0.02,
                           pipeline_max_restarts=3)
        for eng in (ser, pipe):
            eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",),
                             ep_id=1)
            eng.apply_policy(POLICY)
        slot_of = ser.active.snapshot.ep_slot_of
        chunks = unique_chunks(slot_of, n_chunks=10, rows=5)
        serial_outs = [ser.classify(dict(ch), now=100 + i)
                       for i, ch in enumerate(chunks)]

        FAULTS.arm("pipeline.dispatch", mode="hang", delay_s=2.0, times=1)
        tickets = [pipe.submit(dict(ch), now=100 + i)
                   for i, ch in enumerate(chunks)]
        assert pipe.drain(timeout=30)
        FAULTS.disarm("pipeline.dispatch")
        # none blocks forever
        assert all(t.done() for t in tickets)
        rejected = resolved = 0
        for i, t in enumerate(tickets):
            try:
                got = t.result(timeout=1)
            except PipelineError:
                rejected += 1
                continue
            resolved += 1
            for k in OUT_KEYS:
                np.testing.assert_array_equal(
                    got[k], serial_outs[i][k],
                    err_msg=f"pre-stall chunk {i} field {k} diverged")
        assert rejected >= 1, "the hang never wedged anything"
        stats = pipe.pipeline_stats()
        assert stats["restarts"] == 1

        # post-restart submissions: bit-identical to serial on the same
        # submissions (FIFO contract survives the restart)
        post = unique_chunks(slot_of, n_chunks=6, rows=5, base=50000)
        post_serial = [ser.classify(dict(ch), now=200 + i)
                       for i, ch in enumerate(post)]
        post_tickets = [pipe.submit(dict(ch), now=200 + i)
                        for i, ch in enumerate(post)]
        assert pipe.drain(timeout=30)
        for i, (t, want) in enumerate(zip(post_tickets, post_serial)):
            got = t.result(timeout=5)
            for k in OUT_KEYS:
                np.testing.assert_array_equal(
                    got[k], want[k],
                    err_msg=f"post-restart chunk {i} field {k} diverged")
        assert pipe.pipeline_stats()["state"] == "ok"
        pipe.stop()
        ser.stop()

    def test_hard_fail_past_restart_budget(self):
        """Each malformed submission crashes the worker; past
        max_restarts the pipeline goes hard-failed: everything rejected,
        submit fails fast, drain doesn't hang."""
        d = EchoDispatch()
        pl = guarded(d, max_restarts=1)
        bad = {"valid": np.ones(3, bool),
               "sport": np.arange(3, dtype=np.int32)}
        for _ in range(2):                    # restart 1, then hard-fail
            t = pl.submit(dict(bad))
            with pytest.raises(PipelineError):
                t.result(timeout=5)
            time.sleep(0.05)                  # let the restart land
        deadline = time.monotonic() + 5
        while pl.state() != "failed" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pl.state() == "failed"
        with pytest.raises(PipelineUnavailable):
            pl.submit(sub_batch(4, start=0))
        assert pl.drain(timeout=5)
        assert pl.stats()["outstanding"] == 0
        assert pl.metrics.counters["pipeline_hard_failures_total"] == 1
        pl.close(timeout=5)

    def test_close_timeout_sweeps_stranded_tickets(self):
        """close(timeout) with a wedged worker must not strand
        outstanding tickets: after the join timeout they are swept and
        rejected, and the fenced worker waking later is harmless."""
        d = EchoDispatch()
        d.gate.clear()                        # wedge inside dispatch_fn
        pl = guarded(d, queue_batches=32)
        tickets = [pl.submit(sub_batch(4, start=4 * i)) for i in range(5)]
        t0 = time.monotonic()
        pl.close(timeout=0.3)
        assert time.monotonic() - t0 < 5
        for t in tickets:
            assert t.done()
            with pytest.raises(PipelineError):
                t.result(timeout=1)
        assert pl.stats()["outstanding"] == 0
        d.gate.set()                          # wake the fenced worker
        time.sleep(0.1)                       # it must exit without damage
        with pytest.raises(PipelineClosed):
            pl.submit(sub_batch(4, start=0))

    def test_close_without_timeout_never_hangs_on_wedged_worker(self):
        """close() with the default timeout=None on a wedged worker must
        still terminate: the watchdog's shutdown sweep fences the stuck
        thread and rejects the outstanding tickets."""
        d = EchoDispatch()
        d.gate.clear()                        # wedge inside dispatch_fn
        pl = guarded(d, stall_timeout_s=0.2)
        tickets = [pl.submit(sub_batch(4, start=4 * i)) for i in range(3)]
        t0 = time.monotonic()
        pl.close()                            # unbounded join would hang
        assert time.monotonic() - t0 < 10
        for t in tickets:
            assert t.done()
            with pytest.raises(PipelineError):
                t.result(timeout=1)
        assert pl.stats()["outstanding"] == 0
        d.gate.set()

    def test_close_without_timeout_after_hard_fail_zombie(self):
        """A hard-failed pipeline whose last wedged worker thread is still
        alive (stuck in the device call) must not hang close(timeout=None)
        — the fenced worker can never drain, so close stops waiting on
        it."""
        d = EchoDispatch()
        d.gate.clear()                        # wedge inside dispatch_fn
        pl = guarded(d, stall_timeout_s=0.05, max_restarts=0)
        t = pl.submit(sub_batch(4, start=0))
        with pytest.raises(PipelineError):
            t.result(timeout=10)              # first stall → hard-fail
        deadline = time.monotonic() + 5
        while pl.state() != "failed" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pl.state() == "failed"
        t0 = time.monotonic()
        pl.close()                            # zombie alive; must return
        assert time.monotonic() - t0 < 10
        d.gate.set()

    def test_engine_health_folds_pipeline_state(self):
        eng = fake_engine(pipeline_breaker_threshold=3,
                          pipeline_breaker_cooldown_s=0.2)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        slot_of = eng.active.snapshot.ep_slot_of
        assert "pipeline" not in eng.health()          # not started yet
        FAULTS.arm("pipeline.dispatch", mode="fail")
        t = eng.submit(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40000, 443)], slot_of),
            now=100)
        with pytest.raises(PipelineUnavailable):
            t.result(timeout=10)
        h = eng.health()
        assert h["state"] == C.HEALTH_DEGRADED
        assert h["pipeline"]["state"] == "breaker-open"
        assert h["pipeline"]["breaker"]["consecutive_failures"] >= 3
        text = eng.render_metrics()
        assert 'pipeline_breaker_transitions_total{to="open"} 1' in text
        assert "ciliumtpu_pipeline_state 1" in text
        FAULTS.disarm("pipeline.dispatch")
        time.sleep(0.25)
        out = eng.submit(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40001, 443)], slot_of),
            now=101).result(timeout=10)
        assert out["allow"].all()
        h = eng.health()
        assert h["state"] == C.HEALTH_OK
        assert h["pipeline"]["state"] == "ok"
        eng.stop()


# --------------------------------------------------------------------------- #
class TestShutdownRaces:
    def test_drain_racing_close(self):
        """drain() waiters must resolve when close() lands concurrently —
        no deadlock, accounting consistent."""
        d = EchoDispatch()
        d.gate.clear()
        pl = guarded(d, queue_batches=32)
        for i in range(6):
            pl.submit(sub_batch(4, start=4 * i))
        results = {}

        def drainer():
            results["drained"] = pl.drain(timeout=10)

        th = threading.Thread(target=drainer)
        th.start()
        time.sleep(0.05)
        d.gate.set()
        pl.close(timeout=10)                   # clean close: work completes
        th.join(timeout=10)
        assert not th.is_alive()
        assert results["drained"] is True
        assert pl.stats()["outstanding"] == 0

    def test_drain_racing_wedged_close(self):
        """Same race with a wedged worker: the close-timeout sweep must
        release the drain waiter (outstanding reaches zero)."""
        d = EchoDispatch()
        d.gate.clear()
        pl = guarded(d, queue_batches=32)
        for i in range(4):
            pl.submit(sub_batch(4, start=4 * i))
        results = {}

        def drainer():
            results["drained"] = pl.drain(timeout=10)

        th = threading.Thread(target=drainer)
        th.start()
        time.sleep(0.05)
        pl.close(timeout=0.3)                  # worker still gated: sweep
        th.join(timeout=10)
        assert not th.is_alive()
        assert results["drained"] is True      # everything rejected == done
        assert pl.stats()["outstanding"] == 0
        d.gate.set()

    def test_submit_blocked_at_admission_sees_close(self):
        """A producer blocked at a full admission queue must get
        PipelineClosed when close() lands — and the never-admitted
        submission must not leak into _outstanding (drain still
        terminates, outstanding reaches zero)."""
        d = EchoDispatch()
        d.gate.clear()
        pl = guarded(d, queue_batches=1, block_timeout_s=30.0)
        first = pl.submit(sub_batch(4, start=0))     # worker picks this up
        time.sleep(0.05)
        second = pl.submit(sub_batch(4, start=4))    # fills the queue
        errors = {}

        def blocked_submit():
            try:
                pl.submit(sub_batch(4, start=8))
            except BaseException as e:               # noqa: BLE001
                errors["exc"] = e

        th = threading.Thread(target=blocked_submit)
        th.start()
        time.sleep(0.1)
        assert th.is_alive()                         # parked at admission
        d.gate.set()
        pl.close(timeout=10)
        th.join(timeout=10)
        assert not th.is_alive()
        assert isinstance(errors.get("exc"), PipelineClosed)
        # the two accepted submissions completed; the blocked one never
        # entered accounting
        assert first.result(timeout=1)["allow"].all()
        assert second.result(timeout=1)["allow"].all()
        assert pl.stats()["outstanding"] == 0
        assert pl.drain(timeout=1)


# --------------------------------------------------------------------------- #
class TestHangFaultMode:
    def test_hang_is_bounded_and_disarm_releases(self):
        FAULTS.arm("pipeline.dispatch", mode="hang", delay_s=5.0)
        t0 = time.monotonic()
        released = {}

        def firer():
            FAULTS.fire("pipeline.dispatch")
            released["after"] = time.monotonic() - t0

        th = threading.Thread(target=firer)
        th.start()
        time.sleep(0.1)
        FAULTS.disarm("pipeline.dispatch")     # cooperative early release
        th.join(timeout=5)
        assert not th.is_alive()
        assert released["after"] < 1.0
        assert FAULTS.stats()["pipeline.dispatch"]["fired"] >= 1

    def test_hang_cap_is_clamped(self):
        from cilium_tpu.runtime.faults import HANG_HARD_CAP_S, FaultSpec
        spec = FaultSpec(mode="hang", delay_s=10_000.0)
        assert spec.delay_s == 10_000.0        # spec keeps the ask...
        assert HANG_HARD_CAP_S <= 30.0         # ...fire() clamps the stall

    def test_new_points_registered_and_env_grammar(self):
        from cilium_tpu.runtime.faults import POINTS, FaultInjector
        assert "pipeline.finalize" in POINTS
        assert "datapath.transfer" in POINTS
        inj = FaultInjector(env={})
        assert inj.load_spec("pipeline.finalize=hang:0.05;"
                             "datapath.transfer=fail:2") == 2
        armed = inj.armed()
        assert armed["pipeline.finalize"].mode == "hang"
        assert armed["pipeline.finalize"].delay_s == 0.05
        assert armed["datapath.transfer"].times == 2


# --------------------------------------------------------------------------- #
class TestServingAPI:
    @pytest.fixture
    def live_engine(self, tmp_path):
        sock = str(tmp_path / "guard.sock")
        eng = fake_engine(api_socket=sock,
                          pipeline_breaker_threshold=3,
                          pipeline_breaker_cooldown_s=30.0,
                          pipeline_request_timeout_s=5.0)
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy(POLICY)
        eng.regenerate()
        eng.start_background()
        yield eng, sock
        eng.stop()
        FAULTS.reset()

    def test_classify_route_serves_verdicts(self, live_engine):
        from cilium_tpu.runtime.api import UnixAPIClient
        eng, sock = live_engine
        code, doc = UnixAPIClient(sock).post("/v1/classify", {"records": [
            {"src": "192.168.1.10", "dst": "10.1.2.3", "sport": 40000,
             "dport": 443, "proto": "TCP", "ep": 1},
            {"src": "192.168.1.10", "dst": "10.1.2.3", "sport": 40001,
             "dport": 80, "proto": "TCP", "ep": 1},
        ]})
        assert code == 200 and doc["count"] == 2
        assert doc["verdicts"][0]["allow"] is True
        assert doc["verdicts"][1]["allow"] is False
        # parity with the serial path on the same flows
        out = eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 40002, 443)],
            eng.active.snapshot.ep_slot_of))
        assert bool(out["allow"][0]) is True

    def test_classify_route_maps_unavailable_to_503(self, live_engine):
        from cilium_tpu.runtime.api import UnixAPIClient
        eng, sock = live_engine
        client = UnixAPIClient(sock)
        FAULTS.arm("pipeline.dispatch", mode="fail")
        rec = {"src": "192.168.1.10", "dst": "10.1.2.3", "sport": 41000,
               "dport": 443, "proto": "TCP", "ep": 1}
        code, doc = client.post("/v1/classify", {"records": [rec]})
        assert code == 503 and doc["kind"] == "PipelineUnavailable"
        # breaker now open: the next request fails fast, still 503 + body
        code, doc = client.post("/v1/classify", {"records": [rec]})
        assert code == 503 and "error" in doc
        code, h = client.get("/v1/healthz")
        assert code == 200 and h["pipeline"]["state"] == "breaker-open"
        assert h["state"] == C.HEALTH_DEGRADED

    def test_classify_route_validates_body(self, live_engine):
        from cilium_tpu.runtime.api import UnixAPIClient
        _eng, sock = live_engine
        client = UnixAPIClient(sock)
        code, doc = client.post("/v1/classify", {})
        assert code == 400
        code, doc = client.post("/v1/classify",
                                {"records": [{"src": "10.0.0.1"}]})
        assert code == 400 and "missing" in doc["error"]

    def test_serving_error_mapping(self):
        from cilium_tpu.runtime.api import serving_error
        assert serving_error(PipelineDrop("q full"))[0] == 429
        assert serving_error(PipelineDeadlineExceeded("late"))[0] == 429
        assert serving_error(PipelineUnavailable("open"))[0] == 503
        assert serving_error(PipelineClosed("closed"))[0] == 503
        assert serving_error(TimeoutError("slow"))[0] == 503
        assert serving_error(PipelineError("other"))[0] == 503
        assert serving_error(ValueError("bug")) is None


# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestWatchdogSoak:
    def test_soak_10k_submissions_through_forced_restarts(self):
        """`make chaos` tail: 10k direct-dispatch submissions with a
        hang fault tripping three times mid-stream (three watchdog
        restarts). Every ticket resolves or is rejected, every resolved
        row reached the dispatch function exactly once and in submission
        order, and the pipeline ends healthy."""
        d = EchoDispatch()
        pl = Pipeline(d, min_bucket=4, max_bucket=16, flush_ms=0.5,
                      queue_batches=256, block_timeout_s=30.0,
                      stall_timeout_s=0.1, restart_backoff_s=0.01,
                      max_restarts=10)
        FAULTS.arm("pipeline.dispatch", mode="hang", delay_s=0.6, times=3)
        n_sub = 10_000
        tickets = []
        for i in range(n_sub):
            tickets.append(pl.submit(sub_batch(4, start=4 * i)))
        assert pl.drain(timeout=180)
        FAULTS.disarm("pipeline.dispatch")
        assert all(t.done() for t in tickets)
        expected = []
        rejected = 0
        for i, t in enumerate(tickets):
            try:
                t.result(timeout=1)
                expected.extend(range(4 * i, 4 * i + 4))
            except PipelineError:
                rejected += 1
        assert d.sports_seen == expected, \
            "resolved rows lost, reordered, or double-dispatched"
        stats = pl.stats()
        assert stats["restarts"] == 3
        assert rejected >= 3            # at least one window per stall
        assert rejected < n_sub // 10   # ...but the storm stayed contained
        assert stats["state"] == "ok"
        assert stats["outstanding"] == 0
        pl.close(timeout=10)
