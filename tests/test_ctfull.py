"""CT exhaustion semantics (ISSUE 10 tentpole): insert-when-full.

The contract under test, bit-identical across the jnp core, the fused
(interpret-mode) Pallas path and the bounded oracle/FakeDatapath:

- a NEW allowed flow whose probe window holds no free slot tail-evicts the
  window's soonest-expiring *evictable* entry (everything except
  established TCP — SYN-stage/closing/non-TCP), ties to the earliest probe
  offset, contested victims to the lowest packet index;
- slots the batch probe-hit are protected from eviction (snapshot
  semantics);
- a flow that still cannot obtain a slot fails CLOSED: denied with the new
  ``DropReason.CT_FULL`` and the ``ct_full`` out column set, counted in
  ``insert_fail`` (``ct_evicted`` counts the evictions);
- the shadow auditor replays a saturated table's verdicts with zero
  mismatches at sampling 1.0 (``oracle.replay(ct_full=...)`` treats the
  exhaustion signal like ``status`` — externally supplied truth that can
  only EXCUSE a create the replay itself demands).
"""

import numpy as np
import pytest

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.datapath import FakeDatapath, JITDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord
from oracle.datapath import ConntrackTable, Oracle, _ct_expirable

#: the full comparable out surface — ct_full included (the new column)
OUT_KEYS = ("allow", "reason", "status", "ct_full", "remote_identity",
            "redirect")

CT_CAP = 256          # small enough for a test flood to saturate


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def make_engine(datapath_cls, fused="off", cap=CT_CAP):
    cfg = DaemonConfig(ct_capacity=cap, auto_regen=False,
                       fused_kernels=fused)
    eng = Engine(cfg, datapath=datapath_cls(cfg))
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.add_endpoint(["k8s:peer=p0", "k8s:group=g0"],
                     ips=("172.16.0.5",), ep_id=10)
    eng.apply_policy([{
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [{"fromEndpoints": [{"matchLabels": {"group": "g0"}}],
                     "toPorts": [{"ports": [
                         {"port": "80", "protocol": "TCP"}]}]}]}])
    eng.regenerate()
    return eng


def flows(slots, sports, flags=C.TCP_SYN, dport=80):
    s16, _ = parse_addr("172.16.0.5")
    d16, _ = parse_addr("192.168.1.10")
    return batch_from_records(
        [PacketRecord(s16, d16, sp, dport, C.PROTO_TCP, flags, False, 1,
                      C.DIR_INGRESS) for sp in sports], slots)


def assert_same(a, b, msg=""):
    for k in OUT_KEYS:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      f"{msg}:{k}")


# --------------------------------------------------------------------------- #
# predicate + kernel units
# --------------------------------------------------------------------------- #
class TestEvictability:
    def test_established_tcp_protected_everything_else_fair_game(self):
        import jax.numpy as jnp
        from cilium_tpu.kernels.conntrack import ct_evictable
        proto = jnp.asarray([C.PROTO_TCP] * 4 + [C.PROTO_UDP, C.PROTO_ICMP])
        flags = jnp.asarray([
            0,                                           # SYN-stage TCP
            C.CT_FLAG_SEEN_NON_SYN,                      # established TCP
            C.CT_FLAG_SEEN_NON_SYN | C.CT_FLAG_TX_CLOSING,   # closing
            C.CT_FLAG_TX_CLOSING,                        # closing, no ack
            C.CT_FLAG_SEEN_NON_SYN,                      # UDP (flag moot)
            0,                                           # ICMP
        ], dtype=jnp.uint32)
        want = [True, False, True, True, True, True]
        assert np.asarray(ct_evictable(proto, flags)).tolist() == want
        # the oracle's host mirror agrees on every combination
        for p, f, w in zip(np.asarray(proto).tolist(),
                           np.asarray(flags).tolist(), want):
            assert _ct_expirable(int(p), int(f)) == w

    def test_insert_evicts_min_expiry_unprotected(self):
        """Direct kernel check on a tiny table: the eviction victim is the
        soonest-expiring evictable window slot, protected slots are
        skipped, and a window full of protected entries fails the
        insert."""
        import jax.numpy as jnp
        from cilium_tpu.kernels import conntrack as ctk
        cap, pd = 8, 8
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(cap, pd)).items()}
        # fill all 8 slots with evictable SYN entries, distinct expiries
        b0 = flows({1: 0, 10: 1}, range(1000, 1012))
        bj = {k: jnp.asarray(v) for k, v in b0.items()}
        keys = ctk.ct_key_words_jnp(bj)
        nk, ncr, zm, slot, fail, _ = ctk.ct_insert_new(
            ct, keys, jnp.asarray([True] * 12), jnp.uint32(100), pd)
        ct = ctk.ct_apply(ct, bj, slot, jnp.zeros(12, bool), slot >= 0,
                          jnp.uint32(100), new_keys=nk, new_created=ncr,
                          zero_mask=zm)
        assert int((np.asarray(ct["expiry"]) > 100).sum()) == cap
        # stagger expiries so the min is unique and known
        exp = np.asarray(ct["expiry"]).copy()
        exp[:] = 200 + np.arange(cap) * 10
        ct = dict(ct)
        ct["expiry"] = jnp.asarray(exp)
        min_slot = 0                     # expiry 200 — the victim
        one = flows({1: 0, 10: 1}, [7777])
        oj = {k: jnp.asarray(v) for k, v in one.items()}
        okeys = ctk.ct_key_words_jnp(oj)
        nk, ncr, zm, slot, fail, nev = ctk.ct_insert_new(
            ct, okeys, jnp.asarray([True]), jnp.uint32(150), pd,
            evict=True)
        assert int(slot[0]) == min_slot and int(nev) == 1
        assert not bool(np.asarray(fail)[0])
        # same insert with the victim protected → next-soonest wins
        prot = jnp.zeros((cap,), bool).at[min_slot].set(True)
        nk, ncr, zm, slot, fail, nev = ctk.ct_insert_new(
            ct, okeys, jnp.asarray([True]), jnp.uint32(150), pd,
            evict=True, protected=prot)
        assert int(slot[0]) == 1 and int(nev) == 1
        # every slot protected → CT_FULL fail
        nk, ncr, zm, slot, fail, nev = ctk.ct_insert_new(
            ct, okeys, jnp.asarray([True]), jnp.uint32(150), pd,
            evict=True, protected=jnp.ones((cap,), bool))
        assert bool(np.asarray(fail)[0]) and int(nev) == 0

    def test_duplicates_adopt_evict_winner(self):
        import jax.numpy as jnp
        from cilium_tpu.kernels import conntrack as ctk
        cap, pd = 8, 8
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(cap, pd)).items()}
        fill = flows({1: 0, 10: 1}, range(2000, 2012))
        fj = {k: jnp.asarray(v) for k, v in fill.items()}
        fkeys = ctk.ct_key_words_jnp(fj)
        nk, ncr, zm, slot, fail, _ = ctk.ct_insert_new(
            ct, fkeys, jnp.asarray([True] * 12), jnp.uint32(100), pd)
        ct = ctk.ct_apply(ct, fj, slot, jnp.zeros(12, bool), slot >= 0,
                          jnp.uint32(100), new_keys=nk, new_created=ncr,
                          zero_mask=zm)
        dup = flows({1: 0, 10: 1}, [9999, 9999, 9999])
        dj = {k: jnp.asarray(v) for k, v in dup.items()}
        dkeys = ctk.ct_key_words_jnp(dj)
        nk, ncr, zm, slot, fail, nev = ctk.ct_insert_new(
            ct, dkeys, jnp.asarray([True] * 3), jnp.uint32(150), pd,
            evict=True)
        s = np.asarray(slot)
        assert (s >= 0).all() and (s == s[0]).all()   # all adopt one slot
        assert int(nev) == 1                          # ONE eviction


# --------------------------------------------------------------------------- #
# oracle bounded-table semantics
# --------------------------------------------------------------------------- #
class TestBoundedOracle:
    def _oracle(self, cap=8, pd=4):
        return ConntrackTable(capacity=cap, probe_depth=pd)

    def _pkt(self, sport, flags=C.TCP_SYN):
        s16, _ = parse_addr("10.0.0.1")
        d16, _ = parse_addr("10.0.0.2")
        return PacketRecord(s16, d16, sport, 80, C.PROTO_TCP, flags)

    def test_create_fails_when_windows_full_of_established(self):
        tab = self._oracle(cap=4, pd=4)
        for sp in range(100, 104):
            key = tab.create(self._pkt(sp, flags=C.TCP_ACK), now=100)
            assert key is not None
        # all four entries have SEEN_NON_SYN (ACK create) → unevictable
        assert tab.create(self._pkt(999), now=150) is None
        assert tab.insert_fail == 1

    def test_create_evicts_soonest_expiring_syn(self):
        tab = self._oracle(cap=4, pd=4)
        keys = [tab.create(self._pkt(sp), now=100 + i)
                for i, sp in enumerate(range(200, 204))]
        assert all(k is not None for k in keys)
        # SYN entries: expiry 160..163; victim = the 160 one
        victim = keys[0]
        assert tab.create(self._pkt(888), now=150) is not None
        assert victim not in tab.entries
        assert tab.evicted == 1

    def test_unbounded_default_never_fails(self):
        tab = ConntrackTable()
        for sp in range(5000):
            assert tab.create(self._pkt(sp), now=100) is not None
        assert tab.insert_fail == 0

    @staticmethod
    def _open_oracle(tab=None):
        """Oracle with one unenforced endpoint (ep 0): everything allows
        at the policy layer, so CT semantics are the only variable."""
        from cilium_tpu.policy.mapstate import MapState
        from cilium_tpu.policy.repository import (DirectionPolicy,
                                                  EndpointPolicy)
        pol = EndpointPolicy(ep_id=0, identity_id=1, revision=1,
                             egress=DirectionPolicy(False, MapState()),
                             ingress=DirectionPolicy(False, MapState()))
        return Oracle({0: pol}, {}, ct=tab)

    def test_sequential_classify_emits_ct_full(self):
        """The sequential oracle's allowed-NEW flow against a saturated
        unevictable table → deny CT_FULL with ct_full set."""
        tab = self._oracle(cap=4, pd=4)
        oracle = self._open_oracle(tab)
        for sp in range(300, 304):
            v = oracle.classify(self._pkt(sp, flags=C.TCP_ACK), now=100)
            assert v.allow
        v = oracle.classify(self._pkt(777), now=150)
        assert not v.allow and not v.ct_status
        assert v.drop_reason == C.DropReason.CT_FULL and v.ct_full

    def test_replay_ct_full_only_excuses_demanded_creates(self):
        oracle = self._open_oracle()
        p = self._pkt(42)
        # demanded create + ct_full → the CT_FULL deny
        v, create = oracle.replay(p, C.CTStatus.NEW, ct_full=True)
        assert not v.allow and v.drop_reason == C.DropReason.CT_FULL
        assert not create
        # an ESTABLISHED row cannot be excused into a CT_FULL deny
        v, create = oracle.replay(p, C.CTStatus.ESTABLISHED, ct_full=True)
        assert v.allow and v.drop_reason == C.DropReason.OK


# --------------------------------------------------------------------------- #
# the bit-identity contract: jnp core / fused interpret / bounded oracle
# --------------------------------------------------------------------------- #
class TestSaturationParity:
    def _run_flood(self, eng_a, eng_b, fused_label):
        slots = eng_a.active.snapshot.ep_slot_of
        now = 1000
        # establish a protected population (ACK → SEEN_NON_SYN)
        est = flows(slots, range(30000, 30016), flags=0x10)
        assert_same(eng_a.classify(dict(est), now=now),
                    eng_b.classify(dict(est), now=now),
                    f"{fused_label}:establish")
        # flood: distinct SYN flows, several times the table capacity —
        # saturation, tail evictions, CT_FULL fails
        for wave in range(4):
            now += 1
            fl = flows(slots, range(40000 + wave * CT_CAP,
                                    40000 + (wave + 1) * CT_CAP))
            assert_same(eng_a.classify(dict(fl), now=now),
                        eng_b.classify(dict(fl), now=now),
                        f"{fused_label}:wave{wave}")
        # the established population survives the saturated table
        now += 1
        a = eng_a.classify(dict(est), now=now)
        b = eng_b.classify(dict(est), now=now)
        assert_same(a, b, f"{fused_label}:revisit")
        assert (np.asarray(a["status"])[np.asarray(est["valid"])]
                == int(C.CTStatus.ESTABLISHED)).all()
        assert bool(np.asarray(a["allow"])[np.asarray(est["valid"])].all())
        # the flood actually exhausted windows on both engines, identically
        assert eng_a.metrics.insert_fail == eng_b.metrics.insert_fail
        assert eng_a.metrics.ct_evicted == eng_b.metrics.ct_evicted
        assert eng_a.metrics.ct_evicted > 0
        rendered = eng_a.render_metrics()
        assert "ciliumtpu_ct_evicted_total" in rendered
        assert "ciliumtpu_ct_insert_fail_total" in rendered

    def test_jnp_vs_bounded_oracle_bit_identical_under_saturation(self):
        eng_a = make_engine(JITDatapath)
        eng_b = make_engine(FakeDatapath)
        try:
            self._run_flood(eng_a, eng_b, "jnp")
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_fused_interpret_vs_bounded_oracle_under_saturation(self):
        eng_a = make_engine(JITDatapath, fused="on")
        eng_b = make_engine(FakeDatapath)
        try:
            self._run_flood(eng_a, eng_b, "fused")
        finally:
            eng_a.stop()
            eng_b.stop()

    def test_auditor_zero_mismatch_through_saturation(self):
        """The acceptance-criterion form: the shadow auditor at sampling
        1.0 replays a saturated table's verdicts (CT_FULL denies included)
        with zero mismatches and checked > 0."""
        eng = make_engine(JITDatapath)
        eng.auditor.configure(sample_rate=1.0)
        try:
            slots = eng.active.snapshot.ep_slot_of
            now = 1000
            eng.classify(flows(slots, range(30000, 30016), flags=0x10),
                         now=now)
            for wave in range(4):
                now += 1
                eng.classify(flows(slots,
                                   range(41000 + wave * CT_CAP,
                                         41000 + (wave + 1) * CT_CAP)),
                             now=now)
                eng.audit_step(budget=16)
            for _ in range(50):
                step = eng.audit_step(budget=64)
                if not step or not (step.get("replayed")
                                    or step.get("pending")):
                    break
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0
            assert eng.metrics.insert_fail > 0      # genuinely saturated
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# emergency GC
# --------------------------------------------------------------------------- #
class TestEmergencyGC:
    def test_hysteresis_latch_and_ttl_slash(self):
        """Occupancy past ct_pressure_high arms emergency mode (gauge +
        blackbox event), sweeps run full-rate with slashed TTLs and bound
        occupancy, and the latch exits below ct_pressure_low."""
        cfg = DaemonConfig(ct_capacity=256, auto_regen=False,
                           ct_gc_chunk_rows=64, ct_gc_emergency_chunks=4,
                           ct_gc_emergency_ttl_slash_s=55,
                           ct_pressure_high=0.7, ct_pressure_low=0.3)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.add_endpoint(["k8s:peer=p0", "k8s:group=g0"],
                         ips=("172.16.0.5",), ep_id=10)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"group": "g0"}}],
                "toPorts": [{"ports": [
                    {"port": "80", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        try:
            slots = eng.active.snapshot.ep_slot_of
            now = 1000
            eng.classify(flows(slots, range(50000, 50224)), now=now)
            eng.sweep_step(now=now)       # enqueue
            st = eng.sweep_step(now=now)  # harvest → occupancy lands
            occ = eng.metrics.gauges["ct_occupancy"]
            assert occ >= 0.7             # a fraction, not a count
            assert eng._ct_emergency
            assert eng.metrics.gauges["ct_emergency_gc"] == 1
            assert st["emergency"] is False or st["emergency"] is True
            # SYN entries (60s life) die under the 55s slash within 6s
            for _ in range(6):
                now += 2
                st = eng.sweep_step(now=now)
                assert st["emergency"] in (True, False)
            occ = eng.metrics.gauges["ct_occupancy"]
            assert occ <= 0.3
            assert not eng._ct_emergency
            assert eng.metrics.gauges["ct_emergency_gc"] == 0
            assert eng.metrics.counters.get(
                "ct_emergency_sweeps_total", 0) > 0
            kinds = [e["kind"] for e in eng.blackbox._events]
            assert kinds.count("ct-emergency") >= 2   # enter + exit
            # commanded degradation never freezes the recorder
            assert eng.blackbox.stats()["frozen"] is False
        finally:
            eng.stop()

    def test_emergency_spares_established_flows(self):
        cfg = DaemonConfig(ct_capacity=256, auto_regen=False,
                           ct_gc_chunk_rows=256,
                           ct_gc_emergency_ttl_slash_s=55,
                           ct_pressure_high=0.5, ct_pressure_low=0.1)
        eng = Engine(cfg, datapath=JITDatapath(cfg))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.add_endpoint(["k8s:peer=p0", "k8s:group=g0"],
                         ips=("172.16.0.5",), ep_id=10)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"group": "g0"}}],
                "toPorts": [{"ports": [
                    {"port": "80", "protocol": "TCP"}]}]}]}])
        eng.regenerate()
        try:
            slots = eng.active.snapshot.ep_slot_of
            est = flows(slots, range(60000, 60032), flags=0x10)
            eng.classify(dict(est), now=1000)
            eng.classify(flows(slots, range(61000, 61160)), now=1001)
            for i in range(4):
                eng.sweep_step(now=1002 + 2 * i)
            assert eng._ct_emergency
            out = eng.classify(dict(est), now=1010)
            v = np.asarray(est["valid"])
            assert (np.asarray(out["status"])[v]
                    == int(C.CTStatus.ESTABLISHED)).all()
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# the slow flood soak (make ddos-smoke)
# --------------------------------------------------------------------------- #
@pytest.mark.slow
class TestFloodSoak:
    def test_soak_saturated_table_audited_with_ct_insert_faults(self):
        """The acceptance soak: thousands of pipelined flood submissions
        saturate a small CT table with ``ct.insert`` faults armed and the
        auditor at sampling 1.0 — zero mismatches, checked > 0, evictions
        and CT_FULL fails observed, every submission resolves (classified
        or failed closed, FIFO intact)."""
        eng = make_engine(JITDatapath, cap=512)
        eng.auditor.configure(sample_rate=1.0)
        FAULTS.arm("ct.insert", mode="prob", prob=0.02, seed=11)
        try:
            slots = eng.active.snapshot.ep_slot_of
            now = 1000
            est = flows(slots, range(30000, 30032), flags=0x10)
            eng.submit(dict(est), now=now).result(timeout=120)
            rng = np.random.default_rng(3)
            tickets = []
            n_sub = 3000
            for i in range(n_sub):
                if i % 8 == 0:
                    now += 1
                sports = rng.integers(32768, 65535, 48)
                try:
                    tickets.append(eng.submit(
                        flows(slots, sports.tolist()), now=now))
                except Exception:
                    pass                      # breaker-open storms: fine
                if i % 64 == 0:
                    eng.audit_step(budget=32)
                if i % 256 == 0:
                    eng.sweep_step(now=now)
            assert eng.drain(timeout=300)
            resolved = failed = 0
            for t in tickets:
                try:
                    t.result(timeout=30)
                    resolved += 1
                except Exception:
                    failed += 1               # fail-closed is a resolution
            assert resolved + failed == len(tickets)
            assert resolved > 0
            for _ in range(200):
                step = eng.audit_step(budget=128)
                if not step or not (step.get("replayed")
                                    or step.get("pending")):
                    break
            st = eng.auditor.stats()
            assert st["checked_rows"] > 0
            assert st["mismatched_rows"] == 0
            assert eng.metrics.ct_evicted > 0
            assert eng.metrics.insert_fail > 0
            # the established population still classifies ESTABLISHED
            out = eng.submit(dict(est), now=now + 1).result(timeout=120)
            v = np.asarray(est["valid"])
            assert (np.asarray(out["status"])[v]
                    == int(C.CTStatus.ESTABLISHED)).all()
        finally:
            FAULTS.reset()
            eng.stop()
