"""Policy engine golden tests — construct Repository + identities in memory,
resolve, assert MapState contents (the upstream pkg/policy test pattern,
SURVEY.md §4: "it is exactly a verdict-parity test")."""

import pytest

from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rule
from cilium_tpu.model.services import Service, ServiceRegistry
from cilium_tpu.policy import PolicyContext, Repository
from cilium_tpu.policy.mapstate import MapStateKey, PORT_WILDCARD
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.utils import constants as C


@pytest.fixture
def ctx():
    alloc = IdentityAllocator()
    return PolicyContext(
        allocator=alloc,
        selector_cache=SelectorCache(alloc),
        ipcache=IPCache(),
    )


def make_ep(ctx, labels, ep_id=1):
    lbls = Labels.parse(labels)
    ident = ctx.allocator.allocate(lbls)
    return Endpoint(ep_id=ep_id, labels=lbls, identity_id=ident.id)


class TestResolveBasics:
    def test_no_rules_not_enforced(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        pol = repo.resolve(ep)
        assert not pol.ingress.enforced and not pol.egress.enforced
        # unenforced direction: everything misses but that means allow
        assert pol.ingress.lookup(12345, C.PROTO_TCP, 80).decision == C.VERDICT_MISS

    def test_l3_allow_entry(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        peer = ctx.allocator.allocate(Labels.parse(["k8s:role=fe"]))
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}]}],
        })])
        pol = repo.resolve(ep)
        assert pol.ingress.enforced and not pol.egress.enforced
        assert MapStateKey(peer.id, C.PROTO_ANY, *PORT_WILDCARD) in pol.ingress.mapstate
        # peer allowed on any port/proto
        assert pol.ingress.lookup(peer.id, C.PROTO_UDP, 53).decision == C.VERDICT_ALLOW
        # other identity → miss (default deny since enforced)
        assert pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 80).decision == C.VERDICT_MISS

    def test_l4_port_scoping(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        peer = ctx.allocator.allocate(Labels.parse(["k8s:role=fe"]))
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"role": "fe"}}],
                "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}],
            }],
        })])
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(peer.id, C.PROTO_TCP, 80).decision == C.VERDICT_ALLOW
        assert pol.ingress.lookup(peer.id, C.PROTO_TCP, 81).decision == C.VERDICT_MISS
        assert pol.ingress.lookup(peer.id, C.PROTO_UDP, 80).decision == C.VERDICT_MISS

    def test_wildcard_peer_ports_only(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
        })])
        pol = repo.resolve(ep)
        # ANY identity allowed on 443 — including world
        assert pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 443).decision == C.VERDICT_ALLOW
        assert pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 80).decision == C.VERDICT_MISS

    def test_port_range(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [
                {"port": "8080", "endPort": 8090, "protocol": "TCP"}]}]}],
        })])
        pol = repo.resolve(ep)
        for port, want in [(8079, C.VERDICT_MISS), (8080, C.VERDICT_ALLOW),
                           (8085, C.VERDICT_ALLOW), (8090, C.VERDICT_ALLOW),
                           (8091, C.VERDICT_MISS)]:
            assert pol.ingress.lookup(0xdead, C.PROTO_TCP, port).decision == want


class TestDenyPrecedence:
    def test_deny_beats_more_specific_allow(self, ctx):
        """Upstream-documented: deny wins regardless of specificity."""
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        peer = ctx.allocator.allocate(Labels.parse(["k8s:role=fe"]))
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"role": "fe"}}],
                "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}],
            }],
            "ingressDeny": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}]}],
        })])
        pol = repo.resolve(ep)
        res = pol.ingress.lookup(peer.id, C.PROTO_TCP, 80)
        assert res.decision == C.VERDICT_DENY

    def test_deny_scoped_to_port(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        peer = ctx.allocator.allocate(Labels.parse(["k8s:role=fe"]))
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}]}],
            "ingressDeny": [{
                "fromEndpoints": [{"matchLabels": {"role": "fe"}}],
                "toPorts": [{"ports": [{"port": "22", "protocol": "TCP"}]}],
            }],
        })])
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(peer.id, C.PROTO_TCP, 22).decision == C.VERDICT_DENY
        assert pol.ingress.lookup(peer.id, C.PROTO_TCP, 80).decision == C.VERDICT_ALLOW


class TestCIDR:
    def test_cidr_allocates_identity_and_ipcache(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"]}],
        })])
        pol = repo.resolve(ep)
        cidr_id = ctx.ipcache.lookup("10.1.2.3")
        assert cidr_id & C.LOCAL_IDENTITY_SCOPE
        assert pol.egress.lookup(cidr_id, C.PROTO_TCP, 443).decision == C.VERDICT_ALLOW
        # outside the CIDR → world → miss
        assert pol.egress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 443).decision == C.VERDICT_MISS

    def test_cidrset_except_excluded(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDRSet": [
                {"cidr": "10.0.0.0/8", "except": ["10.96.0.0/12"]}]}],
        })])
        pol = repo.resolve(ep)
        in_id = ctx.ipcache.lookup("10.1.2.3")       # → /8 identity
        ex_id = ctx.ipcache.lookup("10.96.0.1")      # → /12 except identity
        assert in_id != ex_id
        assert pol.egress.lookup(in_id, C.PROTO_TCP, 1).decision == C.VERDICT_ALLOW
        assert pol.egress.lookup(ex_id, C.PROTO_TCP, 1).decision == C.VERDICT_MISS

    @pytest.mark.parametrize("wide_first", [True, False])
    def test_narrower_cidr_identity_matches_wider_rule(self, ctx, wide_first):
        """The parent-prefix-label mechanism: /16 identity allocated by one
        rule must still be allowed by another rule's /8 selector — in BOTH
        rule orders, on the FIRST resolve (regression: resolve used to
        allocate mid-expansion, making the first resolve order-dependent)."""
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        wide = parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                           "egress": [{"toCIDR": ["10.0.0.0/8"]}]})
        narrow = parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                             "egress": [{"toCIDR": ["10.1.0.0/16"]}]})
        repo.add([wide, narrow] if wide_first else [narrow, wide])
        pol = repo.resolve(ep)
        narrow_id = ctx.ipcache.lookup("10.1.2.3")   # resolves to /16 (longest)
        assert narrow_id == ctx.allocator.allocate_cidr("10.1.0.0/16").id
        assert pol.egress.lookup(narrow_id, C.PROTO_TCP, 80).decision == C.VERDICT_ALLOW

    def test_rule_delete_releases_identity_and_ipcache(self, ctx):
        """Regression: removed rules must release their CIDR identities and
        ipcache entries (leak check)."""
        repo = Repository(ctx)
        rule = parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                           "egress": [{"toCIDR": ["10.0.0.0/8"]}],
                           "labels": ["k8s:policy=p"]})
        repo.add([rule])
        cidr_id = ctx.ipcache.lookup("10.1.2.3")
        assert cidr_id & C.LOCAL_IDENTITY_SCOPE
        n_sel = len(ctx.selector_cache)
        repo.delete_by_labels(Labels.parse(["k8s:policy=p"]))
        assert ctx.ipcache.lookup("10.1.2.3") == C.IDENTITY_WORLD
        assert ctx.allocator.get(cidr_id) is None
        assert len(ctx.selector_cache) < n_sel

    def test_shared_cidr_survives_one_rule_delete(self, ctx):
        repo = Repository(ctx)
        mk = lambda tag: parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"]}], "labels": [f"k8s:policy={tag}"]})
        repo.add([mk("a"), mk("b")])
        repo.delete_by_labels(Labels.parse(["k8s:policy=a"]))
        # rule b still references the /8 identity: must survive
        assert ctx.ipcache.lookup("10.1.2.3") & C.LOCAL_IDENTITY_SCOPE


class TestEntities:
    def test_world_entity(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toEntities": ["world"]}],
        })])
        pol = repo.resolve(ep)
        assert pol.egress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 443).decision == C.VERDICT_ALLOW
        peer = ctx.allocator.allocate(Labels.parse(["k8s:x=y"]))
        assert pol.egress.lookup(peer.id, C.PROTO_TCP, 443).decision == C.VERDICT_MISS

    def test_cluster_entity_matches_pods_not_world(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        pod = ctx.allocator.allocate(Labels.parse(["k8s:app=db"]))
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toEntities": ["cluster"]}],
        })])
        pol = repo.resolve(ep)
        assert pol.egress.lookup(pod.id, C.PROTO_TCP, 5432).decision == C.VERDICT_ALLOW
        assert pol.egress.lookup(C.IDENTITY_HOST, C.PROTO_TCP, 22).decision == C.VERDICT_ALLOW
        assert pol.egress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 443).decision == C.VERDICT_MISS

    def test_all_entity_is_wildcard(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEntities": ["all"]}],
        })])
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(0xbeef, C.PROTO_TCP, 1).decision == C.VERDICT_ALLOW


class TestEnforcementModes:
    def test_always_mode(self, ctx):
        ctx.enforcement_mode = C.ENFORCEMENT_ALWAYS
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        pol = repo.resolve(ep)
        assert pol.ingress.enforced and pol.egress.enforced

    def test_never_mode(self, ctx):
        ctx.enforcement_mode = C.ENFORCEMENT_NEVER
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                              "ingress": []})])
        pol = repo.resolve(ep)
        assert not pol.ingress.enforced

    def test_per_endpoint_override(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        ep.enforcement = C.ENFORCEMENT_ALWAYS
        pol = repo.resolve(ep)
        assert pol.ingress.enforced

    def test_allow_localhost_entry(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                              "ingress": []})])
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(C.IDENTITY_HOST, C.PROTO_TCP, 22).decision == C.VERDICT_ALLOW


class TestL7AndMerge:
    def test_l7_redirect(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{
                "ports": [{"port": "80", "protocol": "TCP"}],
                "rules": {"http": [{"method": "GET", "path": "/api"}]},
            }]}],
        })])
        pol = repo.resolve(ep)
        res = pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 80)
        assert res.decision == C.VERDICT_REDIRECT
        assert len(res.entry.l7_rules) == 1

    def test_plain_allow_shadows_l7_same_key(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([
            parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                        "ingress": [{"toPorts": [{
                            "ports": [{"port": "80", "protocol": "TCP"}],
                            "rules": {"http": [{"path": "/x"}]}}]}]}),
            parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                        "ingress": [{"toPorts": [{
                            "ports": [{"port": "80", "protocol": "TCP"}]}]}]}),
        ])
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 80).decision == C.VERDICT_ALLOW

    def test_l7_union_same_key(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([
            parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                        "ingress": [{"toPorts": [{
                            "ports": [{"port": "80", "protocol": "TCP"}],
                            "rules": {"http": [{"path": "/a"}]}}]}]}),
            parse_rule({"endpointSelector": {"matchLabels": {"app": "web"}},
                        "ingress": [{"toPorts": [{
                            "ports": [{"port": "80", "protocol": "TCP"}],
                            "rules": {"http": [{"path": "/b"}]}}]}]}),
        ])
        pol = repo.resolve(ep)
        res = pol.ingress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 80)
        assert res.decision == C.VERDICT_REDIRECT
        assert {h.path for h in res.entry.l7_rules} == {"/a", "/b"}


class TestToServices:
    def test_v6_backend_normalized(self, ctx):
        """Regression: non-canonical backend IPs (uppercase v6) must still
        produce a selector that matches the normalized cidr identity label."""
        ctx.services.upsert(Service(name="db6", namespace="prod",
                                    backends=("2001:DB8::1",)))
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toServices": [{"k8sService": {
                "serviceName": "db6", "namespace": "prod"}}]}],
        })])
        pol = repo.resolve(ep)
        backend_id = ctx.ipcache.lookup("2001:db8::1")
        assert backend_id & C.LOCAL_IDENTITY_SCOPE
        assert pol.egress.lookup(backend_id, C.PROTO_TCP, 5432).decision == C.VERDICT_ALLOW

    def test_service_change_rematerializes(self, ctx):
        """Backend set changes must re-materialize and bump the revision."""
        ctx.services.upsert(Service(name="db", namespace="prod",
                                    backends=("10.10.0.5",)))
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toServices": [{"k8sService": {
                "serviceName": "db", "namespace": "prod"}}]}],
        })])
        rev0 = repo.revision
        ctx.services.upsert(Service(name="db", namespace="prod",
                                    backends=("10.10.0.7",)))
        assert repo.revision > rev0
        pol = repo.resolve(ep)
        new_id = ctx.ipcache.lookup("10.10.0.7")
        assert pol.egress.lookup(new_id, C.PROTO_TCP, 5432).decision == C.VERDICT_ALLOW
        # old backend released
        assert ctx.ipcache.lookup("10.10.0.5") == C.IDENTITY_WORLD

    def test_backends_resolved(self, ctx):
        ctx.services.upsert(Service(name="db", namespace="prod",
                                    backends=("10.10.0.5", "10.10.0.6")))
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toServices": [{"k8sService": {
                "serviceName": "db", "namespace": "prod"}}]}],
        })])
        pol = repo.resolve(ep)
        backend_id = ctx.ipcache.lookup("10.10.0.5")
        assert pol.egress.lookup(backend_id, C.PROTO_TCP, 5432).decision == C.VERDICT_ALLOW
        assert pol.egress.lookup(C.IDENTITY_WORLD, C.PROTO_TCP, 5432).decision == C.VERDICT_MISS


class TestIncremental:
    def test_new_identity_visible_after_reresolve(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"fromEndpoints": [{"matchLabels": {"role": "fe"}}]}],
        })])
        pol = repo.resolve(ep)
        late = ctx.allocator.allocate(Labels.parse(["k8s:role=fe", "k8s:v=2"]))
        assert pol.ingress.lookup(late.id, C.PROTO_TCP, 80).decision == C.VERDICT_MISS
        pol2 = repo.resolve(ep)
        assert pol2.ingress.lookup(late.id, C.PROTO_TCP, 80).decision == C.VERDICT_ALLOW

    def test_selector_cache_incremental_notify(self, ctx):
        from cilium_tpu.model.selectors import EndpointSelector
        sel = ctx.selector_cache.add_selector(
            EndpointSelector.from_labels({"role": "fe"}))
        events = []
        sel.subscribe(lambda a, r: events.append((set(a), set(r))))
        fe = ctx.allocator.allocate(Labels.parse(["k8s:role=fe"]))
        assert fe.id in sel.identities
        assert events and events[0][0] == {fe.id}

    def test_replace_by_labels(self, ctx):
        repo = Repository(ctx)
        ep = make_ep(ctx, ["k8s:app=web"])
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}]}],
            "labels": ["k8s:policy=p1"],
        })])
        rev0 = repo.revision
        repo.replace_by_labels(Labels.parse(["k8s:policy=p1"]), [parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "ingress": [{"toPorts": [{"ports": [{"port": "443", "protocol": "TCP"}]}]}],
            "labels": ["k8s:policy=p1"],
        })])
        assert repo.revision > rev0
        pol = repo.resolve(ep)
        assert pol.ingress.lookup(0xabc, C.PROTO_TCP, 80).decision == C.VERDICT_MISS
        assert pol.ingress.lookup(0xabc, C.PROTO_TCP, 443).decision == C.VERDICT_ALLOW
